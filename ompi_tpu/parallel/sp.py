"""Sequence/context parallelism: ring attention.

SURVEY §2.6 SP row and §5.7 — the reference's ring-pass-with-compute-
overlap skeleton (allreduce_intra_ring, coll_base_allreduce.c:341) is
exactly the ring-attention communication pattern: KV blocks circulate the
ring via single-hop ppermute while each step's attention contribution is
accumulated with a numerically-stable online softmax. XLA overlaps the
next hop's DMA with the current block's flash-style compute.

Sequence is sharded over `axis_name`: each rank holds T = S/n tokens.
Causality is enforced against *global* positions, so results match
single-device causal attention exactly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..coll import spmd
from ..core import config

_NEG = -1e30

_impl_var = config.register(
    "parallel", "sp", "impl", type=str, default="xla",
    description="Ring attention implementation: 'xla' (ppermute ring, "
                "compiler-scheduled overlap, any shape) or 'pallas' "
                "(fused kernel with guaranteed DMA/compute overlap; "
                "needs tile-aligned T/Dh and VMEM-resident blocks, "
                "falls back to xla otherwise)",
)


def ring_attention(
    q: jax.Array,  # (T, H, Dh) local queries
    k: jax.Array,  # (T, H, Dh) local keys
    v: jax.Array,  # (T, H, Dh) local values
    axis_name: str = "sp",
    causal: bool = True,
    impl: str | None = None,
) -> jax.Array:
    """Exact attention over the full (sharded) sequence. Returns the
    (T, H, Dh) outputs for this rank's query block."""
    chosen = impl or _impl_var.value
    if chosen not in ("xla", "pallas"):
        from ..core.errors import ArgumentError

        raise ArgumentError(
            f"unknown ring attention impl {chosen!r}; known: xla, pallas"
        )
    if chosen == "pallas":
        from ..coll import pallas_attn

        if pallas_attn.supported(q):
            return pallas_attn.ring_attention_block(
                q, k, v, axis_name, causal=causal
            )
        # unaligned or VMEM-overflowing shapes: the fused kernel can't
        # take them — stream through the XLA path instead of failing
        # at trace time
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    T, H, Dh = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(Dh, q.dtype))

    q_pos = my * T + jnp.arange(T)  # global positions of my queries

    # Online-softmax accumulators.
    m = jnp.full((H, T), _NEG, jnp.float32)
    l = jnp.zeros((H, T), jnp.float32)
    o = jnp.zeros((H, T, Dh), jnp.float32)

    kb, vb = k, v
    for step in range(n):
        src = (my - step) % n  # which rank's KV block we now hold
        kv_pos = src * T + jnp.arange(T)
        # (H, Tq, Tk)
        scores = (
            jnp.einsum("qhd,khd->hqk", q, kb).astype(jnp.float32) * scale
        )
        if causal:
            mask = q_pos[:, None] >= kv_pos[None, :]
            scores = jnp.where(mask[None], scores, _NEG)
        blk_max = scores.max(axis=-1)  # (H, Tq)
        m_new = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])  # (H, Tq, Tk)
        l = l * corr + p.sum(axis=-1)
        o = o * corr[..., None] + jnp.einsum(
            "hqk,khd->hqd", p, vb.astype(jnp.float32)
        )
        m = m_new
        if step != n - 1:
            kb, vb = spmd.ring_shift((kb, vb), axis_name, 1)

    out = o / jnp.maximum(l, 1e-30)[..., None]  # (H, T, Dh)
    return out.transpose(1, 0, 2).astype(q.dtype)  # (T, H, Dh)


def shard_sequence(x: jax.Array, axis_name: str = "sp") -> jax.Array:
    """Slice a replicated (S, ...) tensor to this rank's (S/n, ...)."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    per = x.shape[0] // n
    return lax.dynamic_slice_in_dim(x, idx * per, per, axis=0)

"""parallel/bucketer — gradient bucket coalescing for data parallelism.

A transformer step produces hundreds of gradient leaves, and BENCH host
rows show per-call dispatch overhead dominating collectives below
~64 KiB — so reducing each leaf separately pays that overhead hundreds
of times per step.  The coalescer flattens the gradient pytree into a
few size-capped flat buckets (cvar ``parallel_dp_bucket_bytes``) and
issues ONE allreduce per bucket, so the whole decision stack — tuned's
algorithm table, hier's same-host split, the pallas kernels and the
quantized wire tier (coll/quant) — schedules per *bucket*, at bucket
size, instead of per leaf (the fusion T3/arxiv 2401.16677 motivates;
torch's DDP gradient buckets are the mainstream analog).

Determinism and ordering guarantees (DESIGN.md §12):
  * Bucket composition is a pure function of (pytree structure, leaf
    shapes/dtypes, bucket_bytes): leaves are taken in ``jax.tree``
    flatten order, grouped by dtype (preserving order inside each
    group), concatenated, and cut at element boundaries — never
    mid-element, never reordered.  Repeated calls with the same inputs
    bucket identically, so error-feedback residuals stay aligned.
  * Values are bit-identical to per-leaf dispatch for the exact tiers:
    an elementwise reduction of a concatenation is the concatenation of
    the reductions — per-element operation order is unchanged.

Two entry points mirror the two calling contexts:
  * :func:`allreduce_tree` — traced, inside shard_map/jit (the
    transformer train step); dispatches each bucket through
    ``coll.tuned.allreduce_by_decision``.
  * :func:`allreduce_pytree` — host-side, rank-major buffers through
    the comm vtable (``comm.allreduce`` per bucket), with optional
    error feedback.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..core import config
from ..core.counters import SPC

_bucket_bytes_var = config.register(
    "parallel", "dp", "bucket_bytes",
    type=int, default=4 << 20,
    description="Max bytes per fused gradient-allreduce bucket "
                "(0 disables fusion: one dispatch per leaf)",
)

SPC.counter(
    "parallel_dp_bucket_dispatches",
    "fused gradient buckets dispatched (one collective each)",
)
SPC.counter(
    "parallel_dp_bucket_leaves",
    "gradient leaves coalesced into buckets",
)


class Bucket(NamedTuple):
    """One planned bucket: ``leaf_ids`` index the flattened pytree;
    ``elems`` is the flat element count of the bucket's payload."""
    dtype: Any
    elems: int
    #: (leaf_id, lo, hi): leaf's flat slice [lo, hi) lives in this
    #: bucket at the running offset (a leaf larger than the cap spans
    #: consecutive buckets).
    pieces: tuple


def plan_buckets(tree: Any, bucket_bytes: Optional[int] = None
                 ) -> list[Bucket]:
    """Deterministic bucket plan for a pytree (shapes only, no data).
    The plan length IS the collective-dispatch count of a fused
    allreduce of ``tree``."""
    if bucket_bytes is None:
        bucket_bytes = _bucket_bytes_var.value
    leaves = jax.tree.leaves(tree)
    groups: dict = {}
    for i, leaf in enumerate(leaves):
        dt = jnp.asarray(leaf).dtype
        groups.setdefault(str(dt), (dt, []))[1].append(i)
    plans: list[Bucket] = []
    for _, (dt, ids) in sorted(groups.items()):
        fused = bucket_bytes > 0
        cap = max(1, bucket_bytes // dt.itemsize) if fused else 0
        pieces: list = []
        elems = 0
        for i in ids:
            size = jnp.asarray(leaves[i]).size
            lo = 0
            while lo < size or size == 0:
                take = min(size - lo, cap - elems) if fused else size
                pieces.append((i, lo, lo + take))
                elems += take
                lo += take
                if fused and elems >= cap:
                    plans.append(Bucket(dt, elems, tuple(pieces)))
                    pieces, elems = [], 0
                if size == 0:
                    break
            if not fused and pieces:
                # Fusion disabled: one bucket (dispatch) per leaf.
                plans.append(Bucket(dt, elems, tuple(pieces)))
                pieces, elems = [], 0
        if pieces:
            plans.append(Bucket(dt, elems, tuple(pieces)))
    return plans


def _gather_bucket(leaves: list, bucket: Bucket, flat_axis: int):
    parts = [
        jnp.asarray(leaves[i]).reshape(
            leaves[i].shape[:flat_axis] + (-1,))[..., lo:hi]
        for i, lo, hi in bucket.pieces
    ]
    return jnp.concatenate(parts, axis=-1)


def _scatter_bucket(out_flat: dict, reduced, bucket: Bucket) -> None:
    off = 0
    for i, lo, hi in bucket.pieces:
        out_flat.setdefault(i, []).append(reduced[..., off:off + (hi - lo)])
        off += hi - lo


def _reassemble(leaves: list, out_flat: dict, flat_axis: int) -> list:
    out = []
    for i, leaf in enumerate(leaves):
        arr = jnp.asarray(leaf)
        if i not in out_flat:          # zero-size leaf: nothing moved
            out.append(arr)
            continue
        flat = jnp.concatenate(out_flat[i], axis=-1)
        out.append(flat.reshape(arr.shape))
    return out


#: Jitted gather/reassemble programs keyed by bucket plan: the host-side
#: path re-uses the same plan every step (bucketing is deterministic), so
#: the per-call cost of slicing N leaves into buckets and back is one
#: executable launch each instead of ~2N separate jnp dispatches.
_PLAN_JIT_CACHE: dict = {}


def _plan_jit(plan: list, flat_axis: int, tag: str, make):
    key = (tag, flat_axis,
           tuple((str(b.dtype), b.elems, b.pieces) for b in plan))
    fn = _PLAN_JIT_CACHE.get(key)
    if fn is None:
        fn = _PLAN_JIT_CACHE[key] = jax.jit(make())
    return fn


def _gather_fn(plan: list, flat_axis: int):
    def make():
        def gather(leaves):
            return [_gather_bucket(leaves, b, flat_axis) for b in plan]
        return gather
    return _plan_jit(plan, flat_axis, "gather", make)


def _reassemble_fn(plan: list, flat_axis: int):
    def make():
        def reassemble(leaves, reduced):
            out_flat: dict = {}
            for b, r in zip(plan, reduced):
                _scatter_bucket(out_flat, r, b)
            return _reassemble(leaves, out_flat, flat_axis)
        return reassemble
    return _plan_jit(plan, flat_axis, "reassemble", make)


def allreduce_tree(tree: Any, axis_name: str, op: Any = "sum",
                   bucket_bytes: Optional[int] = None,
                   allow_quant: Optional[bool] = None) -> Any:
    """Traced fused allreduce of a gradient pytree over ``axis_name``
    (inside shard_map/jit): one collective per planned bucket, each
    routed through coll/tuned's decision (so the quant tier and the
    explicit algorithms apply per bucket).  SPC bucket counters are
    recorded at trace time — they count collectives in the compiled
    program, not executions."""
    from ..coll import tuned

    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    plan = plan_buckets(leaves, bucket_bytes)
    SPC.record("parallel_dp_bucket_leaves", len(leaves))
    out_flat: dict = {}
    for bucket in plan:
        payload = _gather_bucket(leaves, bucket, 0)
        reduced = tuned.allreduce_by_decision(
            payload, axis_name, op, allow_quant=allow_quant)
        SPC.record("parallel_dp_bucket_dispatches")
        _scatter_bucket(out_flat, reduced, bucket)
    return jax.tree.unflatten(
        treedef, _reassemble(leaves, out_flat, 0))


def allreduce_pytree(comm, tree: Any, op: Any = "sum",
                     bucket_bytes: Optional[int] = None,
                     error_feedback=None) -> Any:
    """Host-side fused allreduce of a pytree of rank-major ``(size,
    ...)`` buffers through the comm VTABLE: one ``comm.allreduce`` per
    bucket, so component selection (tuned/hier/pallas) and the quant
    tier run per bucket.  ``error_feedback`` is an optional dict used
    as a residual bank: one :class:`ompi_tpu.coll.quant.ErrorFeedback`
    per bucket index, created on first use and carried across calls
    (aligned because bucketing is deterministic — pass the same dict
    every step)."""
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    size = comm.size
    for leaf in leaves:
        arr = jnp.asarray(leaf)
        if arr.ndim < 1 or arr.shape[0] != size:
            raise ValueError(
                f"allreduce_pytree needs rank-major (size, ...) leaves,"
                f" got shape {arr.shape}"
            )
    # Plan over the per-rank payload (axis 0 is the rank axis).
    per_rank = [jnp.asarray(l)[0] for l in leaves]
    plan = plan_buckets(per_rank, bucket_bytes)
    SPC.record("parallel_dp_bucket_leaves", len(leaves))
    payloads = _gather_fn(plan, 1)(leaves)      # (size, elems) each
    reduced = []
    for bi, payload in enumerate(payloads):
        if error_feedback is not None:
            from ..coll.quant import ErrorFeedback

            ef = error_feedback.setdefault(bi, ErrorFeedback())
            payload = ef.compensate(payload)
        reduced.append(comm.allreduce(payload, op))
        SPC.record("parallel_dp_bucket_dispatches")
    return jax.tree.unflatten(
        treedef, _reassemble_fn(plan, 1)(leaves, reduced))

"""parallel/overlap — T3-style tile-granular compute/comm overlap for
the data-parallel gradient reduction.

The bucketer (parallel/bucketer) fuses gradient leaves into size-capped
buckets; until now a bucket's collective could only start once the WHOLE
bucket was produced. This module tracks readiness at *tile* granularity
inside each bucket (T3, arxiv 2401.16677: track output-tile completion
during backprop, trigger sub-operation collectives as tiles land):

* Each planned bucket becomes ONE persistent
  :class:`ompi_tpu.coll.partitioned.PartitionedAllreduce` —
  Psend_init/Precv_init bound once at session construction, re-armed
  every step by ``start()``. A tile and the partition→transfer
  re-blocking under it therefore can never straddle two buckets: the
  bucketer's fusion boundary IS the partitioned-request boundary.
* :meth:`DpOverlapSession.mark_ready` maps a gradient leaf (or a flat
  slice of one) onto the tiles it covers; fully covered tiles fire as
  coalesced ``Pready_range`` bursts inside one fastpath batch-dispatch
  window, and arrivals drain via ``Parrived`` polling from the progress
  engine — the reduction of early tiles overlaps the backward pass
  still producing late ones.
* The transformer hooks (:func:`grad_marker`,
  :func:`capture_ready_schedule`) record the backprop completion order
  at trace time — custom-VJP identities whose backward rule fires as
  each layer's gradients finish — so host-side training loops (and the
  bench) replay production in true backward order.

Per-step accounting lands in :class:`OverlapReport`:
``dp_step_overlap_pct`` is the fraction of allreduce wall-time hidden
under backprop, ``exposed_comm_ms`` the tail left after backward ends.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from functools import partial
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import config
from ..core import progress as _progress
from ..core.counters import SPC
from ..core.errors import ArgumentError, RequestError
from ..ops import SUM
from . import bucketer

_tile_bytes_var = config.register(
    "parallel", "overlap", "tile_bytes",
    type=int, default=256 << 10,
    description="Target bytes per readiness tile inside a gradient "
                "bucket (each tile fires one Pready as its gradient "
                "materializes)",
)

SPC.counter(
    "parallel_overlap_marks",
    "mark_ready calls mapped onto bucket tiles",
)


class LeafPiece(NamedTuple):
    """One leaf's flat slice [leaf_lo, leaf_hi) lives in bucket
    ``bucket`` at bucket offsets [bucket_lo, bucket_hi)."""
    bucket: int
    bucket_lo: int
    bucket_hi: int
    leaf_lo: int
    leaf_hi: int


@dataclasses.dataclass
class OverlapPlan:
    """Deterministic leaf→bucket→tile map for one gradient pytree."""
    buckets: list
    leaf_pieces: dict            # leaf_id -> [LeafPiece]
    leaf_paths: list             # leaf_id -> jax keystr
    treedef: Any
    leaf_shapes: list            # per-rank shapes
    leaf_dtypes: list
    # Per-bucket tile geometry. plan_overlap seeds it from its
    # tile_bytes argument; a session compiling a step program stamps
    # the autotuned geometry (winner-cache override included) back
    # here, so the plan always names the geometry that executes.
    tiles: Optional[list] = None
    tile_elems: Optional[list] = None
    tile_sources: Optional[list] = None


def _tile_geometry(elems: int, nbytes: int, tile_bytes: int) -> tuple:
    """(tiles, tile_elems) for one bucket — the same uniform rounding
    PartitionedAllreduce applies."""
    tiles = max(1, min(-(-nbytes // max(1, tile_bytes)), elems))
    te = -(-elems // tiles)
    return -(-elems // te), te


def plan_overlap(per_rank_leaves: list, treedef,
                 bucket_bytes: Optional[int] = None,
                 tile_bytes: Optional[int] = None) -> OverlapPlan:
    """Build the overlap plan over PER-RANK leaves (shapes only). The
    bucket composition is exactly ``bucketer.plan_buckets`` — fusion
    boundaries are shared with the non-overlapped path."""
    plans = bucketer.plan_buckets(per_rank_leaves, bucket_bytes)
    pieces: dict = {}
    for b_idx, bucket in enumerate(plans):
        off = 0
        for leaf_id, lo, hi in bucket.pieces:
            pieces.setdefault(leaf_id, []).append(
                LeafPiece(b_idx, off, off + (hi - lo), lo, hi)
            )
            off += hi - lo
    paths = [f"leaf{i}" for i in range(len(per_rank_leaves))]
    tb = _tile_bytes_var.value if tile_bytes is None else int(tile_bytes)
    geom = [_tile_geometry(b.elems, b.elems * b.dtype.itemsize, tb)
            for b in plans]
    return OverlapPlan(
        buckets=plans,
        leaf_pieces=pieces,
        leaf_paths=paths,
        treedef=treedef,
        leaf_shapes=[tuple(np.shape(l)) for l in per_rank_leaves],
        leaf_dtypes=[jnp.asarray(l).dtype for l in per_rank_leaves],
        tiles=[g[0] for g in geom],
        tile_elems=[g[1] for g in geom],
        tile_sources=["default"] * len(plans),
    )


@dataclasses.dataclass
class OverlapReport:
    """Per-step overlap accounting (the dp_step_overlap_pct source).

    Window sessions (``window >= 2``) additionally account the step's
    merged broadcast tail: ``tail_ms`` is its dispatch wall-time and
    ``tail_overlap_ms`` the share of it hidden under the NEXT step's
    backward pass (the slipstream headline)."""
    backward_ms: float = 0.0
    comm_ms: float = 0.0
    exposed_comm_ms: float = 0.0
    tiles: int = 0
    buckets: int = 0
    tail_ms: float = 0.0
    tail_overlap_ms: float = 0.0

    @property
    def overlap_pct(self) -> float:
        """Fraction (percent) of allreduce wall-time hidden under the
        backward pass."""
        if self.comm_ms <= 0.0:
            return 100.0
        pct = 100.0 * (1.0 - self.exposed_comm_ms / self.comm_ms)
        return max(0.0, min(100.0, pct))


class _TailNode:
    """One closed step's armed broadcast tail, queued for dispatch.

    The claim protocol (claim under the fire lock, run unlocked) lets
    the pump thread dispatch the tail concurrently with the next step's
    backward while flush()/begin_step() can still force-complete it —
    whoever claims first runs ``finish_tail()``; everyone else waits on
    the event."""

    __slots__ = ("exec_", "phase", "report", "event", "claimed",
                 "result", "error")

    def __init__(self, exec_, phase: int, report: OverlapReport) -> None:
        self.exec_ = exec_
        self.phase = phase
        self.report = report
        self.event = threading.Event()
        self.claimed = False
        self.result = None
        self.error: Optional[BaseException] = None


class DpOverlapSession:
    """Host-side tile-granular gradient allreduce session.

    Bind once per (comm, gradient structure); then every step::

        sess.begin_step()
        for name, value in backward_order:   # as grads materialize
            sess.mark_ready(name, value)
        grads, report = sess.finish()

    Leaves are rank-major ``(size, ...)`` buffers (the driver-model
    SPMD view, same convention as ``bucketer.allreduce_pytree``).

    The session's comm is ONE compiled step program
    (:func:`ompi_tpu.coll.sched.stepprogram.compile_step`): the bucket
    list compiles into a multi-collective ``Program`` — per-bucket tile
    geometry from the autotuner's precedence (explicit ``tile_bytes`` >
    winner cache > model), RS/AG-vs-allreduce as a schedule decision
    (pin per bucket via ``node_choices``), cross-bucket interleave —
    and a :class:`~ompi_tpu.coll.sched.stepprogram.StepExecutor` binds
    it to live transport. ``step_program=False`` drops back to the
    PR 15 per-bucket behaviour (one broadcast and one progress
    callback per bucket) — kept as the bench's comparison arm.

    ``window >= 2`` turns the session into a **slipstream window**
    (coll/sched/slipstream): the bucket list compiles through
    :func:`~ompi_tpu.coll.sched.slipstream.compile_window` (shard
    residency included — elided allgathers never build wire flows),
    and the step loop becomes::

        sess.begin_step(); ...mark_ready...; sess.step()   # step N
        sess.begin_step(); ...mark_ready...; sess.step()   # step N+1
        results = sess.flush()       # [(grads, report), ...] in order

    ``step()`` closes the step at ``wait_reduced()`` — reductions done,
    merged broadcast tail ARMED but not drained — and queues the tail
    for the pump thread, which dispatches it concurrently with step
    N+1's backward tile bursts. Each phase of the window owns its own
    executor (disjoint tag ranges), so step N's tail and step N+1's
    reductions coexist on the fabric. ``finish()`` still works (close +
    flush, last step's result) and :meth:`abort_window` collapses the
    window deterministically (the lifeboat path).
    """

    def __init__(self, comm, template: Any, op: Any = SUM,
                 bucket_bytes: Optional[int] = None,
                 tile_bytes: Optional[int] = None,
                 allow_quant: Optional[bool] = None,
                 tag_base: int = 820,
                 progress_thread: bool = True,
                 step_program: bool = True,
                 node_choices: Optional[list] = None,
                 seed: Optional[int] = None,
                 window: int = 1,
                 ag_deadlines: Optional[list] = None) -> None:
        from ..coll.sched.stepprogram import StepExecutor, compile_step

        leaves, treedef = jax.tree.flatten(template)
        if not leaves:
            raise ArgumentError("empty gradient template")
        size = comm.size
        for leaf in leaves:
            shape = np.shape(leaf)
            if len(shape) < 1 or shape[0] != size:
                raise ArgumentError(
                    f"overlap session needs rank-major (size, ...) "
                    f"leaves, got shape {shape}"
                )
        # Full template shapes, kept separately from the plan's PER-RANK
        # shapes: a 1-D (size,) leaf plans as a per-rank (1,) proxy, and
        # reassembly must restore the original (size,) — not (size, 1).
        self._template_shapes = [tuple(np.shape(l)) for l in leaves]
        per_rank = [
            jax.ShapeDtypeStruct(np.shape(l)[1:] or (1,),
                                 jnp.asarray(l).dtype)
            for l in leaves
        ]
        # plan_buckets sizes leaves via jnp.asarray(...).size — feed it
        # zero-cost shape proxies.
        proxies = [np.zeros(s.shape, s.dtype) for s in per_rank]
        self.plan = plan_overlap(proxies, treedef, bucket_bytes)
        paths_leaves = jax.tree_util.tree_flatten_with_path(template)[0]
        self.plan.leaf_paths = [
            jax.tree_util.keystr(p) for p, _ in paths_leaves
        ]
        self._name_to_leaf = {
            p: i for i, p in enumerate(self.plan.leaf_paths)
        }
        self._comm = comm
        self._op = op
        self._window = int(window)
        if self._window < 1:
            raise ArgumentError(f"window must be >= 1, got {window}")
        if self._window >= 2 and not step_program:
            raise ArgumentError(
                "window sessions pipeline compiled step programs — "
                "window >= 2 needs step_program=True")
        # Compile the step: the bucket list becomes one multi-
        # collective Program, and its executor owns every per-bucket
        # flow. Explicit tile_bytes wins; otherwise the autotuner
        # consults the winner cache, then the model — never a static
        # default. Window sessions compile the two-step slipstream
        # window instead (tail node + shard residency + boundary
        # fusion), and execute its repeated step per phase.
        bucket_list = [(b.elems, b.dtype) for b in self.plan.buckets]
        if self._window >= 2:
            from ..coll.sched import slipstream
            self.compiled_window = slipstream.compile_window(
                size, bucket_list, tile_bytes=tile_bytes, seed=seed,
                node_choices=node_choices, ag_deadlines=ag_deadlines)
            self.compiled = self.compiled_window.step
        else:
            self.compiled_window = None
            self.compiled = compile_step(
                size, bucket_list, tile_bytes=tile_bytes, seed=seed,
                node_choices=node_choices, ag_deadlines=ag_deadlines)
        # One executor per window phase, disjoint tag ranges (a
        # ShardedAllreduce consumes nshards tags, everything else one)
        # plus slack, so step N's armed tail and step N+1's reductions
        # coexist on the fabric without tag collisions.
        self._execs = []
        tag = tag_base
        for _ in range(self._window):
            ex = StepExecutor(
                comm, self.compiled, op=op, allow_quant=allow_quant,
                tag_base=tag, legacy=not step_program)
            self._execs.append(ex)
            tag += sum(getattr(b, "nshards", 1)
                       for b in ex.bindings) + 8
        self._phase = 0
        self._pas = self._exec.bindings
        # Stamp the compiled geometry back into the plan so the plan
        # names what executes (the winner-cache override regression
        # hook).
        self.plan.tiles = [n.tiles for n in self.compiled.nodes]
        self.plan.tile_elems = [n.tile_elems for n in self.compiled.nodes]
        self.plan.tile_sources = [n.tile_source
                                  for n in self.compiled.nodes]
        self._stage = [np.zeros((size, b.elems), b.dtype)
                       for b in self.plan.buckets]
        self._covered = None
        self._fired = None
        self._active = False
        self._report = None
        # Async progress pumper (opal progress-thread analog): drains
        # tile arrivals while BOTH the backward producer and the apply
        # consumer are busy in compute — without it, overlap only
        # happens while some caller is blocked inside the engine.
        self._use_pump_thread = bool(progress_thread)
        self._pump_stop: Optional[threading.Event] = None
        self._pump_thread: Optional[threading.Thread] = None
        # Completed tile runs queued for dispatch off the producer
        # thread: mark_ready() stays a staging memcpy plus bookkeeping,
        # the pump thread pays for wire encode + Pready bursts.
        self._fire_q: deque = deque()
        self._fire_lock = threading.Lock()
        # Window state: closed steps whose broadcast tails are armed
        # but not yet drained. _tails keeps step order (flush returns
        # results in it); _tail_q feeds the pump thread's drain pass.
        self._tails: list = []
        self._tail_q: deque = deque()

    @property
    def _exec(self):
        """The executor owning the CURRENT phase of the window (the
        only executor, for window == 1)."""
        return self._execs[self._phase]

    # -- step lifecycle ---------------------------------------------------

    def begin_step(self) -> "DpOverlapSession":
        """Re-arm the compiled step program (every node flow, one
        dispatch window, compiled interleave order) and reset tile
        coverage."""
        if self._active:
            raise RequestError("begin_step() inside an open step")
        if self._window >= 2:
            # A phase's executor cannot re-arm (start() resets the
            # deferred root-local buffers) until its previous tail
            # consumed them — force-complete same-phase pending tails,
            # and surface any tail error the pump thread stashed.
            for rec in self._tails:
                if rec.phase == self._phase and not rec.event.is_set():
                    self._complete_tail(rec)
            for rec in self._tails:
                if rec.error is not None:
                    err = rec.error
                    self.abort_window()
                    raise err
        self._pas = self._exec.bindings
        self._exec.begin_step()
        self._covered = [
            np.zeros(pa.tiles, np.int64) for pa in self._pas
        ]
        self._covmask = [
            np.zeros(b.elems, bool) for b in self.plan.buckets
        ]
        self._fired = [np.zeros(pa.tiles, bool) for pa in self._pas]
        self._fire_q.clear()
        for buf in self._stage:
            buf.fill(0)
        self._active = True
        self._t0 = time.perf_counter()
        self._t_bwd_end = None
        self._report = None
        # Window mode keeps ONE pump thread alive across the whole
        # window (it drains step N's tail under step N+1's backward);
        # single-step mode still cycles it per step.
        if self._use_pump_thread and self._pump_thread is None:
            self._pump_stop = threading.Event()
            self._pump_thread = threading.Thread(
                target=self._pump_loop, args=(self._pump_stop,),
                name="dp-overlap-progress", daemon=True,
            )
            self._pump_thread.start()
        return self

    def _pump_loop(self, stop: threading.Event) -> None:
        """Background drain: dispatch queued tile runs, then pump the
        progress engine (serialized with every other waiter through the
        engine's pumper lock) until the step's buckets are all reduced
        or finish() signals stop."""
        def _quiet() -> bool:
            return (stop.is_set() or bool(self._fire_q)
                    or bool(self._tail_q)
                    or all(pa.reduced for pa in self._pas))

        while not stop.is_set():
            self._drain_fire_q()
            # Queued window tails dispatch HERE, after (outside) the
            # fire queue's batch-dispatch window: the merged broadcast
            # is a blocking collective, and a live shm fabric buffers
            # posts until window exit — running it inside the coalescing
            # window would deadlock it against its own dispatch.
            self._drain_tails()
            if all(pa.reduced for pa in self._pas):
                stop.wait(0.002)
                continue
            _progress.ENGINE.progress_until(_quiet, timeout=0.02)

    def _drain_fire_q(self) -> bool:
        """Dispatch every queued completed-tile run as Pready bursts in
        one coalescing window. Serialized against concurrent callers
        (pump thread vs finish) by the fire lock."""
        from ..coll.partitioned import _batch_window

        if not self._fire_q:
            return False
        with self._fire_lock:
            if not self._fire_q:
                return False
            with _batch_window():
                while self._fire_q:
                    b, run_lo, run_hi = self._fire_q.popleft()
                    pa = self._pas[b]
                    llo = pa.tile_range(run_lo)[0]
                    lhi = pa.tile_range(run_hi)[1]
                    pa.ready_range(run_lo, run_hi,
                                   self._stage[b][:, llo:lhi])
        return True

    # -- window tails -----------------------------------------------------

    def _drain_tails(self) -> bool:
        """Pump-thread drain pass: dispatch every queued window tail
        (deque.popleft is atomic; _run_tail's claim makes a concurrent
        force-complete a no-op here)."""
        ran = False
        while self._tail_q:
            try:
                rec = self._tail_q.popleft()
            except IndexError:
                break
            self._run_tail(rec)
            ran = True
        return ran

    def _run_tail(self, rec: _TailNode) -> None:
        """Claim-then-run one armed tail: the merged per-root broadcast
        (plus resident-shard assembly) of a closed step. Runs UNLOCKED —
        the broadcast is a blocking collective and must not serialize
        mark_ready's fire queue behind it. Errors are stashed on the
        record (re-raised at the next begin_step/flush), never thrown
        off the pump thread."""
        with self._fire_lock:
            if rec.claimed:
                return
            rec.claimed = True
        t0 = time.perf_counter()
        try:
            rec.result = rec.exec_.finish_tail()
        except BaseException as e:  # commlint: allow(broadexcept)
            # stash-and-signal: the pump thread has no caller to unwind
            # into; begin_step()/flush() re-raise this
            rec.error = e
        tail_ms = (time.perf_counter() - t0) * 1e3
        # The tail overlapped iff the NEXT step's backward was still
        # producing while it ran (step open, bwd-end unmarked).
        overlap_ms = (tail_ms if self._active and self._t_bwd_end is None
                      else 0.0)
        rec.report.tail_ms = tail_ms
        rec.report.tail_overlap_ms = overlap_ms
        SPC.record("sched_tail_overlap_ms", overlap_ms)
        rec.event.set()

    def _complete_tail(self, rec: _TailNode) -> None:
        """Force one tail to completion: run it inline if unclaimed,
        else wait out whoever claimed it (the pump thread, mid-bcast)."""
        self._run_tail(rec)
        rec.event.wait()

    def step(self) -> None:
        """Close the open step WITHOUT draining its broadcast tail —
        the slipstream boundary. Reductions are waited to completion
        (``wait_reduced``), the merged tail stays armed and is queued
        for the pump thread to dispatch under the NEXT step's backward.
        Results come back from :meth:`flush` in step order. Unready
        tiles raise with the step still open (mark the rest and step()
        again); a reduction failure collapses the whole window."""
        if self._window < 2:
            raise RequestError(
                "step() needs a window session (window >= 2) — "
                "single-step sessions use finish()")
        if not self._active:
            raise RequestError("step() before begin_step()")
        self._check_all_fired("step")
        self._t_bwd_end = time.perf_counter()
        try:
            self._drain_fire_q()
            self._exec.wait_reduced()
        except BaseException:  # commlint: allow(broadexcept)
            # cleanup-then-reraise: a mid-window reduction failure
            # (timeout, revoke, lifeboat kill) must not leak armed
            # tails or the pump thread — collapse deterministically
            self.abort_window()
            raise
        t_done = max(pa.t_reduce_done for pa in self._pas)
        t_first = min(pa.t_first_ready for pa in self._pas)
        report = OverlapReport(
            backward_ms=(self._t_bwd_end - self._t0) * 1e3,
            comm_ms=max(0.0, (t_done - t_first) * 1e3),
            exposed_comm_ms=max(0.0, (t_done - self._t_bwd_end) * 1e3),
            tiles=sum(pa.tiles for pa in self._pas),
            buckets=len(self._pas),
        )
        rec = _TailNode(self._exec, self._phase, report)
        self._tails.append(rec)
        self._tail_q.append(rec)
        SPC.record("sched_window_spans_total")
        self._report = report
        self._active = False
        self._phase = (self._phase + 1) % self._window

    def flush(self) -> list:
        """Close the window: auto-close an open step, complete every
        queued tail in step order, stop the pump thread, and return
        ``[(grads, report), ...]`` — one entry per step() since the
        last flush. The session resets to phase 0, ready for the next
        window."""
        if self._window < 2:
            raise RequestError(
                "flush() needs a window session (window >= 2)")
        if self._active:
            self.step()
        try:
            for rec in self._tails:
                self._complete_tail(rec)
                if rec.error is not None:
                    raise rec.error
        except BaseException:  # commlint: allow(broadexcept)
            self.abort_window()
            raise
        self._stop_pump()
        out = []
        for rec in self._tails:
            reduced = [np.asarray(r) for r in rec.result]
            out.append((self._reassemble(reduced), rec.report))
        self._tails = []
        self._tail_q.clear()
        self._phase = 0
        return out

    def mark_ready(self, param, value, slice: Optional[tuple] = None
                   ) -> list:
        """Mark a gradient (or a flat slice of one) materialized.

        ``param`` is a leaf index or a leaf path (jax keystr of the
        template tree); ``value`` is the rank-major ``(size, ...)``
        gradient payload for that leaf (or for ``slice=(lo, hi)``, its
        flat element range). Returns the (bucket, tile) pairs this call
        completed — their Pready bursts dispatch coalesced into one
        batch-dispatch window: inline when the session runs without a
        progress thread, otherwise handed to the pump thread so the
        producer pays only the staging copy."""
        from ..coll.partitioned import _batch_window

        if not self._active:
            raise RequestError("mark_ready() before begin_step()")
        leaf_id = self._resolve(param)
        size = self._comm.size
        host = np.asarray(value).reshape(size, -1)
        lo, hi = (0, host.shape[1]) if slice is None else slice
        leaf_elems = int(
            np.prod(self.plan.leaf_shapes[leaf_id], dtype=np.int64)
        ) if self.plan.leaf_shapes[leaf_id] else 1
        if not 0 <= lo < hi <= max(leaf_elems, 1):
            raise ArgumentError(
                f"mark_ready slice [{lo}, {hi}) outside leaf "
                f"{self.plan.leaf_paths[leaf_id]} ({leaf_elems} elems)"
            )
        if host.shape[1] != hi - lo:
            raise ArgumentError(
                f"mark_ready payload has {host.shape[1]} elems per "
                f"rank, slice [{lo}, {hi}) needs {hi - lo}"
            )
        SPC.record("parallel_overlap_marks")
        # Atomic duplicate/overlap validation (the Pready_burst
        # contract): a mark touching any element already marked ready
        # this step raises BEFORE anything from this call is staged or
        # flagged, so an erroneous overlapping mark can never
        # double-count tile coverage or rewrite a fired tile's slab.
        hits = []
        for piece in self.plan.leaf_pieces.get(leaf_id, ()):
            plo = max(piece.leaf_lo, lo)
            phi = min(piece.leaf_hi, hi)
            if phi <= plo:
                continue
            b = piece.bucket
            blo = piece.bucket_lo + (plo - piece.leaf_lo)
            if self._covmask[b][blo: blo + (phi - plo)].any():
                raise RequestError(
                    f"mark_ready [{lo}, {hi}) of leaf "
                    f"{self.plan.leaf_paths[leaf_id]} overlaps elements "
                    "already marked ready this step"
                )
            hits.append((plo, phi, b, blo))
        completed: list = []
        touched: set = set()
        for plo, phi, b, blo in hits:
            self._covmask[b][blo: blo + (phi - plo)] = True
            self._stage[b][:, blo: blo + (phi - plo)] = (
                host[:, plo - lo: phi - lo]
            )
            pa = self._pas[b]
            t_lo = blo // pa.tile_elems
            t_hi = (blo + (phi - plo) - 1) // pa.tile_elems
            for t in range(t_lo, t_hi + 1):
                tlo, thi = pa.tile_range(t)
                self._covered[b][t] += (
                    min(thi, blo + (phi - plo)) - max(tlo, blo)
                )
                touched.add((b, t))
        # Fire every tile this call completed, as contiguous
        # Pready_range bursts in ONE coalescing window. With the pump
        # thread running the runs are queued instead — the staging slab
        # region of a completed tile is never rewritten, so the deferred
        # dispatch reads exactly what was staged here.
        runs: list = []
        for b in sorted({bt[0] for bt in touched}):
            pa = self._pas[b]
            ready = sorted(
                t for (bb, t) in touched if bb == b
                and not self._fired[b][t]
                and self._covered[b][t] == pa.tile_range(t)[1]
                - pa.tile_range(t)[0]
            )
            for run_lo, run_hi in _runs(ready):
                runs.append((b, run_lo, run_hi))
                for t in range(run_lo, run_hi + 1):
                    self._fired[b][t] = True
                    completed.append((b, t))
        if self._pump_thread is not None:
            self._fire_q.extend(runs)
        elif runs:
            with _batch_window():
                for b, run_lo, run_hi in runs:
                    pa = self._pas[b]
                    llo = pa.tile_range(run_lo)[0]
                    lhi = pa.tile_range(run_hi)[1]
                    pa.ready_range(run_lo, run_hi,
                                   self._stage[b][:, llo:lhi])
        return completed

    def poll(self) -> list:
        """Drive one progress round; return the bucket indices whose
        reduction (combine + bcast) has completed so far. A consumer
        thread can start applying those buckets while later buckets are
        still reducing under the backward pass."""
        if not self._active:
            if all(pa.reduced for pa in self._pas):
                # finish() already drained the step under this poller
                return list(range(len(self._pas)))
            raise RequestError("poll() before begin_step()")
        done = []
        passive = self._pump_thread is not None
        for b, pa in enumerate(self._pas):
            # With the pump thread driving progress, read the flag only:
            # an active sweep here would just contend on the pumper lock.
            if pa.reduced or (not passive and pa.poll()):
                done.append(b)
        return done

    def finish(self) -> tuple:
        """Backward pass over: wait out the tail, reassemble the reduced
        pytree, and report the step's overlap accounting.

        Unready tiles raise WITHOUT tearing anything down — the step
        stays open, so the caller can mark the missing leaves and call
        finish() again (or :meth:`abort_step` to give up). A reduction
        failure (e.g. a bucket's wait timeout) tears the step down.

        On a window session this is close-plus-flush: the open step
        closes, every pending tail drains, and the LAST step's
        ``(grads, report)`` is returned (earlier steps' results are
        discarded — call :meth:`step`/:meth:`flush` to keep them)."""
        if self._window >= 2:
            if not self._active and not self._tails:
                raise RequestError("finish() before begin_step()")
            return self.flush()[-1]
        if not self._active:
            raise RequestError("finish() before begin_step()")
        self._check_all_fired("finish")
        self._t_bwd_end = time.perf_counter()
        try:
            self._drain_fire_q()
            reduced = [np.asarray(r) for r in self._exec.wait_all()]
        except BaseException:  # commlint: allow(broadexcept)
            # cleanup-then-reraise: ANY reduction failure (timeout,
            # revoke, interrupt) must not leak the pump thread or the
            # buckets' progress callbacks
            self.abort_step()
            raise
        self._stop_pump()
        self._active = False
        t_done = max(pa.t_reduce_done for pa in self._pas)
        t_first = min(pa.t_first_ready for pa in self._pas)
        self._report = OverlapReport(
            backward_ms=(self._t_bwd_end - self._t0) * 1e3,
            comm_ms=max(0.0, (t_done - t_first) * 1e3),
            exposed_comm_ms=max(0.0, (t_done - self._t_bwd_end) * 1e3),
            tiles=sum(pa.tiles for pa in self._pas),
            buckets=len(self._pas),
        )
        return self._reassemble(reduced), self._report

    def _check_all_fired(self, verb: str) -> None:
        unfired = [
            (b, t) for b, fired in enumerate(self._fired)
            for t in range(len(fired)) if not fired[t]
        ]
        if unfired:
            raise RequestError(
                f"{verb}() with unready tiles {unfired[:8]} — every "
                "gradient leaf must be mark_ready()'d (the step stays "
                f"open: mark the rest and {verb}() again, or "
                "abort_step())"
            )

    def abort_step(self) -> None:
        """Tear down an open step without completing it: stop the pump
        thread, abort every bucket's partitioned pair (dropping their
        progress callbacks), and close the step so the session is not
        left with a leaked callback or a live thread. In-flight wire
        state is abandoned (DESIGN.md §20); re-arming this session is
        only safe once the fabric has drained. No-op between steps.

        On a window session the window is ONE unit of teardown —
        delegates to :meth:`abort_window`."""
        if self._window >= 2:
            self.abort_window()
            return
        if not self._active:
            return
        self._stop_pump()
        self._exec.abort()
        self._active = False

    def abort_window(self) -> None:
        """Deterministically collapse the whole window: stop the pump
        thread FIRST (so no tail is mid-dispatch), abort every phase's
        executor (armed tails included — their deferred locals are
        abandoned with the rest of the in-flight wire state, DESIGN.md
        §20/§22), drop all queued tails and reset to phase 0. Same-seed
        controllers collapsing at the same step recompile the identical
        window afterwards — this is the lifeboat path. No-op when the
        window is idle."""
        if (not self._active and not self._tails
                and self._pump_thread is None):
            return
        self._stop_pump()
        for ex in self._execs:
            ex.abort()
        self._tails = []
        self._tail_q.clear()
        self._active = False
        self._phase = 0

    def _stop_pump(self) -> None:
        if self._pump_thread is not None:
            self._pump_stop.set()
            self._pump_thread.join()
            self._pump_thread = None
            self._pump_stop = None

    def last_report(self) -> Optional[OverlapReport]:
        return self._report

    # -- helpers ----------------------------------------------------------

    def _resolve(self, param) -> int:
        if isinstance(param, int):
            if not 0 <= param < len(self.plan.leaf_paths):
                raise ArgumentError(f"leaf index {param} out of range")
            return param
        leaf_id = self._name_to_leaf.get(param)
        if leaf_id is None:
            matches = [
                i for i, p in enumerate(self.plan.leaf_paths)
                if str(param) in p
            ]
            if len(matches) != 1:
                raise ArgumentError(
                    f"cannot resolve {param!r} to one gradient leaf "
                    f"(matches: {len(matches)})"
                )
            leaf_id = matches[0]
        return leaf_id

    def _reassemble(self, reduced: list):
        size = self._comm.size
        out_leaves = []
        for i, shape in enumerate(self.plan.leaf_shapes):
            elems = int(np.prod(shape, dtype=np.int64)) if shape else 1
            flat = np.zeros((size, elems), self.plan.leaf_dtypes[i])
            for piece in self.plan.leaf_pieces.get(i, ()):
                flat[:, piece.leaf_lo: piece.leaf_hi] = (
                    reduced[piece.bucket][:, piece.bucket_lo:
                                          piece.bucket_hi]
                )
            out_leaves.append(
                jnp.asarray(flat.reshape(self._template_shapes[i]))
            )
        return jax.tree.unflatten(self.plan.treedef, out_leaves)


def _runs(idx: list) -> list:
    """Collapse a sorted index list into inclusive (lo, hi) runs."""
    runs: list = []
    for t in idx:
        if runs and t == runs[-1][1] + 1:
            runs[-1] = (runs[-1][0], t)
        else:
            runs.append((t, t))
    return runs


# ---------------------------------------------------------------------------
# Traced-side readiness capture (custom-VJP hooks)
# ---------------------------------------------------------------------------

#: Backprop completion order captured at trace time: grad_marker's
#: backward rule appends as each marked boundary's cotangent is formed.
_BWD_ORDER: list = []
_LAST_SCHEDULE: Optional[dict] = None


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def grad_marker(x, name: str = ""):
    """Identity whose BACKWARD rule records ``name`` — placed on a
    layer's input, it fires after every gradient inside that layer has
    been produced, capturing the true backprop tile order for the
    overlap session to replay. Forward value and cotangent pass through
    bit-identical."""
    return x


def _grad_marker_fwd(x, name):
    return x, None


def _grad_marker_bwd(name, _res, g):
    note_backward(name)
    return (g,)


grad_marker.defvjp(_grad_marker_fwd, _grad_marker_bwd)


def note_backward(name: str) -> None:
    """Record one backprop completion boundary (trace-time)."""
    _BWD_ORDER.append(name)


def backward_order() -> tuple:
    return tuple(_BWD_ORDER)


def reset_capture() -> None:
    del _BWD_ORDER[:]
    global _LAST_SCHEDULE
    _LAST_SCHEDULE = None


def capture_ready_schedule(tree: Any) -> Any:
    """Trace-time capture of the gradient readiness schedule at the
    sync seam: records the leaf paths about to be reduced together with
    the backprop order the grad markers observed, then returns ``tree``
    unchanged. Host overlap sessions (and the bench) read
    :func:`last_schedule` to replay production in backward order — this
    is the mark_ready/Pready evidence the ``overlapready`` lint rule
    looks for at blocking-reduction call sites."""
    global _LAST_SCHEDULE
    paths_leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    _LAST_SCHEDULE = {
        "leaf_paths": tuple(
            jax.tree_util.keystr(p) for p, _ in paths_leaves
        ),
        "bwd_order": tuple(_BWD_ORDER),
    }
    return tree


def last_schedule() -> Optional[dict]:
    return _LAST_SCHEDULE


# ---------------------------------------------------------------------------
# Readiness order from jax's own program ordering (effects/donation)
# ---------------------------------------------------------------------------

def jaxpr_backward_order(grad_fn, *args) -> tuple:
    """Gradient-leaf production order read off jax's OWN program order:
    trace ``grad_fn`` (a function returning the gradient pytree) to a
    jaxpr and rank each output leaf by the index of the equation that
    produces it. ``eval_jaxpr`` executes equations in exactly this
    order — it is the schedule jax's donation/effects machinery
    sequences against — so leaf i ranking before leaf j means leaf i's
    gradient materializes first in the compiled backward.

    Returns leaf indices (into the flattened gradient pytree) in
    production order. Requires
    :func:`ompi_tpu.core.jax_compat.jaxpr_ordering_available`.
    """
    closed = jax.make_jaxpr(grad_fn)(*args)
    jaxpr = closed.jaxpr
    pos: dict = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.outvars:
            pos[v] = i
    ranks = []
    for leaf_idx, v in enumerate(jaxpr.outvars):
        # constants / passed-through inputs rank first (produced
        # before any equation runs); Literal outputs have no var
        ranks.append((pos.get(v, -1), leaf_idx))
    return tuple(i for _, i in sorted(ranks))


def readiness_order(grad_fn=None, args: tuple = ()) -> tuple:
    """The overlap session's readiness source: ``("jaxpr", order)``
    from jax's real program ordering when the installed jax exposes it
    (jax_compat-gated), else ``("marker", backward_order())`` — the
    custom-VJP :func:`grad_marker` capture. Both name the same thing:
    the sequence gradients materialize in during the backward pass."""
    from ..core import jax_compat

    if grad_fn is not None and jax_compat.jaxpr_ordering_available():
        try:
            return ("jaxpr", jaxpr_backward_order(grad_fn, *args))
        except Exception:  # commlint: allow(broadexcept)
            pass  # fall back to the marker capture
    return ("marker", backward_order())

"""Expert parallelism: capacity-based MoE dispatch over all_to_all.

SURVEY §2.6 EP row — the reference's alltoallv (vector alltoall,
coll_base_functions.h:75-76) is the MoE dispatch primitive. TPU-native
form: static-shape capacity-based dispatch (XLA needs static shapes, so
ragged alltoallv becomes fixed-capacity buckets with overflow drop — the
standard Switch/Mixtral formulation) over `lax.all_to_all`.

Experts are sharded over `axis_name`: each of the n ranks owns
E_local = E_total / n experts. Top-1 routing; gating weight applied on
combine. Dropped (over-capacity) tokens pass through with zero expert
contribution (residual connections keep them alive).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..coll import spmd


def moe_dispatch_combine(
    x: jax.Array,  # (T, D) local tokens
    router_logits: jax.Array,  # (T, E_total)
    expert_fn: Callable[[int, jax.Array], jax.Array],  # (local_e, (N,D))->(N,D)
    n_local_experts: int,
    axis_name: str = "ep",
    capacity_factor: float = 1.25,
) -> jax.Array:
    """Route each token to its top-1 expert (owned by expert_rank =
    expert // n_local), run the expert, and return combined (T, D).
    """
    n = lax.axis_size(axis_name)
    T, D = x.shape
    e_total = router_logits.shape[-1]
    assert e_total == n * n_local_experts

    probs = jax.nn.softmax(router_logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
    dest = expert // n_local_experts  # owning rank per token
    local_e = expert % n_local_experts

    cap = max(1, int(capacity_factor * T / n))

    # Position of each token within its destination bucket.
    dest_onehot = jax.nn.one_hot(dest, n, dtype=jnp.int32)  # (T, n)
    pos = jnp.cumsum(dest_onehot, axis=0) - 1  # (T, n)
    my_pos = jnp.take_along_axis(pos, dest[:, None], axis=-1)[:, 0]  # (T,)
    keep = my_pos < cap

    # Dispatch buffers: tokens + metadata (local expert id, validity).
    send = jnp.zeros((n, cap, D), x.dtype)
    send = send.at[dest, my_pos].add(jnp.where(keep[:, None], x, 0))
    meta_e = jnp.zeros((n, cap), jnp.int32)
    meta_e = meta_e.at[dest, my_pos].add(jnp.where(keep, local_e + 1, 0))
    # meta_e == 0 marks an empty slot; expert id is meta_e - 1.

    recv = spmd.alltoall_native(send, axis_name)  # (n, cap, D)
    recv_e = spmd.alltoall_native(meta_e[..., None], axis_name)[..., 0]

    flat = recv.reshape(n * cap, D)
    flat_e = recv_e.reshape(n * cap)
    out = jnp.zeros_like(flat)
    for e in range(n_local_experts):
        mask = (flat_e == e + 1)[:, None]
        out = out + jnp.where(mask, expert_fn(e, flat), 0)

    # Return the processed tokens to their source ranks and positions.
    back = spmd.alltoall_native(out.reshape(n, cap, D), axis_name)
    gathered = back[dest, my_pos]  # (T, D)
    return jnp.where(keep[:, None], gathered * gate[:, None], 0.0)


def aux_load_balance_loss(
    router_logits: jax.Array, axis_name: str = "ep", n_local_experts: int = 1
) -> jax.Array:
    """Switch-style load-balancing auxiliary loss over the global expert
    set (fraction-routed × mean-prob, allreduced across ep ranks)."""
    from ..ops import SUM

    probs = jax.nn.softmax(router_logits, axis=-1)  # (T, E)
    top = jnp.argmax(probs, axis=-1)
    e_total = probs.shape[-1]
    frac = jnp.mean(jax.nn.one_hot(top, e_total), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    n = lax.axis_size(axis_name)
    frac = spmd.allreduce_native(frac, axis_name, SUM) / n
    mean_prob = spmd.allreduce_native(mean_prob, axis_name, SUM) / n
    return e_total * jnp.sum(frac * mean_prob)

"""Findings, reports, and the ratchet baseline.

The baseline model follows the "ratchet" discipline: a checked-in JSON
file records per-(rule, file) finding counts; a lint run FAILS only on
counts above the baseline (new debt) and the baseline is re-written when
debt is paid down. Keys are (rule, repo-relative path) rather than line
numbers so unrelated edits don't churn the file.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import os
from collections import Counter
from typing import Iterable, Optional


class Severity(enum.IntEnum):
    """Ordered so max() over findings yields the report severity."""

    NOTE = 0
    WARNING = 1
    ERROR = 2


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint/sanitizer finding, anchored to a source location."""

    rule: str  # rule component name, e.g. "reqlife"
    severity: Severity
    path: str  # repo-relative when possible
    line: int
    message: str

    @property
    def key(self) -> str:
        """Baseline bucket: counts are ratcheted per (rule, file)."""
        return f"{self.rule}:{self.path}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.severity.name.lower()} "
            f"[{self.rule}] {self.message}"
        )

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity.name.lower(),
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


class Report:
    """An ordered collection of findings with baseline comparison."""

    def __init__(self, findings: Iterable[Finding] = ()) -> None:
        self.findings: list[Finding] = sorted(
            findings, key=lambda f: (f.path, f.line, f.rule)
        )

    def __len__(self) -> int:
        return len(self.findings)

    def __iter__(self):
        return iter(self.findings)

    def by_rule(self, rule: str) -> list[Finding]:
        return [f for f in self.findings if f.rule == rule]

    def counts(self) -> dict[str, int]:
        return dict(Counter(f.key for f in self.findings))

    def max_severity(self) -> Severity:
        return max(
            (f.severity for f in self.findings), default=Severity.NOTE
        )

    def render(self) -> str:
        lines = [f.render() for f in self.findings]
        lines.append(
            f"commlint: {len(self.findings)} finding(s)"
            if self.findings else "commlint: clean"
        )
        return "\n".join(lines)

    def to_dict(self) -> dict:
        return {
            "findings": [f.to_dict() for f in self.findings],
            "counts": self.counts(),
        }


class Baseline:
    """The checked-in ratchet: per-(rule, file) allowed finding counts."""

    VERSION = 1

    def __init__(self, counts: Optional[dict[str, int]] = None) -> None:
        self.counts: dict[str, int] = dict(counts or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        return cls(data.get("counts", {}))

    def save(self, path: str) -> None:
        payload = {
            "version": self.VERSION,
            "comment": (
                "commlint ratchet: counts may only decrease. Regenerate "
                "with python -m ompi_tpu.tools.lint ompi_tpu "
                "--write-baseline after paying down debt."
            ),
            "counts": dict(sorted(self.counts.items())),
        }
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=False)
            f.write("\n")
        os.replace(tmp, path)

    @classmethod
    def from_report(cls, report: Report) -> "Baseline":
        return cls(report.counts())

    def regressions(self, report: Report) -> list[str]:
        """Human-readable regressions: buckets whose current count
        exceeds the baseline (new keys count against a baseline of 0)."""
        out = []
        for key, n in sorted(report.counts().items()):
            allowed = self.counts.get(key, 0)
            if n > allowed:
                out.append(
                    f"{key}: {n} finding(s), baseline allows {allowed}"
                )
        return out

    def improvements(self, report: Report) -> list[str]:
        """Buckets where debt was paid down (baseline can be tightened)."""
        current = report.counts()
        out = []
        for key, allowed in sorted(self.counts.items()):
            n = current.get(key, 0)
            if n < allowed:
                out.append(f"{key}: {n} finding(s), baseline allows {allowed}")
        return out

"""ProjectIndex — the whole-program symbol layer under commlint.

Per-file rules only ever needed a parsed AST; the concurrency rules
(analysis/locksmith.py) need *resolution*: which function does
``self._pump`` name, which class owns the ``self._mu`` being held,
which ``threading.Thread(target=...)`` ends up running a given method.
This module parses every source exactly once into a ``FileContext``
(shared with the linter — rules see the same cached tree) and builds:

- a **module table** (dotted module name -> file) honoring the package
  layout and relative imports;
- a **symbol table**: every class (with bases, methods, and best-effort
  ``self.x = ClassName(...)`` attribute types) and every function,
  keyed ``module.Class.method`` / ``module.func``;
- a **call graph** resolver: ``self.m()``, ``mod.f()``, bare ``f()``,
  ``self.attr.m()`` (through the inferred attribute type), and
  imported names;
- a **lock inventory**: every ``threading.Lock/RLock/Condition`` bound
  to a module global or a ``self.`` attribute, with its creation site.
  A ``Condition(self._mu)`` wrapping an inventoried lock aliases to the
  underlying lock's key — acquiring the condition IS acquiring the
  lock;
- a **thread inventory**: every ``threading.Thread(target=...)`` spawn
  site with the resolved target function.

Everything is best-effort static resolution: an unresolvable name
simply contributes nothing (the analyses built on top are linters, not
verifiers). The index is deliberately cheap — one AST walk per file —
so ``Linter.lint_paths`` can build it on every run and hand the cached
``FileContext``s to all rules (the parse-once engine).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Optional

_ALLOW_RE = re.compile(r"#\s*commlint:\s*allow\(\s*([\w\-, ]+?)\s*\)")

#: threading factory callables that mint a lock-like object.
_LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition", "Semaphore",
                             "BoundedSemaphore"})


class FileContext:
    """One parsed source file, shared by every rule.

    Attributes
    ----------
    path:     the path as given to the linter (for error messages)
    relpath:  path relative to the lint root, '/'-normalised — this is
              what appears in findings and baseline keys, so baselines
              are stable across checkouts.
    tree:     the parsed ``ast`` module
    lines:    source split into lines (1-indexed via ``lines[i-1]``)
    index:    the owning ProjectIndex (None for bare snippets)

    The context also memoizes the traversals every rule used to redo
    from scratch — ``walk()``, ``parents()`` — so a 20-rule run pays
    for each exactly once per file.
    """

    def __init__(self, path: str, source: str, relpath: str | None = None):
        self.path = path
        self.relpath = (relpath or path).replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.index: Optional["ProjectIndex"] = None
        self._walk: Optional[list[ast.AST]] = None
        self._parents: Optional[dict[ast.AST, ast.AST]] = None
        self._allow: dict[int, frozenset[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _ALLOW_RE.search(line)
            if m:
                names = frozenset(
                    p.strip() for p in m.group(1).split(",") if p.strip()
                )
                self._allow[i] = names

    def suppressed(self, line: int, rule: str) -> bool:
        """True if ``# commlint: allow(rule)`` covers ``line``
        (same line or the line immediately above)."""
        for ln in (line, line - 1):
            names = self._allow.get(ln)
            if names and (rule in names or "all" in names):
                return True
        return False

    # -- cached traversals (the parse-once engine) ---------------------

    def walk(self) -> list[ast.AST]:
        """``ast.walk(self.tree)`` computed once and reused by every
        rule (the single hottest redundancy in the old per-rule walks)."""
        if self._walk is None:
            self._walk = list(ast.walk(self.tree))
        return self._walk

    def parents(self) -> dict[ast.AST, ast.AST]:
        """child -> parent over the whole tree, computed once."""
        if self._parents is None:
            p: dict[ast.AST, ast.AST] = {}
            for node in self.walk():
                for child in ast.iter_child_nodes(node):
                    p[child] = node
            self._parents = p
        return self._parents


# -- symbol table records ---------------------------------------------------


@dataclass
class FuncInfo:
    """One function or method."""

    key: str                      # "module.Class.method" / "module.func"
    module: str
    relpath: str
    node: ast.AST                 # FunctionDef / AsyncFunctionDef
    cls: Optional["ClassInfo"] = None
    summary: object = None        # locksmith attaches its Summary here

    @property
    def name(self) -> str:
        return self.node.name

    @property
    def line(self) -> int:
        return self.node.lineno

    @property
    def params(self) -> list[str]:
        a = self.node.args
        names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names


@dataclass
class ClassInfo:
    """One class: methods, base names, inferred attribute types, locks."""

    key: str                      # "module.Class"
    module: str
    relpath: str
    node: ast.ClassDef
    bases: list[str] = field(default_factory=list)   # unresolved names
    methods: dict[str, FuncInfo] = field(default_factory=dict)
    attr_types: dict[str, str] = field(default_factory=dict)  # x -> class key
    lock_attrs: dict[str, "LockInfo"] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.node.name


@dataclass
class LockInfo:
    """One inventoried lock (or lock-aliasing Condition)."""

    key: str                      # "module.Class._mu" / "module._mu"
    kind: str                     # Lock / RLock / Condition / ...
    relpath: str
    line: int
    owner: Optional[str] = None   # owning class key, None for module-level
    alias_of: Optional[str] = None  # Condition(self._mu) -> underlying key

    def resolved_key(self) -> str:
        return self.alias_of or self.key


@dataclass
class ThreadSpawn:
    """One ``threading.Thread(target=...)`` site."""

    relpath: str
    line: int
    target: Optional[str]         # resolved FuncInfo key, or None
    target_text: str              # source text of the target expression
    in_func: Optional[str]        # key of the spawning function


class ProjectIndex:
    """Symbol table + call graph + lock/thread inventory for a file set."""

    def __init__(self, base: Optional[str] = None) -> None:
        self.base = base
        self.files: dict[str, FileContext] = {}       # relpath -> ctx
        self.modules: dict[str, str] = {}             # module -> relpath
        self.classes: dict[str, ClassInfo] = {}       # key -> info
        self.functions: dict[str, FuncInfo] = {}      # key -> info
        self.locks: dict[str, LockInfo] = {}          # key -> info
        self.threads: list[ThreadSpawn] = []
        self.errors: list[str] = []
        # per-module import map: alias -> dotted target ("threading",
        # "ompi_tpu.core.config", "ompi_tpu.analysis.report.Finding")
        self.imports: dict[str, dict[str, str]] = {}
        self._package = False
        self._locksmith = None    # cached locksmith.Analysis

    # -- construction --------------------------------------------------

    @classmethod
    def build(cls, root: str,
              paths: Optional[Iterable[str]] = None) -> "ProjectIndex":
        """Index every .py under ``root`` (or just ``paths``)."""
        idx = cls(base=os.path.abspath(root))
        if paths is None:
            paths = sorted(
                os.path.join(dp, f)
                for dp, dns, fns in os.walk(root)
                for f in fns if f.endswith(".py")
                if "__pycache__" not in dp
            )
        for path in paths:
            idx.add_file(path)
        idx.link()
        return idx

    @classmethod
    def from_contexts(cls, contexts: Iterable[FileContext],
                      base: Optional[str] = None) -> "ProjectIndex":
        idx = cls(base=base)
        for ctx in contexts:
            idx.add_context(ctx)
        idx.link()
        return idx

    @classmethod
    def from_sources(cls, sources: dict[str, str],
                     base: Optional[str] = None) -> "ProjectIndex":
        """Test/tool entry: {relpath: source} parsed in-memory."""
        idx = cls(base=base)
        for relpath, src in sorted(sources.items()):
            try:
                idx.add_context(FileContext(relpath, src, relpath=relpath))
            except SyntaxError as exc:
                idx.errors.append(f"{relpath}: syntax error: {exc}")
        idx.link()
        return idx

    def add_file(self, path: str) -> Optional[FileContext]:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError as exc:
            self.errors.append(f"{path}: {exc}")
            return None
        relpath = self._relpath(path)
        try:
            ctx = FileContext(path, source, relpath=relpath)
        except SyntaxError as exc:
            self.errors.append(f"{path}: syntax error: {exc}")
            return None
        return self.add_context(ctx)

    def _relpath(self, path: str) -> str:
        ap = os.path.abspath(path)
        base = self.base
        if base and (ap == base or ap.startswith(base + os.sep)):
            return os.path.relpath(ap, base).replace(os.sep, "/")
        return path.replace(os.sep, "/")

    def add_context(self, ctx: FileContext) -> FileContext:
        ctx.index = self
        self.files[ctx.relpath] = ctx
        module = self.module_name(ctx.relpath)
        self.modules[module] = ctx.relpath
        self._index_module(module, ctx)
        return ctx

    def module_name(self, relpath: str) -> str:
        """Dotted module for a relpath. When the index base is itself a
        package directory (has __init__.py), names are rooted at the
        package so absolute imports resolve."""
        parts = relpath[:-3].split("/") if relpath.endswith(".py") \
            else relpath.split("/")
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        if self.base and os.path.exists(
                os.path.join(self.base, "__init__.py")):
            self._package = True
            parts = [os.path.basename(self.base)] + parts
        return ".".join(p for p in parts if p) or "__main__"

    # -- per-module indexing -------------------------------------------

    def _index_module(self, module: str, ctx: FileContext) -> None:
        imp = self.imports.setdefault(module, {})
        for node in ctx.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    imp[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                target = self._resolve_from(module, node)
                for a in node.names:
                    if a.name == "*":
                        continue
                    imp[a.asname or a.name] = (
                        f"{target}.{a.name}" if target else a.name
                    )
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{module}.{node.name}"
                fi = FuncInfo(
                    key=key, module=module, relpath=ctx.relpath, node=node
                )
                self.functions[key] = fi
                self._index_nested(fi)
            elif isinstance(node, ast.ClassDef):
                self._index_class(module, ctx, node)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                kind = self._lock_factory(module, node.value)
                if kind:
                    key = f"{module}.{node.targets[0].id}"
                    self.locks[key] = LockInfo(
                        key=key, kind=kind, relpath=ctx.relpath,
                        line=node.lineno,
                    )

    def _resolve_from(self, module: str,
                      node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module
        # relative import: walk up from the module's package
        pkg = module.split(".")[:-1]
        up = node.level - 1
        if up > len(pkg):
            return node.module
        head = pkg[: len(pkg) - up]
        return ".".join(head + ([node.module] if node.module else [])) \
            or None

    def _index_class(self, module: str, ctx: FileContext,
                     node: ast.ClassDef) -> None:
        key = f"{module}.{node.name}"
        info = ClassInfo(
            key=key, module=module, relpath=ctx.relpath, node=node,
            bases=[self._base_name(b) for b in node.bases],
        )
        self.classes[key] = info
        for item in node.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            fkey = f"{key}.{item.name}"
            fi = FuncInfo(key=fkey, module=module, relpath=ctx.relpath,
                          node=item, cls=info)
            info.methods[item.name] = fi
            self.functions[fkey] = fi
            self._index_nested(fi)
            self._index_self_assigns(module, ctx, info, item)

    def _index_nested(self, parent: FuncInfo) -> None:
        """Register nested defs under ``parent.<locals>.name`` — pump
        workers and sentinel loops are closures, and their lock
        activity (and Thread targets) must be in the table. ``cls`` is
        inherited: a closure's ``self`` is the enclosing method's.
        Defs anywhere in the parent's statement tree count, but not
        defs inside deeper nested defs (the recursion owns those)."""

        def scan(node: ast.AST) -> None:
            for item in ast.iter_child_nodes(node):
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    key = f"{parent.key}.<locals>.{item.name}"
                    fi = FuncInfo(key=key, module=parent.module,
                                  relpath=parent.relpath, node=item,
                                  cls=parent.cls)
                    self.functions.setdefault(key, fi)
                    self._index_nested(fi)
                elif not isinstance(item, (ast.ClassDef, ast.Lambda)):
                    scan(item)

        scan(parent.node)

    @staticmethod
    def _base_name(node: ast.AST) -> str:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return ""

    def _index_self_assigns(self, module: str, ctx: FileContext,
                            cls: ClassInfo, fn: ast.AST) -> None:
        """``self.x = threading.Lock()`` -> lock inventory;
        ``self.x = ClassName(...)`` -> attribute type inference."""
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            if not (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                continue
            kind = self._lock_factory(module, node.value)
            if kind:
                key = f"{cls.key}.{tgt.attr}"
                alias = None
                if kind in ("Condition", "Semaphore") \
                        and isinstance(node.value, ast.Call) \
                        and node.value.args:
                    alias = self._self_lock_text(node.value.args[0], cls)
                li = LockInfo(key=key, kind=kind, relpath=ctx.relpath,
                              line=node.lineno, owner=cls.key,
                              alias_of=alias)
                self.locks[key] = li
                cls.lock_attrs[tgt.attr] = li
            elif isinstance(node.value, ast.Call):
                ctor = self._callee_key_from_expr(module, node.value.func,
                                                  cls=None)
                if ctor:
                    # may be cross-module / not yet parsed; link()
                    # resolves against the final class table and drops
                    # anything that isn't a known class
                    cls.attr_types.setdefault(tgt.attr, ctor)

    @staticmethod
    def _self_lock_text(node: ast.AST, cls: ClassInfo) -> Optional[str]:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return f"{cls.key}.{node.attr}"
        return None

    def _lock_factory(self, module: str,
                      value: ast.AST) -> Optional[str]:
        """'Lock'/'RLock'/'Condition'/... when ``value`` constructs a
        threading lock object, else None."""
        if not isinstance(value, ast.Call):
            return None
        fn = value.func
        if isinstance(fn, ast.Attribute) and fn.attr in _LOCK_FACTORIES \
                and isinstance(fn.value, ast.Name):
            mod = self.imports.get(module, {}).get(fn.value.id)
            if mod == "threading" or fn.value.id == "threading":
                return fn.attr
        if isinstance(fn, ast.Name) and fn.id in _LOCK_FACTORIES:
            target = self.imports.get(module, {}).get(fn.id, "")
            if target == f"threading.{fn.id}":
                return fn.id
        return None

    # -- linking (cross-module fixups after all files are parsed) ------

    def link(self) -> None:
        """Resolve attr types / condition aliases to final class keys and
        inventory thread spawns (needs the full function table)."""
        for cls in self.classes.values():
            for attr, ctor in list(cls.attr_types.items()):
                resolved = self._resolve_class_key(cls.module, ctor)
                if resolved:
                    cls.attr_types[attr] = resolved
                else:
                    del cls.attr_types[attr]
        for lock in self.locks.values():
            if lock.alias_of and lock.alias_of not in self.locks:
                lock.alias_of = None
        self._inventory_threads()

    def _resolve_class_key(self, module: str, name: str) -> Optional[str]:
        if name in self.classes:
            return name
        tail = name.split(".")[-1]
        local = f"{module}.{tail}"
        if local in self.classes:
            return local
        imp = self.imports.get(module, {})
        target = imp.get(name) or imp.get(tail)
        if target and target in self.classes:
            return target
        return None

    @staticmethod
    def _own_nodes(root: ast.AST) -> Iterable[ast.AST]:
        """Walk a function's own statements, not nested defs' (those
        are separate FuncInfos and would double-count)."""
        stack = [root]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.Lambda)):
                    stack.append(child)

    def _inventory_threads(self) -> None:
        self.threads = []
        for fi in list(self.functions.values()):
            for node in self._own_nodes(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                name = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None)
                if name != "Thread":
                    continue
                target = next(
                    (k.value for k in node.keywords if k.arg == "target"),
                    None,
                )
                if target is None:
                    continue
                key = self._resolve_ref(fi, target)
                self.threads.append(ThreadSpawn(
                    relpath=fi.relpath, line=node.lineno, target=key,
                    target_text=ast.unparse(target), in_func=fi.key,
                ))

    # -- resolution ----------------------------------------------------

    def _callee_key_from_expr(self, module: str, fn: ast.AST,
                              cls: Optional[ClassInfo]) -> Optional[str]:
        """Dotted best-effort name for a callee expression (may not be a
        known symbol yet; callers re-resolve against the tables)."""
        if isinstance(fn, ast.Name):
            imp = self.imports.get(module, {}).get(fn.id)
            return imp or f"{module}.{fn.id}"
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            base = fn.value.id
            if base == "self":
                return None  # handled by resolve_call with cls
            imp = self.imports.get(module, {}).get(base)
            return f"{imp or base}.{fn.attr}"
        return None

    def method_on(self, cls_key: str, name: str,
                  _seen: Optional[set] = None) -> Optional[FuncInfo]:
        """Method lookup walking the (name-resolved) base chain."""
        seen = _seen or set()
        if cls_key in seen:
            return None
        seen.add(cls_key)
        cls = self.classes.get(cls_key)
        if cls is None:
            return None
        if name in cls.methods:
            return cls.methods[name]
        for base in cls.bases:
            bkey = self._resolve_class_key(cls.module, base)
            if bkey:
                m = self.method_on(bkey, name, seen)
                if m is not None:
                    return m
        return None

    def _resolve_ref(self, fi: FuncInfo,
                     ref: ast.AST) -> Optional[str]:
        """Resolve a *reference* (not a call): Thread targets,
        callbacks passed by name."""
        if isinstance(ref, ast.Attribute) \
                and isinstance(ref.value, ast.Name) \
                and ref.value.id == "self" and fi.cls is not None:
            m = self.method_on(fi.cls.key, ref.attr)
            return m.key if m else None
        if isinstance(ref, ast.Name):
            # local (nested) function in the same source scope?
            local = f"{fi.key}.<locals>.{ref.id}"
            for key in (local, f"{fi.module}.{ref.id}"):
                if key in self.functions:
                    return key
            imp = self.imports.get(fi.module, {}).get(ref.id)
            if imp and imp in self.functions:
                return imp
            # nested defs aren't in the function table; fall back to a
            # scan of the enclosing function body
            for node in ast.walk(fi.node):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node.name == ref.id and node is not fi.node:
                    return f"{fi.key}.<locals>.{ref.id}"
        return None

    def resolve_call(self, fi: FuncInfo,
                     call: ast.Call) -> Optional[FuncInfo]:
        """The FuncInfo a call lands in, or None when unresolvable."""
        fn = call.func
        module = fi.module
        if isinstance(fn, ast.Name):
            for key in (f"{module}.{fn.id}",):
                if key in self.functions:
                    return self.functions[key]
            imp = self.imports.get(module, {}).get(fn.id)
            if imp:
                if imp in self.functions:
                    return self.functions[imp]
                if imp in self.classes:
                    return self.method_on(imp, "__init__")
            if f"{module}.{fn.id}" in self.classes:
                return self.method_on(f"{module}.{fn.id}", "__init__")
            return None
        if not isinstance(fn, ast.Attribute):
            return None
        base = fn.value
        if isinstance(base, ast.Name):
            if base.id == "self" and fi.cls is not None:
                return self.method_on(fi.cls.key, fn.attr)
            imp = self.imports.get(module, {}).get(base.id)
            if imp:
                if f"{imp}.{fn.attr}" in self.functions:
                    return self.functions[f"{imp}.{fn.attr}"]
                if imp in self.classes:  # ClassName.method(...)
                    return self.method_on(imp, fn.attr)
            if f"{module}.{base.id}" in self.classes:
                return self.method_on(f"{module}.{base.id}", fn.attr)
            return None
        # self.attr.m() through the inferred attribute type
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self" and fi.cls is not None:
            tkey = fi.cls.attr_types.get(base.attr)
            if tkey:
                return self.method_on(tkey, fn.attr)
        return None

    # -- lock expression resolution ------------------------------------

    def resolve_lock(self, fi: FuncInfo,
                     expr: ast.AST) -> Optional[LockInfo]:
        """The inventoried lock an expression names, or None."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and fi.cls is not None:
            li = self._class_lock(fi.cls.key, expr.attr)
            if li is not None:
                return li
        if isinstance(expr, ast.Name):
            key = f"{fi.module}.{expr.id}"
            if key in self.locks:
                return self.locks[key]
            imp = self.imports.get(fi.module, {}).get(expr.id)
            if imp and imp in self.locks:
                return self.locks[imp]
        # obj.attr where obj's type is inferred
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Attribute) \
                and isinstance(expr.value.value, ast.Name) \
                and expr.value.value.id == "self" and fi.cls is not None:
            tkey = fi.cls.attr_types.get(expr.value.attr)
            if tkey:
                return self._class_lock(tkey, expr.attr)
        return None

    def _class_lock(self, cls_key: str, attr: str,
                    _seen: Optional[set] = None) -> Optional[LockInfo]:
        seen = _seen or set()
        if cls_key in seen:
            return None
        seen.add(cls_key)
        cls = self.classes.get(cls_key)
        if cls is None:
            return None
        if attr in cls.lock_attrs:
            return cls.lock_attrs[attr]
        for base in cls.bases:
            bkey = self._resolve_class_key(cls.module, base)
            if bkey:
                li = self._class_lock(bkey, attr, seen)
                if li is not None:
                    return li
        return None

    # -- consumers ------------------------------------------------------

    def contexts(self) -> list[FileContext]:
        return [self.files[k] for k in sorted(self.files)]

    def locksmith(self):
        """The (cached) whole-program concurrency analysis."""
        if self._locksmith is None:
            from . import locksmith as _locksmith

            self._locksmith = _locksmith.analyze(self)
        return self._locksmith

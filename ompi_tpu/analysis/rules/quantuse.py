"""Quantized-wire eligibility rule (the EQuARX gate, statically).

``quantuse``: coll/tuned refuses the quantized tier at dispatch time
for integer dtypes, order-statistic / non-psum ops, and payloads under
``coll_quant_min_bytes`` (coll/quant.supports + the tuned decision
layer). Violations of those gates in user code are either silent
no-ops (the exact tier is silently substituted) or — when the quant
entry points are called directly — numerically wrong. This rule
mirrors the runtime gate so the misuse surfaces at lint time.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ...core import config
from ..report import Severity
from . import (
    COMMLINT,
    INT_DTYPES,
    LintRule,
    call_arg,
    call_name,
    const_str,
    dtype_name,
    infer_buffers,
    itemsize_of,
    scopes,
    scope_walk,
)

#: Direct quantized-wire entry points (payload is the first argument).
_QUANT_FNS = frozenset({
    "allreduce_quant_ring", "allreduce_block_quant", "quant_roundtrip",
    "allreduce_error_feedback",
})

#: Ops the quant tier refuses: order statistics (any representable-value
#: change alters the result) and every non-psum accumulation.
_REFUSED_OPS = frozenset({
    "max", "min", "maxloc", "minloc", "land", "lor", "lxor", "band",
    "bor", "bxor", "prod",
})

_OP_POS = {
    "allreduce_quant_ring": 2,
    "allreduce_block_quant": 2,
    "allreduce_error_feedback": 3,
}
#: Payload argument position (allreduce_error_feedback takes comm first).
_PAYLOAD_POS = {"allreduce_error_feedback": 1}


def _min_bytes() -> int:
    return int(config.get("coll_quant_min_bytes", 64 << 10) or 64 << 10)


@COMMLINT.register
class QuantMisuseRule(LintRule):
    NAME = "quantuse"
    PRIORITY = 70
    DESCRIPTION = ("quantized-wire calls must satisfy the tuned "
                   "dtype/op/size gates")
    SEVERITY = Severity.ERROR

    def check(self, ctx) -> Iterable:
        min_bytes = _min_bytes()
        for scope, _is_mod in scopes(ctx.tree):
            env = infer_buffers(scope)
            for node in scope_walk(scope):
                fn = call_name(node)
                if fn in _QUANT_FNS:
                    yield from self._check_direct(
                        ctx, node, fn, env, min_bytes
                    )
                elif fn == "decide_allreduce":
                    yield from self._check_decide(ctx, node)

    def _check_direct(self, ctx, node: ast.Call, fn: str, env: dict,
                      min_bytes: int) -> Iterable:
        if ctx.suppressed(node.lineno, self.NAME):
            return
        pos = _PAYLOAD_POS.get(fn, 0)
        payload = node.args[pos] if len(node.args) > pos else None
        info = env.get(payload.id) if isinstance(payload, ast.Name) \
            else None
        dt = (info or {}).get("dtype")
        if dt in INT_DTYPES:
            yield self.finding(
                ctx, node,
                f"{fn}() on an integer payload ({dt}) — the quantized "
                "wire is float-only; tuned's runtime gate would refuse "
                "this (coll/quant.supports)",
            )
        op = const_str(call_arg(node, _OP_POS.get(fn, 2), "op"))
        if op is not None and op.lower() in _REFUSED_OPS:
            yield self.finding(
                ctx, node,
                f"{fn}() with op={op!r} — order-statistic/non-psum "
                "ops must stay exact (quantization changes "
                "representable values)",
            )
        elems = (info or {}).get("elems")
        if elems is not None and dt is not None:
            nbytes = elems * itemsize_of(dt)
            if nbytes < min_bytes:
                yield self.finding(
                    ctx, node,
                    f"{fn}() on a {nbytes}-byte payload, below "
                    f"coll_quant_min_bytes ({min_bytes}) — small "
                    "messages are dispatch-bound; quant only trades "
                    "FLOPs for wire bytes",
                    severity=Severity.WARNING,
                )

    def _check_decide(self, ctx, node: ast.Call) -> Iterable:
        allow = call_arg(node, 99, "allow_quant")
        if not (isinstance(allow, ast.Constant) and allow.value is True):
            return
        if ctx.suppressed(node.lineno, self.NAME):
            return
        dt = dtype_name(call_arg(node, 99, "dtype"))
        if dt in INT_DTYPES:
            yield self.finding(
                ctx, node,
                f"decide_allreduce(allow_quant=True) with dtype={dt} — "
                "integer payloads never take the quantized wire; the "
                "override is a silent no-op",
            )
        op = const_str(call_arg(node, 99, "op"))
        if op is not None and op.lower() in _REFUSED_OPS:
            yield self.finding(
                ctx, node,
                f"decide_allreduce(allow_quant=True) with op={op!r} — "
                "non-psum ops are always exact; the override is a "
                "silent no-op",
            )

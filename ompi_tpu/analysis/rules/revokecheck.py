"""Revoke-check rule.

``revokecheck``: ULFM's hang-prevention contract (ft/lifeboat) only
holds if every retry/progress loop that keeps consuming a communicator
re-checks revocation between attempts. A ``while True:`` retry loop
that catches a failure and ``continue``s without consulting the epoch
fence spins forever against a poisoned communicator — exactly the
dead-peer hang the revocation machinery exists to break (the tuned
dispatch loop calls ``lifeboat.check(comm)`` at the top of every
iteration for this reason). The rule flags comm-consuming retry loops
under ``coll/`` and ``pml/`` that show no epoch/revocation evidence in
the loop body.

Loop shape that is flagged: a ``while`` whose body both consumes the
comm surface (a collective, tagged p2p, or ``progress`` call) and
contains a ``continue`` (the retry signature — a straight-line
bounded loop cannot spin on a revoked comm).

Evidence that satisfies the rule, anywhere in the loop body: a call
named ``check``/``revoked``/``_check_alive``/``_fence_check``, or any
identifier mentioning ``revok`` or ``epoch``.

Suppression: ``# commlint: allow(revokecheck)`` on or above the loop
(or the consuming call), for loops whose termination is otherwise
bounded (drain loops over local state, wall-clock-bounded backoff
loops).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..report import Severity
from . import (
    COLL_BASE_OPS, COMMLINT, LintRule, P2P_TAGGED, call_name,
    scope_walk,
)

#: Call names that consume the comm surface inside a retry loop.
_CONSUMING = frozenset(COLL_BASE_OPS | P2P_TAGGED | {"progress"})

#: Call names that count as revocation-fence evidence.
_EVIDENCE_CALLS = frozenset({
    "check", "revoked", "_check_alive", "_fence_check",
})

#: Identifier substrings that count as evidence (``lifeboat.revoked``,
#: ``comm._revoked``, ``epoch_tag``, ``RevokedError`` handlers...).
_EVIDENCE_WORDS = ("revok", "epoch")


def _loop_walk(loop: ast.While) -> Iterable[ast.AST]:
    """The loop subtree, excluding nested function bodies and nested
    while-loops (inner loops are flagged on their own)."""
    stack: list[ast.AST] = list(ast.iter_child_nodes(loop))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.While)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _idents(node: ast.AST) -> Iterable[str]:
    if isinstance(node, ast.Name):
        yield node.id
    elif isinstance(node, ast.Attribute):
        yield node.attr
    elif isinstance(node, ast.ExceptHandler) and node.type is not None:
        for sub in ast.walk(node.type):
            yield from _idents(sub)


def _has_evidence(loop: ast.While) -> bool:
    for node in _loop_walk(loop):
        if isinstance(node, ast.Call) \
                and call_name(node) in _EVIDENCE_CALLS:
            return True
        for ident in _idents(node):
            low = ident.lower()
            if any(w in low for w in _EVIDENCE_WORDS):
                return True
    return False


def _consuming_calls(loop: ast.While) -> list[ast.Call]:
    return [
        n for n in _loop_walk(loop)
        if isinstance(n, ast.Call) and call_name(n) in _CONSUMING
    ]


def _is_retry_loop(loop: ast.While) -> bool:
    return any(
        isinstance(n, ast.Continue) for n in _loop_walk(loop)
    )


@COMMLINT.register
class RevokeCheckRule(LintRule):
    NAME = "revokecheck"
    PRIORITY = 42
    DESCRIPTION = ("comm-consuming retry loops under coll//pml/ must "
                   "re-check revocation between attempts")
    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable:
        rel = ctx.relpath.replace("\\", "/")
        if "coll/" not in rel and "pml/" not in rel:
            return
        for loop in ctx.walk():
            if not isinstance(loop, ast.While):
                continue
            if not _is_retry_loop(loop):
                continue
            consuming = _consuming_calls(loop)
            if not consuming:
                continue
            if _has_evidence(loop):
                continue
            if ctx.suppressed(loop.lineno, self.NAME):
                continue
            call = consuming[0]
            if ctx.suppressed(call.lineno, self.NAME):
                continue
            yield self.finding(
                ctx, loop,
                f"retry loop consumes the comm surface "
                f"({call_name(call)}) with no epoch/revocation check "
                "between attempts — a revoked communicator spins here "
                "forever instead of raising RevokedError; call "
                "lifeboat.check(comm) per iteration (or annotate "
                "commlint: allow(revokecheck))",
            )

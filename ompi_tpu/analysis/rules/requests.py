"""Request-lifecycle rules (MPI-Checker's request-usage class).

- ``reqlife``: a nonblocking/persistent/partitioned request that is
  discarded at the call site, or bound to a name that is never
  completed (wait/test/result), freed, started, or escaped — the
  classic missing-wait defect.
- ``partready``: a Psend_init request that is started/waited but never
  has MPI_Pready issued for any declared partition — the send can
  never complete (MPI-4 §4.2).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..report import Severity
from . import (
    COMMLINT,
    LintRule,
    REQ_CONSUMER_FNS,
    REQ_CONSUMERS,
    REQ_MAKERS,
    call_name,
    name_uses,
    scope_walk,
    scopes,
)

#: Attribute reads that neither complete nor leak the handle.
_PASSIVE_ATTRS = frozenset({
    "status", "done", "state", "partitions", "sending", "buffer",
    "persistent",
})


def _classify_uses(scope: ast.AST, name: str, assign: ast.Assign):
    """(consumed, escaped, used): how the request handle is treated."""
    consumed = escaped = used = False
    parents = _parent_map(scope)
    for use in name_uses(scope, name):
        if use is assign.targets[0]:
            continue
        if isinstance(use.ctx, ast.Store):
            # rebinding: lifetime analysis past this point is unsound
            escaped = True
            continue
        used = True
        parent = parents.get(use)
        if isinstance(parent, ast.Attribute):
            gp = parents.get(parent)
            if parent.attr in REQ_CONSUMERS and isinstance(gp, ast.Call) \
                    and gp.func is parent:
                consumed = True
            elif parent.attr not in _PASSIVE_ATTRS:
                escaped = True  # unknown method/attr: assume it matters
        elif isinstance(parent, ast.Call):
            # handle passed to a call: wait_all(...) consumes, anything
            # else escapes our analysis
            if call_name(parent) in REQ_CONSUMER_FNS:
                consumed = True
            else:
                escaped = True
        elif isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom,
                                 ast.List, ast.Tuple, ast.Set, ast.Dict,
                                 ast.Starred, ast.Await, ast.Compare,
                                 ast.BoolOp, ast.IfExp, ast.Subscript)):
            escaped = True
        elif isinstance(parent, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                 ast.NamedExpr, ast.keyword)):
            escaped = True
    return consumed, escaped, used


def _parent_map(scope: ast.AST) -> dict[ast.AST, ast.AST]:
    """child -> parent within the scope, memoized on the scope node
    (three rules ask for it; the shared FileContext makes one pay)."""
    cached = getattr(scope, "_commlint_parents", None)
    if cached is None:
        cached = {}
        for node in scope_walk(scope):
            for child in ast.iter_child_nodes(node):
                cached[child] = node
        for child in ast.iter_child_nodes(scope):
            cached.setdefault(child, scope)
        scope._commlint_parents = cached
    return cached


def _request_bindings(scope: ast.AST):
    """(assign, name, maker) for `r = comm.isend(...)`-shaped statements."""
    for node in scope_walk(scope):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            maker = call_name(node.value)
            if maker in REQ_MAKERS:
                yield node, node.targets[0].id, maker


@COMMLINT.register
class RequestLifetimeRule(LintRule):
    NAME = "reqlife"
    PRIORITY = 90
    DESCRIPTION = ("nonblocking/persistent requests must be waited, "
                   "tested, freed, or escape the scope")
    SEVERITY = Severity.ERROR

    def check(self, ctx) -> Iterable:
        for scope, _is_mod in scopes(ctx.tree):
            for node in scope_walk(scope):
                # discarded at the call site: `comm.isend(x, 1)` as a
                # bare expression statement
                if isinstance(node, ast.Expr):
                    maker = call_name(node.value)
                    if maker in REQ_MAKERS and not ctx.suppressed(
                            node.lineno, self.NAME):
                        yield self.finding(
                            ctx, node,
                            f"request from {maker}() is discarded — "
                            "never waited, tested, or freed",
                        )
            for assign, name, maker in _request_bindings(scope):
                if ctx.suppressed(assign.lineno, self.NAME):
                    continue
                consumed, escaped, used = _classify_uses(
                    scope, name, assign
                )
                if consumed or escaped:
                    continue
                if not used:
                    yield self.finding(
                        ctx, assign,
                        f"request {name!r} from {maker}() is never "
                        "used — missing wait/test/free",
                    )
                else:
                    yield self.finding(
                        ctx, assign,
                        f"request {name!r} from {maker}() is inspected "
                        "but never completed (wait/test/result) or "
                        "freed",
                    )


@COMMLINT.register
class PreadyMissingRule(LintRule):
    NAME = "partready"
    PRIORITY = 85
    DESCRIPTION = ("a started Psend_init request needs Pready for its "
                   "declared partitions")
    SEVERITY = Severity.ERROR

    _READY = frozenset({"pready", "pready_range", "pready_list"})
    _READY_FNS = frozenset({"Pready", "Pready_range", "Pready_list"})

    def check(self, ctx) -> Iterable:
        for scope, _is_mod in scopes(ctx.tree):
            parents = _parent_map(scope)
            for assign, name, maker in _request_bindings(scope):
                if maker not in ("psend_init", "Psend_init"):
                    continue
                if ctx.suppressed(assign.lineno, self.NAME):
                    continue
                started = readied = escaped = False
                for use in name_uses(scope, name):
                    if use is assign.targets[0]:
                        continue
                    parent = parents.get(use)
                    if isinstance(parent, ast.Attribute):
                        if parent.attr in self._READY:
                            readied = True
                        elif parent.attr in ("start", "wait", "result"):
                            started = True
                        elif parent.attr not in _PASSIVE_ATTRS \
                                and parent.attr not in REQ_CONSUMERS:
                            escaped = True
                    elif isinstance(parent, ast.Call):
                        fn = call_name(parent)
                        if fn in self._READY_FNS:
                            readied = True
                        elif fn == "start_all":
                            started = True
                        else:
                            escaped = True
                    elif parent is not None and not isinstance(
                            parent, ast.Expr):
                        escaped = True
                if started and not readied and not escaped:
                    yield self.finding(
                        ctx, assign,
                        f"partitioned send {name!r} is started but "
                        "Pready is never issued for any declared "
                        "partition — the transfer cannot complete",
                    )

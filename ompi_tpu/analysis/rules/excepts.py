"""Exception-hygiene rule.

``broadexcept``: ``except Exception:`` (or bare ``except:``) in comm
paths hides deadlocks, wire corruption, and component failures behind
a green run. Silent handlers (body is only pass/.../continue) are
errors; handlers that at least log or transform the exception are
warnings, ratcheted by the self-lint baseline. Justified broad catches
(``__del__``, user-callback dispatch, availability probes) carry a
``# commlint: allow(broadexcept)`` suppression.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..report import Severity
from . import COMMLINT, LintRule

_BROAD = ("Exception", "BaseException")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in _BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _BROAD:
            return True
    return False


def _is_silent(handler: ast.ExceptHandler) -> bool:
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass) or isinstance(stmt, ast.Continue):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, ast.Constant):
            continue  # docstring / Ellipsis
        return False
    return True


@COMMLINT.register
class BroadExceptRule(LintRule):
    NAME = "broadexcept"
    PRIORITY = 60
    DESCRIPTION = ("broad except handlers hide comm failures; silent "
                   "ones are errors")
    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable:
        for node in ctx.walk():
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if ctx.suppressed(node.lineno, self.NAME):
                continue
            if _is_silent(node):
                yield self.finding(
                    ctx, node,
                    "silent broad except (body is pass) — swallows "
                    "comm-path failures; narrow the exception and log "
                    "via core.logging.warn_once",
                    severity=Severity.ERROR,
                )
            else:
                yield self.finding(
                    ctx, node,
                    "broad `except Exception` in a comm path — narrow "
                    "it or justify with `# commlint: "
                    "allow(broadexcept)`",
                )

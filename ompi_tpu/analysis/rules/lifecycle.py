"""Object-lifecycle rule.

``useafterfree``: a communicator/window/file handle used after its
``free()`` (the reference's MPI_Comm_free sets the handle to
MPI_COMM_NULL; here the object raises on next use — at runtime. This
surfaces it statically). The analysis is flow-lite: within one scope,
any Load of the name on a line after the ``free()`` call, with no
intervening rebinding, is flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..report import Severity
from . import COMMLINT, LintRule, name_uses, scope_walk, scopes
from .requests import _parent_map

_FREE_METHODS = frozenset({"free", "Free", "close", "Close"})
#: Only .free()/.close() receivers that look like comm-path handles are
#: tracked; generic file objects etc. have their own idioms (with ...).
_HANDLE_HINTS = ("comm", "win", "window", "dup", "inter", "sub", "fh",
                 "req", "request")


def _freed_names(scope: ast.AST):
    """(name, line) for `name.free()` expression statements."""
    for node in scope_walk(scope):
        if not isinstance(node, ast.Expr):
            continue
        call = node.value
        if not isinstance(call, ast.Call) or call.args or call.keywords:
            continue
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr in _FREE_METHODS \
                and isinstance(fn.value, ast.Name):
            name = fn.value.id
            if any(h in name.lower() for h in _HANDLE_HINTS) \
                    or fn.attr in ("free", "Free"):
                yield name, node.lineno


@COMMLINT.register
class UseAfterFreeRule(LintRule):
    NAME = "useafterfree"
    PRIORITY = 65
    DESCRIPTION = "communicator/window handles must not be used after free()"
    SEVERITY = Severity.ERROR

    def check(self, ctx) -> Iterable:
        for scope, _is_mod in scopes(ctx.tree):
            freed = list(_freed_names(scope))
            if not freed:
                continue
            parents = _parent_map(scope)
            for name, free_line in freed:
                if ctx.suppressed(free_line, self.NAME):
                    continue
                for use in name_uses(scope, name):
                    if use.lineno <= free_line:
                        continue
                    if isinstance(use.ctx, ast.Store):
                        break  # rebound: later uses are a fresh object
                    # Only an operation on the handle is a defect;
                    # inspecting attributes post-free is legitimate.
                    parent = parents.get(use)
                    gp = parents.get(parent) if parent is not None else None
                    is_method_call = (
                        isinstance(parent, ast.Attribute)
                        and isinstance(gp, ast.Call) and gp.func is parent
                        and parent.attr not in ("name", "cid")
                        and not parent.attr.startswith("_")
                    )
                    if not is_method_call:
                        continue
                    if parent.attr in _FREE_METHODS:
                        continue  # double-free is tolerated (idempotent)
                    yield self.finding(
                        ctx, use,
                        f"{name!r}.{parent.attr}() called after free() "
                        f"on line {free_line} — freed handles raise on "
                        "use",
                    )
                    break  # one finding per freed handle

"""Step-program compilation rule.

``stepprogram``: since the training step's comm became the sched
compilation unit (coll/sched/stepprogram), code under ``parallel/``
should bind ONE compiled step program and let its executor own the
per-bucket collective flows — a Python loop constructing per-bucket
collectives by hand recreates exactly the stitched-together shape the
program compiler replaced: the autotuner can't see across buckets, the
Pallas backend emits one kernel per bucket, and the step pays one
progress callback and one broadcast tail per bucket.

The rule flags ``for``/``while`` loops under ``parallel/`` whose body
constructs a partitioned/bucketed collective flow
(``PartitionedAllreduce``, ``BucketedAllreduce``, ``ShardedAllreduce``,
``psend_init``/``precv_init`` pairs) when the enclosing scope shows no
program-compilation evidence — an identifier mentioning
``compile_step``, ``Program``, ``CompiledStep``, ``StepExecutor`` or
``stepprogram`` (the compiled-step surface).

Suppression: ``# commlint: allow(stepprogram)`` on the flagged
construction call (or the loop's / enclosing function's first line),
for loops that knowingly predate or sit outside the compiled-step path
(bring-up shims, comparison arms).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..report import Severity
from . import COMMLINT, LintRule, call_name
from .overlapready import _scope_walk

#: Per-bucket collective flow constructors (the surface the step
#: executor owns now).
_CONSTRUCTORS = frozenset({
    "PartitionedAllreduce", "BucketedAllreduce", "ShardedAllreduce",
    "psend_init", "precv_init",
})

#: Identifier substrings that count as program-compilation evidence.
_EVIDENCE_WORDS = (
    "compile_step", "Program", "CompiledStep", "StepExecutor",
    "stepprogram",
)


def _has_program_evidence(scope: ast.AST) -> bool:
    for node in _scope_walk(scope):
        for ident in _idents(node):
            if any(w in ident for w in _EVIDENCE_WORDS):
                return True
    return False


def _idents(node: ast.AST) -> Iterable[str]:
    if isinstance(node, ast.Name):
        yield node.id
    elif isinstance(node, ast.Attribute):
        yield node.attr
    elif isinstance(node, (ast.Import, ast.ImportFrom)):
        for alias in node.names:
            yield alias.name


@COMMLINT.register
class StepProgramRule(LintRule):
    NAME = "stepprogram"
    PRIORITY = 46
    DESCRIPTION = ("per-bucket collective construction loops under "
                   "parallel/ should bind a compiled step program, not "
                   "stitch collectives together in Python")
    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable:
        rel = ctx.relpath.replace("\\", "/")
        if "parallel/" not in rel:
            return
        # evidence scope: the enclosing function (or the module for
        # top-level loops)
        scopes = [ctx.tree] + [
            n for n in ctx.walk()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        owner: dict = {}
        for scope in scopes:
            for node in _scope_walk(scope):
                owner[id(node)] = scope
        for loop in ctx.walk():
            if not isinstance(loop, (ast.For, ast.AsyncFor, ast.While)):
                continue
            builds = [
                n for n in ast.walk(loop)
                if isinstance(n, ast.Call)
                and call_name(n) in _CONSTRUCTORS
            ]
            if not builds:
                continue
            scope = owner.get(id(loop), ctx.tree)
            if _has_program_evidence(scope):
                continue
            lines = [loop.lineno]
            if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
                lines.append(scope.lineno)
            if any(ctx.suppressed(ln, self.NAME) for ln in lines):
                continue
            for call in builds:
                if ctx.suppressed(call.lineno, self.NAME):
                    continue
                yield self.finding(
                    ctx, call,
                    f"loop constructs {call_name(call)} per bucket with "
                    "no compile_step/Program evidence in scope — the "
                    "step's comm should compile to ONE sched program "
                    "(coll/sched/stepprogram.compile_step) whose "
                    "executor owns the per-bucket flows; bind a "
                    "compiled step (or annotate commlint: "
                    "allow(stepprogram))",
                )

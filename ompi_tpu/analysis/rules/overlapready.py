"""Overlap-readiness rule.

``overlapready``: the tile-granular overlap path (parallel/overlap +
the partitioned part framework) only hides communication if the
gradient/backward code actually feeds it readiness — a blocking
``allreduce``/``allreduce_gradients`` call sitting in a gradient- or
backward-named function serializes the whole reduction behind the
backward pass, exactly the exposed-comm tail the T3-style machinery
exists to remove. The rule flags blocking gradient-reduction call sites
inside gradient/backward-named functions under ``parallel/`` and
``models/`` that show no readiness evidence (a ``mark_ready`` /
``Pready`` / schedule-capture / grad-marker reference) in the same
function scope.

Evidence that satisfies the rule, anywhere in the function: a call or
identifier mentioning ``mark_ready``, ``pready``, ``parrived``,
``grad_marker``, ``capture_ready`` or ``overlap`` (the overlap-session
surface — e.g. ``overlap.capture_ready_schedule(grads)`` at the sync
seam).

Suppression: ``# commlint: allow(overlapready)`` on the flagged call
(or its enclosing function's def line), for call sites that knowingly
stay blocking (tiny trees, debug paths, delegation to an overlap-aware
wrapper).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..report import Severity
from . import COMMLINT, LintRule, call_name

#: Blocking gradient-reduction entry points (the coll vtable call, the
#: dp-layer wrappers, and the bucketer's fused paths).
_BLOCKING = frozenset({
    "allreduce", "allreduce_gradients", "allreduce_tree",
    "allreduce_pytree",
})

#: Function-name fragments marking gradient/backward code.
_GRAD_FN_WORDS = ("grad", "backward", "bwd")

#: Identifier substrings that count as readiness evidence.
_EVIDENCE_WORDS = (
    "mark_ready", "pready", "parrived", "grad_marker", "capture_ready",
    "overlap",
)


def _scope_walk(fn: ast.AST) -> Iterable[ast.AST]:
    """The function subtree, excluding nested function bodies (a nested
    gradient helper is checked on its own)."""
    stack: list[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _idents(node: ast.AST) -> Iterable[str]:
    if isinstance(node, ast.Name):
        yield node.id
    elif isinstance(node, ast.Attribute):
        yield node.attr


def _has_evidence(fn: ast.AST) -> bool:
    for node in _scope_walk(fn):
        for ident in _idents(node):
            low = ident.lower()
            if any(w in low for w in _EVIDENCE_WORDS):
                return True
    return False


@COMMLINT.register
class OverlapReadyRule(LintRule):
    NAME = "overlapready"
    PRIORITY = 44
    DESCRIPTION = ("gradient/backward functions under parallel//models/ "
                   "should feed the tile-overlap path, not block on a "
                   "monolithic allreduce")
    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable:
        rel = ctx.relpath.replace("\\", "/")
        if "parallel/" not in rel and "models/" not in rel:
            return
        for fn in ctx.walk():
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            low = fn.name.lower()
            if not any(w in low for w in _GRAD_FN_WORDS):
                continue
            blocking = [
                n for n in _scope_walk(fn)
                if isinstance(n, ast.Call)
                and call_name(n) in _BLOCKING
            ]
            if not blocking:
                continue
            if _has_evidence(fn):
                continue
            if ctx.suppressed(fn.lineno, self.NAME):
                continue
            for call in blocking:
                if ctx.suppressed(call.lineno, self.NAME):
                    continue
                yield self.finding(
                    ctx, call,
                    f"{fn.name}() blocks on {call_name(call)} with the "
                    "partitioned overlap path available — no "
                    "mark_ready/Pready/schedule-capture evidence in "
                    "scope, so the whole reduction is exposed behind "
                    "the backward pass; feed parallel/overlap (or "
                    "annotate commlint: allow(overlapready))",
                )

"""Concurrency rules — the locksmith engine surfaced through commlint.

These three rules are whole-program: they read the locksmith analysis
(analysis/locksmith.py) computed once over the shared ProjectIndex and
report each finding in the file it anchors to, so suppressions and the
per-``rule:file`` ratchet baseline work exactly like every per-file
rule.  A bare ``lint_source`` snippet gets a one-file index — the
rules still fire on self-contained fixtures (a two-lock cycle inside
one module) but cross-module findings need the tree run.

- ``lockorder`` (ERROR): a cycle in the lock-order graph — two threads
  entering from opposite ends deadlock.  The message carries the full
  ``file:line`` acquire/call witness chain of every edge.
- ``cbunderlock`` (WARNING): a passed-in callable or registered
  callback invoked while a lock is held (the PR 8 ledger class); queue
  under the lock, fire after release.
- ``unguardedwrite`` (WARNING): an attribute written under its class
  lock at some sites and outside any lock at others (the PR 15
  ``_tiles_reduced`` lost-combine class), with the thread-spawn
  inventory naming which threads race.
"""

from __future__ import annotations

from typing import Iterable

from ..report import Finding, Severity
from . import COMMLINT, LintRule


class _LocksmithRule(LintRule):
    """Shared plumbing: pull this file's findings out of the cached
    whole-program analysis."""

    def check(self, ctx) -> Iterable[Finding]:
        if ctx.index is None:
            return
        analysis = ctx.index.locksmith()
        for f in analysis.findings_for(ctx.relpath, self.NAME):
            if not ctx.suppressed(f.line, self.NAME):
                yield f


@COMMLINT.register
class LockOrderRule(_LocksmithRule):
    NAME = "lockorder"
    PRIORITY = 90
    SEVERITY = Severity.ERROR
    DESCRIPTION = ("lock-order cycles across the whole program — "
                   "potential deadlocks with acquire witness chains")


@COMMLINT.register
class CallbackUnderLockRule(_LocksmithRule):
    NAME = "cbunderlock"
    PRIORITY = 60
    SEVERITY = Severity.WARNING
    DESCRIPTION = ("callbacks/passed-in callables invoked while "
                   "holding a lock — defer past release")


@COMMLINT.register
class UnguardedWriteRule(_LocksmithRule):
    NAME = "unguardedwrite"
    PRIORITY = 60
    SEVERITY = Severity.WARNING
    DESCRIPTION = ("attributes written both under a class lock and "
                   "outside any lock — cross-thread data races")

"""commlint rule registry — each rule is an MCA component.

Rules register with the ``commlint`` framework and are selected through
the standard component machinery, so the usual cvar surface applies:
``commlint_select`` filters rules by name (``^broadexcept`` disables
one), and each rule carries a ``commlint_<name>_priority`` var. The
linter driver (analysis/lint.py) runs every selected rule over every
file's AST and merges findings.

Shared AST vocabulary for the comm surface lives here so rules agree on
what a "request maker" or a "collective" is.
"""

from __future__ import annotations

import ast
from typing import Any, Iterable, Optional

from ...core import component as mca
from ..report import Finding, Severity

COMMLINT = mca.framework(
    "commlint", "static communication-correctness rules"
)

#: Calls returning a Request the caller must complete (wait/test/free).
REQ_MAKERS = frozenset({
    "isend", "irecv", "send_init", "recv_init",
    "psend_init", "precv_init", "Psend_init", "Precv_init",
    "iallreduce", "ibcast", "ireduce", "iallgather", "ialltoall",
    "igather", "iscatter", "iscan", "ibarrier", "iallgatherv",
    "ialltoallv", "ireduce_scatter", "ireduce_scatter_block",
    "ineighbor_allgather", "ineighbor_alltoall",
})

#: Attribute calls that complete/consume a request handle.
REQ_CONSUMERS = frozenset({
    "wait", "test", "result", "free", "cancel", "start", "bind",
    "on_complete", "pready", "pready_range", "pready_list", "parrived",
})

#: Free functions that consume request handles passed as arguments.
REQ_CONSUMER_FNS = frozenset({
    "wait_all", "wait_any", "wait_some", "test_all", "test_any",
    "test_some", "start_all", "Pready", "Pready_range", "Pready_list",
    "Parrived",
})

#: Blocking collective entry points (the per-comm coll vtable names).
COLL_BASE_OPS = frozenset({
    "allreduce", "bcast", "reduce", "allgather", "alltoall", "gather",
    "scatter", "scan", "exscan", "barrier", "reduce_scatter",
    "reduce_scatter_block", "allgatherv", "gatherv", "scatterv",
    "alltoallv", "alltoallw", "neighbor_allgather", "neighbor_alltoall",
})

#: All collective spellings: blocking + nonblocking + persistent-init.
COLL_OPS = frozenset(
    set(COLL_BASE_OPS)
    | {f"i{op}" for op in COLL_BASE_OPS}
    | {f"{op}_init" for op in COLL_BASE_OPS}
)

#: Plain p2p calls whose user tag shares the pml tag space.
P2P_TAGGED = frozenset({
    "send", "isend", "recv", "irecv", "send_init", "recv_init",
    "sendrecv", "probe", "iprobe", "improbe",
})

INT_DTYPES = frozenset({
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "bool_",
})
FLOAT_DTYPES = frozenset({
    "float16", "float32", "float64", "bfloat16",
})
_ITEMSIZE = {
    "int8": 1, "uint8": 1, "bool_": 1, "int16": 2, "uint16": 2,
    "float16": 2, "bfloat16": 2, "int32": 4, "uint32": 4, "float32": 4,
    "int64": 8, "uint64": 8, "float64": 8,
}


class LintRule(mca.Component):
    """Base class: one correctness rule over a parsed file.

    Subclasses set NAME (the rule id used in findings, baselines, and
    suppression comments) and implement ``check(ctx)`` yielding
    Findings. ``ctx`` is an analysis.lint.FileContext.
    """

    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx, node: ast.AST, message: str,
                severity: Optional[Severity] = None) -> Finding:
        return Finding(
            rule=self.NAME,
            severity=self.SEVERITY if severity is None else severity,
            path=ctx.relpath,
            line=getattr(node, "lineno", 0),
            message=message,
        )


# -- shared AST helpers -----------------------------------------------------

def call_name(node: ast.AST) -> Optional[str]:
    """The unqualified callee name of a Call ('comm.isend(..)' -> 'isend')."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def call_arg(call: ast.Call, pos: int, kw: str) -> Optional[ast.AST]:
    """Argument by keyword name, falling back to position."""
    for k in call.keywords:
        if k.arg == kw:
            return k.value
    if pos < len(call.args):
        return call.args[pos]
    return None


def const_int(node: Optional[ast.AST]) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    return None


def const_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def dtype_name(node: Optional[ast.AST]) -> Optional[str]:
    """'np.int8' / 'jnp.float32' / 'int8' / "int8" -> the dtype word."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def itemsize_of(dtype: Optional[str]) -> int:
    return _ITEMSIZE.get(dtype or "", 4)


def tree_walk(tree: ast.AST) -> list[ast.AST]:
    """``ast.walk`` memoized on the node — for helpers handed a bare
    tree rather than the FileContext (whose ``walk()`` caches too)."""
    cached = getattr(tree, "_commlint_treewalk", None)
    if cached is None:
        cached = list(ast.walk(tree))
        tree._commlint_treewalk = cached
    return cached


def scopes(tree: ast.Module) -> Iterable[tuple[ast.AST, bool]]:
    """(scope_node, is_module) list: the module plus every function.

    A scope's statements are analyzed together; nested functions form
    their own scopes (their bodies are excluded from the enclosing
    scope's walk by ``scope_walk``).  Memoized on the tree — with the
    parse-once engine every rule shares one FileContext per file, so a
    20-rule run pays for this traversal exactly once.
    """
    cached = getattr(tree, "_commlint_scopes", None)
    if cached is None:
        cached = [(tree, True)] + [
            (node, False) for node in ast.walk(tree)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        tree._commlint_scopes = cached
    return cached


def scope_walk(scope: ast.AST) -> Iterable[ast.AST]:
    """ast.walk restricted to this scope: does not descend into nested
    function definitions (they are separate scopes), but does descend
    into class bodies, loops, withs, and tries.  Memoized on the scope
    node (rules hit the same scopes thousands of times per run)."""
    cached = getattr(scope, "_commlint_walk", None)
    if cached is None:
        cached = []
        stack: list[ast.AST] = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            cached.append(node)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(node))
        scope._commlint_walk = cached
    return cached


def name_uses(scope: ast.AST, name: str) -> list[ast.Name]:
    """Every Name node for `name` inside the scope, document order.
    The per-scope name table is built once and shared by every rule."""
    cache = getattr(scope, "_commlint_names", None)
    if cache is None:
        cache = {}
        for n in scope_walk(scope):
            if isinstance(n, ast.Name):
                cache.setdefault(n.id, []).append(n)
        for uses in cache.values():
            uses.sort(key=lambda n: (n.lineno, n.col_offset))
        scope._commlint_names = cache
    return cache.get(name, [])


def literal_elems(node: Optional[ast.AST]) -> Optional[int]:
    """Element count of a literal shape: 1024 or (8, 128) -> 1024."""
    n = const_int(node)
    if n is not None:
        return n
    if isinstance(node, (ast.Tuple, ast.List)):
        total = 1
        for elt in node.elts:
            v = const_int(elt)
            if v is None:
                return None
            total *= v
        return total
    return None


def infer_buffers(scope: ast.AST) -> dict[str, dict[str, Any]]:
    """Best-effort env: var name -> {'dtype': str|None, 'elems': int|None}
    from literal array constructors and .astype() calls in the scope."""
    env: dict[str, dict[str, Any]] = {}
    ctors = {"zeros", "ones", "full", "empty", "arange", "array",
             "asarray", "normal", "uniform"}
    for node in scope_walk(scope):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        val = node.value
        cn = call_name(val)
        if cn in ctors:
            dt = dtype_name(call_arg(val, -1, "dtype"))
            if dt is None and len(val.args) >= 2:
                # positional dtype: np.zeros((n,), np.int8)
                dt = dtype_name(val.args[-1])
            if dt is not None and dt not in INT_DTYPES \
                    and dt not in FLOAT_DTYPES:
                dt = None
            elems = None
            if cn == "arange":
                elems = const_int(call_arg(val, 0, "stop"))
            elif val.args:
                elems = literal_elems(val.args[0])
            env[tgt.id] = {"dtype": dt, "elems": elems}
        elif cn == "astype" and isinstance(val, ast.Call) \
                and isinstance(val.func, ast.Attribute):
            dt = dtype_name(call_arg(val, 0, "dtype"))
            base = val.func.value
            prev = env.get(base.id) if isinstance(base, ast.Name) else None
            env[tgt.id] = {
                "dtype": dt if dt in INT_DTYPES | FLOAT_DTYPES else None,
                "elems": (prev or {}).get("elems"),
            }
    return env


_registered = False


def ensure_rules() -> None:
    """Import every rule module for its registration side effect."""
    global _registered
    if not _registered:
        from . import collectives  # noqa: F401
        from . import devicesem  # noqa: F401
        from . import excepts  # noqa: F401
        from . import fastpath  # noqa: F401
        from . import growfence  # noqa: F401
        from . import healthseam  # noqa: F401
        from . import lifecycle  # noqa: F401
        from . import locking  # noqa: F401
        from . import metricname  # noqa: F401
        from . import overlapready  # noqa: F401
        from . import polling  # noqa: F401
        from . import quantuse  # noqa: F401
        from . import requests  # noqa: F401
        from . import retuneaudit  # noqa: F401
        from . import revokecheck  # noqa: F401
        from . import schedcutoff  # noqa: F401
        from . import simclock  # noqa: F401
        from . import stepbarrier  # noqa: F401
        from . import stepprogram  # noqa: F401
        from . import tags  # noqa: F401
        from . import tenantscope  # noqa: F401
        from . import tracespan  # noqa: F401

        _registered = True

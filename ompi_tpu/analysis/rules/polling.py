"""Polling-hygiene rule.

``polldeadline``: a ``while`` loop that parks in a fixed
``time.sleep(<const>)`` with no deadline or backoff evidence in the
loop body spins forever when the condition it polls never comes true —
the classic hang mode of modex gets, name-service lookups, and
connection retries. Comm-path polls must either consult a clock
(``time.monotonic()`` / ``perf_counter`` against a deadline) or use
``core.backoff.Backoff``, whose ``sleep()`` is deadline-bounded and
backs off exponentially.

``time.sleep(0)`` anywhere is a bare scheduler yield — usually a
busy-wait in disguise; the one intentional yield (the progress
engine's starvation guard) carries a ``# commlint:
allow(polldeadline)`` suppression.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..report import Severity
from . import COMMLINT, LintRule, scope_walk

#: Names whose appearance inside the loop counts as deadline/backoff
#: evidence: clock reads, deadline arithmetic, or a Backoff object.
_EVIDENCE = frozenset({
    "monotonic", "monotonic_ns", "perf_counter", "perf_counter_ns",
    "process_time", "time_ns", "deadline", "remaining", "expired",
    "Backoff", "backoff", "progress_until", "wait_event",
})


def _is_time_sleep(node: ast.AST) -> bool:
    """Matches ``time.sleep(...)`` and bare ``sleep(...)`` (from-import
    spelling); does NOT match method calls like ``bo.sleep()``."""
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr == "sleep" and isinstance(fn.value, ast.Name) \
            and fn.value.id == "time"
    return isinstance(fn, ast.Name) and fn.id == "sleep"


def _sleep_const(call: ast.Call):
    """The constant numeric sleep argument, or None when dynamic."""
    if len(call.args) != 1:
        return None
    a = call.args[0]
    if isinstance(a, ast.Constant) and isinstance(a.value, (int, float)) \
            and not isinstance(a.value, bool):
        return a.value
    return None


def _has_evidence(loop: ast.While) -> bool:
    for node in scope_walk(loop):
        if isinstance(node, ast.Name) and node.id in _EVIDENCE:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _EVIDENCE:
            return True
    return False


@COMMLINT.register
class PollDeadlineRule(LintRule):
    NAME = "polldeadline"
    PRIORITY = 55
    DESCRIPTION = ("fixed-interval poll loops must be deadline-bounded "
                   "(core.backoff.Backoff or an explicit clock check)")
    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable:
        flagged: set[int] = set()
        for node in ctx.walk():
            if not isinstance(node, ast.While):
                continue
            if _has_evidence(node):
                continue
            for inner in scope_walk(node):
                if not _is_time_sleep(inner):
                    continue
                val = _sleep_const(inner)
                if val is None or val <= 0:
                    continue  # dynamic delay / yield handled below
                if ctx.suppressed(inner.lineno, self.NAME):
                    continue
                if inner.lineno in flagged:
                    continue
                flagged.add(inner.lineno)
                yield self.finding(
                    ctx, inner,
                    "fixed-interval poll loop with no deadline — a "
                    "never-published key spins forever; bound it with "
                    "core.backoff.Backoff(timeout=...) or a "
                    "time.monotonic() deadline",
                    severity=Severity.ERROR,
                )
        for node in ctx.walk():
            if not _is_time_sleep(node):
                continue
            val = _sleep_const(node)
            if val != 0:
                continue
            if ctx.suppressed(node.lineno, self.NAME):
                continue
            if node.lineno in flagged:
                continue
            yield self.finding(
                ctx, node,
                "time.sleep(0) is a bare scheduler yield — a busy-wait "
                "in disguise; justify with `# commlint: "
                "allow(polldeadline)` or use a bounded wait",
            )

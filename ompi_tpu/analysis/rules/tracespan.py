"""Trace-coverage rule.

``tracespan``: a public collective/p2p entry point in coll/ or pml/
that dispatches outside the selection seams never lands on the
commtrace timeline — the flight recorder shows a gap exactly where the
interesting call happened. Components registered with the framework
(``@COLL.register`` / ``@PML.register``) are covered automatically:
trace/span.py wraps every vtable entry and the selected pml at
selection time, so this rule skips them. What it flags is the
*unregistered* surface — module-level helpers or ad-hoc classes that
expose an entry-op name (``allreduce``, ``send``, ...) with no span or
instant call in the body and no selection-time wrap to catch them.

Evidence that satisfies the rule, anywhere in the function body:
a call named ``span``/``instant``/``Span``/``coll_trace_id`` or a
``traced_*`` helper from trace/span.py.

Suppression: ``# commlint: allow(tracespan)`` on the def line, for
entry points that are deliberately span-free (pure-dispatch persistent
starts, internal per-slice helpers).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..report import Severity
from . import COLL_BASE_OPS, COMMLINT, LintRule, call_name, scope_walk, tree_walk

#: Entry-op names whose public implementations belong on the timeline.
_ENTRY_OPS = frozenset(
    set(COLL_BASE_OPS) | {"send", "recv", "isend", "irecv"}
)

#: Call names that count as span evidence inside a body.
_SPAN_CALLS = frozenset({
    "span", "instant", "Span", "coll_trace_id",
    "traced_coll_fn", "maybe_wrap_coll", "maybe_wrap_pml",
    "maybe_wrap_part",
})

#: Directories whose entry points the rule audits ('/'-normalised).
_TRACED_DIRS = ("coll/", "pml/")


def _in_scope(relpath: str) -> bool:
    p = relpath.replace("\\", "/")
    if p.endswith("framework.py"):
        return False  # the seams themselves install the wrapping
    return any(f"/{d}" in p or p.startswith(d) for d in _TRACED_DIRS)


def _registered_classes(tree: ast.Module) -> set[ast.ClassDef]:
    """Classes whose entry ops are wrapped at selection time: anything
    decorated with a framework ``.register`` decorator, plus same-file
    mixin bases of such classes (their methods land in the registered
    component's vtable)."""
    by_name: dict[str, ast.ClassDef] = {}
    registered: set[ast.ClassDef] = set()
    for node in tree_walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        by_name[node.name] = node
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if isinstance(target, ast.Attribute) \
                    and target.attr == "register":
                registered.add(node)
                break
    for cls in list(registered):
        for base in cls.bases:
            if isinstance(base, ast.Name) and base.id in by_name:
                registered.add(by_name[base.id])
    return registered


def _takes_comm(fn: ast.AST) -> bool:
    """True when the def's positional parameters include ``comm`` —
    the signature shape of every vtable/pml entry point. Builder and
    slice-level helpers (no comm param) are out of scope."""
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args]
    return "comm" in names


def _has_span_evidence(fn: ast.AST) -> bool:
    for node in scope_walk(fn):
        if call_name(node) in _SPAN_CALLS:
            return True
    return False


@COMMLINT.register
class TraceSpanRule(LintRule):
    NAME = "tracespan"
    PRIORITY = 40
    DESCRIPTION = ("public coll/pml entry points outside the "
                   "selection seams should run under a trace span")
    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable:
        if not _in_scope(ctx.relpath):
            return
        registered = _registered_classes(ctx.tree)
        covered: set[ast.AST] = set()
        for cls in registered:
            covered.update(ast.walk(cls))
        for node in ctx.walk():
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if node.name not in _ENTRY_OPS:
                continue
            if not _takes_comm(node):
                continue
            if node in covered:
                continue  # selection-time wrap covers registered comps
            if _has_span_evidence(node):
                continue
            if ctx.suppressed(node.lineno, self.NAME):
                continue
            yield self.finding(
                ctx, node,
                f"entry point {node.name}() is outside the selection "
                "seams and emits no trace span/instant — calls through "
                "it leave a gap on the commtrace timeline; wrap the "
                "body in trace.span.span() or emit an instant",
            )

"""SPC metric-name hygiene rule.

``metricname``: every SPC registration (``SPC.record`` /
``record_latency`` / ``counter`` / ``hwm`` / ``timer`` /
``histogram``) mints a pvar name that outlives the code — it becomes
an MPI_T handle (``tools/mpit.pvar_read``), a Prometheus series
(``telemetry/export`` sanitizes but cannot rename), a fleet-view
column the straggler detector maps to a tier by *prefix*
(``telemetry/straggler._METRIC_TIERS``), and a key operators grep in
dashboards. A name that is not ``snake_case`` or whose first segment
is not a known subsystem prefix silently falls out of all of that:
``categories()`` files it under a phantom framework and the skew
detector can never attribute it to a tier.

Checked: calls whose receiver is ``SPC`` (bare or as the tail of an
attribute chain, e.g. ``counters.SPC``) with a literal name argument.
f-string names count when they start with a literal prefix that
reaches at least one ``_`` (``f"coll_{op}_algo"``); fully dynamic
names are invisible to static checking and pass.

Suppression: ``# commlint: allow(metricname)`` on the call line, for
deliberately out-of-band names (scratch counters in tests/bench).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from ..report import Severity
from . import COMMLINT, LintRule

#: SPC methods whose first argument mints/records a metric name.
_SPC_METHODS = frozenset({
    "record", "record_latency", "counter", "hwm", "timer", "histogram",
})

#: First name segment -> the subsystem it files under. Grown with the
#: tree: grep `SPC\.` registrations before trimming this set.
KNOWN_PREFIXES = frozenset({
    "btl", "coll", "convertor", "daemon", "dcn", "fabric", "faultline",
    "fp",
    "ft", "health", "hier", "init", "io", "locksmith", "memchecker", "monitoring",
    "mpit", "mtl", "nbc", "op", "osc", "parallel", "part", "pml",
    "pmpi", "quant", "sanitizer", "sched", "shmem", "sim", "sm",
    "telemetry", "topo", "trace", "vprotocol",
})

_SNAKE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)*$")


def _is_spc_receiver(node: ast.AST) -> bool:
    """True for ``SPC`` and for any attribute chain ending in ``SPC``."""
    if isinstance(node, ast.Name):
        return node.id == "SPC"
    if isinstance(node, ast.Attribute):
        return node.attr == "SPC"
    return False


def _literal_prefix(node: Optional[ast.AST]) -> tuple[Optional[str], bool]:
    """(checkable name text, is_partial). Constant strings check whole;
    f-strings check their leading literal when it spans a ``_``."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, False
    if isinstance(node, ast.JoinedStr) and node.values:
        head = node.values[0]
        if isinstance(head, ast.Constant) and isinstance(head.value, str) \
                and "_" in head.value:
            return head.value, True
    return None, False


def _name_arg(call: ast.Call) -> Optional[ast.AST]:
    for k in call.keywords:
        if k.arg == "name":
            return k.value
    if call.args:
        return call.args[0]
    return None


@COMMLINT.register
class MetricNameRule(LintRule):
    NAME = "metricname"
    PRIORITY = 15
    DESCRIPTION = ("SPC metric names must be snake_case with a known "
                   "subsystem prefix")
    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable:
        for node in ctx.walk():
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute)
                    and fn.attr in _SPC_METHODS
                    and _is_spc_receiver(fn.value)):
                continue
            text, partial = _literal_prefix(_name_arg(node))
            if text is None:
                continue
            if ctx.suppressed(node.lineno, self.NAME):
                continue
            probe = text.rstrip("_") if partial else text
            problem = None
            if not probe or not _SNAKE.match(probe):
                problem = "is not snake_case"
            else:
                prefix = probe.split("_", 1)[0]
                if prefix not in KNOWN_PREFIXES:
                    problem = (f"prefix {prefix!r} is not a known "
                               "subsystem")
            if problem is None:
                continue
            shown = text + ("..." if partial else "")
            yield self.finding(
                ctx, node,
                f"SPC metric name {shown!r} {problem} — pvar listing, "
                "Prometheus export, and straggler tier attribution all "
                "key on snake_case <subsystem>_<metric> names (known "
                "prefixes live in analysis/rules/metricname.py; extend "
                "the set for a new subsystem, or allow() a deliberate "
                "one-off)",
            )

"""Tenant-scope hygiene rule for the bulkhead daemon.

``tenantscope``: the daemon multiplexes many tenants over shared
control planes — the health ledger, the sched winner cache, SLO
accounting. Every one of those surfaces is scope-keyed (``str(cid)``
comm scopes, ``tenant:<id>`` namespaces), and the bulkhead isolation
guarantee holds only while daemon code *names the scope it is acting
for*: a ``seed_scope``/``gc_scope``/``is_denied``/``note_read``/
``set_target``/``note_violation`` call with no tenant-scope evidence
in its arguments either acts on the global scope (one tenant's fault
bleeds into everyone's deny decisions) or meters a tenant's traffic
into an unlabelled bucket (the per-tenant Prometheus series under-
count, silently).

Scope evidence, checked statically over the call's argument subtree:
a ``tenant_scope(...)`` call, or any name/attribute mentioning
``tenant``/``scope``/``cid`` (covers ``str(comm.cid)``, a ``scope=``
local, a ``session.comm`` chain). Only files under the ``daemon``
package are checked — outside it, global-scope calls are legitimate
(the watchtower sets fleet-wide SLOs; tuned consults global tiers).

Suppression: ``# commlint: allow(tenantscope)`` on the call line,
for a deliberate daemon-global action (e.g. draining every scope at
shutdown).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..report import Severity
from . import COMMLINT, LintRule

#: Callees acting on a scope-keyed shared surface. Each takes (or
#: defaults) a scope; the rule demands the argument list show which.
SCOPED_CALLEES = frozenset({
    "seed_scope", "gc_scope", "is_denied", "note_read", "set_target",
    "note_violation",
})

#: Identifier substrings that count as scope evidence.
_EVIDENCE = ("tenant", "scope", "cid")


def _has_scope_evidence(call: ast.Call) -> bool:
    # only the ARGUMENTS count as evidence — the callee attribute
    # itself (``LEDGER.seed_scope``) always mentions "scope" and must
    # not vouch for the call it names
    for kw in call.keywords:
        if kw.arg and any(e in kw.arg.lower() for e in _EVIDENCE):
            return True
    for root in list(call.args) + [kw.value for kw in call.keywords]:
        for sub in ast.walk(root):
            if isinstance(sub, ast.Call):
                fn = sub.func
                callee = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else "")
                if callee == "tenant_scope":
                    return True
            if isinstance(sub, ast.Name) and any(
                    e in sub.id.lower() for e in _EVIDENCE):
                return True
            if isinstance(sub, ast.Attribute) and any(
                    e in sub.attr.lower() for e in _EVIDENCE):
                return True
    return False


@COMMLINT.register
class TenantScopeRule(LintRule):
    NAME = "tenantscope"
    PRIORITY = 16
    DESCRIPTION = ("daemon code touching scope-keyed shared state must "
                   "name the tenant scope it acts for")
    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable:
        parts = ctx.relpath.replace("\\", "/").split("/")
        if "daemon" not in parts:
            return
        for node in ctx.walk():
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            callee = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if callee not in SCOPED_CALLEES:
                continue
            if _has_scope_evidence(node):
                continue
            if ctx.suppressed(node.lineno, self.NAME):
                continue
            yield self.finding(
                ctx, node,
                f"daemon call {callee}() names no tenant scope — the "
                "ledger/cache/SLO surfaces are scope-keyed and an "
                "unscoped call here acts globally (one tenant's fault "
                "or metering bleeding across the bulkhead); pass "
                "tenant_scope(t) / str(comm.cid), or allow() a "
                "deliberate daemon-global action",
            )

"""Schedule-cutoff hygiene rule.

``schedcutoff``: algorithm-selection code must not grow new hard-coded
byte thresholds. Since the schedule compiler landed (coll/sched/), the
single sanctioned home for static size cutoffs is ``sched/priors.py``
— the cold-start prior the autotuner's cache overrides. A literal
``nbytes < 65536``-style compare inside a ``decide_*`` / ``prior_*`` /
``pick_*`` function anywhere else in coll/ is a tuning decision the
cache can never learn past: it silently wins over measured winners and
drifts out of sync with the bucket boundaries the cache keys on.

Flagged: comparisons of a bytes/size-named value against an integer
literal (including const folds like ``64 << 10``) inside an
algorithm-pick function under coll/, outside sched/priors.py.
Cvar-backed thresholds (``_small.value``) are fine — those are
operator-tunable, not hard-coded. Legacy tables predating the rule
carry ``# commlint: allow(schedcutoff)``.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..report import Severity
from . import COMMLINT, LintRule, scope_walk

#: Function-name prefixes that mark an algorithm-pick scope.
_PICK_PREFIXES = ("decide", "prior", "pick", "choose", "select_algo")

#: Smallest literal treated as a byte threshold — filters out rank
#: counts and loop bounds that share the compare shape.
_MIN_THRESHOLD = 512

#: Identifier substrings that mark the compared value as a byte size.
_SIZE_MARKERS = ("byte", "size", "msglen")


def _const_int(node: ast.AST) -> Optional[int]:
    """Fold an integer-literal expression: 4096, 64 << 10, 4 * 1024."""
    if isinstance(node, ast.Constant):
        v = node.value
        return v if isinstance(v, int) and not isinstance(v, bool) \
            else None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_int(node.operand)
        return -v if v is not None else None
    if isinstance(node, ast.BinOp):
        lhs, rhs = _const_int(node.left), _const_int(node.right)
        if lhs is None or rhs is None:
            return None
        if isinstance(node.op, ast.LShift):
            return lhs << rhs
        if isinstance(node.op, ast.Mult):
            return lhs * rhs
        if isinstance(node.op, ast.Add):
            return lhs + rhs
        if isinstance(node.op, ast.Sub):
            return lhs - rhs
        if isinstance(node.op, ast.Pow) and 0 <= rhs < 64:
            return lhs ** rhs
    return None


def _is_size_expr(node: ast.AST) -> bool:
    """True when the expression reads like a byte count: any Name or
    Attribute whose identifier mentions bytes/size."""
    for sub in ast.walk(node):
        ident = None
        if isinstance(sub, ast.Name):
            ident = sub.id
        elif isinstance(sub, ast.Attribute):
            ident = sub.attr
        if ident and any(m in ident.lower() for m in _SIZE_MARKERS):
            return True
    return False


def _in_coll(relpath: str) -> bool:
    p = "/" + relpath
    return "/coll/" in p and not p.endswith("/sched/priors.py")


@COMMLINT.register
class SchedCutoffRule(LintRule):
    NAME = "schedcutoff"
    PRIORITY = 45
    DESCRIPTION = ("hard-coded byte-threshold algorithm picks in coll/ "
                   "belong in sched/priors.py (the tuner's cold-start "
                   "prior), not inline")
    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable:
        if not _in_coll(ctx.relpath):
            return
        for fn in ctx.walk():
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if not fn.name.lstrip("_").startswith(_PICK_PREFIXES):
                continue
            for node in scope_walk(fn):
                if not isinstance(node, ast.Compare):
                    continue
                operands = [node.left, *node.comparators]
                lits = [v for n in operands
                        if (v := _const_int(n)) is not None]
                if not lits or max(lits) < _MIN_THRESHOLD:
                    continue
                if not any(_is_size_expr(n) for n in operands
                           if _const_int(n) is None):
                    continue
                if ctx.suppressed(node.lineno, self.NAME):
                    continue
                yield self.finding(
                    ctx, node,
                    f"hard-coded byte threshold ({max(lits)}) in "
                    f"algorithm pick `{fn.name}` — move the cutoff to "
                    "sched/priors.py (cold-start prior) or a cvar so "
                    "the schedule cache can override it",
                )

"""Retune-audit rule.

``retuneaudit``: the schedule winner cache is the control plane's only
mutable decision state — every ``put()`` or version-``bump()`` changes
which algorithm future collectives run. An install site that emits no
trace instant and bumps no SPC counter is invisible: the flight
recorder shows the algorithm switching with no ``sched.retune`` /
``sched.tune_winner`` event explaining why, and the Prometheus side
shows ``sched_retunes`` flat while behaviour changed. This rule keeps
the evidence contract: each cache-install scope must also carry a
span/instant emission or an SPC record.

Evidence that satisfies the rule, anywhere in the same scope as the
install call: a call named ``instant``/``span``/``record``/
``record_latency``.

Suppression: ``# commlint: allow(retuneaudit)`` on or above the call
line, for deliberately silent installs (test fixtures seeding a cache,
load paths replaying already-evidenced decisions).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..report import Severity
from . import COMMLINT, LintRule, call_name, scope_walk, scopes

#: Attribute-call names that install/replace a cache winner.
_INSTALL_CALLS = frozenset({"put", "bump"})

#: Call names that count as audit evidence inside the same scope.
_EVIDENCE_CALLS = frozenset({
    "instant", "span", "record", "record_latency",
})


def _receiver_chain(node: ast.AST) -> Optional[str]:
    """Dotted receiver of an attribute call: ``_cache.CACHE.bump(...)``
    -> ``_cache.CACHE``. None for non-dotted shapes."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def _is_cache_receiver(chain: str) -> bool:
    """True when the dotted receiver names the schedule cache (the
    ``CACHE`` singleton or a ``*cache`` binding) — modex/osc/pgas
    ``put()`` surfaces never match."""
    last = chain.rsplit(".", 1)[-1]
    return "CACHE" in chain.split(".") or last.lower().endswith("cache")


def _install_calls(scope: ast.AST) -> Iterable[ast.Call]:
    for node in scope_walk(scope):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute):
            continue
        if node.func.attr not in _INSTALL_CALLS:
            continue
        chain = _receiver_chain(node.func.value)
        if chain is not None and _is_cache_receiver(chain):
            yield node


def _has_evidence(scope: ast.AST) -> bool:
    for node in scope_walk(scope):
        if call_name(node) in _EVIDENCE_CALLS:
            return True
    return False


@COMMLINT.register
class RetuneAuditRule(LintRule):
    NAME = "retuneaudit"
    PRIORITY = 41
    DESCRIPTION = ("schedule-cache put()/bump() sites must emit trace "
                   "or SPC evidence in the same scope")
    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable:
        for scope, _is_module in scopes(ctx.tree):
            installs = list(_install_calls(scope))
            if not installs:
                continue
            if _has_evidence(scope):
                continue
            for call in installs:
                if ctx.suppressed(call.lineno, self.NAME):
                    continue
                yield self.finding(
                    ctx, call,
                    f"cache {call.func.attr}() installs a schedule "
                    "winner with no adjacent trace instant or SPC "
                    "record — the algorithm switch leaves no audit "
                    "trail; emit a sched.* instant or count the "
                    "install (or annotate commlint: allow(retuneaudit))",
                )

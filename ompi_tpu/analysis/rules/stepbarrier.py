"""Step-boundary barrier rule.

``stepbarrier``: since slipstream (coll/sched/slipstream) pipelines
compiled step programs across the step boundary, training-loop code
under ``parallel/`` should keep the window open across consecutive
steps — ``step()`` per step, ``flush()`` at window close — instead of
fully draining between them. A ``finish()``/``wait_all()`` (or a raw
``wait``/``bcast`` tail drain) sitting between two consecutive
``begin_step()`` dispatches recreates the PR 16 barrier: step N's
merged broadcast tail is paid exposed, where the window would hide it
under step N+1's backward (and elide resident shards' allgathers
outright).

The rule flags full-drain calls between consecutive step dispatches in
one scope — a ``begin_step ... drain ... begin_step`` straight line, or
a loop body that both dispatches a step and drains it — when the scope
shows no window evidence: an identifier mentioning ``flush``,
``window`` or ``slipstream``.

Suppression: ``# commlint: allow(stepbarrier)`` on the drain call (or
the loop's / enclosing function's first line), for loops that are
deliberately barriered (comparison arms, single-step tools).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..report import Severity
from . import COMMLINT, LintRule, call_name
from .overlapready import _scope_walk

#: Calls that fully drain a step at its boundary.
_DRAINS = frozenset({"finish", "wait_all", "wait", "bcast"})

#: Identifier substrings that count as window evidence.
_EVIDENCE_WORDS = ("flush", "window", "slipstream")


def _idents(node: ast.AST) -> Iterable[str]:
    if isinstance(node, ast.Name):
        yield node.id
    elif isinstance(node, ast.Attribute):
        yield node.attr
    elif isinstance(node, (ast.Import, ast.ImportFrom)):
        for alias in node.names:
            yield alias.name


def _has_window_evidence(scope: ast.AST) -> bool:
    for node in _scope_walk(scope):
        for ident in _idents(node):
            low = ident.lower()
            if any(w in low for w in _EVIDENCE_WORDS):
                return True
    return False


def _ordered_calls(scope: ast.AST) -> list:
    calls = [n for n in _scope_walk(scope) if isinstance(n, ast.Call)]
    return sorted(calls, key=lambda c: (c.lineno, c.col_offset))


@COMMLINT.register
class StepBarrierRule(LintRule):
    NAME = "stepbarrier"
    PRIORITY = 47
    DESCRIPTION = ("full drains between consecutive step dispatches "
                   "under parallel/ recreate the step-boundary barrier "
                   "— window sessions step()/flush() instead")
    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable:
        rel = ctx.relpath.replace("\\", "/")
        if "parallel/" not in rel:
            return
        scopes = [ctx.tree] + [
            n for n in ctx.walk()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            if _has_window_evidence(scope):
                continue
            flagged: dict = {}   # id(call) -> call, insertion-ordered
            for call in self._straight_line(scope):
                flagged.setdefault(id(call), call)
            for call in self._loops(ctx, scope):
                flagged.setdefault(id(call), call)
            yield from self._flag(ctx, scope, flagged.values())

    def _straight_line(self, scope) -> Iterable:
        """begin_step ... drain ... begin_step in program order."""
        seen_begin = False
        pending: list = []
        for call in _ordered_calls(scope):
            name = call_name(call)
            if name == "begin_step":
                if seen_begin and pending:
                    yield from pending
                seen_begin = True
                pending = []
            elif seen_begin and name in _DRAINS:
                pending.append(call)

    def _loops(self, ctx, scope) -> Iterable:
        """A loop body that both dispatches a step and drains it runs
        consecutive steps with a barrier between every pair."""
        for node in _scope_walk(scope):
            if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                continue
            names = {}
            for n in ast.walk(node):
                if isinstance(n, ast.Call):
                    names.setdefault(call_name(n), []).append(n)
            if "begin_step" not in names:
                continue
            drains = [c for d in sorted(_DRAINS)
                      for c in names.get(d, ())]
            if not drains:
                continue
            if ctx.suppressed(node.lineno, self.NAME):
                continue
            yield from drains

    def _flag(self, ctx, scope, drains) -> Iterable:
        lines = []
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            lines.append(scope.lineno)
        if any(ctx.suppressed(ln, self.NAME) for ln in lines):
            return
        for call in drains:
            if ctx.suppressed(call.lineno, self.NAME):
                continue
            yield self.finding(
                ctx, call,
                f"{call_name(call)}() fully drains the step between "
                "consecutive begin_step() dispatches with no "
                "window/flush evidence in scope — the slipstream "
                "window (parallel/overlap window >= 2, or "
                "dp.window_session) hides the broadcast tail under "
                "the next backward; pipeline with step()/flush() (or "
                "annotate commlint: allow(stepbarrier))",
            )

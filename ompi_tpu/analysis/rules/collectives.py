"""Collective-schedule rules (the GC3 idea: schedules are programs).

``colldiv``: collective call sequences that diverge across
rank-dependent branches. MPI requires every rank of a communicator to
issue the same collective sequence; an ``if rank == 0:`` branch whose
body calls a different collective sequence than its else-branch (or
calls collectives with no else at all) deadlocks the job. Only the
operation sequence is compared — differing root/op ARGUMENTS across
ranks are legal.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..report import Severity
from . import COMMLINT, COLL_OPS, LintRule, call_name

_RANK_WORDS = ("rank", "process_index", "pid", "proc_id")


def _mentions_rank(test: ast.AST) -> bool:
    for node in ast.walk(test):
        ident = None
        if isinstance(node, ast.Name):
            ident = node.id
        elif isinstance(node, ast.Attribute):
            ident = node.attr
        if ident is None:
            continue
        low = ident.lower()
        if any(w in low for w in _RANK_WORDS):
            return True
    return False


def _coll_sequence(stmts: list[ast.stmt]) -> list[str]:
    """Collective op names in program order across the statement list,
    descending into nested control flow but not nested functions."""
    out: list[str] = []

    class V(ast.NodeVisitor):
        def visit_Call(self, node: ast.Call) -> None:
            fn = call_name(node)
            if fn in COLL_OPS:
                out.append(fn)
            self.generic_visit(node)

        def visit_FunctionDef(self, node) -> None:
            pass  # separate schedule

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node) -> None:
            pass

    v = V()
    for s in stmts:
        v.visit(s)
    return out


@COMMLINT.register
class CollectiveDivergenceRule(LintRule):
    NAME = "colldiv"
    PRIORITY = 75
    DESCRIPTION = ("collective call sequences must not diverge across "
                   "rank-dependent branches")
    SEVERITY = Severity.ERROR

    def check(self, ctx) -> Iterable:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.If):
                continue
            if not _mentions_rank(node.test):
                continue
            if ctx.suppressed(node.lineno, self.NAME):
                continue
            body = _coll_sequence(node.body)
            orelse = _coll_sequence(node.orelse)
            if body == orelse:
                continue
            # An early return/raise/abort branch is a legitimate exit —
            # collectives after it are unreachable for those ranks only
            # if the job is ending anyway.
            yield self.finding(
                ctx, node,
                "collective sequence diverges across a rank-dependent "
                f"branch: if-side {body or ['<none>']} vs else-side "
                f"{orelse or ['<none>']} — ranks will block in "
                "different collectives (deadlock)",
            )

"""Collective-schedule rules (the GC3 idea: schedules are programs).

``colldiv``: collective call sequences that diverge across
rank-dependent branches. MPI requires every rank of a communicator to
issue the same collective sequence; an ``if rank == 0:`` branch whose
body calls a different collective sequence than its else-branch (or
calls collectives with no else at all) deadlocks the job. Only the
operation sequence is compared — differing root/op ARGUMENTS across
ranks are legal.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from ..report import Severity
from . import COMMLINT, COLL_OPS, LintRule, call_name

#: Identifier *words* that mark a rank-dependent value.  Matching is by
#: word, not substring: ``nranks``/``world_size`` are sizes, the same
#: on every rank, and must not trip the rule.
_RANK_WORDS = frozenset({"rank", "pid"})
#: Multi-word identifiers matched whole.
_RANK_IDENTS = frozenset({"process_index", "proc_id"})
#: Word-set spellings that are sizes, never a rank (``my_nranks`` etc.
#: never exist, but ``local_rank_count`` would: ``count``/``size``/
#: ``n``-prefixed words veto the rank reading of that identifier).
_SIZE_WORDS = frozenset({"nranks", "size", "count", "num", "n"})

_WORD_SPLIT_RE = re.compile(r"[a-z0-9]+")

#: Receiver name words that look communicator-shaped — only calls like
#: ``comm.allgather(...)`` count as collectives; ``ir.allgather(...)``
#: builds schedule IR and ``fleet.gather(...)`` sweeps a KV store.
_COMM_WORDS = frozenset({"comm", "communicator", "world", "self"})


def _ident_words(ident: str) -> list[str]:
    s = re.sub(r"([a-z0-9])([A-Z])", r"\1_\2", ident)
    return _WORD_SPLIT_RE.findall(s.lower())


def _is_rank_ident(ident: str) -> bool:
    if ident.lower() in _RANK_IDENTS:
        return True
    words = _ident_words(ident)
    return bool(_RANK_WORDS.intersection(words)) \
        and not _SIZE_WORDS.intersection(words)


def _mentions_rank(test: ast.AST) -> bool:
    for node in ast.walk(test):
        ident = None
        if isinstance(node, ast.Name):
            ident = node.id
        elif isinstance(node, ast.Attribute):
            ident = node.attr
        if ident is not None and _is_rank_ident(ident):
            return True
    return False


def _comm_receiver(node: ast.Call) -> bool:
    """True when the callee's receiver plausibly is a communicator:
    a bare name (``allreduce(...)``), ``self``, or a dotted chain whose
    terminal name reads communicator-ish (``comm``, ``self.comm``,
    ``world_comm``).  IR builders (``ir.allgather``) and non-comm
    objects (``fleet.gather``) stay out of the sequence."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return True
    if not isinstance(fn, ast.Attribute):
        return False
    recv = fn.value
    if isinstance(recv, ast.Name):
        ident = recv.id
    elif isinstance(recv, ast.Attribute):
        ident = recv.attr
    elif isinstance(recv, ast.Call):
        ident = call_name(recv) or ""
    else:
        return False
    return bool(_COMM_WORDS.intersection(_ident_words(ident)))


def _coll_sequence(stmts: list[ast.stmt]) -> list[str]:
    """Collective op names in program order across the statement list,
    descending into nested control flow but not nested functions."""
    out: list[str] = []

    class V(ast.NodeVisitor):
        def visit_Call(self, node: ast.Call) -> None:
            fn = call_name(node)
            if fn in COLL_OPS and _comm_receiver(node):
                out.append(fn)
            self.generic_visit(node)

        def visit_FunctionDef(self, node) -> None:
            pass  # separate schedule

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node) -> None:
            pass

    v = V()
    for s in stmts:
        v.visit(s)
    return out


@COMMLINT.register
class CollectiveDivergenceRule(LintRule):
    NAME = "colldiv"
    PRIORITY = 75
    DESCRIPTION = ("collective call sequences must not diverge across "
                   "rank-dependent branches")
    SEVERITY = Severity.ERROR

    def check(self, ctx) -> Iterable:
        for node in ctx.walk():
            if not isinstance(node, ast.If):
                continue
            if not _mentions_rank(node.test):
                continue
            if ctx.suppressed(node.lineno, self.NAME):
                continue
            body = _coll_sequence(node.body)
            orelse = _coll_sequence(node.orelse)
            if body == orelse:
                continue
            # An early return/raise/abort branch is a legitimate exit —
            # collectives after it are unreachable for those ranks only
            # if the job is ending anyway.
            yield self.finding(
                ctx, node,
                "collective sequence diverges across a rank-dependent "
                f"branch: if-side {body or ['<none>']} vs else-side "
                f"{orelse or ['<none>']} — ranks will block in "
                "different collectives (deadlock)",
            )

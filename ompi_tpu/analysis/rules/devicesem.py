"""Device-semaphore rule.

``devicesem``: a Pallas kernel under ``coll/`` that issues remote DMAs
(``pltpu.make_async_remote_copy``) owns real hardware state — DMA
semaphores the copy signals on completion. Three ways that state goes
wrong, each a silent-corruption or deadlock bug on the chip that no
CPU test can catch:

- a copy is **started but never waited**: the kernel exits with the
  DMA in flight and the next collective on the same ``collective_id``
  inherits a half-signalled semaphore;
- a copy is waited **only on some control-flow paths** (a wait inside
  an ``if`` whose condition doesn't also gate the start): the
  untaken path leaks the in-flight copy;
- the kernel takes no **DMA semaphore scratch** at all
  (``scratch_shapes`` with ``pltpu.SemaphoreType.DMA``): the copy has
  nowhere safe to signal.

The rule is deliberately scoped to ``coll/`` files — the only place
device kernels live — and to the documented Mosaic spelling, so
host-side request code never matches.

Suppression: ``# commlint: allow(devicesem)`` on or above the line,
for kernels that hand the wait to a helper the AST walk can't see
through.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..report import Severity
from . import COMMLINT, LintRule, call_name, scope_walk, scopes

_MAKER = "make_async_remote_copy"

#: Completion spellings: full wait, or the split-phase send/recv halves
#: (a kernel may legitimately wait only its half — the sender drains
#: send_sem, the matched receiver drains recv_sem).
_WAITS = frozenset({"wait", "wait_send", "wait_recv"})


def _attr_calls_on(scope: ast.AST, name: str,
                   attrs: frozenset) -> list[ast.Call]:
    """Every ``name.<attr>()`` call in the scope (document order)."""
    out = []
    for node in scope_walk(scope):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in attrs \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == name:
            out.append(node)
    return out


def _contains(root: ast.AST, target: ast.AST) -> bool:
    return any(n is target for n in ast.walk(root))


def _is_none_guard(test: ast.AST, handle: str) -> bool:
    """``if handle is not None:`` — the guard is exactly "was the copy
    started", so a wait under it cannot leak an in-flight DMA."""
    return (isinstance(test, ast.Compare)
            and isinstance(test.left, ast.Name)
            and test.left.id == handle
            and len(test.ops) == 1
            and isinstance(test.ops[0], ast.IsNot)
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None)


def _conditional_only(scope: ast.AST, handle: str, waits: list[ast.Call],
                      starts: list[ast.Call]) -> Optional[ast.Call]:
    """The first wait that sits under an ``if`` which neither contains
    every start nor null-guards the handle — i.e. some path starts the
    copy but skips the wait. None when at least one wait covers every
    started path."""
    ifs = [n for n in scope_walk(scope) if isinstance(n, ast.If)]
    flagged = None
    for w in waits:
        guarded = [i for i in ifs if _contains(i, w)]
        # balanced pairings: an If that also contains all the starts
        # gates the whole copy; an `is not None` guard on the handle
        # is the started-at-all test itself
        guarded = [i for i in guarded
                   if not all(_contains(i, s) for s in starts)
                   and not _is_none_guard(i.test, handle)]
        if not guarded:
            return None  # this wait covers every started path
        flagged = flagged or w
    return flagged


@COMMLINT.register
class DeviceSemRule(LintRule):
    NAME = "devicesem"
    PRIORITY = 44
    DESCRIPTION = ("coll/ Pallas kernels must take DMA-semaphore "
                   "scratch and wait every started remote copy on "
                   "all control-flow paths")
    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable:
        if not ctx.relpath.startswith("coll/"):
            return
        makers = [n for n in ctx.walk()
                  if isinstance(n, ast.Call) and call_name(n) == _MAKER]
        if not makers:
            return
        # file-level: somewhere a pallas_call must allocate DMA
        # semaphores in scratch_shapes for these copies to signal on
        has_dma_scratch = any(
            isinstance(n, ast.Call) and any(
                k.arg == "scratch_shapes" and any(
                    isinstance(a, ast.Attribute) and a.attr == "DMA"
                    for a in ast.walk(k.value))
                for k in n.keywords)
            for n in ctx.walk())
        if not has_dma_scratch:
            first = makers[0]
            if not ctx.suppressed(first.lineno, self.NAME):
                yield self.finding(
                    ctx, first,
                    "file issues make_async_remote_copy but no "
                    "pallas_call allocates DMA semaphores in "
                    "scratch_shapes (pltpu.SemaphoreType.DMA) — the "
                    "copies have no completion semaphore to signal "
                    "(or annotate commlint: allow(devicesem))",
                )
        for scope, _is_module in scopes(ctx.tree):
            for node in scope_walk(scope):
                if not isinstance(node, ast.Call):
                    continue
                # fire-and-forget: make_async_remote_copy(...).start()
                # leaves no handle to wait on
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "start" \
                        and call_name(node.func.value) == _MAKER:
                    if not ctx.suppressed(node.lineno, self.NAME):
                        yield self.finding(
                            ctx, node,
                            "remote copy started without binding the "
                            "handle — nothing can wait this DMA; bind "
                            "it and wait both semaphores (or annotate "
                            "commlint: allow(devicesem))",
                        )
                    continue
                if call_name(node) != _MAKER:
                    continue
                # bound handle: X = make_async_remote_copy(...)
                assign = next(
                    (a for a in scope_walk(scope)
                     if isinstance(a, ast.Assign) and a.value is node
                     and len(a.targets) == 1
                     and isinstance(a.targets[0], ast.Name)), None)
                if assign is None:
                    continue  # non-Name binding: the .start() check
                    # above still covers the chained spelling
                handle = assign.targets[0].id
                starts = _attr_calls_on(scope, handle,
                                        frozenset({"start"}))
                waits = _attr_calls_on(scope, handle, _WAITS)
                if starts and not waits:
                    if not ctx.suppressed(node.lineno, self.NAME):
                        yield self.finding(
                            ctx, node,
                            f"remote copy {handle!r} is start()ed but "
                            "never wait()ed in this scope — the kernel "
                            "can exit with the DMA in flight (or "
                            "annotate commlint: allow(devicesem))",
                        )
                    continue
                if starts and waits:
                    cond = _conditional_only(scope, handle, waits,
                                             starts)
                    if cond is not None \
                            and not ctx.suppressed(cond.lineno,
                                                   self.NAME):
                        yield self.finding(
                            ctx, cond,
                            f"remote copy {handle!r} is waited only "
                            "inside a conditional that does not gate "
                            "its start — the untaken path leaks an "
                            "in-flight DMA (or annotate commlint: "
                            "allow(devicesem))",
                        )

"""Fast-path latency-hygiene rule.

``fastsleep``: a constant ``time.sleep(<c>)`` on the small-message fast
path (btl/sm, the pml engine, the progress pump, coll/sm) puts a fixed
latency floor under every message that crosses it — the exact failure
mode the fastpath rework removed (a single 1 ms park was ~30x the
whole-descriptor-hop budget). Unlike ``polldeadline`` this rule is not
about unbounded loops: even a deadline-bounded constant sleep is wrong
here. Fast-path waits must ride an event primitive — the shm doorbell
(``wait_event``), the fastpath ring futex (``fp_recv``/``fp_sendrecv``),
a condition variable, or ``core.backoff.Backoff`` whose delays grow
from a sub-millisecond floor.

Suppression: ``# commlint: allow(fastsleep)`` on the sleep line, for
the rare wait that genuinely models elapsed wall time (fault drills).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..report import Severity
from . import COMMLINT, LintRule
from .polling import _is_time_sleep, _sleep_const

#: Modules on the small-message hot path. Matched against the
#: '/'-normalised repo-relative path, so both repo-root and package-root
#: lint invocations agree.
_FAST_PATH = (
    "btl/sm.py",
    "core/progress.py",
    "coll/smcoll.py",
)
_FAST_PATH_DIRS = ("pml/",)


def _on_fast_path(relpath: str) -> bool:
    p = relpath.replace("\\", "/")
    if any(p.endswith(suffix) for suffix in _FAST_PATH):
        return True
    return any(f"/{d}" in p or p.startswith(d) for d in _FAST_PATH_DIRS)


@COMMLINT.register
class FastPathSleepRule(LintRule):
    NAME = "fastsleep"
    PRIORITY = 54  # right below polldeadline: same family, narrower scope
    DESCRIPTION = ("no constant time.sleep on the sm/pml fast path — "
                   "wait on the doorbell/futex primitives instead")
    SEVERITY = Severity.ERROR

    def check(self, ctx) -> Iterable:
        if not _on_fast_path(ctx.relpath):
            return
        for node in ctx.walk():
            if not _is_time_sleep(node):
                continue
            val = _sleep_const(node)
            if val is None or val <= 0:
                continue  # dynamic delays and yields are polldeadline's
            if ctx.suppressed(node.lineno, self.NAME):
                continue
            yield self.finding(
                ctx, node,
                f"constant time.sleep({val!r}) on the small-message "
                "fast path adds a fixed latency floor to every message "
                "crossing it; park on the shm doorbell (wait_event), "
                "the fastpath ring futex, or core.backoff.Backoff",
            )

"""Health-probe coverage rule.

``healthseam``: a transport component registered at the btl/pml
selection seam (``@BTL.register`` / ``@PML.register`` /
``@MTL.register``) carries traffic the health supervisor is supposed
to keep alive — but a tier without a registered prober is invisible
to it: the ledger can quarantine it on in-band failures yet nothing
ever re-probes it back to HEALTHY, so one wedge silently downgrades
the job for its remaining lifetime (the exact BENCH_r03-r05 failure
the health subsystem exists to end).

Evidence that satisfies the rule, anywhere in the file: a call named
``register_probe`` / ``register_health_probe`` /
``register_health_probes`` — the component either registers its
canary directly or exposes the registration helper its wiring seam
calls.

Seam-file exemptions (the ``tracespan`` pattern): ``framework.py``
(the seams themselves), ``template.py`` (the documented skeleton),
and ``self.py``/``ici.py`` (in-process loopback — there is no
transport to die).

Suppression: ``# commlint: allow(healthseam)`` on the class line, for
components that deliberately delegate liveness to the engine they
ride (pml/ob1 and pml/cm sit on the fabric engine, whose probe covers
them).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..report import Severity
from . import COMMLINT, LintRule, call_name, tree_walk

#: Directories whose registered components the rule audits.
_SEAM_DIRS = ("btl/", "pml/")

#: Seam/skeleton files exempt from the requirement.
_EXEMPT_FILES = ("framework.py", "template.py", "self.py", "ici.py")

#: Call names that count as prober evidence inside a file.
_PROBE_CALLS = frozenset({
    "register_probe", "register_health_probe", "register_health_probes",
})

#: Framework attributes whose .register decorator marks a transport
#: component (coll components ride these, they don't carry bytes).
_TRANSPORT_FWS = frozenset({"BTL", "PML", "MTL"})


def _in_scope(relpath: str) -> bool:
    p = relpath.replace("\\", "/")
    if any(p.endswith(x) for x in _EXEMPT_FILES):
        return False
    return any(f"/{d}" in p or p.startswith(d) for d in _SEAM_DIRS)


def _registered_transport_classes(tree: ast.Module) -> list[ast.ClassDef]:
    out = []
    for node in tree_walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if isinstance(target, ast.Attribute) \
                    and target.attr == "register" \
                    and isinstance(target.value, ast.Name) \
                    and target.value.id in _TRANSPORT_FWS:
                out.append(node)
                break
    return out


def _has_probe_evidence(tree: ast.Module) -> bool:
    return any(call_name(n) in _PROBE_CALLS for n in tree_walk(tree))


@COMMLINT.register
class HealthSeamRule(LintRule):
    NAME = "healthseam"
    PRIORITY = 35
    DESCRIPTION = ("transport components registered at btl/pml "
                   "selection should register a health prober")
    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable:
        if not _in_scope(ctx.relpath):
            return
        classes = _registered_transport_classes(ctx.tree)
        if not classes:
            return
        if _has_probe_evidence(ctx.tree):
            return
        for cls in classes:
            if ctx.suppressed(cls.lineno, self.NAME):
                continue
            yield self.finding(
                ctx, cls,
                f"transport component {cls.name} registers at the "
                "selection seam but this file registers no health "
                "prober — a quarantined tier through it can never be "
                "background-restored; call health.prober."
                "register_probe at wiring (or allow() if liveness is "
                "delegated to the engine underneath)",
            )

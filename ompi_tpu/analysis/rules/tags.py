"""Tag-space rules.

``parttags``: part/persist re-blocks a partitioned pair's traffic into
a derived pml tag namespace — transfer k of user tag t travels as
``(t + 1) * part_persist_tag_stride + k`` (DESIGN.md §11). Plain p2p
traffic on the same communicator whose literal tag lands inside an
active derived band is matched against partitioned transfers and
silently corrupts both streams. The rule mirrors that arithmetic
statically: it collects the derived bands implied by every
Psend_init/Precv_init literal tag in the module and flags plain
send/recv-family tags that fall inside any band (and, more weakly, any
plain tag at or above the stride once partitioned communication is in
use at all).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ...core import config
from ..report import Severity
from . import (
    COMMLINT,
    LintRule,
    P2P_TAGGED,
    call_arg,
    call_name,
    const_int,
    scope_walk,
)

_PART_INITS = {
    # callee -> (positional index of tag, kw name)
    "psend_init": 3,
    "precv_init": 2,
    "Psend_init": 4,
    "Precv_init": 3,
}
_P2P_TAG_POS = {
    "send": 1, "isend": 1, "send_init": 1,
    "recv": 1, "irecv": 1, "recv_init": 1,
    "probe": 1, "iprobe": 1, "improbe": 1,
    "sendrecv": 3,
}


def _tag_stride() -> int:
    try:
        from ...part import persist  # noqa: F401 - registers the cvar
    except ImportError:
        pass
    return int(config.get("part_persist_tag_stride", 4096) or 4096)


@COMMLINT.register
class PartTagCollisionRule(LintRule):
    NAME = "parttags"
    PRIORITY = 80
    DESCRIPTION = ("plain p2p tags must stay clear of part/persist's "
                   "derived tag namespace")
    SEVERITY = Severity.ERROR

    def check(self, ctx) -> Iterable:
        stride = _tag_stride()
        part_tags: list[int] = []
        plain: list[tuple[ast.Call, str, int]] = []
        for node in ctx.walk():
            if not isinstance(node, ast.Call):
                continue
            fn = call_name(node)
            if fn in _PART_INITS:
                t = const_int(call_arg(node, _PART_INITS[fn], "tag"))
                part_tags.append(0 if t is None else t)
            elif fn in P2P_TAGGED:
                t = const_int(
                    call_arg(node, _P2P_TAG_POS.get(fn, 1), "tag")
                )
                if t is not None and t >= 0:
                    plain.append((node, fn, t))
        if not part_tags:
            return
        bands = sorted(
            ((t + 1) * stride, (t + 2) * stride) for t in part_tags
        )
        for node, fn, t in plain:
            if ctx.suppressed(node.lineno, self.NAME):
                continue
            hit = next(
                ((lo, hi) for lo, hi in bands if lo <= t < hi), None
            )
            if hit is not None:
                yield self.finding(
                    ctx, node,
                    f"{fn}() tag {t} collides with part/persist's "
                    f"derived band [{hit[0]}, {hit[1]}) for partitioned "
                    f"user tag {hit[0] // stride - 1} — plain and "
                    "partitioned traffic will cross-match",
                )
            elif t >= stride:
                yield self.finding(
                    ctx, node,
                    f"{fn}() tag {t} is inside the derived tag "
                    f"namespace (>= part_persist_tag_stride {stride}) "
                    "while partitioned communication is in use — keep "
                    f"user tags below {stride}",
                    severity=Severity.WARNING,
                )

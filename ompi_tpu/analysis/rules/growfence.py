"""Grow-fence rule.

``growfence``: the elastic pipelines (lifeboat's shrink, lazarus'
grow) keep the fleet safe across membership changes only if every code
path that constructs or resizes a communicator is fenced by the epoch
machinery — a comm built from a revoked parent, or handed out without
the epoch bump/check, re-opens exactly the split-brain window the
wire-tag epoch namespace exists to close (a straggling pre-change op
could rendezvous with the new membership's traffic). The rule flags
function scopes under ``ft/`` and ``daemon/`` that construct or resize
communicators (``Communicator(...)``, ``.dup()``, ``.create(...)``,
``.split(...)``) with no epoch-fence evidence in the same scope.

Evidence that satisfies the rule, anywhere in the scope: a call named
``check``/``revoked``/``_check_alive``/``_fence_check``/``epoch_tag``,
or any identifier mentioning ``epoch`` or ``revok`` (reading
``comm.epoch`` for the bump or the log line, handling
``RevokedError``, consulting ``_revoked``).

Suppression: ``# commlint: allow(growfence)`` on or above the
constructing call (or the enclosing def), for construction sites whose
fence provably lives in the caller.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..report import Severity
from . import COMMLINT, LintRule, call_name, scope_walk, scopes

#: Call names that construct or resize a communicator.
_CONSTRUCTING = frozenset({"Communicator", "dup", "create", "split"})

#: Call names that count as epoch-fence evidence.
_EVIDENCE_CALLS = frozenset({
    "check", "revoked", "_check_alive", "_fence_check", "epoch_tag",
})

#: Identifier substrings that count as evidence (``comm.epoch``,
#: ``RevokedError``, ``_revoked``, ``epoch_tag``...).
_EVIDENCE_WORDS = ("epoch", "revok")


def _idents(node: ast.AST) -> Iterable[str]:
    if isinstance(node, ast.Name):
        yield node.id
    elif isinstance(node, ast.Attribute):
        yield node.attr
    elif isinstance(node, ast.ExceptHandler) and node.type is not None:
        for sub in ast.walk(node.type):
            yield from _idents(sub)


def _has_evidence(scope: ast.AST) -> bool:
    for node in scope_walk(scope):
        if isinstance(node, ast.Call):
            if call_name(node) in _EVIDENCE_CALLS:
                return True
            # reflective probes: getattr(comm, "_revoked", False)
            if call_name(node) in ("getattr", "hasattr", "setattr"):
                for arg in node.args:
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, str) \
                            and any(w in arg.value.lower()
                                    for w in _EVIDENCE_WORDS):
                        return True
        for ident in _idents(node):
            low = ident.lower()
            if any(w in low for w in _EVIDENCE_WORDS):
                return True
    return False


def _constructing_calls(scope: ast.AST) -> list[ast.Call]:
    out = []
    for node in scope_walk(scope):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name not in _CONSTRUCTING:
            continue
        # 'create'/'dup'/'split' must be attribute calls on something
        # comm-like; a bare create() name is some other factory
        if name != "Communicator" \
                and not isinstance(node.func, ast.Attribute):
            continue
        # string-literal arguments mean str.split(",") or a name-keyed
        # factory (ShmLane.create(f"...")) — not a communicator op,
        # which takes ranks/colors
        if name in ("create", "split") and any(
            isinstance(a, ast.JoinedStr)
            or (isinstance(a, ast.Constant) and isinstance(a.value, str))
            for a in node.args
        ):
            continue
        out.append(node)
    return out


@COMMLINT.register
class GrowFenceRule(LintRule):
    NAME = "growfence"
    PRIORITY = 43
    DESCRIPTION = ("communicator construction/resizing under "
                   "ft//daemon/ must show epoch-fence evidence in "
                   "the same scope")
    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable:
        rel = ctx.relpath.replace("\\", "/")
        if "ft/" not in rel and "daemon/" not in rel:
            return
        for scope, _is_module in scopes(ctx.tree):
            constructing = _constructing_calls(scope)
            if not constructing:
                continue
            if _has_evidence(scope):
                continue
            for call in constructing:
                anchor = getattr(scope, "lineno", call.lineno)
                if ctx.suppressed(anchor, self.NAME):
                    continue
                if ctx.suppressed(call.lineno, self.NAME):
                    continue
                yield self.finding(
                    ctx, call,
                    f"{call_name(call)}() constructs/resizes a "
                    "communicator with no epoch-fence evidence in "
                    "scope — a comm built from a revoked parent (or "
                    "handed out without the epoch bump) re-opens the "
                    "split-brain window; check revocation or stamp "
                    "the epoch here (or annotate commlint: "
                    "allow(growfence))",
                )

"""Virtual-clock seam coverage rule.

``simclock``: control-plane decision paths — the armada simulator
itself, the health subsystem (ledger cooldowns, prober scheduling,
sentinel deadlines), bulkhead QoS admission, and the telemetry
sampler — must read time through the ``core/clock`` seam
(``clock.monotonic`` / ``clock.sleep`` / ``clock.wait_event``), never
``time.time`` / ``time.monotonic`` / ``time.sleep`` directly. A
direct call is invisible to an installed ``SimClock``: under the
fleet simulator that code path would mix real seconds into a virtual
timeline, silently breaking both the time compression (a 10-minute
scenario stalls on real sleeps) and the same-seed replay contract (a
decision keyed on wall time differs across runs).

Meters stay real by design: ``time.perf_counter`` (phase timings,
events/s) and ``time.time_ns`` (sample timestamps — data, not
decisions) are not flagged.

Suppression: ``# commlint: allow(simclock)`` on the offending line,
for the rare path that genuinely wants wall time (e.g. the seam's own
default implementation).
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..report import Severity
from . import COMMLINT, LintRule

#: Path fragments whose files the rule audits (decision paths wired
#: through the core/clock seam).
_SCOPE_DIRS = ("sim/", "health/")
_SCOPE_FILES = ("daemon/qos.py", "telemetry/sampler.py")

#: ``time.<attr>`` calls that bypass the seam. perf_counter and
#: time_ns are meters/timestamps, deliberately absent.
_BANNED_ATTRS = frozenset({"time", "monotonic", "sleep"})

#: The seam module itself delegates to ``time`` when no sim clock is
#: installed — that is the one sanctioned direct caller.
_EXEMPT_FILES = ("core/clock.py",)


def _in_scope(relpath: str) -> bool:
    p = relpath.replace("\\", "/")
    if any(p.endswith(x) for x in _EXEMPT_FILES):
        return False
    if any(p.endswith(x) for x in _SCOPE_FILES):
        return True
    return any(f"/{d}" in p or p.startswith(d) for d in _SCOPE_DIRS)


def _banned_time_call(node: ast.AST):
    """The offending attr name when ``node`` is ``time.<banned>(...)``."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in _BANNED_ATTRS \
            and isinstance(fn.value, ast.Name) and fn.value.id == "time":
        return fn.attr
    return None


@COMMLINT.register
class SimClockRule(LintRule):
    NAME = "simclock"
    PRIORITY = 30
    DESCRIPTION = ("control-plane decision paths must read time "
                   "through the core/clock seam, not time.* directly")
    SEVERITY = Severity.WARNING

    def check(self, ctx) -> Iterable:
        if not _in_scope(ctx.relpath):
            return
        for node in ctx.walk():
            attr = _banned_time_call(node)
            if attr is None:
                continue
            if ctx.suppressed(node.lineno, self.NAME):
                continue
            yield self.finding(
                ctx, node,
                f"direct time.{attr}() in a clock-seam decision path "
                "— this is invisible to an installed SimClock and "
                "breaks virtual-time compression and same-seed "
                "replay; use core.clock."
                f"{'monotonic' if attr != 'sleep' else 'sleep'}() "
                "(or allow() if wall time is genuinely intended)",
            )

"""commlint — communication-correctness analysis (static + sanitizer).

Two cooperating halves, in the spirit of MPI correctness tooling
(MUST-style runtime match checking, MPI-Checker-style static
request-lifecycle analysis; see PAPERS.md — GC3 treats collective
schedules as analyzable programs, EQuARX motivates checking quant-tier
eligibility before dispatch):

- ``analysis.lint``: an AST- and schedule-level linter whose rules are
  MCA components (framework ``commlint``, selectable via the
  ``commlint_select`` cvar) walking user programs AND this framework
  itself. Findings ratchet against a checked-in baseline
  (``selfcheck_baseline.json``) so existing debt can only shrink.
- ``analysis.sanitizer``: an opt-in runtime that interposes on the
  pml/coll/part vtables and the request lifecycle, matching per-rank
  call sequences at barriers and Finalize — leaked requests, unmatched
  sends, derived-tag collisions, cross-rank collective-order
  divergence — reported through SPC pvars and a structured report.

CLI: ``python -m ompi_tpu.tools.lint <path>``.
"""

from .report import Baseline, Finding, Report, Severity

__all__ = ["Baseline", "Finding", "Report", "Severity"]

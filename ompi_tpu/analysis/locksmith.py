"""locksmith — whole-program concurrency analysis over a ProjectIndex.

The runtime is deeply threaded (overlap pump threads, the medic
Supervisor, the telemetry sampler, the daemon pump, slipstream's
cross-step tail drain) and the two worst shipped bugs were both lock
bugs the per-file linter could not see: a ledger->breaker lock-order
pin (PR 8) and a lost-combine race on an unguarded tally (PR 15).
locksmith graduates the analysis layer from per-file pattern lint to a
whole-program concurrency model:

- **lockset dataflow**: every function is scanned once for the locks
  it acquires (``with self._mu:`` regions, explicit
  ``acquire()``/``release()``), the calls it makes *while holding
  them*, and the ``self.x`` writes in each region.  Locksets propagate
  through the ProjectIndex call graph, so holding ``ledger._mu`` while
  calling into ``breaker.open_breaker`` (which takes ``breaker._mu``)
  produces a cross-module edge with the full call-chain witness.

- **lock-order graph + deadlock cycles** (commlint rule ``lockorder``,
  ERROR): a directed edge A->B means "B was acquired while A was
  held"; every elementary cycle is a potential deadlock, reported with
  the complete ``file:line`` acquire/call witness chain of each edge.

- **callback-under-lock** (rule ``cbunderlock``, WARNING): invoking a
  passed-in callable or a registered-callback attribute while holding
  a lock — the PR 8 class.  The fix idiom is the ledger's
  ``_drain_restored``: queue under the lock, fire after release.

- **guarded-by inference** (rule ``unguardedwrite``, WARNING): an
  attribute written under its class's lock at some sites and outside
  any lock at others is a data race candidate — the PR 15
  ``_tiles_reduced`` class.  The thread-spawn inventory names which
  spawned threads actually reach the attribute.

- **runtime lock witness**: the dynamic half (commsan's validation
  idiom applied to locks).  ``witness()`` interposes
  ``threading.Lock/RLock/Condition`` creation, records every
  actually-observed acquisition-order edge per thread, and at finalize
  reports runtime cycles plus static edges never witnessed — the
  static model is validated the same way commsan validates request
  lifecycles.

Everything is best-effort static analysis: unresolved receivers and
dynamic dispatch contribute nothing.  Intentional exceptions carry
``# commlint: allow(<rule>)`` with a justification, and the historical
remainder rides the per-rule:file ratchet baseline like every other
commlint rule.
"""

from __future__ import annotations

import ast
import os
import re
import sys
import threading
from dataclasses import dataclass, field
from typing import Iterable, Optional

from .report import Finding, Severity

#: Attribute/variable name words that mark a callable as a registered
#: callback/handler (the defer-outside-the-lock contract).
_CB_WORDS = frozenset({
    "cb", "cbs", "callback", "callbacks", "hook", "hooks", "handler",
    "handlers", "listener", "listeners", "subscriber", "subscribers",
    "observer", "observers", "watcher", "watchers",
})

_WORD_RE = re.compile(r"[a-z0-9]+")


def _name_words(ident: str) -> frozenset[str]:
    """snake_case/camelCase identifier -> lowercase word set
    (word-boundary matching: 'nranks' yields {'nranks'}, not 'rank')."""
    s = re.sub(r"([a-z0-9])([A-Z])", r"\1_\2", ident)
    return frozenset(_WORD_RE.findall(s.lower()))


def _is_cb_name(ident: str) -> bool:
    return bool(_name_words(ident) & _CB_WORDS)


# -- per-function scan ------------------------------------------------------


@dataclass(frozen=True)
class Frame:
    """One witness step: a source location plus what happened there."""

    relpath: str
    line: int
    what: str

    def render(self) -> str:
        return f"{self.relpath}:{self.line} ({self.what})"


@dataclass
class CallSite:
    callee: str                      # FuncInfo key
    frame: Frame
    held: dict[str, Frame]           # lock key -> acquire frame


@dataclass
class CbCall:
    desc: str                        # what was invoked
    frame: Frame
    held: dict[str, Frame]


@dataclass
class Write:
    attr: str                        # "module.Class.attr"
    frame: Frame
    held: frozenset[str]
    func: str                        # writing function key


@dataclass
class Summary:
    """What one function does with locks (intra-procedural facts)."""

    func: str
    acquires: dict[str, Frame] = field(default_factory=dict)
    edges: dict[tuple[str, str], list[Frame]] = field(default_factory=dict)
    calls: list[CallSite] = field(default_factory=list)
    writes: list[Write] = field(default_factory=list)
    cb_calls: list[CbCall] = field(default_factory=list)


class _Scan:
    """Lockset walker over one function body.

    Tracks the held-lock environment through ``with`` nesting and
    explicit acquire()/release() statements; branches are walked with
    the entry lockset (conservative: a branch cannot add to the
    lockset seen after the statement)."""

    def __init__(self, index, fi) -> None:
        self.index = index
        self.fi = fi
        self.sum = Summary(func=fi.key)
        self.tainted: set[str] = set()   # names bound from callback attrs
        self.params = set(fi.params)

    def run(self) -> Summary:
        self._body(self.fi.node.body, {})
        return self.sum

    # -- statement dispatch --------------------------------------------

    def _body(self, stmts, held: dict[str, Frame]) -> None:
        held = dict(held)   # acquire()/release() mutate locally
        for stmt in stmts:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._with(stmt, held)
            elif isinstance(stmt, ast.If):
                self._expr(stmt.test, held)
                self._body(stmt.body, held)
                self._body(stmt.orelse, held)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._taint_target(stmt.target, stmt.iter)
                self._expr(stmt.iter, held)
                self._body(stmt.body, held)
                self._body(stmt.orelse, held)
            elif isinstance(stmt, ast.While):
                self._expr(stmt.test, held)
                self._body(stmt.body, held)
                self._body(stmt.orelse, held)
            elif isinstance(stmt, ast.Try):
                self._body(stmt.body, held)
                for h in stmt.handlers:
                    self._body(h.body, held)
                self._body(stmt.orelse, held)
                self._body(stmt.finalbody, held)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue   # separate scope (indexed separately)
            elif isinstance(stmt, ast.Expr) and self._acquire_stmt(
                    stmt.value, held):
                continue
            else:
                if isinstance(stmt, ast.Assign):
                    self._taint_assign(stmt)
                self._writes(stmt, held)
                self._expr(stmt, held)

    def _with(self, stmt, held: dict[str, Frame]) -> None:
        new = dict(held)
        for item in stmt.items:
            self._expr(item.context_expr, held)
            li = self.index.resolve_lock(self.fi, item.context_expr)
            if li is None:
                continue
            key = li.resolved_key()
            frame = Frame(self.fi.relpath, item.context_expr.lineno,
                          f"acquire {key}")
            self._acquired(key, frame, new)
        self._body(stmt.body, new)

    def _acquire_stmt(self, value, held: dict[str, Frame]) -> bool:
        """Handle standalone ``x.acquire()`` / ``x.release()``; returns
        True when consumed (held mutated for the rest of this body)."""
        if not (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr in ("acquire", "release")):
            return False
        li = self.index.resolve_lock(self.fi, value.func.value)
        if li is None:
            return False
        key = li.resolved_key()
        if value.func.attr == "acquire":
            frame = Frame(self.fi.relpath, value.lineno, f"acquire {key}")
            self._acquired(key, frame, held)
        else:
            held.pop(key, None)
        return True

    def _acquired(self, key: str, frame: Frame,
                  held: dict[str, Frame]) -> None:
        self.sum.acquires.setdefault(key, frame)
        for hkey, hframe in held.items():
            if hkey != key:
                self.sum.edges.setdefault((hkey, key), [hframe, frame])
        held[key] = frame

    # -- expression scan (calls, callbacks) ----------------------------

    def _expr(self, node, held: dict[str, Frame]) -> None:
        for sub in self._expr_walk(node):
            if isinstance(sub, ast.Call):
                self._call(sub, held)

    @staticmethod
    def _expr_walk(node):
        """ast.walk without descending into nested defs/lambdas (their
        bodies execute later, under whatever locks *they* see)."""
        stack = [node]
        while stack:
            n = stack.pop()
            yield n
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(n))

    def _call(self, call: ast.Call, held: dict[str, Frame]) -> None:
        fn = call.func
        if isinstance(fn, ast.Attribute) and fn.attr in (
                "acquire", "release", "locked"):
            if self.index.resolve_lock(self.fi, fn.value) is not None:
                return   # lock ops inside expressions: not a call edge
        callee = self.index.resolve_call(self.fi, call)
        if callee is not None:
            self.sum.calls.append(CallSite(
                callee=callee.key,
                frame=Frame(self.fi.relpath, call.lineno,
                            f"call {callee.key}"),
                held=dict(held),
            ))
            return
        if not held:
            return
        desc = self._callback_desc(fn)
        if desc is not None:
            self.sum.cb_calls.append(CbCall(
                desc=desc,
                frame=Frame(self.fi.relpath, call.lineno,
                            f"invoke {desc}"),
                held=dict(held),
            ))

    def _callback_desc(self, fn) -> Optional[str]:
        """Non-None when the callee expression is callback-shaped:
        a passed-in callable parameter, a name bound from a registered
        callback collection, or a callback-named attribute."""
        if isinstance(fn, ast.Name):
            if fn.id in self.params and fn.id != "self":
                return f"passed-in callable {fn.id!r}"
            if fn.id in self.tainted or _is_cb_name(fn.id):
                return f"registered callback {fn.id!r}"
            return None
        if isinstance(fn, ast.Attribute) and _is_cb_name(fn.attr) \
                and not fn.attr[:1].isupper() \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "self":
            # self-receivers only: logging.StreamHandler(...) or
            # logger.addHandler(...) are constructors/registrations on
            # foreign objects, not registered-callback dispatch
            return f"callback attribute .{fn.attr}"
        if isinstance(fn, ast.Subscript):
            base = fn.value
            if isinstance(base, ast.Attribute) and _is_cb_name(base.attr):
                return f"callback table .{base.attr}[...]"
            if isinstance(base, ast.Name) and (
                    base.id in self.tainted or _is_cb_name(base.id)):
                return f"callback table {base.id!r}[...]"
        return None

    # -- callback taint -------------------------------------------------

    def _taint_assign(self, stmt: ast.Assign) -> None:
        if not self._cb_source(stmt.value):
            return
        for tgt in stmt.targets:
            if isinstance(tgt, ast.Name):
                self.tainted.add(tgt.id)

    def _taint_target(self, target, source) -> None:
        if self._cb_source(source) and isinstance(target, ast.Name):
            self.tainted.add(target.id)

    def _cb_source(self, expr) -> bool:
        for sub in self._expr_walk(expr):
            if isinstance(sub, ast.Attribute) and _is_cb_name(sub.attr):
                return True
            if isinstance(sub, ast.Name) and sub.id in self.tainted:
                return True
        return False

    # -- attribute writes ----------------------------------------------

    def _writes(self, stmt, held: dict[str, Frame]) -> None:
        if self.fi.cls is None:
            return
        targets: list = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for tgt in targets:
            for t in self._flatten_target(tgt):
                attr = self._self_attr(t)
                if attr is None:
                    continue
                self.sum.writes.append(Write(
                    attr=f"{self.fi.cls.key}.{attr}",
                    frame=Frame(self.fi.relpath, stmt.lineno,
                                f"write self.{attr}"),
                    held=frozenset(held),
                    func=self.fi.key,
                ))

    @staticmethod
    def _flatten_target(tgt) -> list:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            out = []
            for e in tgt.elts:
                out.extend(_Scan._flatten_target(e))
            return out
        return [tgt]

    @staticmethod
    def _self_attr(t) -> Optional[str]:
        # self.x = / self.x[...] = : both mutate the attribute's value
        if isinstance(t, (ast.Subscript,)):
            t = t.value
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self":
            return t.attr
        return None


# -- whole-program analysis -------------------------------------------------


@dataclass
class Edge:
    """One lock-order edge with its acquire/call witness chain."""

    src: str
    dst: str
    witness: list[Frame]

    def render(self) -> str:
        chain = " -> ".join(f.render() for f in self.witness)
        return f"{self.src} -> {self.dst} [{chain}]"


class Analysis:
    """The whole-program result: summaries, graph, cycles, findings."""

    def __init__(self, index) -> None:
        self.index = index
        self.summaries: dict[str, Summary] = {}
        self.entry_held: dict[str, frozenset[str]] = {}
        self.edges: dict[tuple[str, str], Edge] = {}
        self.cycles: list[list[Edge]] = []
        self.findings: list[Finding] = []
        self._by_file: Optional[dict[tuple[str, str], list[Finding]]] = None

    # -- queries --------------------------------------------------------

    def findings_for(self, relpath: str, rule: str) -> list[Finding]:
        if self._by_file is None:
            by: dict[tuple[str, str], list[Finding]] = {}
            for f in self.findings:
                by.setdefault((f.path, f.rule), []).append(f)
            self._by_file = by
        return self._by_file.get((relpath, rule), [])

    def holders(self) -> dict[str, list[str]]:
        """lock key -> sorted function keys that acquire it directly."""
        out: dict[str, set[str]] = {}
        for s in self.summaries.values():
            for lock in s.acquires:
                out.setdefault(lock, set()).add(s.func)
        return {k: sorted(v) for k, v in sorted(out.items())}

    def waiters(self) -> dict[str, list[Edge]]:
        """lock key -> edges that acquire it while something is held
        (who waits on this lock with another lock pinned)."""
        out: dict[str, list[Edge]] = {}
        for e in self.edges.values():
            out.setdefault(e.dst, []).append(e)
        return {k: sorted(v, key=lambda e: e.src)
                for k, v in sorted(out.items())}

    def to_dot(self) -> str:
        """GraphViz dump of the lock-order graph; cycle edges in red."""
        in_cycle = {(e.src, e.dst) for cyc in self.cycles for e in cyc}
        lines = ["digraph lockorder {", '  rankdir="LR";']
        names = sorted({k for e in self.edges for k in e}
                       | set(self.index.locks))
        for n in names:
            li = self.index.locks.get(n)
            label = f"{n}\\n{li.relpath}:{li.line}" if li else n
            lines.append(f'  "{n}" [label="{label}"];')
        for (src, dst), e in sorted(self.edges.items()):
            attr = ' [color="red",penwidth=2]' \
                if (src, dst) in in_cycle else ""
            lines.append(f'  "{src}" -> "{dst}"{attr};')
        lines.append("}")
        return "\n".join(lines)


def analyze(index) -> Analysis:
    """Run the full concurrency analysis over an index (cached there —
    prefer ``index.locksmith()``)."""
    an = Analysis(index)
    for key, fi in index.functions.items():
        try:
            an.summaries[key] = _Scan(index, fi).run()
        except RecursionError:   # pathological nesting: skip the func
            an.summaries[key] = Summary(func=key)
    _build_edges(an)
    _entry_locksets(an)
    _find_cycles(an)
    _emit_findings(an)
    _count(an)
    return an


def _entry_locksets(an: Analysis) -> None:
    """locks guaranteed held on entry to each function: the meet
    (intersection) over every static call site of (caller's entry set
    ∪ locks held at the call).  Functions with no in-repo callers are
    entry points: nothing guaranteed.  This is what keeps private
    helpers like ledger._transition — only ever called under ``_mu`` —
    from reading as unguarded."""
    callers: dict[str, list[tuple[str, frozenset[str]]]] = {}
    for s in an.summaries.values():
        for call in s.calls:
            callers.setdefault(call.callee, []).append(
                (s.func, frozenset(call.held)))
    TOP = None   # "not yet constrained" (identity for intersection)
    entry: dict[str, Optional[frozenset[str]]] = {
        k: (TOP if k in callers else frozenset())
        for k in an.summaries
    }
    for _ in range(16):   # decreasing lattice, tiny lock universe
        changed = False
        for fkey, sites in callers.items():
            acc: Optional[frozenset[str]] = TOP
            for caller, held in sites:
                ce = entry.get(caller) or frozenset()
                site = held | ce
                acc = site if acc is TOP else (acc & site)
            if acc is not TOP and entry.get(fkey) != acc:
                entry[fkey] = acc
                changed = True
        if not changed:
            break
    an.entry_held = {k: (v or frozenset()) for k, v in entry.items()}


def _trans_acquires(an: Analysis, key: str,
                    memo: dict, stack: set) -> dict[str, list[Frame]]:
    """lock -> call/acquire witness path for every lock the function
    acquires transitively (first path found wins)."""
    if key in memo:
        return memo[key]
    if key in stack:
        return {}
    stack.add(key)
    out: dict[str, list[Frame]] = {}
    s = an.summaries.get(key)
    if s is not None:
        for lock, frame in s.acquires.items():
            out.setdefault(lock, [frame])
        for call in s.calls:
            sub = _trans_acquires(an, call.callee, memo, stack)
            for lock, path in sub.items():
                if lock not in out and len(path) < 12:
                    out[lock] = [call.frame] + path
    stack.discard(key)
    memo[key] = out
    return out


def _build_edges(an: Analysis) -> None:
    memo: dict = {}
    for s in an.summaries.values():
        for (src, dst), frames in s.edges.items():
            an.edges.setdefault((src, dst),
                                Edge(src=src, dst=dst, witness=frames))
        for call in s.calls:
            if not call.held:
                continue
            sub = _trans_acquires(an, call.callee, memo, set())
            for lock, path in sub.items():
                for hkey, hframe in call.held.items():
                    if lock == hkey:
                        continue
                    an.edges.setdefault(
                        (hkey, lock),
                        Edge(src=hkey, dst=lock,
                             witness=[hframe] + path),
                    )


def _find_cycles(an: Analysis, max_len: int = 4,
                 max_cycles: int = 64) -> None:
    """Elementary cycles up to ``max_len`` edges; each reported once
    (rooted at its lexicographically-smallest lock)."""
    adj: dict[str, list[str]] = {}
    for src, dst in an.edges:
        adj.setdefault(src, []).append(dst)
    for v in adj.values():
        v.sort()
    seen: set[tuple[str, ...]] = set()

    def dfs(root: str, node: str, path: list[str]) -> None:
        if len(an.cycles) >= max_cycles:
            return
        for nxt in adj.get(node, ()):
            if nxt == root and len(path) >= 2:
                cyc = tuple(path)
                if min(cyc) == root and cyc not in seen:
                    seen.add(cyc)
                    an.cycles.append([
                        an.edges[(path[i], path[(i + 1) % len(path)])]
                        for i in range(len(path))
                    ])
            elif nxt > root and nxt not in path and len(path) < max_len:
                dfs(root, nxt, path + [nxt])

    for root in sorted(adj):
        dfs(root, root, [root])


def _emit_findings(an: Analysis) -> None:
    for cyc in an.cycles:
        locks = [e.src for e in cyc] + [cyc[0].src]
        chain = "; ".join(e.render() for e in cyc)
        anchor = cyc[0].witness[0]
        an.findings.append(Finding(
            rule="lockorder", severity=Severity.ERROR,
            path=anchor.relpath, line=anchor.line,
            message=(
                "potential deadlock: lock-order cycle "
                f"{' -> '.join(locks)}; witness: {chain} — two threads "
                "entering from opposite ends block forever; impose one "
                "global order or drop to a single lock"
            ),
        ))
    for s in an.summaries.values():
        for cb in s.cb_calls:
            lock, frame = next(iter(sorted(cb.held.items())))
            an.findings.append(Finding(
                rule="cbunderlock", severity=Severity.WARNING,
                path=cb.frame.relpath, line=cb.frame.line,
                message=(
                    f"{cb.desc} invoked while holding {lock} (acquired "
                    f"at {frame.relpath}:{frame.line}) — a callback "
                    "that blocks or re-enters the lock deadlocks; "
                    "queue under the lock and fire after release (the "
                    "ledger._drain_restored idiom)"
                ),
            ))
    _guarded_by(an)
    an.findings.sort(key=lambda f: (f.path, f.line, f.rule))


def _guarded_by(an: Analysis) -> None:
    """Attributes written both under a class lock and outside any lock."""
    index = an.index
    by_attr: dict[str, list[Write]] = {}
    for s in an.summaries.values():
        for w in s.writes:
            by_attr.setdefault(w.attr, []).append(w)
    reach_memo: dict[str, set[str]] = {}
    for attr, writes in sorted(by_attr.items()):
        cls_key = attr.rsplit(".", 1)[0]
        cls = index.classes.get(cls_key)
        if cls is None or not cls.lock_attrs:
            continue
        own_locks = {li.resolved_key() for li in cls.lock_attrs.values()}

        def eff(w: Write) -> frozenset[str]:
            return w.held | an.entry_held.get(w.func, frozenset())

        guarded = [w for w in writes if eff(w) & own_locks]
        unguarded = [
            w for w in writes
            if not eff(w) and not w.func.endswith(".__init__")
        ]
        if not guarded or not unguarded:
            continue
        g = guarded[0]
        lockname = sorted(eff(g) & own_locks)[0]
        writers = {w.func for w in writes}
        racing = _racing_threads(an, writers, reach_memo)
        race = (
            "; racing threads: " + ", ".join(racing)
            if racing else "; no spawn site resolved to a racing thread "
            "(pump/supervisor callbacks may still race)"
        )
        w0 = unguarded[0]
        an.findings.append(Finding(
            rule="unguardedwrite", severity=Severity.WARNING,
            path=w0.frame.relpath, line=w0.frame.line,
            message=(
                f"self.{attr.rsplit('.', 1)[1]} is written under "
                f"{lockname} at {len(guarded)} site(s) (e.g. "
                f"{g.frame.relpath}:{g.frame.line}) but unguarded here"
                + (f" and at {len(unguarded) - 1} more site(s)"
                   if len(unguarded) > 1 else "")
                + " — concurrent writers can lose updates (the "
                "_tiles_reduced lost-combine class); hold the lock or "
                "document the happens-before" + race
            ),
        ))


def _racing_threads(an: Analysis, writers: set[str],
                    memo: dict[str, set[str]]) -> list[str]:
    """Thread spawns whose target's transitive callees include one of
    the writer functions."""
    out = []
    for spawn in an.index.threads:
        if spawn.target is None:
            continue
        reach = memo.get(spawn.target)
        if reach is None:
            reach = set()
            stack = [spawn.target]
            while stack:
                k = stack.pop()
                if k in reach:
                    continue
                reach.add(k)
                s = an.summaries.get(k)
                if s is not None:
                    stack.extend(c.callee for c in s.calls)
            memo[spawn.target] = reach
        if writers & reach:
            out.append(f"{spawn.relpath}:{spawn.line} "
                       f"(target {spawn.target_text})")
    return sorted(set(out))


def _count(an: Analysis) -> None:
    try:
        from ..core.counters import SPC
    except Exception:   # commlint: allow(broadexcept)
        return          # analysis layer must not require the runtime
    SPC.record("locksmith_functions_scanned", len(an.summaries))
    SPC.record("locksmith_locks_inventoried", len(an.index.locks))
    SPC.record("locksmith_order_edges", len(an.edges))
    for rule in ("lockorder", "cbunderlock", "unguardedwrite"):
        n = sum(1 for f in an.findings if f.rule == rule)
        if n:
            SPC.record(f"locksmith_findings_{rule}", n)


# -- runtime lock witness ---------------------------------------------------

_THIS_FILE = os.path.abspath(__file__)
_STDLIB_THREADING = os.path.abspath(threading.__file__)


class _WitnessLock:
    """Wraps a real threading lock; every acquire/release reports to
    the witness with this lock's identity (static key when the
    creation site matches the index inventory)."""

    def __init__(self, real, key: str, witness: "LockWitness") -> None:
        self._real = real
        self.key = key
        self._w = witness

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._real.acquire(blocking, timeout)
        if ok:
            self._w._on_acquire(self)
        return ok

    def release(self) -> None:
        self._w._on_release(self)
        self._real.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        fn = getattr(self._real, "locked", None)
        return bool(fn()) if fn is not None else False

    # Condition protocol (RLock hosts): keep the witness's held stack
    # balanced across cv.wait()'s release/reacquire.  Resolved via
    # __getattr__ so a plain-Lock host raises AttributeError at
    # *access* time — Condition.__init__ probes with try/except and
    # must fall back to acquire()/release() for locks without these.
    def __getattr__(self, name: str):
        if name == "_release_save":
            fn = self._real._release_save
            w, me = self._w, self

            def _release_save():
                w._on_release(me)
                return fn()
            return _release_save
        if name == "_acquire_restore":
            fn = self._real._acquire_restore
            w, me = self._w, self

            def _acquire_restore(state):
                fn(state)
                w._on_acquire(me)
            return _acquire_restore
        if name == "_is_owned":
            return self._real._is_owned
        raise AttributeError(name)


@dataclass
class _ObservedEdge:
    count: int = 0
    thread: str = ""
    site: tuple[str, int] = ("", 0)


class LockWitness:
    """Opt-in runtime acquisition-order recorder.

    ``install()`` interposes ``threading.Lock/RLock/Condition`` so
    every lock created while the witness is active is wrapped; each
    wrapped lock's creation site is matched against the static
    inventory (when an index is given) so runtime edges and static
    edges share a key space.  ``report()`` returns runtime cycles
    (ERROR) plus static edges never witnessed (NOTE — untested order
    assumptions, commsan's "modeled but never exercised" class).
    """

    def __init__(self, index=None) -> None:
        self.index = index
        self.edges: dict[tuple[str, str], _ObservedEdge] = {}
        self._tls = threading.local()
        self._mu = threading.Lock()   # guards .edges  # commlint: allow(unguardedwrite)
        self._orig: Optional[tuple] = None
        self._site_to_key: dict[tuple[str, int], str] = {}
        if index is not None:
            for li in index.locks.values():
                base = os.path.basename(li.relpath)
                self._site_to_key[(base, li.line)] = li.resolved_key()

    # -- interposition --------------------------------------------------

    def install(self) -> "LockWitness":
        if self._orig is not None:
            return self
        self._orig = (threading.Lock, threading.RLock,
                      threading.Condition)
        orig_lock, orig_rlock, orig_cond = self._orig

        def _key() -> Optional[str]:
            f = sys._getframe(2)
            while f is not None and os.path.abspath(
                    f.f_code.co_filename) == _THIS_FILE:
                f = f.f_back
            if f is None:
                return "<unknown>"
            fname = os.path.abspath(f.f_code.co_filename)
            if fname == _STDLIB_THREADING:
                # threading's own plumbing (Thread/Event/Timer
                # internals) — interposing it only adds noise edges
                # among locks no user code can ever hold.
                return None
            base = os.path.basename(fname)
            site = (base, f.f_lineno)
            return self._site_to_key.get(site, f"{base}:{f.f_lineno}")

        def make_lock():
            key = _key()
            real = orig_lock()
            return real if key is None else _WitnessLock(real, key, self)

        def make_rlock():
            key = _key()
            real = orig_rlock()
            return real if key is None else _WitnessLock(real, key, self)

        def make_condition(lock=None):
            if lock is None:
                key = _key()
                if key is not None:
                    lock = _WitnessLock(orig_rlock(), key, self)
            return orig_cond(lock)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        threading.Condition = make_condition
        return self

    def uninstall(self) -> None:
        if self._orig is not None:
            (threading.Lock, threading.RLock,
             threading.Condition) = self._orig
            self._orig = None

    def __enter__(self) -> "LockWitness":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- recording ------------------------------------------------------

    def _held(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _on_acquire(self, lock: _WitnessLock) -> None:
        st = self._held()
        if any(h is lock for h in st):   # RLock re-entry: no new edge
            st.append(lock)
            return
        new_edges = []
        for h in st:
            if h.key != lock.key:
                new_edges.append((h.key, lock.key))
        st.append(lock)
        if new_edges:
            f = sys._getframe(2)
            while f is not None and os.path.abspath(
                    f.f_code.co_filename) == _THIS_FILE:
                f = f.f_back
            site = (os.path.basename(f.f_code.co_filename), f.f_lineno) \
                if f else ("", 0)
            with self._mu:
                for pair in new_edges:
                    e = self.edges.get(pair)
                    if e is None:
                        e = self.edges[pair] = _ObservedEdge()
                    e.count += 1
                    if e.count == 1:
                        e.thread = threading.current_thread().name
                        e.site = site

    def _on_release(self, lock: _WitnessLock) -> None:
        st = self._held()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is lock:
                del st[i]
                return

    # -- finalize -------------------------------------------------------

    def report(self) -> list[Finding]:
        findings: list[Finding] = []
        with self._mu:
            observed = dict(self.edges)
        adj: dict[str, set[str]] = {}
        for src, dst in observed:
            adj.setdefault(src, set()).add(dst)
        seen_cycles: set[frozenset] = set()
        for (src, dst), e in sorted(observed.items()):
            # runtime cycle: any path dst ->* src among observed edges
            if _reaches(adj, dst, src):
                cyc_key = frozenset((src, dst))
                if cyc_key in seen_cycles:
                    continue
                seen_cycles.add(cyc_key)
                back = observed.get((dst, src))
                via = (f"; reverse edge observed on thread "
                       f"{back.thread!r} at {back.site[0]}:{back.site[1]}"
                       if back is not None else "")
                findings.append(Finding(
                    rule="witness-cycle", severity=Severity.ERROR,
                    path=e.site[0], line=e.site[1],
                    message=(
                        f"runtime lock-order cycle: {src} -> {dst} "
                        f"observed {e.count}x on thread {e.thread!r}"
                        f"{via} — an interleaving of these threads "
                        "deadlocks"
                    ),
                ))
        if self.index is not None:
            static = self.index.locksmith()
            for (src, dst), edge in sorted(static.edges.items()):
                if (src, dst) not in observed:
                    f0 = edge.witness[0]
                    findings.append(Finding(
                        rule="witness-unseen", severity=Severity.NOTE,
                        path=f0.relpath, line=f0.line,
                        message=(
                            f"static lock-order edge {src} -> {dst} was "
                            "never witnessed at runtime — the ordering "
                            "assumption is untested by this run"
                        ),
                    ))
        try:
            from ..core.counters import SPC

            SPC.record("locksmith_witness_edges", len(observed))
            cycles = sum(1 for f in findings
                         if f.rule == "witness-cycle")
            if cycles:
                SPC.record("locksmith_witness_cycles", cycles)
        except Exception:   # commlint: allow(broadexcept)
            pass
        return findings


def _reaches(adj: dict[str, set[str]], src: str, dst: str) -> bool:
    stack, seen = [src], set()
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(adj.get(n, ()))
    return False


def witness(index=None) -> LockWitness:
    """``with locksmith.witness(index) as w: ...; w.report()``"""
    return LockWitness(index)


# -- sanitizer seam ---------------------------------------------------------

_ACTIVE_WITNESS: Optional[LockWitness] = None


def witness_enable(index=None) -> LockWitness:
    """Install the process-wide witness (the sanitizer's opt-in lock
    mode).  Idempotent; returns the active witness.  Without an index
    one is built over the package now — runtime lock keys must match
    the static inventory from creation time, not from finalize."""
    global _ACTIVE_WITNESS
    if _ACTIVE_WITNESS is None:
        if index is None:
            from .index import ProjectIndex

            index = ProjectIndex.build(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
        _ACTIVE_WITNESS = LockWitness(index).install()
    return _ACTIVE_WITNESS


def witness_active() -> Optional[LockWitness]:
    return _ACTIVE_WITNESS


def witness_finalize() -> list[Finding]:
    """Uninstall and report — called from sanitizer.finalize_check."""
    global _ACTIVE_WITNESS
    w = _ACTIVE_WITNESS
    if w is None:
        return []
    _ACTIVE_WITNESS = None
    w.uninstall()
    return w.report()

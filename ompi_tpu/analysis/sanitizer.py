"""commsan — opt-in runtime communication sanitizer.

The dynamic half of commlint (DESIGN.md §13). Where the linter reasons
about source, the sanitizer watches the live comm stack, in the style of
MUST / MPI-Checker's runtime mode:

- **request tracking**: every `core.request.Request` reports its
  creation/start/completion/free to the tracker (module-global hook in
  core/request.py — zero cost when disabled). Requests still ACTIVE at
  finalize are leaks (the missing-wait defect), reported through
  ``core.memchecker`` — an unwaited recv buffer is exactly a buffer that
  stays undefined forever.
- **p2p matching**: a pass-through PML wrapper (the ft/vprotocol
  interposition idiom) counts sends and posted recvs per directed
  ``(cid, src, dst)`` pair; unmatched sends surface at finalize.
- **collective ordering**: ``Communicator._coll_call`` reports every
  collective; the per-process ``cid:op`` sequence is CRC-chained, marked
  at each barrier, published through the modex at finalize, and compared
  across processes — rank-divergent collective order is the classic
  deadlock the linter's ``colldiv`` rule can only approximate.
- **partitioned contracts**: a part-framework wrapper annotates
  Psend_init requests; an ACTIVE partitioned send whose partitions were
  never all Pready'd is flagged (the runtime twin of ``partready``).

Everything reports through SPC pvars (``sanitizer_*``) plus one
structured report at finalize (reusing analysis.report.Finding, so the
static and dynamic halves render identically).

Enable with ``sanitizer.enable()`` *before* ``ompi_tpu.init()`` (the
PML/part wrappers interpose at selection time), or set the
``sanitizer_base_enable`` cvar — ``init()`` honors it.
"""

from __future__ import annotations

import os
import threading
import traceback
import zlib
from collections import Counter as _Counter
from dataclasses import dataclass, field
from typing import Any, Optional

from ..core import config
from ..core import request as _request
from ..core.counters import SPC
from ..core.errors import OmpiTpuError
from ..core.logging import get_logger, show_help
from .report import Finding, Report, Severity

logger = get_logger("analysis.sanitizer")

_enable = config.register(
    "sanitizer", "base", "enable", type=bool, default=False,
    description="Interpose the runtime communication sanitizer at init",
)
_fatal = config.register(
    "sanitizer", "base", "fatal", type=bool, default=True,
    description="Raise at finalize when the sanitizer found defects",
)
_max_events = config.register(
    "sanitizer", "base", "max_events", type=int, default=4096,
    description="Collective-sequence events kept verbatim (the CRC "
                "chain keeps matching past the cap)",
)
_lockwitness = config.register(
    "sanitizer", "base", "lockwitness", type=bool, default=False,
    description="Interpose inventoried threading locks (locksmith "
                "witness): record runtime acquisition-order edges; "
                "finalize reports runtime cycles and static lock-order "
                "edges never witnessed",
)

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class SanitizerError(OmpiTpuError):
    errclass = "ERR_OTHER"


def _origin() -> tuple[str, int]:
    """First stack frame outside the ompi_tpu package (the user call
    site), newest-first; falls back to the newest frame."""
    stack = traceback.extract_stack(limit=25)
    for fr in reversed(stack[:-1]):
        if not os.path.abspath(fr.filename).startswith(_PKG_ROOT):
            return fr.filename, fr.lineno or 0
    fr = stack[-1]
    return fr.filename, fr.lineno or 0


@dataclass
class _Rec:
    req: Any
    kind: str
    origin: tuple[str, int]
    detail: str = ""


@dataclass
class _CollLog:
    seq: list[str] = field(default_factory=list)
    crc: int = 0
    count: int = 0
    barrier_marks: list[tuple[int, int]] = field(default_factory=list)


class Tracker:
    """Per-process sanitizer state (one per enable()/finalize cycle)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._live: dict[int, _Rec] = {}
        self._coll = _CollLog()
        self._sends: _Counter = _Counter()  # "cid:src:dst" -> n
        self._recvs: _Counter = _Counter()  # "cid:src:dst" ('*' wildcard)

    # -- request lifecycle hooks (called from core/request.py) ---------

    def created(self, req) -> None:
        with self._lock:
            self._live[id(req)] = _Rec(
                req, type(req).__name__, _origin()
            )
            SPC.hwm("sanitizer_live_requests_hwm", len(self._live))
        SPC.record("sanitizer_requests_tracked")

    def started(self, req) -> None:
        # persistent re-arm: track the new active cycle's call site
        with self._lock:
            if id(req) not in self._live:
                self._live[id(req)] = _Rec(
                    req, type(req).__name__, _origin()
                )

    def completed(self, req) -> None:
        with self._lock:
            self._live.pop(id(req), None)

    def freed(self, req) -> None:
        with self._lock:
            self._live.pop(id(req), None)

    def annotate(self, req, kind: str, detail: str = "") -> None:
        with self._lock:
            rec = self._live.get(id(req))
            if rec is not None:
                rec.kind = kind
                rec.detail = detail

    # -- traffic recording (called from the pml/part wrappers) ---------

    def p2p_send(self, comm, src, dst, tag) -> None:
        s = -1 if src is None else int(src)
        with self._lock:
            self._sends[f"{comm.cid}:{s}:{int(dst)}"] += 1
        SPC.record("sanitizer_sends_recorded")

    def p2p_recv(self, comm, src, tag, dst) -> None:
        s = "*" if src is None or int(src) < 0 else str(int(src))
        with self._lock:
            self._recvs[f"{comm.cid}:{s}:{int(dst)}"] += 1
        SPC.record("sanitizer_recvs_recorded")

    def record_coll(self, comm, opname: str) -> None:
        key = f"{comm.cid}:{opname}"
        cap = int(_max_events.value or 4096)
        with self._lock:
            log = self._coll
            log.crc = zlib.crc32(key.encode(), log.crc)
            log.count += 1
            if len(log.seq) < cap:
                log.seq.append(key)
            if opname == "barrier":
                log.barrier_marks.append((log.count, log.crc))
        SPC.record("sanitizer_coll_recorded")

    # -- finalize-time analysis ----------------------------------------

    def _leak_findings(self) -> list[Finding]:
        out = []
        with self._lock:
            recs = list(self._live.values())
        for rec in recs:
            state = getattr(rec.req, "state", None)
            if state is not _request.RequestState.ACTIVE:
                continue
            where = rec.detail and f" ({rec.detail})" or ""
            out.append(Finding(
                rule="san-leak", severity=Severity.ERROR,
                path=rec.origin[0], line=rec.origin[1],
                message=f"leaked {rec.kind}{where}: still active at "
                        "finalize — missing wait/test/free",
            ))
            flagged = getattr(rec.req, "_flagged", None)
            if flagged is not None and getattr(rec.req, "sending", False) \
                    and not all(flagged):
                missing = [i for i, f in enumerate(flagged) if not f]
                out.append(Finding(
                    rule="san-partready", severity=Severity.ERROR,
                    path=rec.origin[0], line=rec.origin[1],
                    message=f"partitioned send: partition(s) {missing} "
                            "never marked Pready this cycle — the "
                            "transfer cannot complete",
                ))
        return out

    def _payload(self) -> dict:
        with self._lock:
            return {
                "coll_seq": list(self._coll.seq),
                "coll_crc": self._coll.crc,
                "coll_count": self._coll.count,
                "barriers": [list(m) for m in self._coll.barrier_marks],
                "sends": dict(self._sends),
                "recvs": dict(self._recvs),
            }

    @staticmethod
    def _unmatched_findings(sends: _Counter, recvs: _Counter
                            ) -> list[Finding]:
        """Directed-pair accounting: sends to (cid, dst) must be covered
        by specific recvs plus the destination's wildcard posts."""
        out = []
        wild = _Counter()
        for key, n in recvs.items():
            cid, src, dst = key.split(":")
            if src == "*":
                wild[f"{cid}:{dst}"] += n
        for key, n in sorted(sends.items()):
            cid, src, dst = key.split(":")
            specific = recvs.get(key, 0)
            if src == "-1":  # unattributed source: match any specific
                specific = sum(
                    v for k, v in recvs.items()
                    if k.split(":")[0] == cid and k.split(":")[2] == dst
                )
            short = n - specific
            if short <= 0:
                continue
            avail = wild[f"{cid}:{dst}"]
            take = min(short, avail)
            wild[f"{cid}:{dst}"] -= take
            short -= take
            if short > 0:
                out.append(Finding(
                    rule="san-unmatched", severity=Severity.ERROR,
                    path="<runtime>", line=0,
                    message=f"{short} send(s) {src}->{dst} on cid {cid} "
                            "with no matching posted recv",
                ))
        return out

    def _divergence_findings(self, mine: dict, peers: dict[int, dict],
                             my_rank: int) -> list[Finding]:
        out = []
        for rank, theirs in sorted(peers.items()):
            if theirs["coll_crc"] == mine["coll_crc"] \
                    and theirs["coll_count"] == mine["coll_count"]:
                continue
            a, b = mine["coll_seq"], theirs["coll_seq"]
            idx = next(
                (i for i, (x, y) in enumerate(zip(a, b)) if x != y),
                min(len(a), len(b)),
            )
            here = a[idx] if idx < len(a) else "<nothing>"
            there = b[idx] if idx < len(b) else "<nothing>"
            # first barrier epoch already past the divergence point
            epoch = next(
                (k for k, (cnt, _crc) in enumerate(mine["barriers"])
                 if cnt > idx), None,
            )
            at = f" (before barrier #{epoch})" if epoch is not None else ""
            out.append(Finding(
                rule="san-colldiv", severity=Severity.ERROR,
                path="<runtime>", line=0,
                message=f"collective order diverges from rank {rank} at "
                        f"call #{idx}{at}: this rank issued {here}, "
                        f"rank {rank} issued {there} — ranks block in "
                        "different collectives (deadlock)",
            ))
        return out

    def report(self) -> Report:
        findings = self._leak_findings()
        mine = self._payload()
        my_rank, nproc = 0, 1
        try:
            import jax

            nproc = jax.process_count()
            my_rank = jax.process_index()
        except (ImportError, RuntimeError, ValueError):
            pass
        if nproc > 1:
            from ..runtime import modex

            peers: dict[int, dict] = {}
            try:
                modex.put(f"sanitizer/fin/{my_rank}", mine)
                for r in range(nproc):
                    if r != my_rank:
                        peers[r] = modex.get(
                            f"sanitizer/fin/{r}", timeout_s=20.0
                        )
            except modex.ModexError as exc:
                logger.warning("cross-rank compare skipped: %s", exc)
            findings.extend(
                self._divergence_findings(mine, peers, my_rank)
            )
            sends = _Counter(mine["sends"])
            recvs = _Counter(mine["recvs"])
            for p in peers.values():
                sends.update(p["sends"])
                recvs.update(p["recvs"])
            if my_rank == 0:
                findings.extend(self._unmatched_findings(sends, recvs))
        else:
            findings.extend(self._unmatched_findings(
                _Counter(mine["sends"]), _Counter(mine["recvs"])
            ))
        return Report(findings)


# -- module-level state ------------------------------------------------

_TRACKER: Optional[Tracker] = None


def active() -> bool:
    return _TRACKER is not None


def tracker() -> Optional[Tracker]:
    return _TRACKER


def enable() -> Tracker:
    """Install the sanitizer. Call before init()/first communication —
    the PML/part wrappers interpose at component-selection time and a
    communicator's cached pml is not rewrapped retroactively."""
    global _TRACKER
    if _TRACKER is None:
        _TRACKER = Tracker()
        _request.set_tracker(_TRACKER)
        # NOTE: deliberately does not set the enable cvar — programmatic
        # enable() covers one init/finalize cycle; only the cvar (user
        # config) makes the sanitizer sticky across re-inits.
        from ..part import framework as part_fw
        from ..pml import framework as pml_fw

        pml_fw.reset_selection()
        part_fw.reset_selection()
        logger.info("communication sanitizer enabled")
    return _TRACKER


def maybe_enable() -> None:
    """init()-time hook: honor the sanitizer_base_enable and
    sanitizer_base_lockwitness cvars."""
    if _enable.value and not active():
        enable()
    if _lockwitness.value:
        from . import locksmith

        locksmith.witness_enable()


def record_coll(comm, opname: str) -> None:
    t = _TRACKER
    if t is not None:
        t.record_coll(comm, opname)


def finalize_check() -> Optional[BaseException]:
    """Run the finalize-time matching; returns (not raises) the error so
    api.finalize can finish teardown first and a second finalize stays
    clean."""
    global _TRACKER
    t = _TRACKER
    from . import locksmith

    wit_findings = locksmith.witness_finalize()
    if t is None and not wit_findings:
        return None
    if t is not None:
        _TRACKER = None
        _request.set_tracker(None)
        from ..part import framework as part_fw
        from ..pml import framework as pml_fw

        pml_fw.reset_selection()
        part_fw.reset_selection()
        rep = t.report()
    else:
        rep = Report([])
    if wit_findings:
        rep = Report(list(rep.findings) + wit_findings)
    if not len(rep):
        logger.info("sanitizer: clean at finalize")
        return None
    SPC.record("sanitizer_findings", len(rep))
    show_help("sanitizer report", "%s", rep.render(), once=False)
    if not _fatal.value:
        return None
    if rep.max_severity() < Severity.WARNING:
        # witness-unseen notes (static edges this run never exercised)
        # are coverage information, not defects
        return None
    leaks = rep.by_rule("san-leak")
    if leaks:
        from ..core import memchecker

        return memchecker.leak_report(
            f"sanitizer: {len(leaks)} leaked request(s) at finalize\n"
            + rep.render()
        )
    return SanitizerError(
        "sanitizer findings at finalize\n" + rep.render()
    )


# -- interposition wrappers --------------------------------------------

class SanitizerPml:
    """Pass-through PML recording p2p traffic (vprotocol idiom: wraps
    rather than replaces the selected component; unknown attributes —
    improbe, comm_freed, _infer_source — delegate to the host)."""

    NAME = "sanitizer"

    def __init__(self, host) -> None:
        self.host = host

    def __getattr__(self, name):
        return getattr(self.host, name)

    def _src(self, comm, value, source):
        infer = getattr(self.host, "_infer_source", None)
        if source is None and infer is not None:
            try:
                return infer(comm, value, source)
            except Exception:  # commlint: allow(broadexcept)
                return None  # inference is best-effort bookkeeping
        return source

    def isend(self, comm, value, dest, tag, source=None):
        t = _TRACKER
        if t is not None:
            t.p2p_send(comm, self._src(comm, value, source), dest, tag)
        req = self.host.isend(comm, value, dest, tag, source=source)
        if t is not None:
            t.annotate(
                req, "isend",
                f"dst={dest} tag={tag} comm={comm.name}",
            )
        return req

    def send(self, comm, value, dest, tag, source=None):
        t = _TRACKER
        if t is not None:
            t.p2p_send(comm, self._src(comm, value, source), dest, tag)
            # blocking send completes before return; count the matching
            # side only.
        return self.host.send(comm, value, dest, tag, source=source)

    def irecv(self, comm, source, tag, *, dest):
        t = _TRACKER
        if t is not None:
            t.p2p_recv(comm, source, tag, dest)
        req = self.host.irecv(comm, source, tag, dest=dest)
        if t is not None:
            t.annotate(
                req, "irecv",
                f"src={source} tag={tag} comm={comm.name}",
            )
        return req

    def recv(self, comm, source, tag, *, dest):
        t = _TRACKER
        if t is not None:
            t.p2p_recv(comm, source, tag, dest)
        return self.host.recv(comm, source, tag, dest=dest)


class SanitizerPart:
    """Pass-through part component annotating partitioned requests."""

    NAME = "sanitizer"

    def __init__(self, host) -> None:
        self.host = host

    def __getattr__(self, name):
        return getattr(self.host, name)

    def psend_init(self, comm, value, partitions, dest, tag=0, *,
                   source=None):
        req = self.host.psend_init(
            comm, value, partitions, dest, tag, source=source
        )
        t = _TRACKER
        if t is not None:
            t.annotate(
                req, "psend_init",
                f"partitions={partitions} dst={dest} tag={tag} "
                f"comm={comm.name}",
            )
        return req

    def precv_init(self, comm, partitions, source, tag=0, *, dest, like):
        req = self.host.precv_init(
            comm, partitions, source, tag, dest=dest, like=like
        )
        t = _TRACKER
        if t is not None:
            t.annotate(
                req, "precv_init",
                f"partitions={partitions} src={source} tag={tag} "
                f"comm={comm.name}",
            )
        return req


def maybe_wrap_pml(selected):
    if _enable.value and not active():
        enable()
    return SanitizerPml(selected) if active() else selected


def maybe_wrap_part(selected):
    if _enable.value and not active():
        enable()
    return SanitizerPart(selected) if active() else selected

"""commlint driver — static communication-correctness analysis.

The linter walks Python sources, parses them once, and hands each file
to every selected rule component (``analysis/rules/``, an MCA framework
— rules are selectable/disableable via the ``commlint_select`` and
``commlint_<rule>_priority`` cvars like any other component stack).

Suppressions are source-level: a ``# commlint: allow(<rule>)`` comment
on the flagged line or the line above silences that rule there. The
self-lint ratchet (``analysis/report.Baseline``) handles the historical
remainder: per-``rule:file`` finding counts are checked in, only count
*increases* fail.

Typical use::

    from ompi_tpu.analysis.lint import Linter
    rep = Linter().lint_paths(["ompi_tpu"])
    print(rep.render())

or ``python -m ompi_tpu.tools.lint <path>``.
"""

from __future__ import annotations

import ast
import os
import re
import time
from typing import Iterable, Sequence

from ..core import config
from .report import Finding, Report, Severity
from .rules import COMMLINT, ensure_rules

_ALLOW_RE = re.compile(r"#\s*commlint:\s*allow\(\s*([\w\-, ]+?)\s*\)")

config.register(
    "commlint", "base", "exclude",
    type=str, default="__pycache__,.git,build,dist",
    description="comma-separated directory names the linter skips",
)


class FileContext:
    """One parsed source file, shared by every rule.

    Attributes
    ----------
    path:     the path as given to the linter (for error messages)
    relpath:  path relative to the lint root, '/'-normalised — this is
              what appears in findings and baseline keys, so baselines
              are stable across checkouts.
    tree:     the parsed ``ast`` module
    lines:    source split into lines (1-indexed via ``lines[i-1]``)
    """

    def __init__(self, path: str, source: str, relpath: str | None = None):
        self.path = path
        self.relpath = (relpath or path).replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._allow: dict[int, frozenset[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _ALLOW_RE.search(line)
            if m:
                names = frozenset(
                    p.strip() for p in m.group(1).split(",") if p.strip()
                )
                self._allow[i] = names

    def suppressed(self, line: int, rule: str) -> bool:
        """True if ``# commlint: allow(rule)`` covers ``line``
        (same line or the line immediately above)."""
        for ln in (line, line - 1):
            names = self._allow.get(ln)
            if names and (rule in names or "all" in names):
                return True
        return False


class Linter:
    """Runs the selected rule components over files/trees."""

    def __init__(self, select: str | None = None,
                 base: str | None = None):
        ensure_rules()
        self.base = os.path.abspath(base) if base else None
        if select is not None:
            # scope the filter cvar to this selection so one Linter's
            # --select doesn't leak into later instances
            prev = config.get("commlint_select", "") or ""
            config.set("commlint_select", select)
            try:
                self.rules = COMMLINT.select_all()
            finally:
                config.set("commlint_select", prev)
        else:
            self.rules = COMMLINT.select_all()
        self.errors: list[str] = []  # unparseable files, I/O failures
        self.files_checked = 0
        self.elapsed_ms = 0.0

    # -- discovery ----------------------------------------------------

    def _excluded(self) -> frozenset[str]:
        raw = config.get("commlint_base_exclude",
                         "__pycache__,.git,build,dist") or ""
        return frozenset(p.strip() for p in raw.split(",") if p.strip())

    def iter_sources(self, paths: Sequence[str]) -> Iterable[str]:
        skip = self._excluded()
        for path in paths:
            if os.path.isfile(path):
                yield path
                continue
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in skip and not d.startswith(".")
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)

    def _relpath(self, path: str) -> str:
        ap = os.path.abspath(path)
        base = self.base
        if base and (ap == base or ap.startswith(base + os.sep)):
            return os.path.relpath(ap, base)
        return path

    # -- linting ------------------------------------------------------

    def lint_source(self, source: str, path: str = "<string>",
                    relpath: str | None = None) -> list[Finding]:
        try:
            ctx = FileContext(path, source, relpath=relpath)
        except SyntaxError as exc:
            self.errors.append(f"{path}: syntax error: {exc}")
            return []
        findings: list[Finding] = []
        for rule in self.rules:
            try:
                findings.extend(rule.check(ctx))
            except Exception as exc:  # commlint: allow(broadexcept)
                # A crashing rule must not take the whole run down;
                # surface it as a run error instead.
                self.errors.append(
                    f"{path}: rule {rule.NAME!r} crashed: {exc!r}"
                )
        return findings

    def lint_file(self, path: str) -> list[Finding]:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError as exc:
            self.errors.append(f"{path}: {exc}")
            return []
        self.files_checked += 1
        return self.lint_source(source, path=path,
                                relpath=self._relpath(path))

    def lint_paths(self, paths: Sequence[str]) -> Report:
        t0 = time.perf_counter()
        findings: list[Finding] = []
        for src in self.iter_sources(paths):
            findings.extend(self.lint_file(src))
        self.elapsed_ms = (time.perf_counter() - t0) * 1e3
        return Report(findings)


def lint_tree(root: str, select: str | None = None) -> Report:
    """Convenience: lint every .py under ``root``, findings keyed
    relative to it (the form the self-lint baseline uses)."""
    linter = Linter(select=select, base=root)
    return linter.lint_paths([root])

"""commlint driver — static communication-correctness analysis.

The linter discovers Python sources, parses each exactly once into a
shared ``ProjectIndex`` (analysis/index.py), and hands every file's
cached ``FileContext`` to every selected rule component
(``analysis/rules/``, an MCA framework — rules are selectable /
disableable via the ``commlint_select`` and ``commlint_<rule>_priority``
cvars like any other component stack).  Whole-program rules (the
locksmith concurrency set) reach through ``ctx.index`` for the symbol
table, call graph, and lock inventory built from the same parse.

Suppressions are source-level: a ``# commlint: allow(<rule>)`` comment
on the flagged line or the line above silences that rule there. The
self-lint ratchet (``analysis/report.Baseline``) handles the historical
remainder: per-``rule:file`` finding counts are checked in, only count
*increases* fail.

Typical use::

    from ompi_tpu.analysis.lint import Linter
    rep = Linter().lint_paths(["ompi_tpu"])
    print(rep.render())

or ``python -m ompi_tpu.tools.lint <path>``.
"""

from __future__ import annotations

import os
import time
from typing import Iterable, Sequence

from ..core import config
from .index import FileContext, ProjectIndex   # noqa: F401 (re-export)
from .report import Finding, Report
from .rules import COMMLINT, ensure_rules

config.register(
    "commlint", "base", "exclude",
    type=str, default="__pycache__,.git,build,dist",
    description="comma-separated directory names the linter skips",
)


class Linter:
    """Runs the selected rule components over files/trees."""

    def __init__(self, select: str | None = None,
                 base: str | None = None):
        ensure_rules()
        self.base = os.path.abspath(base) if base else None
        if select is not None:
            # scope the filter cvar to this selection so one Linter's
            # --select doesn't leak into later instances
            prev = config.get("commlint_select", "") or ""
            config.set("commlint_select", select)
            try:
                self.rules = COMMLINT.select_all()
            finally:
                config.set("commlint_select", prev)
        else:
            self.rules = COMMLINT.select_all()
        self.errors: list[str] = []  # unparseable files, I/O failures
        self.files_checked = 0
        self.elapsed_ms = 0.0

    # -- discovery ----------------------------------------------------

    def _excluded(self) -> frozenset[str]:
        raw = config.get("commlint_base_exclude",
                         "__pycache__,.git,build,dist") or ""
        return frozenset(p.strip() for p in raw.split(",") if p.strip())

    def iter_sources(self, paths: Sequence[str]) -> Iterable[str]:
        skip = self._excluded()
        for path in paths:
            if os.path.isfile(path):
                yield path
                continue
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in skip and not d.startswith(".")
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)

    def _relpath(self, path: str) -> str:
        ap = os.path.abspath(path)
        base = self.base
        if base and (ap == base or ap.startswith(base + os.sep)):
            return os.path.relpath(ap, base)
        return path

    # -- linting ------------------------------------------------------

    def _load(self, path: str,
              index: ProjectIndex) -> FileContext | None:
        """Parse one file into the shared index (None on error)."""
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError as exc:
            self.errors.append(f"{path}: {exc}")
            return None
        try:
            ctx = FileContext(path, source, relpath=self._relpath(path))
        except SyntaxError as exc:
            self.errors.append(f"{path}: syntax error: {exc}")
            return None
        self.files_checked += 1
        return index.add_context(ctx)

    def _check(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        for rule in self.rules:
            try:
                findings.extend(rule.check(ctx))
            except Exception as exc:  # commlint: allow(broadexcept)
                # A crashing rule must not take the whole run down;
                # surface it as a run error instead.
                self.errors.append(
                    f"{ctx.path}: rule {rule.NAME!r} crashed: {exc!r}"
                )
        return findings

    def lint_source(self, source: str, path: str = "<string>",
                    relpath: str | None = None) -> list[Finding]:
        """Lint a bare snippet: a one-file index (whole-program rules
        see just this module)."""
        index = ProjectIndex(base=self.base)
        try:
            ctx = FileContext(path, source, relpath=relpath)
        except SyntaxError as exc:
            self.errors.append(f"{path}: syntax error: {exc}")
            return []
        index.add_context(ctx)
        index.link()
        return self._check(ctx)

    def lint_file(self, path: str) -> list[Finding]:
        index = ProjectIndex(base=self.base)
        ctx = self._load(path, index)
        if ctx is None:
            return []
        index.link()
        return self._check(ctx)

    def lint_paths(self, paths: Sequence[str]) -> Report:
        """The parse-once engine: every discovered file enters the
        shared ProjectIndex, then every rule sees every cached tree."""
        t0 = time.perf_counter()
        index = ProjectIndex(base=self.base)
        ctxs: list[FileContext] = []
        for src in self.iter_sources(paths):
            ctx = self._load(src, index)
            if ctx is not None:
                ctxs.append(ctx)
        index.link()
        findings: list[Finding] = []
        for ctx in ctxs:
            findings.extend(self._check(ctx))
        self.elapsed_ms = (time.perf_counter() - t0) * 1e3
        return Report(findings)


def lint_tree(root: str, select: str | None = None) -> Report:
    """Convenience: lint every .py under ``root``, findings keyed
    relative to it (the form the self-lint baseline uses)."""
    linter = Linter(select=select, base=root)
    return linter.lint_paths([root])

"""Top-level runtime API: init / finalize / world communicators.

TPU-native equivalent of MPI_Init / MPI_Finalize (reference:
ompi/runtime/ompi_mpi_init.c:384 — the init sequence in SURVEY §3.1).
The reference's sequence maps as:

- opal_init_util           → core registries import (config/components)
- ompi_rte_init (PMIx)     → jax backend init (+ jax.distributed when
                              multi-host; the coordinator is the PMIx
                              server analog)
- modex publish/fence      → runtime.mesh.discover(): device coords,
                              host indices, slice ids straight from the
                              runtime — no wire exchange needed
- add_procs                → Proc list construction
- coll comm select         → Communicator.__init__ vtable merge
"""

from __future__ import annotations

import atexit
import threading
from typing import Optional, Sequence

from .communicator import Communicator
from .core import config
from .core.counters import SPC
from .core.errors import NotInitializedError
from .core.logging import get_logger
from .group import Group
from .runtime import mesh as mesh_mod

logger = get_logger("runtime")

_lock = threading.Lock()
_state: Optional["_World"] = None


class _World:
    def __init__(self, procs, comm_world, comm_self):
        self.procs = procs
        self.comm_world = comm_world
        self.comm_self = comm_self


def init(
    devices: Optional[Sequence] = None,
    *,
    distributed: bool = False,
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Communicator:
    """Initialize the runtime and return COMM_WORLD.

    `distributed=True` runs jax.distributed.initialize first (multi-host:
    the coordinator plays the PMIx-server role; all hosts then see the
    global device set and execute this same driver program).
    Idempotent: re-init returns the existing world.
    """
    global _state
    with _lock:
        if _state is not None:
            return _state.comm_world
        if distributed:
            import jax

            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        procs = mesh_mod.discover(devices)
        if not procs:
            raise NotInitializedError("no devices discovered")
        world_group = Group(range(len(procs)))
        comm_world = Communicator(world_group, procs, name="WORLD")
        comm_self = Communicator(Group([0]), procs, name="SELF")
        _state = _World(procs, comm_world, comm_self)
        SPC.record("init_calls")
        logger.info(
            "initialized: %d ranks over %s",
            len(procs),
            {p.platform for p in procs},
        )
        from .analysis import sanitizer as _sanitizer

        _sanitizer.maybe_enable()
        from . import trace as _trace

        _trace.at_init(comm_world)
        from . import health as _health

        _health.at_init()
        from . import telemetry as _telemetry

        try:
            import jax

            _fleet_n = int(jax.process_count())
        except Exception:  # commlint: allow(broadexcept)
            _fleet_n = 1
        _telemetry.at_init(fleet_size=_fleet_n)
    # at_init_bottom fires after _lock is released: _state is already
    # committed, and a hook calling back into init()/finalize() must
    # not deadlock on the non-reentrant module lock (the ledger
    # callback-under-lock class locksmith flags).
    from .hook import run_hooks

    run_hooks("at_init_bottom", comm_world)
    return comm_world


def initialized() -> bool:
    return _state is not None


def finalize() -> None:
    """Tear down communicators (MPI_Finalize). Safe to call twice.

    When the sanitizer is active its finalize matching runs first (leaked
    requests, unmatched sends, cross-rank collective order); teardown
    always completes, and the sanitizer's verdict is raised at the very
    end so a second finalize is a clean no-op."""
    global _state
    san_err = None
    with _lock:
        if _state is None:
            return
        from .communicator import live_comms
        from .hook import run_hooks

        # at_finalize_top must observe live state strictly before any
        # teardown and before a racing second finalize() can proceed;
        # hooks are documented to not re-enter init/finalize.
        run_hooks("at_finalize_top", _state.comm_world)  # commlint: allow(cbunderlock)
        from .analysis import sanitizer as _sanitizer

        san_err = _sanitizer.finalize_check()
        try:
            from .monitoring.monitoring import maybe_dump_at_finalize

            maybe_dump_at_finalize()
        except ImportError:
            pass
        try:
            from . import trace as _trace

            _trace.at_finalize(_state.comm_world)
        except ImportError:
            pass
        try:
            from . import health as _health

            _health.at_finalize()
        except ImportError:
            pass
        try:
            from . import telemetry as _telemetry

            _telemetry.at_finalize()
        except ImportError:
            pass
        try:
            from .io import fbtl as _fbtl
            from .io.file import live_files

            for fh in list(live_files):
                try:
                    fh.close()
                except Exception:
                    logger.exception("finalize: file close failed")
            _fbtl.shutdown_pool()
        except ImportError:
            pass
        for comm in list(live_comms):
            if not comm._freed:
                comm.free()
        _state = None
    if san_err is not None:
        raise san_err


def _world() -> _World:
    if _state is None:
        raise NotInitializedError(
            "ompi_tpu.init() has not been called (or finalize() already was)"
        )
    return _state


def world() -> Communicator:
    return _world().comm_world


def abort(error_code: int = 1) -> None:
    """MPI_Abort: kill the job. In the driver model there is one
    controller process per host; exiting it tears down the device work."""
    import os
    import sys

    logger.error("abort(%d) called", error_code)
    sys.stderr.flush()
    os._exit(error_code)


# -- MPI-4 partitioned communication (reference: ompi/mca/part) -------------

def Psend_init(comm, value, partitions: int, dest: int, tag: int = 0, *,
               source=None):
    """MPI_Psend_init: a persistent partitioned send of `value` split
    into `partitions` contiguous partitions."""
    return comm.psend_init(value, partitions, dest, tag, source=source)


def Precv_init(comm, partitions: int, source: int, tag: int = 0, *,
               dest: int, like):
    """MPI_Precv_init: `like` supplies the receive shape/dtype."""
    return comm.precv_init(partitions, source, tag, dest=dest, like=like)


def Pready(request, partition: int) -> None:
    """MPI_Pready: mark one send partition filled (eager drain)."""
    request.pready(partition)


def Pready_range(request, lo: int, hi: int) -> None:
    """MPI_Pready_range (inclusive bounds, matching the MPI binding)."""
    request.pready_range(lo, hi)


def Pready_list(request, partitions) -> None:
    """MPI_Pready_list."""
    request.pready_list(partitions)


def Parrived(request, partition: int) -> bool:
    """MPI_Parrived: poll one receive partition's completion."""
    return request.parrived(partition)


class _CommProxy:
    """Module-level COMM_WORLD / COMM_SELF handles that resolve lazily
    (usable before init; raise cleanly if the runtime is down)."""

    def __init__(self, attr: str) -> None:
        self._attr = attr

    def _resolve(self) -> Communicator:
        return getattr(_world(), self._attr)

    def __getattr__(self, name):
        return getattr(self._resolve(), name)

    def __repr__(self) -> str:
        if _state is None:
            return f"<{self._attr} (uninitialized)>"
        return repr(self._resolve())


COMM_WORLD = _CommProxy("comm_world")
COMM_SELF = _CommProxy("comm_self")

atexit.register(finalize)

"""Process topologies: cartesian, graph, and distributed graph.

TPU-native equivalent of ompi/mca/topo (reference:
topo_base_cart_create.c and friends; treematch rank reordering in
ompi/mca/topo/treematch). Topologies attach to a communicator and give
rank↔coordinate mapping, neighbor enumeration (the substrate for halo
exchange / neighbor collectives, reference coll_base_functions.h:62-66),
and hardware-aware reordering: `reorder=True` runs the real treematch
algorithm (topo/treematch.py) — the requested neighbor structure is
matched onto the ICI coordinates, minimizing weighted hop distance.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core.errors import ArgumentError, TopologyError


class CartTopology:
    """MPI_Cart: n-dimensional (optionally periodic) grid."""

    def __init__(self, comm, dims: Sequence[int], periods: Sequence[bool],
                 ) -> None:
        total = int(np.prod(dims))
        if total != comm.size:
            raise ArgumentError(
                f"cart dims {tuple(dims)} need {total} ranks, comm has "
                f"{comm.size}"
            )
        self.comm = comm
        self.dims = tuple(int(d) for d in dims)
        self.periods = tuple(bool(p) for p in periods)
        if len(self.periods) != len(self.dims):
            raise ArgumentError("dims/periods length mismatch")

    @property
    def ndims(self) -> int:
        return len(self.dims)

    def coords(self, rank: int) -> tuple[int, ...]:
        """MPI_Cart_coords (row-major, C order)."""
        self.comm.check_rank(rank)
        out = []
        r = rank
        for d in reversed(self.dims):
            out.append(r % d)
            r //= d
        return tuple(reversed(out))

    def rank(self, coords: Sequence[int]) -> int:
        """MPI_Cart_rank; periodic dims wrap, others must be in range."""
        if len(coords) != self.ndims:
            raise ArgumentError("coords length mismatch")
        r = 0
        for c, d, p in zip(coords, self.dims, self.periods):
            if p:
                c = c % d
            elif not 0 <= c < d:
                raise TopologyError(
                    f"coordinate {c} out of range for non-periodic dim {d}"
                )
            r = r * d + c
        return r

    def shift(self, direction: int, disp: int
              ) -> tuple[Optional[int], Optional[int]]:
        """MPI_Cart_shift for every rank is derivable; driver form:
        returns (source, dest) for a given rank via shift_for."""
        raise TypeError("use shift_for(rank, direction, disp)")

    def shift_for(self, rank: int, direction: int, disp: int
                  ) -> tuple[Optional[int], Optional[int]]:
        if not 0 <= direction < self.ndims:
            raise ArgumentError(f"direction {direction} out of range")
        c = list(self.coords(rank))
        src_c, dst_c = list(c), list(c)
        src_c[direction] -= disp
        dst_c[direction] += disp

        def resolve(cc):
            try:
                return self.rank(cc)
            except TopologyError:
                return None  # MPI_PROC_NULL

        return resolve(src_c), resolve(dst_c)

    def neighbors(self, rank: int) -> list[int]:
        """±1 neighbors per dimension, in (dim, -/+) order; PROC_NULL
        omitted — the neighbor-collective ordering."""
        out = []
        for d in range(self.ndims):
            src, dst = self.shift_for(rank, d, 1)
            for n in (src, dst):
                if n is not None:
                    out.append(n)
        return out

    def sub(self, remain_dims: Sequence[bool]) -> dict[tuple, object]:
        """MPI_Cart_sub: partition into sub-grids along kept dims;
        returns {fixed_coords: communicator-with-CartTopology}."""
        if len(remain_dims) != self.ndims:
            raise ArgumentError("remain_dims length mismatch")
        drop = [d for d in range(self.ndims) if not remain_dims[d]]
        colors: list[int] = []
        keys: list[int] = []
        for r in range(self.comm.size):
            c = self.coords(r)
            color = 0
            for d in drop:
                color = color * self.dims[d] + c[d]
            key = 0
            for d in range(self.ndims):
                if remain_dims[d]:
                    key = key * self.dims[d] + c[d]
            colors.append(color)
            keys.append(key)
        split = self.comm.split(colors, keys)
        out = {}
        sub_dims = [self.dims[d] for d in range(self.ndims)
                    if remain_dims[d]]
        sub_periods = [self.periods[d] for d in range(self.ndims)
                       if remain_dims[d]]
        for color, comm in split.items():
            fixed = []
            cc = color
            for d in reversed(drop):
                fixed.append(cc % self.dims[d])
                cc //= self.dims[d]
            comm.topo = CartTopology(comm, sub_dims, sub_periods)
            out[tuple(reversed(fixed))] = comm
        return out


class GraphTopology:
    """MPI_Graph: global adjacency (index/edges CSR form)."""

    def __init__(self, comm, index: Sequence[int], edges: Sequence[int]
                 ) -> None:
        if len(index) != comm.size:
            raise ArgumentError("index length must equal comm size")
        self.comm = comm
        self.index = tuple(index)
        self.edges = tuple(edges)
        for e in self.edges:
            comm.check_rank(e)

    def neighbors(self, rank: int) -> list[int]:
        self.comm.check_rank(rank)
        lo = self.index[rank - 1] if rank else 0
        return list(self.edges[lo:self.index[rank]])

    def neighbor_count(self, rank: int) -> int:
        return len(self.neighbors(rank))


class DistGraphTopology:
    """MPI_Dist_graph: per-rank in/out neighbor lists (driver form: the
    controller supplies all ranks' adjacency)."""

    def __init__(self, comm, sources: dict[int, Sequence[int]],
                 destinations: dict[int, Sequence[int]]) -> None:
        self.comm = comm
        self.sources = {r: tuple(v) for r, v in sources.items()}
        self.destinations = {r: tuple(v) for r, v in destinations.items()}

    def in_neighbors(self, rank: int) -> tuple[int, ...]:
        return self.sources.get(rank, ())

    def out_neighbors(self, rank: int) -> tuple[int, ...]:
        return self.destinations.get(rank, ())


def dims_create(nnodes: int, ndims: int) -> tuple[int, ...]:
    """MPI_Dims_create: balanced factorization, decreasing order."""
    dims = [1] * ndims
    n = nnodes
    f = 2
    factors = []
    while f * f <= n:
        while n % f == 0:
            factors.append(f)
            n //= f
        f += 1
    if n > 1:
        factors.append(n)
    for f in sorted(factors, reverse=True):
        dims[dims.index(min(dims))] *= f
    return tuple(sorted(dims, reverse=True))


def _reordered(comm, topo_of) -> Optional[object]:
    """Treematch reorder: build the requested topology's comm graph on
    the ORIGINAL rank order, match it to the ICI coordinates, and return
    a comm whose rank order realizes the matching (reference:
    ompi/mca/topo/treematch tm_mapping.c; None = identity was optimal).
    """
    from . import treematch as tm

    probe = topo_of(comm)  # neighbor structure only; not attached
    W = tm.comm_graph_weights(comm, topo=probe)
    if not W.any():
        return None
    order = tm.reorder_ranks(comm, W=W)
    if order == list(comm.group.world_ranks):
        return None
    from ..group import Group

    return comm.create(Group(order))


def cart_create(comm, dims: Sequence[int],
                periods: Optional[Sequence[bool]] = None,
                reorder: bool = False):
    """MPI_Cart_create: returns a new communicator with `.topo` set.

    reorder=True runs treematch: ranks are permuted so the cartesian
    neighbor graph maps onto ICI-close devices (weighted-hop-distance
    minimizing; topo/treematch.py)."""
    if periods is None:
        periods = [False] * len(dims)
    new = None
    if reorder:
        new = _reordered(
            comm, lambda c: CartTopology(c, dims, periods)
        )
    if new is None:
        new = comm.dup()
    new.topo = CartTopology(new, dims, periods)
    new.set_name(f"{comm.name}.cart{tuple(dims)}")
    return new


def graph_create(comm, index: Sequence[int], edges: Sequence[int],
                 reorder: bool = False):
    """MPI_Graph_create; reorder=True treematches the explicit adjacency
    onto the ICI coordinates (the reference's treematch consumes exactly
    this graph form)."""
    new = None
    if reorder:
        new = _reordered(
            comm, lambda c: GraphTopology(c, index, edges)
        )
    if new is None:
        new = comm.dup()
    new.topo = GraphTopology(new, index, edges)
    return new


def dist_graph_create(comm, sources: dict, destinations: dict,
                      reorder: bool = False):
    """MPI_Dist_graph_create — the reference treematch's actual entry
    point (mca_topo_treematch_dist_graph_create)."""
    new = None
    if reorder:
        new = _reordered(
            comm, lambda c: DistGraphTopology(c, sources, destinations)
        )
    if new is None:
        new = comm.dup()
    new.topo = DistGraphTopology(new, sources, destinations)
    return new


# ---------------------------------------------------------------------------
# Neighbor collectives (reference: coll_base_functions.h:62-66,
# libnbc nbc_ineighbor_*.c) — driver forms over the p2p stack.
# ---------------------------------------------------------------------------

def neighbor_allgather(comm, x):
    """Each rank receives its topology neighbors' blocks, in neighbor
    order (in-neighbors for dist_graph). x: rank-major (size, ...).
    Returns {rank: (n_neigh, ...)}."""
    import jax.numpy as jnp

    topo = comm.topo
    if topo is None:
        raise TopologyError("communicator has no topology")
    _, ins = edge_fns(topo)
    arr = jnp.asarray(x)
    out = {}
    for r in range(comm.size):
        neigh = ins(r)
        out[r] = jnp.stack([arr[n] for n in neigh]) if neigh else (
            jnp.zeros((0,) + arr.shape[1:], arr.dtype)
        )
    return out


def edge_fns(topo):
    """(outs, ins) accessor pair for any topology kind — dist_graph
    distinguishes directions, cart/graph edges are symmetric."""
    def outs(r):
        if isinstance(topo, DistGraphTopology):
            return topo.out_neighbors(r)
        return topo.neighbors(r)

    def ins(r):
        if isinstance(topo, DistGraphTopology):
            return topo.in_neighbors(r)
        return topo.neighbors(r)

    return outs, ins


def neighbor_alltoall(comm, sendblocks: dict):
    """sendblocks[r] = (n_out_neighbors(r), ...) blocks, one per out
    neighbor in order; returns recvblocks[r] likewise from in neighbors.
    """
    import jax.numpy as jnp

    topo = comm.topo
    if topo is None:
        raise TopologyError("communicator has no topology")
    outs, ins = edge_fns(topo)

    # Mailbox delivery: a FIFO per (src, dst) pair — duplicate edges
    # (e.g. a periodic cart dimension of size 2 lists the same neighbor
    # twice) pair the k-th out-occurrence with the k-th in-occurrence,
    # the MPI position-wise matching; a plain dict would silently drop
    # all but the last duplicate's block.
    mail: dict[tuple[int, int], list] = {}
    for r in range(comm.size):
        blocks = sendblocks[r]
        for j, dst in enumerate(outs(r)):
            mail.setdefault((r, dst), []).append(blocks[j])
    out = {}
    for r in range(comm.size):
        got = []
        for src in ins(r):
            q = mail.get((src, r))
            if not q:
                # MPI semantics: every in-edge occurrence must have a
                # matching out-edge occurrence at the source; a silent
                # skip would misalign received blocks against
                # in-neighbor order.
                raise TopologyError(
                    f"rank {r} lists {src} as in-neighbor but rank "
                    f"{src} does not list {r} as out-neighbor (or edge "
                    f"multiplicities differ)"
                )
            got.append(q.pop(0))
        out[r] = jnp.stack(got) if got else None
    return out


def hardware_fingerprint(procs=None) -> str:
    """Stable digest of the hardware topology a schedule was tuned on.

    Canonicalizes what changes a collective schedule's cost surface —
    rank count, the host-group and slice-group partition shapes, and
    the device kinds — and hashes it, so the tuned schedule cache
    (coll/sched/cache.py) is keyed to "machines shaped like this" and a
    cache warmed on one v5e-16 pod slice is valid on every identically
    shaped slice, while a reshape (different host fan-out, different
    chip) re-tunes instead of replaying stale winners.
    """
    import hashlib
    import json

    from ..runtime import mesh

    if procs is None:
        procs = mesh.discover()
    canon = {
        "nranks": len(procs),
        "hosts": sorted(len(g) for g in mesh.hosts_of(procs).values()),
        "slices": sorted(len(g) for g in mesh.slices_of(procs).values()),
        "kinds": sorted({p.platform for p in procs}),
    }
    blob = json.dumps(canon, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]

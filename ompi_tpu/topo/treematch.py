"""Treematch — rank reordering matching the comm graph to the ICI mesh.

TPU-native equivalent of ompi/mca/topo/treematch (reference:
treematch/tm_tree.h — hierarchical grouping of the communication matrix;
tm_mapping.c — mapping grouped ranks onto the hardware tree, with
exchange-based refinement). The reference builds an affinity tree over
the comm matrix and matches it level-by-level against the hardware
topology tree; this module does the same with TPU geometry:

1. **hardware tree**: recursive bisection of the device slots along the
   widest ICI coordinate dimension — the natural hierarchy of a TPU
   mesh/torus (slice > plane > row > chip), standing in for the
   hwloc tree treematch consumes.
2. **affinity grouping**: at each tree node, ranks are partitioned to
   the children's capacities maximizing intra-group communication
   weight (greedy seeding + Kernighan-Lin-style swap refinement — the
   tm_grouping analog with arity fixed by the hardware split).
3. **refinement**: a final pairwise-exchange hill-climb on the exact
   objective sum_ij W[i,j] * hop(slot_i, slot_j) (tm_mapping's exchange
   pass).

The objective is weighted hop distance over the ICI mesh (Manhattan,
with per-dimension wraparound for torus links), i.e. congestion-free
nearest-neighbor cost — the right first-order model for ICI, where each
hop adds a store-and-forward latency and shares link bandwidth.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core.counters import SPC


def hop_distance(a: Sequence[int], b: Sequence[int],
                 wrap_dims: Optional[Sequence[int]] = None) -> int:
    """Manhattan hop count between ICI coordinates; `wrap_dims[d]` > 0
    enables torus wraparound with that dimension size."""
    total = 0
    for d, (x, y) in enumerate(zip(a, b)):
        diff = abs(int(x) - int(y))
        if wrap_dims is not None and d < len(wrap_dims) and wrap_dims[d]:
            diff = min(diff, int(wrap_dims[d]) - diff)
        total += diff
    return total


def _distance_matrix(coords: Sequence[Sequence[int]],
                     wrap_dims: Optional[Sequence[int]]) -> np.ndarray:
    n = len(coords)
    D = np.zeros((n, n), np.int64)
    for i in range(n):
        for j in range(i + 1, n):
            D[i, j] = D[j, i] = hop_distance(
                coords[i], coords[j], wrap_dims
            )
    return D


def total_hop_weight(W: np.ndarray, D: np.ndarray,
                     perm: Sequence[int]) -> float:
    """Objective: sum of comm weight x hop distance under `perm`
    (perm[rank] = hardware slot)."""
    p = np.asarray(perm)
    return float((W * D[np.ix_(p, p)]).sum()) / 2.0


def _bisect_slots(slots: list[int], coords) -> tuple[list[int], list[int]]:
    """Split slots into two halves along the widest coordinate spread —
    one level of the hardware tree."""
    cs = np.asarray([coords[s] for s in slots])
    spread = cs.max(axis=0) - cs.min(axis=0)
    dim = int(np.argmax(spread))
    order = sorted(slots, key=lambda s: (coords[s][dim], tuple(coords[s])))
    half = len(order) // 2
    return order[:half], order[half:]


def _partition_ranks(W: np.ndarray, ranks: list[int], size_a: int
                     ) -> tuple[list[int], list[int]]:
    """Partition `ranks` into (A of size_a, B) maximizing intra-group
    weight: greedy affinity seeding + swap refinement (tm_grouping)."""
    if size_a == 0:
        return [], list(ranks)
    ranks = list(ranks)
    sub = W[np.ix_(ranks, ranks)].astype(np.float64)
    # seed A with the heaviest-communicating pair's endpoint, then grow
    # by max attraction to A minus attraction to the remainder
    n = len(ranks)
    in_a = np.zeros(n, bool)
    seed = int(np.argmax(sub.sum(axis=1)))
    in_a[seed] = True
    while in_a.sum() < size_a:
        gain = np.where(
            in_a, -np.inf,
            sub[:, in_a].sum(axis=1) - sub[:, ~in_a].sum(axis=1),
        )
        in_a[int(np.argmax(gain))] = True
    # KL-style refinement: swap (a, b) pairs while intra-weight improves
    improved = True
    while improved:
        improved = False
        a_idx = np.where(in_a)[0]
        b_idx = np.where(~in_a)[0]
        # connection of each vertex to A and B
        to_a = sub[:, in_a].sum(axis=1)
        to_b = sub[:, ~in_a].sum(axis=1)
        best_gain, best_pair = 0.0, None
        for a in a_idx:
            for b in b_idx:
                # gain of swapping a<->b for intra-group weight
                gain = (to_a[b] - to_b[b]) + (to_b[a] - to_a[a]) \
                    - 2 * sub[a, b]
                if gain > best_gain + 1e-12:
                    best_gain, best_pair = gain, (a, b)
        if best_pair is not None:
            a, b = best_pair
            in_a[a], in_a[b] = False, True
            improved = True
    A = [ranks[i] for i in np.where(in_a)[0]]
    B = [ranks[i] for i in np.where(~in_a)[0]]
    return A, B


def _map_recursive(W: np.ndarray, ranks: list[int], slots: list[int],
                   coords, assign: dict[int, int]) -> None:
    if len(slots) <= 1 or len(set(map(tuple, (coords[s] for s in slots)))) == 1:
        for r, s in zip(ranks, slots):
            assign[r] = s
        return
    slots_a, slots_b = _bisect_slots(slots, coords)
    ranks_a, ranks_b = _partition_ranks(W, ranks, len(slots_a))
    _map_recursive(W, ranks_a, slots_a, coords, assign)
    _map_recursive(W, ranks_b, slots_b, coords, assign)


def _refine(W: np.ndarray, D: np.ndarray, perm: list[int],
            max_rounds: int = 8) -> list[int]:
    """Exchange hill climb on the exact objective (tm_mapping.c's
    exchange refinement): pairwise swaps, plus 3-cycle rotations on
    small comms to escape swap-stable local minima (a single swap
    cannot unwind a rotated triangle; three-rank cycles can)."""
    import itertools

    n = len(perm)
    perm = list(perm)

    def swap_delta(i: int, j: int) -> float:
        # O(n) exact cost change of swapping slots of ranks i and j:
        # sum_{k != i,j} (W[i,k] - W[j,k]) (D[pj,pk] - D[pi,pk]);
        # the (i,j) pair's own distance is unchanged by the swap.
        p = np.asarray(perm)
        vec = (W[i] - W[j]) * (D[perm[j], p] - D[perm[i], p])
        return float(vec.sum() - vec[i] - vec[j])

    for _ in range(max_rounds):
        improved = False
        for i in range(n):
            for j in range(i + 1, n):
                if swap_delta(i, j) < -1e-12:
                    perm[i], perm[j] = perm[j], perm[i]
                    improved = True
        if not improved and n <= 32:
            base = total_hop_weight(W, D, perm)
            for i, j, k in itertools.permutations(range(n), 3):
                cand = list(perm)
                cand[i], cand[j], cand[k] = perm[j], perm[k], perm[i]
                cost = total_hop_weight(W, D, cand)
                if cost < base - 1e-12:
                    perm = cand
                    improved = True
                    break
        if not improved:
            break
    return perm


def treematch_permutation(
    W: np.ndarray,
    coords: Sequence[Sequence[int]],
    wrap_dims: Optional[Sequence[int]] = None,
) -> list[int]:
    """Compute perm[rank] = hardware slot minimizing weighted hop
    distance. W is the (n, n) symmetric comm-weight matrix; coords[s]
    the ICI coordinates of slot s."""
    W = np.asarray(W, np.float64)
    n = W.shape[0]
    if W.shape != (n, n) or len(coords) != n:
        raise ValueError(
            f"need square W and one coord per slot: W{W.shape}, "
            f"{len(coords)} coords"
        )
    W = (W + W.T) / 2.0  # symmetrize: hops are undirected
    np.fill_diagonal(W, 0.0)  # self-traffic never crosses a link
    assign: dict[int, int] = {}
    _map_recursive(W, list(range(n)), list(range(n)), coords, assign)
    perm = [assign[r] for r in range(n)]
    D = _distance_matrix(coords, wrap_dims)
    perm = _refine(W, D, perm)
    SPC.record("topo_treematch_reorders")
    return perm


def comm_graph_weights(comm, topo=None) -> np.ndarray:
    """Comm-weight matrix from an attached topology's neighbor lists
    (unit weight per neighbor edge — the cart/graph creation case; the
    monitoring matrix can be passed to treematch_permutation directly
    for measured-traffic reordering)."""
    n = comm.size
    W = np.zeros((n, n), np.float64)
    src = topo if topo is not None else comm.topo
    if src is None:
        return W
    if hasattr(src, "neighbors"):
        for r in range(n):
            for nb in src.neighbors(r):
                W[r, nb] += 1.0
    else:  # DistGraphTopology: directed out-edges
        for r in range(n):
            for nb in src.out_neighbors(r):
                W[r, nb] += 1.0
    return W


def proc_coords(procs) -> tuple[list[tuple[int, ...]], None]:
    """Coordinates for a proc list; linear positions when the platform
    exposes none (CPU test meshes) so distance degrades to rank
    distance."""
    if procs and procs[0].coords is not None:
        return [tuple(p.coords) for p in procs], None
    return [(i,) for i in range(len(procs))], None


def reorder_ranks(comm, W: Optional[np.ndarray] = None,
                  wrap_dims: Optional[Sequence[int]] = None) -> list[int]:
    """World-rank order for a reordered communicator: rank i of the new
    comm is placed on the slot treematch assigns it (reference entry:
    mca_topo_treematch_dist_graph_create)."""
    coords, _ = proc_coords(comm.procs)
    if W is None:
        W = comm_graph_weights(comm)
    perm = treematch_permutation(W, coords, wrap_dims)
    # perm[rank] = slot. The reordered communicator's rank r must sit on
    # the device currently at parent slot perm[r], so the new Group
    # lists, in new-rank order, the world rank owning that slot.
    return [comm.group.world_rank(perm[r]) for r in range(comm.size)]

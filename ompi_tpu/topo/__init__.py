"""Process topologies (reference: ompi/mca/topo)."""

from .topology import (
    CartTopology,
    DistGraphTopology,
    GraphTopology,
    cart_create,
    dims_create,
    dist_graph_create,
    graph_create,
    hardware_fingerprint,
    neighbor_allgather,
    neighbor_alltoall,
)

__all__ = [
    "CartTopology", "DistGraphTopology", "GraphTopology", "cart_create",
    "dims_create", "dist_graph_create", "graph_create",
    "hardware_fingerprint", "neighbor_allgather", "neighbor_alltoall",
]

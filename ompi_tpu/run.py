"""Launcher shim — the mpirun analog.

The reference's mpirun IS the external PRRTE runtime (reference:
ompi/tools/mpirun/Makefile.am:25-29 — a symlink to `prte`; SURVEY §3.5
concludes the TPU build "needs only a thin launcher shim" because
placement is the platform's job and wire-up is `jax.distributed`).
This is that shim:

    python -m ompi_tpu.run [options] prog.py [args...]

Single-host: exec the program with auto-init. Multi-host: set the
jax.distributed coordinator variables (the PMIx-server analog) so the
program's `ompi_tpu.init(distributed=True)` wires every host; one
invocation per host (GKE/SLURM index arithmetic supplied via flags or
inherited env).
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="ompi_tpu.run",
        description="Launch a program under the ompi_tpu runtime",
    )
    ap.add_argument(
        "--coordinator", default=None,
        help="host:port of the jax.distributed coordinator "
        "(multi-host; process 0's address)",
    )
    ap.add_argument(
        "--num-processes", type=int, default=None,
        help="total controller processes in the job",
    )
    ap.add_argument(
        "--process-id", type=int, default=None,
        help="this controller's index (0-based)",
    )
    ap.add_argument(
        "--mca", action="append", default=[], metavar="VAR=VALUE",
        help="set a config var (reference: mpirun --mca), repeatable",
    )
    ap.add_argument(
        "--display-comm-method", action="store_true",
        help="print the transport selection table at init "
        "(reference: hook/comm_method)",
    )
    ap.add_argument("--no-auto-init", action="store_true",
                    help="do not call ompi_tpu.init() before the program")
    ap.add_argument("prog", help="python program to run")
    ap.add_argument("args", nargs=argparse.REMAINDER)
    return ap


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    for spec in args.mca:
        if "=" not in spec:
            raise SystemExit(f"--mca expects VAR=VALUE, got {spec!r}")
        var, val = spec.split("=", 1)
        # env-source precedence, exactly like OMPI_MCA_* variables
        os.environ[f"OMPITPU_MCA_{var}"] = val
    if args.display_comm_method:
        os.environ["OMPITPU_MCA_hook_comm_method_display"] = "1"

    distributed = args.coordinator is not None
    if distributed:
        if args.num_processes is None or args.process_id is None:
            raise SystemExit(
                "--coordinator requires --num-processes and --process-id"
            )

    if not args.no_auto_init:
        import ompi_tpu

        ompi_tpu.init(
            distributed=distributed,
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )

    sys.argv = [args.prog] + args.args
    runpy.run_path(args.prog, run_name="__main__")
    if not args.no_auto_init:
        import ompi_tpu

        ompi_tpu.finalize()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Deadline-aware weighted dispatch over tenant sessions.

One pump round serves each QoS class its ``weight`` in dispatch
quanta — guaranteed 8, burst 4, scavenger 1 — so a scavenger flood
can delay a guaranteed tenant by at most one residual quantum per
round, which is what pins the tenant_isolation bench's ≤10%
degradation bound. Within a class the order is earliest logical
deadline first (arrival slot + class horizon), tie-broken by
(tenant, sid) so the schedule is a pure function of the workload:
no wall clock anywhere in the ordering.

Fault attribution (the bulkhead edge): a dispatch that fails charges
tuned's per-comm ledger scope as usual; the dispatcher then *absorbs*
that comm scope into the tenant namespace and answers the client with
a RESULT(ok=False) — the fault is the tenant's, the pump keeps
serving everyone else. A RevokedError marks the session REVOKED (its
comm died — rank kill or revocation storm) for the service layer to
recover or evict; it is never charged to other tenants' scopes.
"""

from __future__ import annotations

import time

import numpy as np

from ..core.counters import SPC
from ..core.errors import RevokedError
from ..coll.sched import slo
from ..ft import inject
from .bulkhead import tenant_scope
from .qos import GUARANTEED, BURST, SCAVENGER
from . import protocol
from .session import ATTACHED, DRAINING, REVOKED, Request, Session

#: class service order per pump round (highest weight first)
SERVICE_ORDER = (GUARANTEED, BURST, SCAVENGER)


def _execute(session: Session, req: Request):
    """Run one collective on the session's comm. Payload semantics
    mirror the driver-model test idiom: allreduce distributes the
    (size, ...) rank-major payload, bcast roots rank 0's value."""
    comm = session.comm
    if req.op == "allreduce":
        return np.asarray(
            comm.allreduce(comm.put_rank_major(req.payload),
                           op=req.params.get("op", "sum"))
        )
    if req.op == "bcast":
        return np.asarray(
            comm.bcast(req.payload, root=req.params.get("root", 0))
        )
    if req.op == "barrier":
        comm.barrier()
        return None
    if req.op == "nop":
        # flood-synthetic filler: burns the flooder's own dispatch
        # quantum without touching the mesh
        return None
    raise protocol.ProtocolError(f"unknown daemon op {req.op!r}")


class Dispatcher:
    def __init__(self, daemon) -> None:
        self.daemon = daemon

    # -- candidate selection (pure logical order) ----------------------

    def _runnable(self, qos) -> list[Session]:
        out = [
            s for t in self.daemon.tenants.values()
            for s in t.sessions.values()
            if t.qos is qos and s.queue
            and s.state in (ATTACHED, DRAINING)
        ]
        out.sort(key=lambda s: (s.head_deadline(), s.tenant.name,
                                s.sid))
        return out

    def pump_round(self) -> int:
        """Serve every class its quantum; returns requests completed.
        Re-sorts after each dispatch so EDF order tracks queue heads.
        """
        served = 0
        for qos in SERVICE_ORDER:
            for _ in range(qos.weight):
                runnable = self._runnable(qos)
                if not runnable:
                    break
                self._dispatch_one(runnable[0])
                served += 1
        return served

    # -- one dispatch --------------------------------------------------

    def _dispatch_one(self, session: Session) -> None:
        daemon = self.daemon
        tenant = session.tenant
        req = session.queue.popleft()
        session.queued_bytes -= req.nbytes
        # the deny observation the isolation drill asserts stays
        # empty for compliant tenants (scope = this session's comm)
        denied = daemon.bulkhead.denied_tiers(session.comm)
        if denied:
            tenant.meter["denied_tier_observations"] += len(denied)
        daemon.log.note(
            f"dispatch tenant={tenant.name} sid={session.sid} "
            f"seq={req.seq} op={req.op} class={tenant.qos.name} "
            f"slot={req.arrival_slot} deadline={req.deadline_slot} "
            f"denied={len(denied)}"
        )
        # shared winner-cache read, accounted to the tenant scope
        daemon.note_cache_read(scope=tenant_scope(tenant.name))
        inject.on_daemon("dispatch", tenant=tenant.name,
                         cid=session.comm.cid)
        t0 = time.perf_counter()
        try:
            out = _execute(session, req)
        except RevokedError:
            session.state = REVOKED
            # fault stays with this tenant: absorb its comm scope
            daemon.bulkhead.absorb(tenant.name, session.comm,
                                   cause="revoked")
            tenant.meter["errors"] += 1
            req.reply = protocol.result(
                req.params["msg"], ok=False, detail="session revoked"
            )
            session.completed[req.seq] = req.reply
            daemon.log.note(
                f"revoked tenant={tenant.name} sid={session.sid} "
                f"seq={req.seq}"
            )
            return
        except Exception as exc:  # commlint: allow(broadexcept)
            # tier fault already ledgered by tuned under this comm's
            # scope; any failure crossing the daemon boundary is
            # answered, absorbed, and contained — never propagated
            # into the pump.
            daemon.bulkhead.absorb(tenant.name, session.comm,
                                   cause="dispatch-fault")
            tenant.meter["errors"] += 1
            req.reply = protocol.result(
                req.params["msg"], ok=False,
                detail=f"{type(exc).__name__}: {exc}",
            )
            session.completed[req.seq] = req.reply
            daemon.log.note(
                f"fault tenant={tenant.name} sid={session.sid} "
                f"seq={req.seq} exc={type(exc).__name__}"
            )
            return
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        tenant.meter["dispatched"] += 1
        SPC.record("daemon_dispatches")
        # SLO metering (wall-clock, meter-only — never in the log)
        target_us = tenant.qos.slo_p50_us
        if target_us and elapsed_ms * 1e3 > target_us:
            over_s = (elapsed_ms * 1e3 - target_us) / 1e6
            slo.note_violation(tenant_scope(tenant.name), over_s)
            tenant.meter["slo_violation_ms"] += over_s * 1e3
        req.reply = protocol.result(req.params["msg"], out)
        session.completed[req.seq] = req.reply

"""bulkhead wire protocol v1: the client<->daemon message frame.

One frame = 4-byte magic + 1 version byte + a dss-packed 6-tuple
``(kind, tenant, session, epoch, seq, body)``. dss already ships
ndarrays (the submit payloads) and dicts (everything else), so the
protocol layer is a thin, versioned envelope: a daemon that doesn't
speak the client's version rejects at decode, before any state is
touched.

Epoch stamping rides lifeboat's tag namespace: every admitted request
gets a wire tag ``stamp(cid, epoch, seq)`` in the same
``(cid+1) << 20`` id space as commtrace span ids and the revocation
fence, so a reply from a pre-eviction epoch can never be confused
with post-recovery traffic — the fence rejects it structurally, no
timestamps involved.

Request kinds (client -> daemon):
    hello    version/feature probe, no session required
    attach   open a session: tenant + qos class (+ optional ranks)
    submit   one collective: op, payload, params
    detach   close a session (drains first — never drops work)

Reply kinds (daemon -> client):
    welcome  hello response: version, qos classes, daemon name
    attached session id, comm cid, epoch, granted class
    admit    request admitted: seq + wire tag
    reject   admission refused: reason + seeded retry_after_ms
    result   completed collective: payload or error detail
    evicted  session was evicted (cause, final meter)
    detached clean close acknowledgement
    error    malformed / unknown-session / protocol fault
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ..core import dss
from ..core.errors import OmpiTpuError

PROTOCOL_VERSION = 1
MAGIC = b"OTPD"

# request kinds
HELLO = "hello"
ATTACH = "attach"
SUBMIT = "submit"
DETACH = "detach"
REQUEST_KINDS = frozenset((HELLO, ATTACH, SUBMIT, DETACH))

# reply kinds
WELCOME = "welcome"
ATTACHED = "attached"
ADMIT = "admit"
REJECT = "reject"
RESULT = "result"
EVICTED = "evicted"
DETACHED = "detached"
ERROR = "error"
REPLY_KINDS = frozenset((WELCOME, ATTACHED, ADMIT, REJECT, RESULT,
                         EVICTED, DETACHED, ERROR))


class ProtocolError(OmpiTpuError):
    errclass = "ERR_ARG"


@dataclass
class Message:
    """One protocol frame. ``body`` carries the kind-specific fields
    (op/payload for submit, reason/retry_after_ms for reject, ...)."""

    kind: str
    tenant: str = ""
    session: int = 0
    epoch: int = 0
    seq: int = 0
    body: dict = field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.kind not in REQUEST_KINDS and \
                self.kind not in REPLY_KINDS:
            raise ProtocolError(f"unknown message kind {self.kind!r}")


def stamp(cid: int, epoch: int, seq: int) -> int:
    """The request's wire tag in lifeboat's epoch-tag namespace:
    cid field above bit 20, epoch in bits 12..19, sequence below.
    Identical layout to ``lifeboat.epoch_tag`` so the revocation
    fence and commtrace spans see daemon traffic natively."""
    return ((cid + 1) << 20) | ((epoch & 0xFF) << 12) | (seq & 0xFFF)


def encode(msg: Message) -> bytes:
    return MAGIC + bytes((PROTOCOL_VERSION,)) + dss.pack(
        msg.kind, msg.tenant, int(msg.session), int(msg.epoch),
        int(msg.seq), msg.body,
    )


def decode(buf: bytes) -> Message:
    buf = bytes(buf)
    if len(buf) < len(MAGIC) + 1 or buf[:len(MAGIC)] != MAGIC:
        raise ProtocolError("not a bulkhead frame (bad magic)")
    version = buf[len(MAGIC)]
    if version != PROTOCOL_VERSION:
        raise ProtocolError(
            f"protocol version {version} unsupported "
            f"(daemon speaks {PROTOCOL_VERSION})"
        )
    try:
        kind, tenant, session, epoch, seq, body = \
            dss.unpack(buf[len(MAGIC) + 1:])
    except (dss.DssError, ValueError) as exc:
        raise ProtocolError(f"frame payload undecodable: {exc}") \
            from exc
    return Message(kind=kind, tenant=tenant, session=session,
                   epoch=epoch, seq=seq, body=body)


def reject(request: Message, *, reason: str,
           retry_after_ms: float) -> Message:
    """The canonical REJECT: always carries a machine-actionable
    reason and a positive seeded retry-after — admission refusal is
    flow control, never a silent drop."""
    return Message(REJECT, tenant=request.tenant,
                   session=request.session, epoch=request.epoch,
                   seq=request.seq,
                   body={"reason": reason,
                         "retry_after_ms": float(retry_after_ms)})


def error(detail: str, *, request: Optional[Message] = None) -> Message:
    m = request or Message(ERROR)
    return Message(ERROR, tenant=m.tenant, session=m.session,
                   epoch=m.epoch, seq=m.seq,
                   body={"detail": detail})


def result(request: Message, payload: Any = None, *,
           ok: bool = True, detail: str = "") -> Message:
    body: dict = {"ok": bool(ok)}
    if payload is not None:
        body["payload"] = payload
    if detail:
        body["detail"] = detail
    return Message(RESULT, tenant=request.tenant,
                   session=request.session, epoch=request.epoch,
                   seq=request.seq, body=body)

"""Zero-copy shared-memory ingest lane for local daemon clients.

Rides the PR-6 fastpath slab/ring machinery directly: the daemon owns
``ShmEndpoint(prefix, 0)``, each local client attaches as rank 1 and
posts protocol frames with ``fp_send`` — small frames (≤ 256 B:
hello/attach/barrier/detach and every reply header) ride the inline
descriptor tier, larger submits land in slab frames the daemon
*decodes in place* from the receive view (PiP-style: the payload
bytes are read straight out of the client's posted frame, released
back to the slab pool after decode — no intermediate copy buffer).
Frames too large for a slab frame spill to ``send_small``'s v2 path
exactly like organic fastpath traffic.

When the native engine is unavailable (no compiler in the container,
cvar off) the lane degrades to an in-process deque pair with the same
API, so every daemon test and drill runs identically — the shm lane
is a transport, never a semantic.

The client attach path goes through the dpm name service: the daemon
publishes ``bulkhead/<name>`` (prefix + protocol version), clients
``lookup_name`` it under a seeded ``core/backoff.Backoff`` deadline —
no bare spin loops (polldeadline's contract).
"""

from __future__ import annotations

import threading
from typing import Optional

from ..btl import sm
from ..core.backoff import Backoff
from ..core.counters import SPC
from ..core.errors import OmpiTpuError


class IngestError(OmpiTpuError):
    errclass = "ERR_INTERN"


def shm_available() -> bool:
    return sm.engine_available()


class LocalLane:
    """In-process fallback lane: two bounded deques. Deterministic
    and allocation-cheap — the drill/test transport."""

    kind = "local"

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._to_daemon: list[tuple[int, bytes]] = []
        self._to_client: list[tuple[int, bytes]] = []

    # client side
    def submit(self, tag: int, frame: bytes) -> bool:
        with self._mu:
            self._to_daemon.append((tag, bytes(frame)))
        return True

    def poll_reply(self) -> Optional[tuple[int, bytes]]:
        with self._mu:
            if not self._to_client:
                return None
            return self._to_client.pop(0)

    # daemon side
    def drain(self, max_msgs: int = 16) -> list:
        """List of (tag, frame, release_token); token -1 = nothing to
        release (API parity with the shm lane's slab tokens)."""
        with self._mu:
            out = self._to_daemon[:max_msgs]
            del self._to_daemon[:max_msgs]
        return [(tag, frame, -1) for tag, frame in out]

    def release(self, token: int) -> None:
        pass  # nothing slab-backed to return

    def reply(self, tag: int, frame: bytes) -> bool:
        with self._mu:
            self._to_client.append((tag, bytes(frame)))
        return True

    def close(self) -> None:
        with self._mu:
            self._to_daemon.clear()
            self._to_client.clear()


class ShmLane:
    """Fastpath-backed lane. Daemon is fp rank 0, the client rank 1.

    Descriptor tags carry the protocol's epoch-stamped wire tag, so a
    stale client's post-eviction frames are identifiable before
    decode (the fence check is the service layer's job; the lane only
    moves bytes)."""

    kind = "shm"
    DAEMON_RANK = 0
    CLIENT_RANK = 1

    def __init__(self, ep, peer: int, *, prefix: str = "",
                 connected: bool = True) -> None:
        self.ep = ep
        self.peer = peer
        self.prefix = prefix
        self._connected = connected

    @classmethod
    def create(cls, prefix: str) -> "ShmLane":
        # Daemon side: publish our segment now, attach the client's
        # LAZILY — the daemon starts long before any client exists,
        # and must never block its pump waiting for one.
        ep = sm.ShmEndpoint(prefix, cls.DAEMON_RANK)
        return cls(ep, cls.CLIENT_RANK, prefix=prefix, connected=False)

    @classmethod
    def attach(cls, prefix: str) -> "ShmLane":
        ep = sm.ShmEndpoint(prefix, cls.CLIENT_RANK)
        ep.connect(cls.DAEMON_RANK)
        return cls(ep, cls.DAEMON_RANK, prefix=prefix)

    def _ensure_peer(self, timeout_s: float = 0.05) -> bool:
        if self._connected:
            return True
        try:
            self.ep.connect(self.peer, timeout_s=timeout_s)
        except sm.ShmError:
            return False  # no client yet: nothing to drain
        self._connected = True
        return True

    def _post(self, tag: int, frame: bytes) -> bool:
        if self.ep.fp_send(self.peer, tag, frame):
            SPC.record("daemon_ingest_fp_frames")
            return True
        # ring/slab full or frame larger than a slab frame: spill to
        # the v2 small-message path like any fastpath producer
        self.ep.send_small(self.peer, tag, frame)
        SPC.record("daemon_ingest_spills")
        return True

    # client side
    def submit(self, tag: int, frame: bytes) -> bool:
        return self._post(tag, frame)

    def poll_reply(self) -> Optional[tuple[int, bytes]]:
        got = self.ep.fp_try_recv_view(self.peer)
        if got is None:
            return None
        tag, view, tok = got
        try:
            return tag, bytes(view)
        finally:
            self.ep.fp_release(tok)

    # daemon side
    def drain(self, max_msgs: int = 16) -> list:
        """List of (tag, view, release_token). Frame-backed views
        alias the client's slab frame IN the shared segment — the
        service decodes straight out of it (PiP-style, no staging
        copy) and must ``release(token)`` afterwards; inline payloads
        arrive pre-materialized by fp_drain_views."""
        if not self._ensure_peer():
            return []
        return self.ep.fp_drain_views(self.peer, max_msgs=max_msgs)

    def release(self, token: int) -> None:
        self.ep.fp_release(token)

    def reply(self, tag: int, frame: bytes) -> bool:
        return self._post(tag, frame)

    def close(self) -> None:
        self.ep.close()


def connect_client(daemon_name: str = "bulkhead", *,
                   timeout: float = 5.0) -> "ShmLane":
    """Client attach: resolve ``bulkhead/<name>`` through the dpm
    name service (lookup_name polls under its own Backoff deadline)
    and attach to the daemon's shm prefix. Version skew is rejected
    here, before any frame is posted."""
    from ..runtime import dpm

    port = dpm.lookup_name(f"bulkhead/{daemon_name}", timeout=timeout)
    if not isinstance(port, dict) or "prefix" not in port:
        raise IngestError(
            f"daemon {daemon_name!r}: bad name-service record"
        )
    from . import protocol

    version = port.get("version")
    if version != protocol.PROTOCOL_VERSION:
        raise IngestError(
            f"daemon {daemon_name!r} speaks protocol {version}, "
            f"client speaks {protocol.PROTOCOL_VERSION}"
        )
    return ShmLane.attach(port["prefix"])


def wait_reply(lane, *, timeout: float = 10.0,
               seed: int = 0) -> tuple[int, bytes]:
    """Deadline-bounded reply poll (Backoff evidence, never a bare
    spin). Raises IngestError past the deadline."""
    bo = Backoff(initial=1e-5, maximum=0.005, timeout=timeout,
                 seed=seed)
    while True:
        got = lane.poll_reply()
        if got is not None:
            return got
        if not bo.sleep():
            raise IngestError(
                f"no daemon reply within {timeout}s"
            )

"""bulkhead — per-tenant fault isolation over the health ledger.

The ledger (PR 8) already scopes every (tier, state) entry by
communicator cid, and tuned's dispatch charges failures to
``str(comm.cid)``. The bulkhead turns those comm scopes into a
*tenant* boundary by adding one durable namespace per tenant,
``tenant:<id>``, and moving state across the two scope kinds at the
session lifecycle edges:

    attach   seed the fresh session comm's scope FROM the tenant
             namespace — a tenant that wedged its device tier five
             sessions ago is still denied it on session six
    absorb   after a session-scoped fault, mirror the comm scope's
             non-HEALTHY entries INTO the tenant namespace — the
             quarantine survives the session
    evict    lifeboat.detach() the comm (revoke → quiesce → free →
             comm-scope GC); when the tenant's last session is gone
             and the eviction is tenant-level, GC the tenant
             namespace too — zero orphaned scopes

Shared warm state (sched winner cache, fastpath rings, the device
tunnel) is never scoped to a tenant, so none of this touches it: one
tenant's quarantine denies *its* scopes only, and ``is_denied`` for
every other tenant keeps consulting (their scope, global) exactly as
before.

Decisions land in a numbered, timestamp-free log (ledger/lifeboat
idiom) whose sha256 is byte-identical across same-seed controllers.
"""

from __future__ import annotations

import hashlib
import threading

from ..core.counters import SPC
from ..ft import lifeboat
from ..health import ledger as health

TENANT_PREFIX = "tenant:"


def tenant_scope(tenant: str) -> str:
    """The tenant's durable ledger namespace."""
    return TENANT_PREFIX + tenant


class DecisionLog:
    """Numbered timestamp-free decision lines + sha256 digest — the
    same byte-identity contract as the ledger transition log and
    lifeboat's recovery log."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._lines: list[str] = []

    def note(self, line: str) -> None:
        with self._mu:
            self._lines.append(f"{len(self._lines)} {line}")

    def lines(self) -> list[str]:
        with self._mu:
            return list(self._lines)

    def digest(self) -> str:
        with self._mu:
            text = "\n".join(self._lines)
        return hashlib.sha256(text.encode()).hexdigest()


class Bulkhead:
    """Scope plumbing between session comms and tenant namespaces."""

    def __init__(self, log: DecisionLog) -> None:
        self.log = log

    def on_attach(self, tenant: str, comm) -> int:
        """Seed the new session comm scope from the tenant namespace
        (then from global, which seed_scope's default path already
        gives every comm via tuned's normal consult order)."""
        seeded = health.LEDGER.seed_scope(
            str(comm.cid), src=tenant_scope(tenant),
            cause="bulkhead-attach",
        )
        if seeded:
            self.log.note(
                f"seed tenant={tenant} cid={comm.cid} "
                f"entries={seeded}"
            )
        return seeded

    def absorb(self, tenant: str, comm, *, cause: str) -> int:
        """Mirror the session comm's non-HEALTHY ledger entries into
        the tenant namespace so the fault outlives the session."""
        absorbed = health.LEDGER.seed_scope(
            tenant_scope(tenant), src=str(comm.cid),
            cause=f"bulkhead-{cause}",
        )
        if absorbed:
            SPC.record("daemon_faults_absorbed", absorbed)
            self.log.note(
                f"absorb tenant={tenant} cid={comm.cid} "
                f"cause={cause} entries={absorbed}"
            )
        return absorbed

    def denied_tiers(self, comm) -> list[str]:
        """Tiers the ledger denies for this session's scope — the
        per-dispatch observation the isolation drill asserts stays
        empty for the compliant tenant."""
        scope = str(comm.cid)
        return [t for t in health.TIERS
                if health.LEDGER.is_denied(t, scope)]

    def evict_session(self, tenant: str, comm, *, cause: str) -> dict:
        """One session's deterministic teardown: absorb its faults
        into the tenant namespace, then lifeboat's revoke → quiesce →
        detach (which GCs the comm scope)."""
        absorbed = self.absorb(tenant, comm, cause=cause)
        report = lifeboat.detach(comm, cause=f"evict-{tenant}")
        self.log.note(
            f"evict tenant={tenant} cid={comm.cid} cause={cause} "
            f"absorbed={absorbed} drained={report['drained']} "
            f"cancelled={report['cancelled']} "
            f"ledger_gc={report['ledger_gc']}"
        )
        SPC.record("daemon_evictions")
        return report

    def release_tenant(self, tenant: str) -> int:
        """Tenant-level eviction epilogue: GC the tenant namespace.
        After this, ``health.LEDGER.scopes()`` must show no scope
        owned by the tenant — the zero-orphaned-scopes invariant."""
        gcd = health.LEDGER.gc_scope(tenant_scope(tenant),
                                     cause="evict")
        self.log.note(f"release tenant={tenant} ledger_gc={gcd}")
        return gcd

"""bulkhead — the multi-tenant comm daemon.

One long-lived service multiplexes many client sessions onto one
device mesh: a versioned wire protocol over a zero-copy shm ingest
lane, per-tenant QoS (guaranteed / burst / scavenger) with
deterministic weighted admission, bulkhead fault isolation over the
health ledger's scope namespaces, and lifeboat-grade eviction. See
docs/DAEMON.md.
"""

from .bulkhead import Bulkhead, DecisionLog, tenant_scope
from .ingest import IngestError, LocalLane, ShmLane, shm_available, \
    wait_reply
from .protocol import (Message, PROTOCOL_VERSION, ProtocolError,
                       decode, encode, stamp)
from .qos import (ADMITTED, BURST, GUARANTEED, SCAVENGER, Admission,
                  QosClass, qos_class, tenant_seed)
from .service import Daemon, DaemonError, current, start, stop
from .session import Request, Session, Tenant

__all__ = [
    "ADMITTED", "Admission", "BURST", "Bulkhead", "Daemon",
    "DaemonError", "DecisionLog", "GUARANTEED", "IngestError",
    "LocalLane", "Message", "PROTOCOL_VERSION", "ProtocolError",
    "QosClass", "Request", "SCAVENGER", "Session", "ShmLane",
    "Tenant", "current", "decode", "encode", "qos_class",
    "shm_available", "stamp", "start", "stop", "tenant_scope",
    "tenant_seed", "wait_reply",
]

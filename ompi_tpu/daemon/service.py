"""The bulkhead daemon: one long-lived comm service, many tenants.

Multiplexes client sessions onto one device mesh. Each session owns a
communicator carved from the daemon's base comm (so tuned's dispatch,
the health ledger, commtrace spans, and lifeboat's revocation fence
all scope to it natively); each tenant owns an admission token
bucket, bounded queues, a meter, and a ``tenant:<id>`` ledger
namespace the bulkhead moves fault state through.

Event flow per pump round::

    lane.drain -> decode -> handle (admit/reject) -> refill tokens
        -> dispatcher.pump_round (weighted EDF) -> replies out

Everything the daemon *decides* — attach, admit, reject (with its
seeded retry-after), dispatch order, absorb, evict, recover — lands
in one numbered timestamp-free decision log; same seed + same
workload replays byte-identically on another controller
(``Daemon.digest()``). Wall-clock exists only in meters.

Eviction is lifeboat's pipeline: absorb faults into the tenant
namespace, revoke → quiesce → detach each session comm (queued work
is answered with EVICTED, never dropped), then GC the tenant
namespace — ``health.LEDGER.scopes()`` shows zero orphaned scopes
afterwards.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Optional

import numpy as np

from ..core import config
from ..core.backoff import Backoff
from ..core.counters import SPC
from ..core.errors import OmpiTpuError
from ..core.logging import get_logger
from ..coll.sched import cache as scache
from ..coll.sched import slo
from ..ft import inject
from . import ingest, protocol
from .bulkhead import Bulkhead, DecisionLog, tenant_scope
from .dispatch import Dispatcher
from .qos import ADMITTED, QosError, qos_class, tenant_seed
from .session import (ATTACHED, DETACHED, DRAINING, EVICTED, REVOKED,
                      Request, Session, Tenant)

logger = get_logger("daemon")

_max_sessions_var = config.register(
    "daemon", "base", "max_sessions", type=int, default=64,
    description="Hard cap on concurrently attached sessions across "
                "all tenants (attach beyond it is rejected)",
)
_state_path_var = config.register(
    "daemon", "base", "state_path", type=str, default="",
    description="When set, every pump atomically rewrites this JSON "
                "status snapshot and consumes operator commands from "
                "'<path>.cmd' — the tools/daemon CLI seam",
)
_lane_var = config.register(
    "daemon", "base", "lane", type=str, default="auto",
    description="Ingest lane: 'shm' (fastpath slab/ring), 'local' "
                "(in-process), 'auto' (shm when the native engine is "
                "available)",
)


class DaemonError(OmpiTpuError):
    errclass = "ERR_INTERN"


class Daemon:
    def __init__(self, base_comm=None, *, name: str = "bulkhead",
                 seed: int = 0,
                 lane: Optional[str] = None) -> None:
        if base_comm is None:
            from .. import api

            base_comm = api.world()
        self.name = name
        self.seed = int(seed)
        self.world = base_comm
        self.log = DecisionLog()
        self.bulkhead = Bulkhead(self.log)
        self.dispatcher = Dispatcher(self)
        self.tenants: dict[str, Tenant] = {}
        self.sessions: dict[int, Session] = {}
        self.history: dict[str, dict] = {}  # evicted tenants' meters
        self._mu = threading.RLock()
        self._next_sid = 1
        self._slot = 0  # logical arrival clock (never wall time)
        self._stopped = False
        lane_kind = lane if lane is not None else _lane_var.value
        if lane_kind == "auto":
            lane_kind = "shm" if ingest.shm_available() else "local"
        if lane_kind == "shm":
            self.lane: Any = ingest.ShmLane.create(
                f"bkd{os.getpid()}x{self.seed}"
            )
            # rendezvous record for connect_client(): clients resolve
            # the shm prefix + protocol version through dpm before
            # posting any frame
            from ..runtime import dpm

            dpm.publish_name(
                f"bulkhead/{name}",
                {"prefix": self.lane.prefix,
                 "version": protocol.PROTOCOL_VERSION},
            )
        elif lane_kind == "local":
            self.lane = ingest.LocalLane()
        else:
            raise DaemonError(f"unknown ingest lane {lane_kind!r}")
        self.log.note(
            f"start name={name} seed={self.seed} "
            f"version={protocol.PROTOCOL_VERSION} "
            f"lane={self.lane.kind} base_cid={base_comm.cid}"
        )

    # -- logical time ----------------------------------------------------

    def _tick(self) -> int:
        self._slot += 1
        return self._slot

    def note_cache_read(self, *, scope: str) -> None:
        scache.CACHE.note_read(scope=scope)

    # -- wire entry ------------------------------------------------------

    def handle(self, msg: protocol.Message) -> protocol.Message:
        """One request in, one reply out — the single choke point
        both the shm lane and in-process clients go through."""
        with self._mu:
            if self._stopped:
                return protocol.error("daemon stopped", request=msg)
            try:
                if msg.kind == protocol.HELLO:
                    return self._handle_hello(msg)
                if msg.kind == protocol.ATTACH:
                    return self._handle_attach(msg)
                if msg.kind == protocol.SUBMIT:
                    return self._handle_submit(msg)
                if msg.kind == protocol.DETACH:
                    return self._handle_detach(msg)
            except (protocol.ProtocolError, QosError) as exc:
                return protocol.error(str(exc), request=msg)
            return protocol.error(
                f"unexpected request kind {msg.kind!r}", request=msg
            )

    # -- hello -----------------------------------------------------------

    def _handle_hello(self, msg: protocol.Message) -> protocol.Message:
        from .qos import CLASSES

        return protocol.Message(
            protocol.WELCOME, tenant=msg.tenant,
            body={
                "name": self.name,
                "version": protocol.PROTOCOL_VERSION,
                "classes": sorted(CLASSES),
                "lane": self.lane.kind,
            },
        )

    # -- attach ----------------------------------------------------------

    def _handle_attach(self, msg: protocol.Message) -> protocol.Message:
        if not msg.tenant:
            raise protocol.ProtocolError("attach requires a tenant id")
        qos_name = msg.body.get("qos", "burst")
        qos = qos_class(qos_name)
        if len(self.sessions) >= _max_sessions_var.value:
            # attach pressure is admission pressure: bounded, counted,
            # answered with a seeded retry-after
            t = self._tenant(msg.tenant, qos)
            t.meter["rejected"] += 1
            verdict, retry_ms = t.admission.try_admit(
                queued=t.qos.queue_depth, queued_bytes=0, nbytes=0
            )
            self.log.note(
                f"reject tenant={msg.tenant} op=attach "
                f"reason=max_sessions retry_after_ms={retry_ms}"
            )
            return protocol.reject(msg, reason="max_sessions",
                                   retry_after_ms=retry_ms)
        tenant = self._tenant(msg.tenant, qos)
        inject.on_daemon("attach", tenant=tenant.name)
        ranks = msg.body.get("ranks")
        if ranks:
            comm = self.world.create(
                self.world.group.incl(list(ranks))
            )
        else:
            comm = self.world.dup()
        sid = self._next_sid
        self._next_sid += 1
        session = Session(sid, tenant, comm)
        tenant.sessions[sid] = session
        self.sessions[sid] = session
        tenant.meter["sessions"] += 1
        SPC.record("daemon_sessions_attached")
        seeded = self.bulkhead.on_attach(tenant.name, comm)
        if tenant.qos.slo_p50_us:
            slo.set_target(str(comm.cid), tenant.qos.slo_p50_us)
        self.log.note(
            f"attach tenant={tenant.name} sid={sid} cid={comm.cid} "
            f"epoch={comm.epoch} class={tenant.qos.name} "
            f"ranks={len(ranks) if ranks else comm.size} "
            f"seeded={seeded}"
        )
        return protocol.Message(
            protocol.ATTACHED, tenant=tenant.name, session=sid,
            epoch=comm.epoch,
            body={"cid": comm.cid, "qos": tenant.qos.name,
                  "size": comm.size},
        )

    def _tenant(self, name: str, qos) -> Tenant:
        t = self.tenants.get(name)
        if t is None:
            t = Tenant(name, qos,
                       seed=tenant_seed(self.seed, name))
            self.tenants[name] = t
        return t

    # -- submit / admission ----------------------------------------------

    def _handle_submit(self, msg: protocol.Message) -> protocol.Message:
        session = self.sessions.get(msg.session)
        if session is None:
            return protocol.error(
                f"unknown session {msg.session}", request=msg
            )
        if session.state in (EVICTED, DETACHED):
            return protocol.Message(
                protocol.EVICTED, tenant=msg.tenant,
                session=msg.session,
                body={"cause": session.state},
            )
        if session.state == REVOKED:
            return protocol.error(
                "session comm revoked; recover_tenant() or detach",
                request=msg,
            )
        tenant = session.tenant
        # adversarial-tenant probes: flood/hog amplify HERE, through
        # the same admission path as organic traffic
        for spec in inject.on_daemon("submit", tenant=tenant.name,
                                     cid=session.comm.cid):
            if spec.action == "flood":
                self._flood(session, spec.rate)
            elif spec.action == "hog":
                self._hog(tenant, spec.nbytes)
        op = msg.body.get("op", "")
        payload = msg.body.get("payload")
        nbytes = int(np.asarray(payload).nbytes) \
            if payload is not None else 0
        tenant.meter["requests"] += 1
        verdict, retry_ms = tenant.admission.try_admit(
            queued=tenant.queued(),
            queued_bytes=tenant.queued_bytes(),
            nbytes=nbytes,
        )
        if verdict != ADMITTED:
            tenant.meter["rejected"] += 1
            self.log.note(
                f"reject tenant={tenant.name} sid={session.sid} "
                f"op={op} reason={verdict} "
                f"retry_after_ms={retry_ms}"
            )
            return protocol.reject(msg, reason=verdict,
                                   retry_after_ms=retry_ms)
        seq = session.next_seq()
        slot = self._tick()
        tag = protocol.stamp(session.comm.cid, session.comm.epoch,
                             seq)
        params = dict(msg.body.get("params") or {})
        params["msg"] = msg
        req = Request(
            seq=seq, op=op, payload=payload, nbytes=nbytes, tag=tag,
            arrival_slot=slot,
            deadline_slot=slot + tenant.qos.deadline_slots,
            params=params,
        )
        session.queue.append(req)
        session.queued_bytes += nbytes
        tenant.meter["admitted"] += 1
        tenant.meter["bytes"] += nbytes
        self.log.note(
            f"admit tenant={tenant.name} sid={session.sid} "
            f"seq={seq} op={op} bytes={nbytes} slot={slot} "
            f"deadline={req.deadline_slot}"
        )
        return protocol.Message(
            protocol.ADMIT, tenant=tenant.name,
            session=session.sid, epoch=session.comm.epoch, seq=seq,
            body={"tag": tag, "slot": slot},
        )

    def _flood(self, session: Session, rate: int) -> None:
        """Amplify a flood@daemon firing: ``rate`` synthetic no-op
        submits pushed through admission. Admitted ones clog the
        flooding tenant's own (bounded) queue; the rest are rejected
        and counted. One summary decision line keeps the log compact
        and deterministic."""
        tenant = session.tenant
        admitted = rejected = 0
        for _ in range(rate):
            verdict, _retry = tenant.admission.try_admit(
                queued=tenant.queued(),
                queued_bytes=tenant.queued_bytes(), nbytes=0,
            )
            if verdict != ADMITTED:
                tenant.meter["rejected"] += 1
                rejected += 1
                continue
            admitted += 1
            seq = session.next_seq()
            slot = self._tick()
            nop = protocol.Message(
                protocol.SUBMIT, tenant=tenant.name,
                session=session.sid, body={"op": "nop"},
            )
            session.queue.append(Request(
                seq=seq, op="nop", payload=None, nbytes=0,
                tag=protocol.stamp(session.comm.cid,
                                   session.comm.epoch, seq),
                arrival_slot=slot,
                deadline_slot=slot + tenant.qos.deadline_slots,
                params={"msg": nop},
            ))
        tenant.meter["flood_synthetic"] += rate
        SPC.record("daemon_flood_synthetic", rate)
        self.log.note(
            f"flood tenant={tenant.name} sid={session.sid} "
            f"rate={rate} admitted={admitted} rejected={rejected}"
        )

    def _hog(self, tenant: Tenant, nbytes: int) -> None:
        """Charge a hog@daemon firing against the tenant's queue
        byte budget — subsequent submits hit R_BYTES until eviction
        (or detach) releases the charge."""
        tenant.hogged_bytes += nbytes
        tenant.meter["hog_bytes"] += nbytes
        SPC.record("daemon_hog_bytes", nbytes)
        self.log.note(
            f"hog tenant={tenant.name} bytes={nbytes} "
            f"hogged={tenant.hogged_bytes}"
        )

    # -- detach ----------------------------------------------------------

    def _handle_detach(self, msg: protocol.Message) -> protocol.Message:
        session = self.sessions.get(msg.session)
        if session is None:
            return protocol.error(
                f"unknown session {msg.session}", request=msg
            )
        tenant = session.tenant
        inject.on_daemon("detach", tenant=tenant.name,
                         cid=session.comm.cid)
        session.state = DRAINING
        # drain-before-detach: queued work completes (bounded — the
        # queue is bounded and nothing new is admitted in DRAINING)
        while session.queue:
            self.dispatcher.pump_round()
        self.bulkhead.evict_session(tenant.name, session.comm,
                                    cause="detach")
        slo.set_target(str(session.comm.cid), None)
        session.state = DETACHED
        tenant.sessions.pop(session.sid, None)
        self.sessions.pop(session.sid, None)
        tenant.meter["sessions"] -= 1
        self.log.note(
            f"detach tenant={tenant.name} sid={session.sid} "
            f"cid={session.comm.cid}"
        )
        return protocol.Message(
            protocol.DETACHED, tenant=tenant.name,
            session=session.sid,
            body={"completed": len(session.completed)},
        )

    # -- eviction (operator / policy) ------------------------------------

    def evict(self, tenant_name: str, *,
              cause: str = "operator") -> dict:
        """Tenant-level eviction: every session revoked → quiesced →
        detached (queued requests answered EVICTED — never silently
        dropped), hog charges released, SLO targets cleared, tenant
        namespace GC'd. Deterministic: one numbered line per phase."""
        with self._mu:
            tenant = self.tenants.get(tenant_name)
            if tenant is None:
                raise DaemonError(f"unknown tenant {tenant_name!r}")
            dropped = 0
            for session in sorted(tenant.sessions.values(),
                                  key=lambda s: s.sid):
                for req in session.queue:
                    req.reply = protocol.Message(
                        protocol.EVICTED, tenant=tenant_name,
                        session=session.sid, seq=req.seq,
                        body={"cause": cause},
                    )
                    session.completed[req.seq] = req.reply
                    dropped += 1
                session.queue.clear()
                session.queued_bytes = 0
                self.bulkhead.evict_session(tenant_name,
                                            session.comm,
                                            cause=cause)
                slo.set_target(str(session.comm.cid), None)
                session.state = EVICTED
                self.sessions.pop(session.sid, None)
            tenant.sessions.clear()
            tenant.hogged_bytes = 0
            tenant.meter["evictions"] += 1
            tenant.meter["sessions"] = 0
            released = self.bulkhead.release_tenant(tenant_name)
            slo.set_target(tenant_scope(tenant_name), None)
            self.history[tenant_name] = dict(tenant.meter,
                                             qos=tenant.qos.name)
            self.tenants.pop(tenant_name, None)
            self.log.note(
                f"evicted tenant={tenant_name} cause={cause} "
                f"answered={dropped} released={released}"
            )
            return {"tenant": tenant_name, "answered": dropped,
                    "released": released}

    # -- recovery --------------------------------------------------------

    def recover_tenant(self, tenant_name: str, *,
                       onto: Any = None) -> dict:
        """Recover a tenant whose session comms were revoked (rank
        death): lifeboat's shrink pipeline per session, then rebind —
        the session keeps its sid and meter, gets a fresh comm, cid
        scope seeded from the tenant namespace, epoch bumped.

        With ``onto`` (a grown world from ``lazarus.grow``), every
        session rebinds onto a dup of it instead — revoked sessions
        skip the shrink (the grown comm already carries the bumped
        epoch and the re-admitted ranks), and LIVE sessions move too:
        a session left on the pre-grow comm would keep running at the
        shrunk size forever."""
        with self._mu:
            from ..ft import lifeboat

            tenant = self.tenants.get(tenant_name)
            if tenant is None:
                raise DaemonError(f"unknown tenant {tenant_name!r}")
            recovered = 0
            for session in sorted(tenant.sessions.values(),
                                  key=lambda s: s.sid):
                revoked = session.state == REVOKED or \
                    lifeboat.revoked(session.comm)
                if onto is None and not revoked:
                    continue
                old = session.comm
                if onto is not None:
                    lifeboat.check(onto)  # epoch fence: never rebind
                    # onto a world revoked since it grew
                    new = onto.dup()
                    new.epoch = onto.epoch
                else:
                    new = lifeboat.recover(old, quiesce_timeout=0.5,
                                           seed=self.seed)
                session.comm = new
                session.state = ATTACHED
                self.bulkhead.on_attach(tenant_name, new)
                if tenant.qos.slo_p50_us:
                    slo.set_target(str(new.cid),
                                   tenant.qos.slo_p50_us)
                    slo.set_target(str(old.cid), None)
                recovered += 1
                verb = "regrow" if onto is not None else "recover"
                self.log.note(
                    f"{verb} tenant={tenant_name} "
                    f"sid={session.sid} cid={old.cid}->{new.cid} "
                    f"epoch={old.epoch}->{new.epoch} "
                    f"survivors={new.size}"
                )
            SPC.record("daemon_recoveries", recovered)
            return {"tenant": tenant_name, "recovered": recovered}

    # -- pump ------------------------------------------------------------

    def pump(self, rounds: int = 1) -> int:
        """The daemon's heartbeat: ingest, refill, dispatch."""
        served = 0
        for _ in range(rounds):
            with self._mu:
                self._pump_lane()
                for t in self.tenants.values():
                    t.admission.refill()
                served += self.dispatcher.pump_round()
        state_path = _state_path_var.value
        if state_path:
            self.process_control(state_path + ".cmd")
            self.save_state(state_path)
        return served

    def _pump_lane(self) -> None:
        for tag, frame, token in self.lane.drain():
            try:
                msg = protocol.decode(frame)
            except protocol.ProtocolError as exc:
                SPC.record("daemon_protocol_errors")
                reply = protocol.error(str(exc))
            else:
                reply = self.handle(msg)
            finally:
                self.lane.release(token)
            self.lane.reply(tag, protocol.encode(reply))

    def drain(self, *, timeout: float = 30.0) -> int:
        """Pump until every dispatchable queue is empty (deadline-
        bounded: a REVOKED session's queue cannot drain — recover or
        evict it first; past the deadline this raises)."""
        bo = Backoff(initial=1e-4, maximum=0.01, timeout=timeout,
                     seed=self.seed)
        served = 0
        while True:
            pending = sum(
                len(s.queue) for s in self.sessions.values()
                if s.state in (ATTACHED, DRAINING)
            )
            if pending == 0:
                return served
            served += self.pump()
            if not bo.sleep():
                raise DaemonError(
                    f"drain deadline ({timeout}s) with {pending} "
                    f"request(s) stuck"
                )

    # -- client fetch ----------------------------------------------------

    def fetch(self, sid: int, seq: int) -> Optional[protocol.Message]:
        """Pop a completed request's reply (RESULT / EVICTED)."""
        session = self.sessions.get(sid)
        if session is None:
            return None
        return session.completed.pop(seq, None)

    # -- introspection / metering ----------------------------------------

    def metering(self) -> dict:
        """Per-tenant meter snapshot (active + evicted) — the
        telescope export reads this for the labelled series."""
        with self._mu:
            out = {}
            for name, t in self.tenants.items():
                m = dict(t.meter)
                m["sessions"] = len(t.sessions)
                m["queued"] = t.queued()
                m["queued_bytes"] = t.queued_bytes()
                m["qos"] = t.qos.name
                out[name] = m
            for name, meter in self.history.items():
                if name not in out:
                    m = dict(meter)
                    m["qos"] = m.get("qos", "")
                    out[name] = m
            viol = slo.violation_minutes()
            for name, m in out.items():
                m["slo_violation_minutes"] = round(
                    viol.get(tenant_scope(name), 0.0), 6
                )
            return out

    def status(self) -> dict:
        with self._mu:
            return {
                "name": self.name,
                "version": protocol.PROTOCOL_VERSION,
                "lane": self.lane.kind,
                "seed": self.seed,
                "slot": self._slot,
                "base_cid": self.world.cid,
                "sessions": [
                    {
                        "sid": s.sid,
                        "tenant": s.tenant.name,
                        "qos": s.tenant.qos.name,
                        "cid": s.comm.cid,
                        "epoch": s.comm.epoch,
                        "state": s.state,
                        "queued": len(s.queue),
                        "queued_bytes": s.queued_bytes,
                    }
                    for s in sorted(self.sessions.values(),
                                    key=lambda s: s.sid)
                ],
                "tenants": self.metering(),
                "digest": self.log.digest(),
                "cache_scope_reads": scache.CACHE.scope_reads(),
            }

    def digest(self) -> str:
        return self.log.digest()

    # -- state file / control channel (tools/daemon CLI) -----------------

    def save_state(self, path: str) -> None:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(self.status(), fh, indent=1, sort_keys=True)
        os.replace(tmp, path)

    def process_control(self, cmd_path: str) -> int:
        """Consume operator commands (JSON lines appended by the
        CLI): {"cmd": "evict", "tenant": X} / {"cmd": "drain"}.
        Unknown or malformed commands are logged, never fatal."""
        try:
            with open(cmd_path, "r", encoding="utf-8") as fh:
                lines = fh.readlines()
        except FileNotFoundError:
            return 0
        except OSError as exc:
            logger.warning("daemon: control file unreadable: %s", exc)
            return 0
        os.unlink(cmd_path)
        done = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                cmd = json.loads(line)
            except ValueError as exc:
                logger.warning("daemon: bad control line %r: %s",
                               line, exc)
                continue
            kind = cmd.get("cmd")
            try:
                if kind == "evict" and cmd.get("tenant"):
                    self.evict(cmd["tenant"], cause="cli")
                    done += 1
                elif kind == "drain":
                    self.drain()
                    done += 1
                else:
                    logger.warning("daemon: unknown control %r", cmd)
            except (DaemonError, OmpiTpuError) as exc:
                logger.warning("daemon: control %r failed: %s",
                               cmd, exc)
        return done

    # -- shutdown --------------------------------------------------------

    def stop(self) -> None:
        with self._mu:
            if self._stopped:
                return
            self._stopped = True
            for name in sorted(self.tenants):
                self.evict(name, cause="shutdown")
            if self.lane.kind == "shm":
                from ..runtime import dpm

                dpm.unpublish_name(f"bulkhead/{self.name}")
            self.lane.close()
            self.log.note("stop")


# -- module singleton ---------------------------------------------------

_CURRENT: Optional[Daemon] = None


def start(base_comm=None, **kw) -> Daemon:
    global _CURRENT
    if _CURRENT is not None and not _CURRENT._stopped:
        raise DaemonError("a daemon is already running; stop() first")
    _CURRENT = Daemon(base_comm, **kw)
    return _CURRENT


def current() -> Optional[Daemon]:
    if _CURRENT is not None and _CURRENT._stopped:
        return None
    return _CURRENT


def stop() -> None:
    global _CURRENT
    if _CURRENT is not None:
        _CURRENT.stop()
        _CURRENT = None

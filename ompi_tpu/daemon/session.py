"""Session and tenant state for the bulkhead daemon.

A *tenant* is the isolation + accounting unit (admission tokens, byte
budget, meter, ledger namespace); a *session* is one attached client
with its own communicator (and therefore its own ledger comm scope
and epoch-tagged slice of the wire tag namespace).

All scheduling state is logical: arrival slots, deadline slots,
token counts — never wall-clock — so the daemon's decisions replay
byte-identically across same-seed controllers. Wall-clock exists
only in the *meter* (SLO violation minutes, latency), which is
deliberately outside the decision log, mirroring lifeboat's
phase-timing split.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

from .qos import Admission, QosClass

# session lifecycle
ATTACHED = "attached"
DRAINING = "draining"   # detach requested: no new admits, queue drains
REVOKED = "revoked"     # comm poisoned (rank death / revocation)
EVICTED = "evicted"
DETACHED = "detached"


@dataclass
class Request:
    """One admitted collective. ``deadline_slot`` is logical EDF time
    (arrival slot + the class horizon); ``tag`` is the epoch-stamped
    wire tag from protocol.stamp."""

    seq: int
    op: str
    payload: Any
    nbytes: int
    tag: int
    arrival_slot: int
    deadline_slot: int
    params: dict = field(default_factory=dict)
    reply: Optional[Any] = None  # protocol.Message once completed


class Session:
    def __init__(self, sid: int, tenant: "Tenant", comm) -> None:
        self.sid = sid
        self.tenant = tenant
        self.comm = comm
        self.state = ATTACHED
        self.queue: deque[Request] = deque()
        self.queued_bytes = 0
        self.seq = 0
        self.completed: dict[int, Any] = {}  # seq -> reply Message

    @property
    def qos(self) -> QosClass:
        return self.tenant.qos

    def next_seq(self) -> int:
        self.seq += 1
        return self.seq

    def head_deadline(self) -> int:
        return self.queue[0].deadline_slot if self.queue else 1 << 62


class Tenant:
    """Admission + metering scope shared by all of one tenant's
    sessions. ``hogged_bytes`` is the synthetic queue-memory charge a
    hog@daemon fault injects — it consumes the same byte budget as
    real queued payloads, so the bulkhead drill exercises the exact
    production reject path."""

    def __init__(self, name: str, qos: QosClass, *,
                 seed: int) -> None:
        self.name = name
        self.qos = qos
        self.admission = Admission(qos, seed=seed)
        self.sessions: dict[int, Session] = {}
        self.hogged_bytes = 0
        self.meter = {
            "sessions": 0,
            "requests": 0,
            "admitted": 0,
            "rejected": 0,
            "dispatched": 0,
            "bytes": 0,
            "evictions": 0,
            "denied_tier_observations": 0,
            "flood_synthetic": 0,
            "hog_bytes": 0,
            "slo_violation_ms": 0.0,
            "errors": 0,
        }

    def queued(self) -> int:
        return sum(len(s.queue) for s in self.sessions.values())

    def queued_bytes(self) -> int:
        return self.hogged_bytes + sum(
            s.queued_bytes for s in self.sessions.values()
        )

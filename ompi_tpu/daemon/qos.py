"""Per-tenant QoS classes and deterministic weighted admission.

Three service classes multiplex one device mesh:

    guaranteed  reserved share: widest queues, most dispatch quanta
                per pump round, an SLO target the dispatcher meters
                violation minutes against
    burst       best-effort with headroom: admitted freely while the
                mesh keeps up, throttled first under pressure
    scavenger   strictly-residual: one dispatch quantum per round and
                a small queue — an adversarial scavenger flood can
                only burn its own (bounded) budget

Admission is a deterministic token bucket per tenant: capacity and
refill come from the class, refills happen per *pump round* (logical
time), and every refusal carries a retry-after drawn from a seeded
``core/backoff.Backoff`` — same seed, same workload, byte-identical
decisions. Nothing here reads the wall clock; that is what makes the
daemon's decision-log digest reproducible across controllers.

Backpressure invariants (the never-silent contract):
  * per-tenant queues are bounded by ``queue_depth`` — growth beyond
    it is a REJECT, not memory
  * per-tenant queued payload bytes are bounded by ``byte_budget``
    (hog@daemon charges this same budget)
  * every reject is counted (SPC + tenant meter), logged (numbered
    decision line), and answered (REJECT + retry_after_ms)
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass

from ..core.backoff import Backoff
from ..core.counters import SPC
from ..core.errors import OmpiTpuError


class QosError(OmpiTpuError):
    errclass = "ERR_ARG"


@dataclass(frozen=True)
class QosClass:
    """One service class. ``weight`` is the dispatch quantum (requests
    served per pump round), ``deadline_slots`` the logical EDF horizon
    (arrival slot + horizon = deadline slot), ``slo_p50_us`` the
    latency target violation minutes are metered against (0 = none).
    """

    name: str
    weight: int
    queue_depth: int
    byte_budget: int
    admit_tokens: int     # token-bucket capacity
    refill: int           # tokens restored per pump round
    deadline_slots: int
    slo_p50_us: float = 0.0


GUARANTEED = QosClass("guaranteed", weight=8, queue_depth=64,
                      byte_budget=16 << 20, admit_tokens=64,
                      refill=32, deadline_slots=64,
                      slo_p50_us=50_000.0)
BURST = QosClass("burst", weight=4, queue_depth=32,
                 byte_budget=8 << 20, admit_tokens=32, refill=16,
                 deadline_slots=256)
SCAVENGER = QosClass("scavenger", weight=1, queue_depth=16,
                     byte_budget=1 << 20, admit_tokens=8, refill=2,
                     deadline_slots=4096)

CLASSES = {c.name: c for c in (GUARANTEED, BURST, SCAVENGER)}


def qos_class(name: str) -> QosClass:
    try:
        return CLASSES[name]
    except KeyError:
        raise QosError(
            f"unknown qos class {name!r}; expected one of "
            f"{sorted(CLASSES)}"
        ) from None


def tenant_seed(base_seed: int, tenant: str) -> int:
    """Deterministic per-tenant RNG seed: the daemon seed folded with
    a crc32 of the tenant name — stable across controllers, distinct
    across tenants."""
    return (int(base_seed) << 1) ^ zlib.crc32(tenant.encode())


ADMITTED = "admitted"
R_QUEUE = "queue_full"
R_BYTES = "byte_budget"
R_RATE = "rate"


class Admission:
    """Per-tenant admission state: token bucket + seeded retry-after.

    ``try_admit`` is called with the tenant's *current* queue load so
    the bounded-queue and byte-budget checks see hog charges too; it
    never blocks and never drops — the caller turns a refusal into a
    REJECT reply carrying ``retry_after_ms``."""

    def __init__(self, qos: QosClass, *, seed: int) -> None:
        self.qos = qos
        self.tokens = float(qos.admit_tokens)
        # no deadline: next_delay() is a pure seeded schedule the
        # rejected client honours before re-submitting
        self._backoff = Backoff(initial=0.001, maximum=0.25,
                                seed=seed)
        self.rejects = 0
        self.admits = 0

    def refill(self) -> None:
        """One pump round of logical time: restore ``refill`` tokens
        up to the bucket capacity."""
        self.tokens = min(float(self.qos.admit_tokens),
                          self.tokens + self.qos.refill)

    def try_admit(self, *, queued: int, queued_bytes: int,
                  nbytes: int) -> tuple[str, float]:
        """(verdict, retry_after_ms). verdict is ``admitted`` or a
        reject reason; retry_after_ms is 0.0 on admit, else the next
        seeded backoff delay."""
        reason = None
        if queued >= self.qos.queue_depth:
            reason = R_QUEUE
        elif queued_bytes + nbytes > self.qos.byte_budget:
            reason = R_BYTES
        elif self.tokens < 1.0:
            reason = R_RATE
        if reason is None:
            self.tokens -= 1.0
            self.admits += 1
            self._backoff.reset()
            return ADMITTED, 0.0
        self.rejects += 1
        SPC.record("daemon_admission_rejects")
        retry_ms = round(self._backoff.next_delay() * 1e3, 3)
        # escalate: next_delay() alone doesn't advance the attempt
        # counter, and consecutive rejects should push the tenant
        # further out (reset on the next admit)
        self._backoff.attempts += 1
        return reason, retry_ms

"""Monitoring / profiling interposition (reference:
ompi/mca/common/monitoring + PERUSE + SPC)."""

from .monitoring import MONITOR, Monitoring, profile_api, profiled

__all__ = ["MONITOR", "Monitoring", "profile_api", "profiled"]

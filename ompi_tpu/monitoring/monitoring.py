"""Monitoring: per-peer traffic accounting + profiling interposition.

TPU-native equivalent of the reference's monitoring components
(reference: ompi/mca/common/monitoring/common_monitoring.c — pml/coll/
osc interposition recording per-peer bytes and message counts,
internal vs external traffic, dumped at finalize or queried via MPI_T;
README:27-60) and of PERUSE request-lifecycle hooks (ompi/peruse).

The pml (ob1) and coll layers call into the singleton below on every
operation when enabled; `flush()` renders the same per-peer matrix the
reference dumps. PMPI-style interposition — wrapping the public API —
is `profile_api()`, the functools analog of the weak-symbol shim
(reference: ompi/mpi/c/allreduce.c:36-41).
"""

from __future__ import annotations

import functools
import threading
import time
from collections import defaultdict
from typing import Callable, Optional

from ..core import config
from ..core.counters import SPC

_enabled = config.register(
    "monitoring", "base", "enable", type=bool, default=False,
    description="Record per-peer p2p/coll/osc traffic matrices",
)
_dump_at_finalize = config.register(
    "monitoring", "base", "dump_at_finalize", type=bool, default=False,
    description="Print the traffic summary at finalize (reference: "
    "common_monitoring dumps at MPI_Finalize)",
)


def maybe_dump_at_finalize() -> None:
    if _dump_at_finalize.value and MONITOR.enabled:
        import json

        payload = MONITOR.flush()
        sanitizer = {
            k: v for k, v in SPC.snapshot().items()
            if k.startswith("sanitizer_")
        }
        if sanitizer:
            payload["sanitizer"] = sanitizer
        hists = SPC.histogram_snapshots()
        if hists:
            payload["latency_histograms"] = hists
        from ..health import ledger as _health_ledger

        if _health_ledger.LEDGER.tracked():
            payload["health"] = _health_ledger.snapshot()
        # Through core/logging's user-facing channel (not a bare
        # print): the dump lands on the same stream as the rest of the
        # run's diagnostics, banner-framed like every other
        # user-requested report.
        from ..core.logging import show_help

        show_help(
            "monitoring summary",
            "%s", json.dumps(payload, indent=2),
            once=False,
        )


class Monitoring:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.reset()

    @property
    def enabled(self) -> bool:
        return _enabled.value

    def enable(self, on: bool = True) -> None:
        config.VARS.set("monitoring_base_enable", on)

    def reset(self) -> None:
        with getattr(self, "_lock", threading.Lock()):
            # (cid, src, dst) -> [messages, bytes]
            self.p2p = defaultdict(lambda: [0, 0])
            # (cid, opname) -> [calls, bytes]
            self.coll = defaultdict(lambda: [0, 0])
            # (cid, origin, target, kind) -> [ops, bytes]
            self.osc = defaultdict(lambda: [0, 0])

    # -- recording hooks ---------------------------------------------------

    def record_p2p(self, cid: int, src: int, dst: int, nbytes: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            ent = self.p2p[(cid, src, dst)]
            ent[0] += 1
            ent[1] += nbytes

    def record_coll(self, cid: int, opname: str, nbytes: int) -> None:
        if not self.enabled:
            return
        with self._lock:
            ent = self.coll[(cid, opname)]
            ent[0] += 1
            ent[1] += nbytes

    def record_osc(self, cid: int, target: int, kind: str, nbytes: int
                   ) -> None:
        if not self.enabled:
            return
        with self._lock:
            ent = self.osc[(cid, 0, target, kind)]
            ent[0] += 1
            ent[1] += nbytes

    # -- reporting ---------------------------------------------------------

    def peer_matrix(self, comm_size: int, cid: Optional[int] = None
                    ) -> list[list[int]]:
        """Bytes sent src->dst (the reference's dump format)."""
        mat = [[0] * comm_size for _ in range(comm_size)]
        with self._lock:
            for (c, src, dst), (_, nbytes) in self.p2p.items():
                if cid is not None and c != cid:
                    continue
                if src < comm_size and dst < comm_size:
                    mat[src][dst] += nbytes
        return mat

    def peer_totals(self) -> dict[str, list[int]]:
        """Per-link p2p totals collapsed over communicators:
        ``"src->dst" -> [messages, bytes]``. The fixed small shape the
        telemetry sampler snapshots every tick (the full cid-keyed
        matrices stay in ``flush()``)."""
        out: dict[str, list[int]] = {}
        with self._lock:
            for (_, src, dst), (msgs, nbytes) in self.p2p.items():
                ent = out.setdefault(f"{src}->{dst}", [0, 0])
                ent[0] += msgs
                ent[1] += nbytes
        return out

    def flush(self) -> dict:
        with self._lock:
            return {
                "p2p": {
                    f"{c}:{s}->{d}": tuple(v)
                    for (c, s, d), v in self.p2p.items()
                },
                "coll": {
                    f"{c}:{op}": tuple(v)
                    for (c, op), v in self.coll.items()
                },
                "osc": {
                    f"{c}:{o}->{t}:{k}": tuple(v)
                    for (c, o, t, k), v in self.osc.items()
                },
            }


MONITOR = Monitoring()


# -- PMPI-style API interposition -------------------------------------------

_PROFILE_HOOKS: list[Callable] = []


def profile_api(hook: Callable[[str, float], None]) -> Callable[[], None]:
    """Register a hook(name, seconds) called after every profiled public
    API call; returns an unregister function. The PMPI shim analog."""
    _PROFILE_HOOKS.append(hook)

    def unregister() -> None:
        if hook in _PROFILE_HOOKS:
            _PROFILE_HOOKS.remove(hook)

    return unregister


def profiled(name: str):
    """Decorator: time a public API function and feed profile hooks
    (and an SPC timer)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            if not _PROFILE_HOOKS:
                return fn(*a, **kw)
            t0 = time.perf_counter()
            try:
                return fn(*a, **kw)
            finally:
                dt = time.perf_counter() - t0
                SPC.counter(f"{name}_seconds", unit="seconds").add(dt)
                for hook in list(_PROFILE_HOOKS):
                    hook(name, dt)

        return wrapper

    return deco

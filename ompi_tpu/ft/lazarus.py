"""lazarus — elastic scale-UP: warm spares, grow-after-shrink, and
preemption-tolerant rejoin.

Lifeboat (``ft/lifeboat.py``) is the shrink half of ULFM-grade
elasticity: revoke → quiesce → agree → shrink → re-admit. A production
fleet on preemptible capacity needs the other half — a
killed-and-replaced rank must rejoin within a bounded number of steps
instead of forcing a restart. This module is that inverse pipeline::

    grow(comm, spares) = agree → admit → epoch-bump → expand →
                         state-migration → catch-up

**Warm spares.** ``add_spare(wr)`` registers a standby world rank (the
PiP warm-standby pattern applied to daemon-owned meshes). A spare is a
rank present in the survivor comm's retained ``_world_procs`` table but
not in its group — shrink keeps the full table precisely so a later
grow can re-address the vacated slots.

**Admission (the medic ladder).** Before a spare touches real traffic
it is walked through the health ledger's PROBATION machinery in its own
``spare:<wr>`` scope: forced QUARANTINED, then canary probes
(``health/prober.probe_tier`` by default — the same deadline-bounded
device canaries the medic supervisor runs) must walk it QUARANTINED →
PROBATION → HEALTHY. A canary failure re-quarantines *with cause* (the
readmit idempotency contract) and retries ride a bounded seeded
``Backoff`` — a flaky spare is rejected, never admitted, and never
stalls the pipeline.

**Epoch bump + expand.** ``elastic.grow`` constructs the grown comm
over survivors + admitted spares; the new comm is born at
``parent.epoch + 1`` so its wire-tag namespace (``lifeboat.epoch_tag``)
is disjoint from anything a straggling pre-grow op could emit.

**State migration.** The sched winner cache migrates ``r<old>`` keys to
``r<new>`` — the PR 12 shrink migration in reverse. Keys retained from
a previous life at ``new_n`` (shrink deliberately keeps them) are
*reused*, not re-tuned: growing back to a prior size is warm-start by
construction (``lifeboat._migrate_sched_cache`` promises "the old keys
stay — a respawn back to old_n re-uses them"). The health ledger's new
comm scope is seeded from global, the spare scopes are GC'd, the fleet
merge un-deads the joiners (``fleet.mark_alive``), and watchtower
baselines reset on grow exactly as on shrink.

**Catch-up.** The joiner converges by continuous parameter/optimizer
snapshot streaming over the comm plane itself: the snapshot is
serialized once, split into fixed-size chunks, and each chunk rides the
comm's point-to-point path (device-resident transfers on whatever tier
the pml selected — the DCN path cross-host, with its existing link
failover) under a ``sentinel.run_bounded`` deadline, sha256-verified
end to end. Survivors keep training meanwhile (``survivor_step`` fires
once per chunk), so ``rejoin_steps == ceil(len(snapshot)/chunk_bytes)``
is a *pure function of snapshot size* — bounded, measured, and
deterministic.

Determinism: every decision lands in a numbered timestamp-free log
(ledger idiom); ``digest()`` hashes it — byte-identical across
same-seed controllers (two-subprocess drill). Wall-clock phase timings
live in ``last_report()``, outside the log.
"""

from __future__ import annotations

import hashlib
import io
import threading
import time
from typing import Any, Callable, Optional

from ..core import config
from ..core.counters import SPC
from ..core.errors import CommError
from ..core.logging import get_logger
from . import elastic, lifeboat

logger = get_logger("ft.lazarus")

__all__ = [
    "GrowError", "add_spare", "digest", "grow", "last_report", "log",
    "remove_spare", "reset", "spares",
]

_spare_attempts = config.register(
    "ft", "lazarus", "spare_attempts", type=int, default=2,
    description="Admission walks a flaky spare retries before it is "
    "rejected (each retry re-quarantines and re-runs the full "
    "PROBATION ladder under seeded backoff)",
)
_chunk_bytes = config.register(
    "ft", "lazarus", "chunk_bytes", type=int, default=1 << 16,
    description="Catch-up snapshot stream chunk size; rejoin_steps = "
    "ceil(snapshot_bytes / chunk_bytes) — one survivor step per chunk",
)
_chunk_deadline_s = config.register(
    "ft", "lazarus", "chunk_deadline_s", type=float, default=1.0,
    description="run_bounded stall deadline per streamed catch-up "
    "chunk (a wedged link is a tier fault, never a hang)",
)


class GrowError(CommError):
    """The grow pipeline could not admit any spare (every candidate
    failed its canary ladder) or was asked to grow a revoked comm."""

    errclass = "ERR_COMM"


# -- module state --------------------------------------------------------

_mu = threading.RLock()
#: timestamp-free decision log (ledger idiom: numbered lines).
_log: list[str] = []
#: warm-spare pool: world ranks standing by for admission.
_pool: set[int] = set()
_last_report: dict = {}

#: catch-up stream tag — below the epoch bits of the wire-tag
#: namespace, constant because chunks are strictly send-then-recv
#: sequenced (never concurrently outstanding per joiner).
_CATCHUP_TAG = 3091


def _note(line: str) -> None:
    with _mu:
        _log.append(f"{len(_log)} {line}")


def log() -> list[str]:
    with _mu:
        return list(_log)


def digest() -> str:
    """sha256 of the grow decision log — byte-identical across
    same-seed controllers (the lifeboat/ledger contract)."""
    with _mu:
        return hashlib.sha256("\n".join(_log).encode()).hexdigest()


def last_report() -> dict:
    """Wall-clock phase breakdown of the most recent grow() —
    deliberately OUTSIDE the decision log so timings never perturb
    the byte-identity contract."""
    with _mu:
        return dict(_last_report)


def reset() -> None:
    """Forget the log, the spare pool, and tracking (test teardown)."""
    with _mu:
        _log.clear()
        _pool.clear()
        _last_report.clear()


# -- the warm-spare pool -------------------------------------------------

def add_spare(world_rank: int) -> None:
    """Register a warm standby rank. Idempotent; logged once."""
    wr = int(world_rank)
    with _mu:
        if wr in _pool:
            return
        _pool.add(wr)
    _note(f"spare add wr={wr}")
    SPC.record("ft_spares_registered")


def remove_spare(world_rank: int) -> None:
    """Withdraw a standby rank (preempted before it was needed)."""
    wr = int(world_rank)
    with _mu:
        if wr not in _pool:
            return
        _pool.discard(wr)
    _note(f"spare remove wr={wr}")


def spares() -> list[int]:
    """The warm pool, sorted (the deterministic admission order)."""
    with _mu:
        return sorted(_pool)


# -- admission: the medic PROBATION ladder -------------------------------

def _walk_ladder(wr: int, *, canary: Optional[Callable[[int], bool]],
                 attempts: int, seed: int) -> tuple[int, bool]:
    """Walk spare ``wr`` through QUARANTINED → PROBATION → HEALTHY in
    its own ``spare:<wr>`` ledger scope. Returns (attempts_used,
    admitted). Canary-fail → retry is idempotent: every walk starts by
    forcing QUARANTINED, and a failure re-quarantines with cause
    before the seeded bounded backoff schedules the retry."""
    from ..core.backoff import Backoff
    from ..health import ledger as health, prober

    scope = f"spare:{wr}"
    needed = int(config.get("health_ledger_probation_successes", 2)) + 1
    attempts = max(1, int(attempts))
    bo = Backoff(initial=0.01, maximum=0.25, seed=seed ^ (wr << 1),
                 timeout=2.0)
    if canary is None:
        prober.ensure_builtin_probes()
    attempt = 0
    for attempt in range(attempts):
        health.LEDGER.quarantine("device", scope=scope, cause="admit")
        failed = False
        for _ in range(needed):
            if canary is None:
                # the medic canary: deadline-bounded, and it feeds the
                # ledger in this scope itself
                ok = bool(prober.probe_tier("device", scope=scope))
            else:
                try:
                    ok = bool(canary(wr))
                except Exception:  # commlint: allow(broadexcept)
                    ok = False
                if ok:
                    health.LEDGER.report_success("device", scope=scope)
                else:
                    health.LEDGER.report_failure(
                        "device", scope=scope, cause="canary")
            if not ok:
                health.LEDGER.quarantine("device", scope=scope,
                                         cause="canary_failed")
                failed = True
                break
        if not failed and health.LEDGER.state("device", scope) \
                == health.HEALTHY:
            return attempt, True
        if attempt + 1 < attempts and not bo.sleep():
            break
    return attempt, False


# -- state migration: the shrink migration in reverse --------------------

def _migrate_sched_cache(old_n: int, new_n: int,
                         seed: Optional[int] = None
                         ) -> tuple[int, int]:
    """Move the winner cache to the grown world: every key tuned for
    ``r<old_n>`` gets a ``r<new_n>`` counterpart. A counterpart that
    already exists — shrink retains old-size keys exactly for this —
    is REUSED (warm-start by construction, zero tuning); a missing one
    is installed through the retune sweep. Returns (migrated,
    reused)."""
    from ..coll.sched import autotune, cache as scache, retune

    fp = autotune.fingerprint()
    entries = scache.CACHE.entries()
    migrated = reused = 0
    for key in sorted(entries):
        parsed = retune.parse_key(key)
        if parsed is None or parsed["nranks"] != old_n:
            continue
        new_key = scache.cache_key(
            parsed["opname"], scache.bucket_bytes(parsed["bucket"]),
            new_n,
            None if parsed["dtype"] == "any" else parsed["dtype"],
            fp,
        )
        if new_key in entries:
            reused += 1
            continue
        if retune.retune_key(new_key, reason="grow",
                             seed=seed) is not None:
            migrated += 1
    return migrated, reused


# -- catch-up: snapshot streaming over the comm plane --------------------

def _serialize(state: Any) -> bytes:
    """Deterministic byte encoding of a parameter/optimizer pytree:
    every leaf as an npy record in tree-flatten order."""
    import jax
    import numpy as np

    buf = io.BytesIO()
    for leaf in jax.tree.flatten(state)[0]:
        np.lib.format.write_array(buf, np.asarray(leaf),
                                  allow_pickle=False)
    return buf.getvalue()


def _stream_catchup(new, joiners: list[int], payload: bytes, *,
                    chunk_bytes: int, chunk_deadline_s: float,
                    stream: Optional[Callable[[int, bytes, int], None]],
                    survivor_step: Optional[Callable[[], None]]
                    ) -> tuple[int, int]:
    """Stream ``payload`` to every joiner in fixed-size chunks; one
    survivor training step interleaves per chunk, so the returned
    (chunks, steps) is a pure function of the snapshot size. Each real
    chunk is a point-to-point transfer under a ``run_bounded`` stall
    deadline (a wedged link faults, never hangs) and is sha256-verified
    after the round trip."""
    import numpy as np

    from ..health import sentinel

    nchunks = (len(payload) + chunk_bytes - 1) // chunk_bytes
    if not joiners or nchunks == 0:
        return 0, 0
    # the lowest SURVIVOR streams (a joiner can hold group rank 0 when
    # it re-occupies the smallest world slot — it must not self-stream)
    joined = set(joiners)
    src = next(i for i, wr in enumerate(new.group.world_ranks)
               if wr not in joined)
    jranks = [new.group.world_ranks.index(wr) for wr in joiners]
    for i in range(nchunks):
        chunk = payload[i * chunk_bytes:(i + 1) * chunk_bytes]
        if stream is not None:
            # modeled transport (armada: data-plane ops are impossible
            # on sim devices) — count the chunk, skip the wire
            for wr in joiners:
                stream(wr, chunk, i)
        else:
            arr = np.frombuffer(chunk, dtype=np.uint8)
            want = hashlib.sha256(chunk).hexdigest()
            for jr in jranks:
                def _round_trip(jr=jr):
                    new.send(arr, jr, _CATCHUP_TAG, source=src)
                    return new.recv(src, _CATCHUP_TAG, dest=jr)
                got = sentinel.run_bounded(
                    _round_trip, chunk_deadline_s,
                    what=f"lazarus.catchup chunk={i} joiner={jr}")
                got_sha = hashlib.sha256(
                    np.asarray(got).tobytes()).hexdigest()
                if got_sha != want:
                    raise GrowError(
                        f"catch-up chunk {i} corrupt in flight to "
                        f"group rank {jr}: {got_sha[:12]} != "
                        f"{want[:12]}")
        SPC.record("ft_catchup_chunks_total", len(joiners))
        if survivor_step is not None:
            survivor_step()
    return nchunks, nchunks


# -- the grow pipeline ---------------------------------------------------

def grow(comm, spares: Optional[list] = None, *,
         seed: Optional[int] = None,
         canary: Optional[Callable[[int], bool]] = None,
         state: Any = None,
         stream: Optional[Callable[[int, bytes, int], None]] = None,
         survivor_step: Optional[Callable[[], None]] = None,
         chunk_bytes: Optional[int] = None,
         chunk_deadline_s: Optional[float] = None,
         migrate_cache: bool = True) -> Any:
    """The deterministic grow pipeline — the inverse of
    ``lifeboat.recover``: agree → admit (PROBATION ladder per spare) →
    epoch-bump → expand → state-migration → catch-up. Returns the
    grown communicator; phase timings land in ``last_report()``, every
    decision in the timestamp-free log.

    ``spares`` defaults to the registered warm pool. ``canary`` (a
    ``wr -> bool`` probe) overrides the medic prober ladder — armada
    and tests inject it. ``state`` is the parameter/optimizer snapshot
    streamed to joiners; ``stream`` replaces the real point-to-point
    transport with a model (armada). ``survivor_step`` fires once per
    chunk — the survivors' training step the joiner converges under."""
    from ..health import ledger as health
    from ..telemetry import fleet, watchtower

    lifeboat.check(comm)  # a revoked comm must recover, not grow
    pool = spares if spares is not None else globals()["spares"]()
    current = set(comm.group.world_ranks)
    candidates = sorted(int(s) for s in set(pool) - current)
    if not candidates:
        raise GrowError(f"{comm.name}: no spare ranks to admit")
    seed_v = int(seed) if seed is not None else 0
    attempts = max(1, int(_spare_attempts.value))
    cbytes = int(chunk_bytes if chunk_bytes is not None
                 else _chunk_bytes.value)
    cdeadline = float(chunk_deadline_s if chunk_deadline_s is not None
                      else _chunk_deadline_s.value)

    phases: dict[str, float] = {}
    t0 = time.perf_counter()

    def _mark(phase: str) -> None:
        nonlocal t0
        now = time.perf_counter()
        phases[f"{phase}_ms"] = round((now - t0) * 1e3, 3)
        t0 = now

    # agree: every survivor votes to admit — the agreement's job is
    # masking a death arriving mid-grow (a survivor dying now re-roots
    # instead of splitting the set that believes the grow happened).
    lifeboat.agree(comm, [1] * comm.size)
    _mark("agree")

    # admit: the medic ladder per spare, deterministic order
    admitted: list[int] = []
    rejected: list[int] = []
    for wr in candidates:
        used, ok = _walk_ladder(wr, canary=canary, attempts=attempts,
                                seed=seed_v)
        if ok:
            admitted.append(wr)
            _note(f"admit wr={wr} attempts={used + 1} result=healthy")
            SPC.record("ft_spare_admissions")
        else:
            rejected.append(wr)
            _note(f"admit wr={wr} attempts={used + 1} result=rejected")
            SPC.record("ft_spare_rejections")
    _mark("admit")
    if not admitted:
        _note(f"grow cid={comm.cid} result=no-admissible-spares "
              f"rejected={rejected}")
        raise GrowError(
            f"{comm.name}: every spare failed the canary ladder "
            f"({rejected})")

    # expand + epoch bump: the grown comm's tag namespace is disjoint
    # from the parent epoch's by construction
    elastic.revive(admitted)
    new = elastic.grow(comm, admitted)
    new.epoch = comm.epoch + 1
    with _mu:
        _pool.difference_update(admitted)
    _mark("expand")

    # state migration: winner cache r<old> -> r<new> (retained keys
    # reused), ledger scope seeded, fleet un-deaded, baselines reset
    migrated, reused = _migrate_sched_cache(
        comm.size, new.size, seed=seed) if migrate_cache else (0, 0)
    gcd = health.LEDGER.gc_scope(str(comm.cid), cause="grow")
    for wr in admitted:
        gcd += health.LEDGER.gc_scope(f"spare:{wr}", cause="grow")
    seeded = health.LEDGER.seed_scope(str(new.cid), cause="grow")
    alive = sum(1 for wr in admitted if fleet.mark_alive(wr))
    baselines = watchtower.reset_baselines(reason="grow")
    _mark("migrate")

    # catch-up: bounded, measured convergence under live training
    payload = b"" if state is None else _serialize(state)
    chunks, steps = _stream_catchup(
        new, admitted, payload, chunk_bytes=cbytes,
        chunk_deadline_s=cdeadline, stream=stream,
        survivor_step=survivor_step)
    if steps:
        SPC.record("ft_rejoin_steps", steps)
    _mark("catchup")

    sha = hashlib.sha256(payload).hexdigest()[:16]
    _note(
        f"grow cid={comm.cid}->{new.cid} "
        f"epoch={comm.epoch}->{new.epoch} joiners={admitted} "
        f"rejected={rejected} survivors={new.size} "
        f"cache_migrated={migrated} cache_reused={reused} "
        f"ledger_gc={gcd} ledger_seeded={seeded} "
        f"baselines_reset={baselines} fleet_alive={alive} "
        f"catchup_chunks={chunks} catchup_bytes={len(payload)} "
        f"rejoin_steps={steps} sha={sha}"
    )
    SPC.record("ft_grows")
    from ..trace import span as tspan

    tspan.instant("ft.grow", cat="ft", cid=comm.cid, new_cid=new.cid,
                  epoch=new.epoch, joiners=admitted,
                  survivors=new.size, rejoin_steps=steps)
    with _mu:
        _last_report.clear()
        _last_report.update({
            "phases": phases, "joiners": admitted,
            "rejected": rejected, "survivors": new.size,
            "cache_migrated": migrated, "cache_reused": reused,
            "ledger_gc": gcd, "ledger_seeded": seeded,
            "catchup_chunks": chunks,
            "catchup_bytes": len(payload),
            "rejoin_steps": steps,
        })
    logger.info("lazarus: grew %s -> %s (%d ranks, joiners=%s, "
                "rejoin_steps=%d)", comm.name, new.name, new.size,
                admitted, steps)
    return new

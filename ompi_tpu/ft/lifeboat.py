"""lifeboat — ULFM-grade elastic recovery: epochs, revoke, agree,
and the deterministic shrink→respawn pipeline.

The reference Open MPI ships ULFM (ompi/mpiext/ftmpi:
MPI_Comm_revoke / MPIX_Comm_agree / MPI_Comm_shrink) as a first-class
capability; this module is its driver-model port, built over the
``ft/elastic`` skeleton and wired into everything PRs 8-11 added
(health ledger scopes, sched winner cache, telemetry fleet merge,
watchtower baselines):

**Epoch fence.** Every communicator carries ``epoch`` (bumped by
``recover``) and ``_revoked``. The stamp rides the wire tag namespace
exactly like commtrace span ids — ``epoch_tag`` packs (cid, epoch)
into the same ``(cid+1) << 20`` id space ``trace/span.py`` uses — so
fencing costs zero extra wire traffic. The in-band check is ONE
attribute read (``Communicator._check_alive``), which is what keeps
the fp 64 B RTT ratchet under 1%: every dispatch raises
``RevokedError`` instead of hanging on a dead peer.

**Revoke.** ``revoke(comm)`` poisons the comm locally (the in-band
flag every dispatch piggybacks on) AND publishes a modex marker
(``revoke/<cid>``), the out-of-band path other controllers' rate-
limited ``check`` probes observe within a bounded window. Where
sentinel's ``run_bounded`` used to convert a dead-peer stall into a
tier fault, the tuned dispatch now converts it into a revocation when
the comm is poisoned — all survivors exit the collective the same way.

**Agree.** ``agree(comm, flags)`` is the two-phase, failure-masking
agreement (MPIX_Comm_agree semantics: bitwise AND over survivor
flags). Phase one combines votes up a binomial tree re-rooted around
the known-dead set; phase two confirms the dead set did not move while
voting — if it did, the round re-roots and retries. Every survivor
gets the same flags or every survivor gets the raise; never
split-brain.

**Recover.** ``recover(comm)`` runs the deterministic pipeline:
quiesce (crcp bookmark; a timeout cancel-and-marks stragglers) →
agree → shrink → epoch bump → state re-admission — sched cache keys
migrate to the new ``r<nranks>``/topology fingerprint through the
existing retune sweep (warm, not cold-start), the health ledger's
comm-scoped entries are GC'd and the new scope re-seeded from the
global scope, telemetry/fleet drops the dead ranks permanently, and
watchtower baselines reset so post-shrink p50s aren't judged against
pre-shrink predictions. ``respawn`` re-admits a rank through
PROBATION with a canary probe before it carries real traffic.

Determinism: the recovery decision log is timestamp-free (numbered
lines, ledger idiom) and ``digest()`` hashes it — byte-identical
across same-seed controllers. Wall-clock phase timings live in
``last_report()``, outside the log.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Callable, Optional

from ..core import config
from ..core.counters import SPC
from ..core.errors import CommError, RevokedError
from ..core.logging import get_logger
from . import elastic, events

logger = get_logger("ft.lifeboat")

__all__ = [
    "AgreeError", "RevokedError", "agree", "check", "digest",
    "disable", "enable", "epoch_tag", "last_report", "log",
    "maybe_wrap_pml", "readmit", "recover", "reset", "respawn",
    "revoke", "revoked",
]

_probe_every = config.register(
    "ft", "lifeboat", "probe_every", type=int, default=64,
    description="Out-of-band revocation probe rate: every Nth "
    "lifeboat.check consults the modex poison marker (0 disables the "
    "probe; the in-band epoch fence always runs)",
)
_agree_rounds = config.register(
    "ft", "lifeboat", "agree_rounds", type=int, default=3,
    description="Re-root retries the two-phase agreement masks "
    "mid-vote failures with before raising on every survivor",
)


class AgreeError(CommError):
    """The fault-tolerant agreement could not conclude (no survivors,
    or the failure set kept moving for ``agree_rounds`` rounds). Raised
    identically on every survivor — never split-brain."""

    errclass = "ERR_COMM"


# -- module state --------------------------------------------------------

_mu = threading.RLock()
#: cid -> minimum live epoch: operations on that cid below the fence
#: raise RevokedError (the structural half — a shrunk comm has a new
#: cid, so old-epoch traffic can never match the new comm's tags).
_fence: dict[int, int] = {}
#: timestamp-free decision log (ledger idiom: numbered lines).
_log: list[str] = []
_handler_id: Optional[int] = None
_probe_tick = 0
_last_report: dict = {}


def _note(line: str) -> None:
    with _mu:
        _log.append(f"{len(_log)} {line}")


def log() -> list[str]:
    with _mu:
        return list(_log)


def digest() -> str:
    """sha256 of the recovery decision log — byte-identical across
    same-seed controllers (ledger/watchtower contract)."""
    with _mu:
        return hashlib.sha256("\n".join(_log).encode()).hexdigest()


def last_report() -> dict:
    """Wall-clock phase breakdown of the most recent recover() —
    deliberately OUTSIDE the decision log so timings never perturb the
    byte-identity contract."""
    with _mu:
        return dict(_last_report)


def reset() -> None:
    """Forget fences, the log, and tracking (test teardown)."""
    global _probe_tick
    disable()
    with _mu:
        _fence.clear()
        _log.clear()
        _last_report.clear()
        _probe_tick = 0


# -- epoch fence ---------------------------------------------------------

def epoch_tag(comm) -> int:
    """The (cid, epoch) stamp in the wire tag namespace — the same
    ``(cid+1) << 20`` id space commtrace span ids ride, so the fence
    costs zero extra wire traffic. Epochs occupy the bits below the
    cid field; tags/sequence numbers stay beneath them."""
    return ((comm.cid + 1) << 20) | ((comm.epoch & 0xFF) << 12)


def revoked(comm) -> bool:
    """In-band poison state: the comm's own flag, or an epoch below
    the cid's fence."""
    return bool(comm._revoked) or \
        comm.epoch < _fence.get(comm.cid, 0)


def check(comm) -> None:
    """The dispatch fence. The in-band half is one attribute read; the
    out-of-band half (only while lifeboat is enabled, and only every
    ``probe_every``-th call) probes the modex poison marker so a
    revocation published by another controller lands within a bounded
    window even mid-collective."""
    global _probe_tick
    if revoked(comm):
        raise RevokedError(
            f"{comm.name} (cid={comm.cid} epoch={comm.epoch}) has "
            f"been revoked; run ft.lifeboat.recover"
        )
    if _handler_id is None:
        return
    every = _probe_every.value
    if every <= 0:
        return
    _probe_tick += 1
    if _probe_tick % every:
        return
    from ..runtime import modex

    try:
        marker = modex.peer_revoke(comm.cid, timeout_s=0)
    except modex.ModexError:
        return  # nobody revoked this cid — the healthy common case
    if int(marker.get("epoch", 0)) > comm.epoch:
        comm._revoked = True
        _note(f"absorb cid={comm.cid} epoch={comm.epoch} "
              f"marker_epoch={marker.get('epoch')}")
        SPC.record("ft_revokes_absorbed")
        raise RevokedError(
            f"{comm.name} (cid={comm.cid}): revocation marker "
            f"observed via modex"
        )


# -- revoke --------------------------------------------------------------

def revoke(comm, *, cause: str = "user") -> None:
    """MPI_Comm_revoke: poison the communicator so every pending and
    future operation on it raises RevokedError instead of hanging on a
    dead peer. Idempotent. Propagates in-band (the flag every dispatch
    reads) and out-of-band (a modex marker peers' ``check`` probes)."""
    with _mu:
        already = comm._revoked
        comm._revoked = True
        _fence[comm.cid] = max(_fence.get(comm.cid, 0),
                               comm.epoch + 1)
    if already:
        return
    _note(f"revoke cid={comm.cid} epoch={comm.epoch} cause={cause}")
    SPC.record("ft_revokes")
    from ..trace import span as tspan

    tspan.instant("ft.revoke", cat="ft", cid=comm.cid,
                  epoch=comm.epoch, cause=cause)
    from ..runtime import modex

    try:
        modex.publish_revoke(comm.cid, {
            "cid": comm.cid, "epoch": comm.epoch + 1, "cause": cause,
        })
    except Exception:  # commlint: allow(broadexcept)
        # out-of-band propagation is best-effort: the in-band fence
        # (and the PROC_FAILED event fan-out) still poisons survivors
        logger.exception("lifeboat: revoke marker publish failed")
    logger.warning("lifeboat: %s revoked (cause=%s)", comm.name, cause)


def _on_failure(ev: events.Event) -> None:
    """PROC_FAILED fan-out: revoke every live communicator containing
    the dead world rank (the in-band piggyback — survivors observe the
    poison at their very next dispatch on any affected comm)."""
    wr = ev.info.get("world_rank")
    if wr is None:
        return
    from ..communicator import live_comms

    # cid order, not WeakSet order: the decision log must be
    # byte-identical across same-seed controllers
    for comm in sorted(live_comms, key=lambda c: c.cid):
        if not comm._revoked and not comm._freed \
                and wr in comm.group:
            revoke(comm, cause=f"proc_failed:{wr}")


def enable() -> None:
    """Arm auto-revocation: PROC_FAILED events (probes, faultline
    rank_kill, DCN liveness) revoke every comm containing the dead
    rank. Also enables elastic's failure tracking (the known-dead set
    agree/recover re-root around). Idempotent."""
    global _handler_id
    elastic.enable()
    with _mu:
        if _handler_id is None:
            _handler_id = events.register(
                events.EventClass.PROC_FAILED, _on_failure
            )


def disable() -> None:
    global _handler_id
    with _mu:
        if _handler_id is not None:
            events.deregister(_handler_id)
            _handler_id = None


# -- fault-tolerant agreement -------------------------------------------

def _vote_tree(survivors: list[int]) -> list[tuple[int, int]]:
    """Binomial combine edges (child, parent) over the survivor list,
    re-rooted at survivors[0]: round k merges position i+2^k into
    position i. Pure function of the list — the logged tree shape is
    deterministic."""
    edges = []
    n = len(survivors)
    span = 1
    while span < n:
        for i in range(0, n - span, span * 2):
            edges.append((survivors[i + span], survivors[i]))
        span *= 2
    return edges


def agree(comm, flags) -> int:
    """MPIX_Comm_agree: bitwise AND of the surviving ranks' flags,
    masking failures. Two phases per round: (1) combine votes up a
    binomial tree re-rooted around the known-dead set; (2) confirm the
    dead set did not move while voting — a mid-vote death re-roots and
    retries (``ft_lifeboat_agree_rounds`` rounds). Returns the agreed
    flags on every survivor, or raises AgreeError on every survivor —
    never split-brain. ``flags`` is a per-rank sequence (bools coerce
    to 0/1); dead ranks' entries are ignored."""
    rounds = max(1, int(_agree_rounds.value))
    for attempt in range(rounds):
        dead = elastic.failed_ranks()
        survivors = [
            r for r, wr in enumerate(comm.group.world_ranks)
            if wr not in dead
        ]
        if not survivors:
            _note(f"agree cid={comm.cid} epoch={comm.epoch} "
                  f"attempt={attempt} result=no-survivors")
            SPC.record("ft_agree_failures")
            raise AgreeError(f"{comm.name}: no survivors to agree")
        # phase 1: tree vote (the controller holds every survivor's
        # flag; the combine order is the logged binomial tree)
        votes = {r: int(flags[r]) for r in survivors}
        result = None
        for child, parent in _vote_tree(survivors):
            votes[parent] &= votes[child]
        result = votes[survivors[0]]
        # phase 2: confirm — a death during the vote invalidates the
        # tree (its edges may have combined a dead rank's stale flag)
        if elastic.failed_ranks() != dead:
            _note(f"agree cid={comm.cid} epoch={comm.epoch} "
                  f"attempt={attempt} result=re-root")
            SPC.record("ft_agree_reroots")
            continue
        _note(f"agree cid={comm.cid} epoch={comm.epoch} "
              f"attempt={attempt} root={survivors[0]} "
              f"survivors={len(survivors)} flags={result}")
        SPC.record("ft_agrees")
        return result
    _note(f"agree cid={comm.cid} epoch={comm.epoch} "
          f"result=unstable after {rounds} rounds")
    SPC.record("ft_agree_failures")
    raise AgreeError(
        f"{comm.name}: failure set still moving after {rounds} "
        f"agreement rounds"
    )


# -- the recovery pipeline ----------------------------------------------

def _migrate_sched_cache(old_n: int, new_n: int,
                         seed: Optional[int] = None) -> int:
    """Move the winner cache to the shrunk world: every key tuned for
    ``r<old_n>`` gets a ``r<new_n>`` counterpart installed through the
    existing retune sweep (warm re-tune, not cold-start). The old keys
    stay — a respawn back to old_n re-uses them. Returns the number of
    keys migrated."""
    from ..coll.sched import autotune, cache as scache, retune

    fp = autotune.fingerprint()
    entries = scache.CACHE.entries()
    migrated = 0
    for key in sorted(entries):
        parsed = retune.parse_key(key)
        if parsed is None or parsed["nranks"] != old_n:
            continue
        new_key = scache.cache_key(
            parsed["opname"], scache.bucket_bytes(parsed["bucket"]),
            new_n,
            None if parsed["dtype"] == "any" else parsed["dtype"],
            fp,
        )
        if new_key in entries:
            continue
        if retune.retune_key(new_key, reason="recover",
                             seed=seed) is not None:
            migrated += 1
    return migrated


def recover(comm, *, quiesce_timeout: float = 1.0,
            seed: Optional[int] = None,
            migrate_cache: bool = True) -> Any:
    """The deterministic recovery pipeline: revoke (idempotent) →
    quiesce → agree → shrink → epoch bump → state re-admission.
    Returns the shrunk communicator, whose collectives are
    bit-identical to a survivor-only reference. Phase timings land in
    ``last_report()``; the decision log stays timestamp-free."""
    from ..coll.sched import cache as scache
    from ..health import ledger as health
    from ..telemetry import fleet, watchtower
    from . import crcp

    phases: dict[str, float] = {}
    t0 = time.perf_counter()

    def _mark(phase: str) -> None:
        nonlocal t0
        now = time.perf_counter()
        phases[f"{phase}_ms"] = round((now - t0) * 1e3, 3)
        t0 = now

    revoke(comm, cause="recover")
    _mark("revoke")
    # quiesce: drain what can drain; a timeout cancel-and-marks the
    # stragglers (crcp's bkmrk fix), so either way the bookmark is
    # clean when shrink runs.
    cancelled = drained = 0
    try:
        bm = crcp.quiesce(comm, timeout=quiesce_timeout)
        drained = bm.drained_waits
    except crcp.QuiesceTimeout as exc:
        bm = getattr(exc, "bookmark", None)
        cancelled = bm.cancelled if bm is not None else 0
    _mark("quiesce")
    dead = elastic.failed_ranks()
    # agree on the shrink: every survivor votes 1 — the agreement's
    # job here is masking mid-pipeline failures (a second death during
    # recovery re-roots instead of splitting the survivor set).
    agree(comm, [1] * comm.size)
    _mark("agree")
    new = elastic.shrink(comm, dead=dead)
    new.epoch = comm.epoch + 1
    _mark("shrink")
    migrated = _migrate_sched_cache(comm.size, new.size,
                                    seed=seed) if migrate_cache else 0
    gcd = health.LEDGER.gc_scope(str(comm.cid))
    seeded = health.LEDGER.seed_scope(str(new.cid))
    dead_sorted = sorted(dead)
    fleet.mark_dead(dead_sorted)
    baselines = watchtower.reset_baselines(reason="recover")
    _mark("readmit")
    _note(
        f"recover cid={comm.cid}->{new.cid} "
        f"epoch={comm.epoch}->{new.epoch} dead={dead_sorted} "
        f"survivors={new.size} cache_migrated={migrated} "
        f"ledger_gc={gcd} ledger_seeded={seeded} "
        f"baselines_reset={baselines}"
    )
    SPC.record("ft_recovers")
    from ..trace import span as tspan

    tspan.instant("ft.recover", cat="ft", cid=comm.cid,
                  new_cid=new.cid, epoch=new.epoch,
                  dead=dead_sorted, survivors=new.size)
    with _mu:
        _last_report.clear()
        _last_report.update({
            "phases": phases, "dead": dead_sorted,
            "survivors": new.size, "cache_migrated": migrated,
            "ledger_gc": gcd, "quiesce_cancelled": cancelled,
            "quiesce_drained": drained,
        })
    logger.info("lifeboat: recovered %s -> %s (%d survivors, dead=%s)",
                comm.name, new.name, new.size, dead_sorted)
    return new


def detach(comm, *, cause: str = "detach",
           quiesce_timeout: float = 1.0) -> dict:
    """Deterministic teardown of one communicator: revoke → quiesce →
    free → ledger scope GC. This is recover() without the shrink — the
    comm is leaving, not surviving. The daemon's eviction pipeline
    reuses it so an evicted tenant's sessions drain through exactly
    the recovery machinery (outstanding waits cancelled-and-marked,
    scope entries collected, a numbered timestamp-free log line), and
    a same-seed eviction keeps the digest byte-identical."""
    from ..health import ledger as health
    from . import crcp

    revoke(comm, cause=cause)
    cancelled = drained = 0
    try:
        bm = crcp.quiesce(comm, timeout=quiesce_timeout)
        drained = bm.drained_waits
    except crcp.QuiesceTimeout as exc:
        bm = getattr(exc, "bookmark", None)
        cancelled = bm.cancelled if bm is not None else 0
    comm.free()
    gcd = health.LEDGER.gc_scope(str(comm.cid), cause=cause)
    _note(
        f"detach cid={comm.cid} epoch={comm.epoch} cause={cause} "
        f"drained={drained} cancelled={cancelled} ledger_gc={gcd}"
    )
    SPC.record("ft_detaches")
    from ..trace import span as tspan

    tspan.instant("ft.detach", cat="ft", cid=comm.cid,
                  epoch=comm.epoch, cause=cause)
    return {"drained": drained, "cancelled": cancelled,
            "ledger_gc": gcd}


# -- respawn / re-admission ---------------------------------------------

def readmit(comm, *, canary: Optional[Callable[[], bool]] = None,
            attempts: int = 1, backoff: Optional[Any] = None) -> bool:
    """Admit a (re)spawned rank's communicator through PROBATION: the
    comm-scope device tier starts QUARANTINED, the canary probe (a
    device liveness sweep by default) must pass, and its successes
    walk the ledger QUARANTINED → PROBATION → HEALTHY before the comm
    carries real traffic. Returns True when the tier reached HEALTHY.

    Canary-fail → retry is idempotent: every walk (first attempt or
    retry) starts by forcing the tier to QUARANTINED, and a failed
    canary charges the failure *and then re-quarantines with cause* —
    a failure landing mid-PROBATION would otherwise leave partial
    success/failure counts behind, making a second ``readmit`` start
    from an ambiguous ladder position. Retries (``attempts`` > 1) are
    separated by a bounded seeded ``Backoff`` (deadline exhaustion
    stops retrying early); pass ``backoff`` to pin the schedule."""
    from ..core.backoff import Backoff
    from ..health import ledger as health

    scope = str(comm.cid)

    def _default_canary() -> bool:
        return not events.check_devices(comm)

    probe = canary or _default_canary
    # the +1 covers the QUARANTINED->PROBATION probe itself
    needed = int(config.get("health_ledger_probation_successes", 2)) + 1
    attempts = max(1, int(attempts))
    if backoff is None:
        # seeded by the cid so the retry schedule is a pure function
        # of the comm being readmitted; bounded so a flaky canary can
        # never stall admission indefinitely
        backoff = Backoff(initial=0.01, maximum=0.25, seed=comm.cid,
                          timeout=2.0)
    for attempt in range(attempts):
        # pin the walk's starting state: whether this is the first
        # attempt or a retry after a mid-ladder canary failure, the
        # tier begins QUARANTINED with the success count cleared
        health.LEDGER.quarantine("device", scope=scope,
                                 cause="readmit")
        failed = False
        for _ in range(needed):
            try:
                ok = bool(probe())
            except Exception:  # commlint: allow(broadexcept)
                ok = False
            if not ok:
                health.LEDGER.report_failure("device", scope=scope,
                                             cause="canary")
                # the failure may have landed mid-PROBATION (which
                # re-quarantines via hysteresis) or in QUARANTINED
                # (which only bumps the count) — force the state so
                # the NEXT walk is unambiguous either way
                health.LEDGER.quarantine("device", scope=scope,
                                         cause="canary_failed")
                _note(f"readmit cid={comm.cid} attempt={attempt} "
                      f"result=canary-failed")
                SPC.record("ft_readmit_failures")
                failed = True
                break
            health.LEDGER.report_success("device", scope=scope)
        if not failed:
            healthy = health.LEDGER.state("device", scope) \
                == health.HEALTHY
            _note(f"readmit cid={comm.cid} attempt={attempt} "
                  f"result={'healthy' if healthy else 'probation'}")
            SPC.record("ft_readmits")
            return healthy
        if attempt + 1 < attempts and not backoff.sleep():
            _note(f"readmit cid={comm.cid} attempt={attempt} "
                  f"result=backoff-exhausted")
            break
    return False


def respawn(comm, manager, *, like: Any = None,
            canary: Optional[Callable[[], bool]] = None
            ) -> tuple[Any, Any, dict]:
    """elastic.respawn + lifeboat hardening: the restored comm gets
    the bumped epoch and is re-admitted through PROBATION with a
    canary probe before it carries real traffic."""
    new_comm, state, meta = elastic.respawn(comm, manager, like=like)
    new_comm.epoch = comm.epoch + 1
    readmit(new_comm, canary=canary)
    return new_comm, state, meta


# -- pml guard (pml/framework.select_for_comm interposition) ------------

class LifeboatPml:
    """Always-on pass-through PML raising RevokedError on any p2p
    against a revoked comm — the pml/ half of the dispatch fence (the
    coll/ half lives in tuned's retry loop). One attribute read per
    call; unknown attributes — including NAME — delegate (sanitizer
    wrapper idiom), so `comm.pml.NAME` still reports the selection."""

    def __init__(self, host) -> None:
        self.host = host

    def __getattr__(self, name):
        return getattr(self.host, name)

    @staticmethod
    def _fence_check(comm) -> None:
        if comm._revoked:
            raise RevokedError(
                f"{comm.name} (cid={comm.cid}) has been revoked; "
                f"run ft.lifeboat.recover"
            )

    def send(self, comm, value, dest, tag, source=None):
        self._fence_check(comm)
        return self.host.send(comm, value, dest, tag, source=source)

    def isend(self, comm, value, dest, tag, source=None):
        self._fence_check(comm)
        return self.host.isend(comm, value, dest, tag, source=source)

    def recv(self, comm, source, tag, *, dest):
        self._fence_check(comm)
        return self.host.recv(comm, source, tag, dest=dest)

    def irecv(self, comm, source, tag, *, dest):
        self._fence_check(comm)
        return self.host.irecv(comm, source, tag, dest=dest)

    def probe(self, comm, source, tag, *, dest, blocking=False):
        self._fence_check(comm)
        return self.host.probe(comm, source, tag, dest=dest,
                               blocking=blocking)


def maybe_wrap_pml(selected):
    """pml/framework hook: the revocation fence wraps outermost so a
    poisoned comm raises before the sanitizer accounts (or faultline
    perturbs) an operation that will never run."""
    if selected is None:
        return selected
    return LifeboatPml(selected)

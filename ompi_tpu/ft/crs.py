"""CRS — checkpoint/restart service framework.

TPU-native equivalent of opal/mca/crs (reference: crs/self = app
callbacks, crs/none; driven by opal-checkpoint/opal-restart tools,
SURVEY §5.3-5.4). The reference snapshots *process images*; the TPU
analog snapshots *array state* (SURVEY §5.4: "the TPU analog is
array-state checkpointing, not process images"): a pytree of jax.Arrays
plus JSON metadata, written atomically (tmp + rename) so a crash
mid-checkpoint never corrupts the previous snapshot.

Components:
- **arrays**: numpy .npz payload + treedef sidecar; restore re-places
  leaves on devices (optionally to a target sharding).
- **orbax**: delegates to orbax.checkpoint when importable — the
  ecosystem-standard path for large sharded state.
- **app**: registered application callbacks (reference crs/self's
  checkpoint/continue/restart hooks).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Callable, Optional

import numpy as np

from ..core import component as mca
from ..core.counters import SPC
from ..core.errors import OmpiTpuError
from ..core.logging import get_logger

logger = get_logger("ft.crs")

CRS = mca.framework("crs", "checkpoint/restart service")


class CheckpointError(OmpiTpuError):
    errclass = "ERR_OTHER"


class CrsComponent(mca.Component):
    def save(self, path: str, state: Any, meta: dict) -> None:
        raise NotImplementedError

    def load(self, path: str, like: Any = None) -> tuple[Any, dict]:
        """Restore. `like` is an abstract/concrete template pytree: when
        given, the result has its structure and its leaves' placement
        (device_put to matching shardings); when omitted the result is a
        flat {keypath: np.ndarray} dict."""
        raise NotImplementedError


def _paths_of(state):
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    keys = [jax.tree_util.keystr(kp) for kp, _ in flat]
    if len(set(keys)) != len(keys):
        raise CheckpointError("duplicate pytree key paths")
    return keys, [leaf for _, leaf in flat], treedef


@CRS.register
class ArraysCrs(CrsComponent):
    """Atomic npz snapshot of a pytree of arrays."""

    NAME = "arrays"
    PRIORITY = 20
    DESCRIPTION = "npz array-state snapshots"

    def save(self, path: str, state: Any, meta: dict) -> None:
        import jax

        keys, leaves, treedef = _paths_of(state)
        host = [np.asarray(l) for l in leaves]
        tmp = path + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        np.savez(
            os.path.join(tmp, "arrays.npz"),
            **{f"leaf{i}": h for i, h in enumerate(host)},
        )
        doc = {
            "keys": keys,
            "treedef": str(treedef),
            "meta": meta,
            "format": "ompi_tpu.crs.arrays.v1",
        }
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(doc, f)
        # Crash-safe replacement: the previous snapshot is moved aside
        # (not deleted) before the new one lands, so at every instant
        # either `path` or `path + ".old"` holds a complete snapshot —
        # including when recovering from a crash that left only `.old`
        # (then `.old` must survive until the new snapshot is in place).
        old = path + ".old"
        if os.path.exists(path):
            if os.path.exists(old):
                shutil.rmtree(old)
            os.rename(path, old)
        os.rename(tmp, path)
        shutil.rmtree(old, ignore_errors=True)
        SPC.record("ft_checkpoints_saved")

    def load(self, path: str, like: Any = None) -> tuple[Any, dict]:
        import jax

        # save() guarantees that at every instant either `path` or
        # `path + ".old"` holds a complete snapshot — consume that
        # guarantee: fall back to .old when a crash landed between the
        # two renames.
        if not os.path.exists(os.path.join(path, "meta.json")):
            old = path + ".old"
            if os.path.exists(os.path.join(old, "meta.json")):
                path = old
        with open(os.path.join(path, "meta.json")) as f:
            doc = json.load(f)
        if doc.get("format") != "ompi_tpu.crs.arrays.v1":
            raise CheckpointError(f"{path}: unknown snapshot format")
        data = np.load(os.path.join(path, "arrays.npz"))
        leaves = [data[f"leaf{i}"] for i in range(len(doc["keys"]))]
        SPC.record("ft_checkpoints_loaded")
        if like is None:
            return dict(zip(doc["keys"], leaves)), doc["meta"]
        want_keys, want_leaves, treedef = _paths_of(like)
        if want_keys != doc["keys"]:
            raise CheckpointError(
                f"template structure mismatch: snapshot has "
                f"{doc['keys'][:4]}..., template {want_keys[:4]}..."
            )
        placed = []
        for raw, tmpl in zip(leaves, want_leaves):
            if hasattr(tmpl, "sharding"):
                placed.append(jax.device_put(raw, tmpl.sharding))
            else:
                placed.append(raw)
        state = jax.tree_util.tree_unflatten(treedef, placed)
        return state, doc["meta"]


@CRS.register
class OrbaxCrs(CrsComponent):
    """Orbax-backed snapshots (sharded-state capable)."""

    NAME = "orbax"
    PRIORITY = 10
    DESCRIPTION = "orbax.checkpoint array-state snapshots"

    def available(self, **ctx: Any) -> bool:
        try:
            import orbax.checkpoint  # noqa: F401

            return True
        except ImportError:
            return False

    def save(self, path: str, state: Any, meta: dict) -> None:
        import orbax.checkpoint as ocp

        path = os.path.abspath(path)
        if os.path.exists(path):
            shutil.rmtree(path)
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(os.path.join(path, "state"), state)
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump({"meta": meta,
                       "format": "ompi_tpu.crs.orbax.v1"}, f)
        SPC.record("ft_checkpoints_saved")

    def load(self, path: str, like: Any = None) -> tuple[Any, dict]:
        import orbax.checkpoint as ocp

        path = os.path.abspath(path)
        with open(os.path.join(path, "meta.json")) as f:
            doc = json.load(f)
        with ocp.StandardCheckpointer() as ckptr:
            if like is not None:
                state = ckptr.restore(os.path.join(path, "state"), like)
            else:
                state = ckptr.restore(os.path.join(path, "state"))
        SPC.record("ft_checkpoints_loaded")
        return state, doc["meta"]


@CRS.register
class AppCrs(CrsComponent):
    """Application-callback checkpointing (reference: crs/self —
    OPAL_CRS_CHECKPOINT/CONTINUE/RESTART callbacks)."""

    NAME = "app"
    PRIORITY = 0
    DESCRIPTION = "application checkpoint/restart callbacks"

    def __init__(self, framework) -> None:
        super().__init__(framework)
        self.checkpoint_cb: Optional[Callable[[str], dict]] = None
        self.restart_cb: Optional[Callable[[str, dict], Any]] = None

    def register_callbacks(self, checkpoint: Callable[[str], dict],
                           restart: Callable[[str, dict], Any]) -> None:
        self.checkpoint_cb = checkpoint
        self.restart_cb = restart

    def save(self, path: str, state: Any, meta: dict) -> None:
        if self.checkpoint_cb is None:
            raise CheckpointError("no app checkpoint callback registered")
        os.makedirs(path, exist_ok=True)
        app_meta = self.checkpoint_cb(path) or {}
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump({"meta": {**meta, **app_meta},
                       "format": "ompi_tpu.crs.app.v1"}, f)
        SPC.record("ft_checkpoints_saved")

    def load(self, path: str, like: Any = None) -> tuple[Any, dict]:
        if self.restart_cb is None:
            raise CheckpointError("no app restart callback registered")
        with open(os.path.join(path, "meta.json")) as f:
            doc = json.load(f)
        state = self.restart_cb(path, doc["meta"])
        SPC.record("ft_checkpoints_loaded")
        return state, doc["meta"]


def select(**ctx) -> CrsComponent:
    return CRS.select_one(**ctx)


def component(name: str) -> CrsComponent:
    return CRS.component(name)

"""CRCP — checkpoint coordination: quiescing in-flight communication.

TPU-native equivalent of ompi/mca/crcp/bkmrk (reference: the "bookmark"
protocol exchanges per-peer sent/received counts and drains traffic
until they agree, crcp_bkmrk_pml.c, SURVEY §5.3). In the driver model
both sides' state is directly visible, so the bookmark exchange
collapses to an inspection of the PML matching lists plus a progress
loop — but the contract is the same: after `quiesce()` returns, no
message is in flight on the communicator, so a checkpoint taken then is
consistent.

Collectives are bulk-synchronous XLA programs, so quiescing them is
`jax.block_until_ready` on outstanding plans — handled by the request
layer; only p2p has cross-call state to drain.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..core import progress as progress_mod
from ..core.counters import SPC
from ..core.errors import OmpiTpuError
from ..core.logging import get_logger

logger = get_logger("ft.crcp")


class QuiesceTimeout(OmpiTpuError):
    errclass = "ERR_PENDING"


@dataclass
class Bookmark:
    """The drain report (reference: bkmrk's per-peer counters)."""

    comm: str
    unexpected: int = 0  # sends no recv has matched yet
    posted: int = 0  # recvs no send has matched yet
    drained_waits: int = 0
    details: list = field(default_factory=list)

    @property
    def quiet(self) -> bool:
        return self.unexpected == 0 and self.posted == 0


def _inspect(comm) -> Bookmark:
    bm = Bookmark(comm=comm.name)
    pml = comm.pml
    st = getattr(pml, "_state", None)
    base = pml
    # vprotocol interposition forwards state inspection to its host pml
    while hasattr(base, "host"):
        base = base.host
        st = getattr(base, "_state", None)
    if st is None:
        return bm
    s = base._state(comm)
    bm.unexpected = len(s.unexpected)
    bm.posted = len(s.posted)
    for p in s.unexpected:
        bm.details.append(
            ("unmatched-send", p.env.src, p.env.dst, p.env.tag)
        )
    for r in s.posted:
        bm.details.append(
            ("unmatched-recv", r.want_src, r.dst, r.want_tag)
        )
    return bm


def inspect(comm) -> Bookmark:
    """Non-blocking bookmark: current in-flight counts."""
    return _inspect(comm)


def quiesce(comm, timeout: float = 5.0,
            require_empty: bool = True) -> Bookmark:
    """Progress until the communicator's p2p channels are quiet.

    With require_empty (the bkmrk contract), raises QuiesceTimeout if
    unmatched traffic remains after `timeout` — the caller must not
    checkpoint. With require_empty=False, returns the residual bookmark
    for the caller to persist alongside the snapshot (message-logging
    restart can replay it, vprotocol analog)."""
    from ..core.backoff import Backoff

    # Drive progress every iteration; the sleep between polls backs
    # off 1 ms -> 10 ms (a quiesce that isn't quiet in a few polls is
    # waiting on a remote, not on this process's CPU). The caller's
    # timeout still bounds the whole wait.
    bo = Backoff(initial=0.001, maximum=0.01, timeout=timeout)
    waits = 0
    while True:
        bm = _inspect(comm)
        bm.drained_waits = waits
        if bm.quiet:
            SPC.record("ft_quiesce_ok")
            return bm
        if bo.expired:
            SPC.record("ft_quiesce_timeout")
            if require_empty:
                raise QuiesceTimeout(
                    f"{comm.name}: traffic still in flight after "
                    f"{timeout}s: {bm.details[:8]}"
                )
            return bm
        progress_mod.progress()
        waits += 1
        bo.sleep()

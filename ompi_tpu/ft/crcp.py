"""CRCP — checkpoint coordination: quiescing in-flight communication.

TPU-native equivalent of ompi/mca/crcp/bkmrk (reference: the "bookmark"
protocol exchanges per-peer sent/received counts and drains traffic
until they agree, crcp_bkmrk_pml.c, SURVEY §5.3). In the driver model
both sides' state is directly visible, so the bookmark exchange
collapses to an inspection of the PML matching lists plus a progress
loop — but the contract is the same: after `quiesce()` returns, no
message is in flight on the communicator, so a checkpoint taken then is
consistent.

Collectives are bulk-synchronous XLA programs, so quiescing them is
`jax.block_until_ready` on outstanding plans — handled by the request
layer; only p2p has cross-call state to drain.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..core import progress as progress_mod
from ..core.counters import SPC
from ..core.errors import OmpiTpuError
from ..core.logging import get_logger

logger = get_logger("ft.crcp")


class QuiesceTimeout(OmpiTpuError):
    errclass = "ERR_PENDING"


@dataclass
class Bookmark:
    """The drain report (reference: bkmrk's per-peer counters)."""

    comm: str
    unexpected: int = 0  # sends no recv has matched yet
    posted: int = 0  # recvs no send has matched yet
    drained_waits: int = 0
    cancelled: int = 0  # stragglers cancel-and-marked on timeout
    details: list = field(default_factory=list)

    @property
    def quiet(self) -> bool:
        return self.unexpected == 0 and self.posted == 0


def _inspect(comm) -> Bookmark:
    bm = Bookmark(comm=comm.name)
    pml = comm.pml
    st = getattr(pml, "_state", None)
    base = pml
    # vprotocol interposition forwards state inspection to its host pml
    while hasattr(base, "host"):
        base = base.host
        st = getattr(base, "_state", None)
    if st is None:
        return bm
    s = base._state(comm)
    bm.unexpected = len(s.unexpected)
    bm.posted = len(s.posted)
    for p in s.unexpected:
        bm.details.append(
            ("unmatched-send", p.env.src, p.env.dst, p.env.tag)
        )
    for r in s.posted:
        bm.details.append(
            ("unmatched-recv", r.want_src, r.dst, r.want_tag)
        )
    return bm


def inspect(comm) -> Bookmark:
    """Non-blocking bookmark: current in-flight counts."""
    return _inspect(comm)


def _base_pml(comm):
    base = comm.pml
    while hasattr(base, "host"):
        base = base.host
    return base


def cancel_stragglers(comm) -> int:
    """Cancel-and-mark every in-flight p2p operation on ``comm``: the
    unmatched sends are dropped from the matching lists and the posted
    receives are cancelled (their waiters observe CANCELLED, never a
    hang). Run by the quiesce timeout path — and usable directly by
    recover() — so a follow-up bookmark starts clean instead of
    inheriting half-drained state. Returns the straggler count."""
    base = _base_pml(comm)
    if not hasattr(base, "_state"):
        return 0
    mu = getattr(base, "_mu", None)
    cancelled = 0
    if mu is not None:
        mu.acquire()
    try:
        s = base._state(comm)
        for r in list(s.posted):
            if hasattr(r, "cancel"):
                r.cancel()
                cancelled += 1
        # cancelled recvs self-purge from the posted list on the next
        # match pass; clear eagerly so the very next inspect is quiet
        s.posted.clear()
        cancelled += len(s.unexpected)
        s.unexpected.clear()
    finally:
        if mu is not None:
            mu.release()
    if cancelled:
        SPC.record("ft_quiesce_cancelled", cancelled)
    return cancelled


def quiesce(comm, timeout: float = 5.0,
            require_empty: bool = True) -> Bookmark:
    """Progress until the communicator's p2p channels are quiet.

    With require_empty (the bkmrk contract), raises QuiesceTimeout if
    unmatched traffic remains after `timeout` — the caller must not
    checkpoint. With require_empty=False, returns the residual bookmark
    for the caller to persist alongside the snapshot (message-logging
    restart can replay it, vprotocol analog)."""
    from ..core.backoff import Backoff

    # Drive progress every iteration; the sleep between polls backs
    # off 1 ms -> 10 ms (a quiesce that isn't quiet in a few polls is
    # waiting on a remote, not on this process's CPU). The caller's
    # timeout still bounds the whole wait.
    bo = Backoff(initial=0.001, maximum=0.01, timeout=timeout)
    waits = 0
    while True:
        bm = _inspect(comm)
        bm.drained_waits = waits
        if bm.quiet:
            SPC.record("ft_quiesce_ok")
            return bm
        if bo.expired:
            SPC.record("ft_quiesce_timeout")
            if require_empty:
                # Cancel-and-mark the stragglers before raising: a
                # QuiesceTimeout must not leave half-drained matching
                # state behind — a follow-up recover() starts from a
                # clean bookmark instead of inheriting it.
                bm.cancelled = cancel_stragglers(comm)
                exc = QuiesceTimeout(
                    f"{comm.name}: traffic still in flight after "
                    f"{timeout}s ({bm.cancelled} cancelled): "
                    f"{bm.details[:8]}"
                )
                exc.bookmark = bm  # recover() reads the counts
                raise exc
            return bm
        progress_mod.progress()
        waits += 1
        bo.sleep()

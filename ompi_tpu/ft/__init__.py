"""Fault tolerance: failure events, checkpoint/restart, quiesce, logging.

TPU-native equivalent of the reference FT stack (SURVEY §5.3-5.4):
PMIx failure events → `events`; opal/mca/crs → `crs`; crcp/bkmrk →
`crcp`; vprotocol/pessimist → `vprotocol`; opal_cr runtime +
opal-checkpoint tooling → `manager`.
"""

from . import crcp, crs, elastic, events, manager, vprotocol
from .crs import CheckpointError
from .events import Event, EventClass, ProcFailedError
from .manager import CheckpointManager

__all__ = [
    "CheckpointError", "CheckpointManager", "Event", "EventClass",
    "ProcFailedError", "crcp", "crs", "elastic", "events", "manager",
    "vprotocol",
]

"""vprotocol/pessimist — message logging for deterministic replay.

TPU-native equivalent of ompi/mca/vprotocol/pessimist hosted by pml/v
(reference: vprotocol_pessimist_sender_based.c sender-based payload
logging, vprotocol_pessimist_eventlog.c delivery-order event log,
SURVEY §5.3). The interposition pattern mirrors pml/v: a wrapper PML
forwards every call to the host PML, recording

- **send events**: envelope + a host copy of the payload (sender-based
  logging — the payload survives the sender's device state), and
- **delivery events**: the (src, tag, seq) each recv actually matched —
  the only nondeterminism MPI allows (wildcard source/tag).

`replay()` re-executes the log against a fresh communicator: sends are
re-issued from logged payloads in order, recvs are re-posted with their
*resolved* sources/tags, so the original matching order is reproduced
exactly — the pessimist guarantee.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ..core import config
from ..core.counters import SPC
from ..core.errors import OmpiTpuError
from ..core.logging import get_logger
from ..pml.framework import PmlComponent

logger = get_logger("ft.vprotocol")

enable_var = config.register(
    "vprotocol", "pessimist", "enable", type=bool, default=False,
    description="Interpose the message-logging PML (pml/v analog)",
)


class ReplayError(OmpiTpuError):
    errclass = "ERR_OTHER"


@dataclass
class SendEvent:
    seq: int
    src: int
    dst: int
    tag: int
    payload: Any  # host copy


@dataclass
class DeliveryEvent:
    seq: int  # matches the SendEvent seq delivered
    src: int
    dst: int
    tag: int
    wildcard_src: bool
    wildcard_tag: bool


@dataclass
class EventLog:
    sends: list[SendEvent] = field(default_factory=list)
    deliveries: list[DeliveryEvent] = field(default_factory=list)

    def clear(self) -> None:
        self.sends.clear()
        self.deliveries.clear()


class PessimistPml(PmlComponent):
    """Interposition wrapper around the selected host PML."""

    NAME = "v"
    DESCRIPTION = "pessimist message-logging interposition"

    def __init__(self, framework, host: PmlComponent) -> None:
        super().__init__(framework)
        self.host = host
        self.log = EventLog()
        self._seq = itertools.count(0)
        self._req_seq: dict[int, int] = {}  # id(SendRequest) -> seq
        self._lock = threading.Lock()

    # -- send side ---------------------------------------------------------

    def isend(self, comm, value, dest, tag, source=None):
        import jax

        # Sender-based logging MUST precede the host send: when a
        # matching recv is already posted, ob1 delivers synchronously
        # inside host.isend and the delivery callback must find the
        # send already in the log (else it records seq=-1 and replay
        # fails for the recv-before-send pattern).
        infer = getattr(self.host, "_infer_source", None)
        src = infer(comm, value, source) if infer is not None else source
        host_copy = jax.tree.map(lambda l: np.asarray(l), value)
        with self._lock:
            seq = next(self._seq)
            ev = SendEvent(seq, src, dest, tag, host_copy)
            self.log.sends.append(ev)
        SPC.record("vprotocol_sends_logged")
        try:
            req = self.host.isend(comm, value, dest, tag, source=source)
        except Exception:
            with self._lock:
                try:
                    self.log.sends.remove(ev)
                except ValueError:
                    pass
            raise
        with self._lock:
            self._req_seq[id(req)] = seq
        return req

    def send(self, comm, value, dest, tag, source=None):
        req = self.isend(comm, value, dest, tag, source=source)
        req.wait()
        return req

    # -- recv side ---------------------------------------------------------

    def _log_delivery(self, req, want_src, want_tag) -> None:
        # The matched pending send is identified through the envelope of
        # the completed request's status.
        def on_complete(r):
            st = r.status
            if st is None or r.status.cancelled:
                return
            with self._lock:
                # find the logged send this delivery corresponds to:
                # earliest un-delivered send with this (src, dst, tag)
                delivered = {d.seq for d in self.log.deliveries}
                seq = -1
                for ev in self.log.sends:
                    if (ev.seq not in delivered and ev.src == st.source
                            and ev.dst == r.dst and ev.tag == st.tag):
                        seq = ev.seq
                        break
                self.log.deliveries.append(
                    DeliveryEvent(
                        seq, st.source, r.dst, st.tag,
                        wildcard_src=want_src < 0,
                        wildcard_tag=want_tag < 0,
                    )
                )
            SPC.record("vprotocol_deliveries_logged")

        req.on_complete(on_complete)

    def irecv(self, comm, source, tag, dest=None):
        req = self.host.irecv(comm, source, tag, dest=dest)
        self._log_delivery(req, source, tag)
        return req

    def recv(self, comm, source, tag, dest=None):
        req = self.irecv(comm, source, tag, dest=dest)
        req.wait()
        return req.result()

    # -- pass-through ------------------------------------------------------

    def probe(self, comm, source, tag, **kw):
        return self.host.probe(comm, source, tag, **kw)

    def comm_freed(self, comm) -> None:
        if hasattr(self.host, "comm_freed"):
            self.host.comm_freed(comm)


def replay(comm, log: EventLog) -> list[Any]:
    """Deterministically re-execute a log on `comm`: returns the received
    payloads in original delivery order. Wildcard recvs are replayed with
    their RESOLVED source/tag (the pessimist rule: nondeterministic
    choices are fixed by the log)."""
    results = []
    # Snapshot: if `comm` itself runs under the logging PML (recovery
    # with logging re-armed), replay traffic appends to `log` — iterate
    # the pre-replay state only.
    sends = list(log.sends)
    deliveries = list(log.deliveries)
    send_by_seq = {ev.seq: ev for ev in sends}
    issued: set[int] = set()
    for d in deliveries:
        if d.seq < 0:
            raise ReplayError(
                f"delivery {d} has no matched send event in the log"
            )
        ev = send_by_seq.get(d.seq)
        if ev is None:
            raise ReplayError(f"send seq {d.seq} missing from log")
        # Re-issue every logged send up to and including this one's seq
        # so ordering between same-(src,dst,tag) sends is preserved.
        for s in sends:
            if s.seq <= d.seq and s.seq not in issued:
                # replay: receiver side completes these
                comm.isend(s.payload, s.dst, s.tag, source=s.src)  # commlint: allow(reqlife)
                issued.add(s.seq)
        out = comm.recv(d.src, d.tag, dest=d.dst)
        results.append(out)
    # flush any logged sends never delivered (they were in flight)
    for s in sends:
        if s.seq not in issued:
            # re-injected in-flight sends; the restarted peer receives them
            comm.isend(s.payload, s.dst, s.tag, source=s.src)  # commlint: allow(reqlife)
    SPC.record("vprotocol_replays")
    return results


def maybe_wrap(pml: PmlComponent, framework) -> PmlComponent:
    """Called by the PML selection path: interpose when enabled
    (reference: pml/v loads when vprotocol is requested)."""
    if enable_var.value and not isinstance(pml, PessimistPml):
        logger.info("interposing pessimist message-logging PML")
        return PessimistPml(framework, pml)
    return pml

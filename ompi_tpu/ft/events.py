"""Failure detection and event routing.

TPU-native equivalent of the PMIx event machinery the reference wires at
init (reference: ompi_mpi_init.c:524 PMIx_Register_event_handler →
ompi_errhandler_callback; errhandlers per comm/win/file). The driver
model has no daemon: failure signals come from (a) the JAX runtime
surfacing device/ICI errors as exceptions, (b) explicit probes
(`check_devices`), and (c) test injection (`inject`). All three funnel
through one registry that routes to registered handlers and then to the
errhandlers of affected communicators.
"""

from __future__ import annotations

import enum
import itertools
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..core.counters import SPC
from ..core.errors import OmpiTpuError
from ..core.logging import get_logger

logger = get_logger("ft.events")


class EventClass(enum.Enum):
    PROC_FAILED = "proc_failed"  # a rank/device is gone
    DEVICE_ERROR = "device_error"  # device raised but may survive
    CHECKPOINT = "checkpoint"  # a checkpoint is being taken
    RESTART = "restart"  # state was restored
    USER = "user"


class ProcFailedError(OmpiTpuError):
    errclass = "ERR_PROC_FAILED"


@dataclass
class Event:
    evclass: EventClass
    info: dict = field(default_factory=dict)

    @property
    def rank(self) -> Optional[int]:
        return self.info.get("rank")


Handler = Callable[[Event], None]

_handlers: dict[int, tuple[EventClass, Handler]] = {}
_ids = itertools.count(1)
_lock = threading.Lock()


def register(evclass: EventClass, handler: Handler) -> int:
    """Register a handler; returns an id for deregister (the PMIx
    Register_event_handler analog)."""
    with _lock:
        hid = next(_ids)
        _handlers[hid] = (evclass, handler)
        return hid


def deregister(hid: int) -> None:
    with _lock:
        _handlers.pop(hid, None)


def clear() -> None:
    with _lock:
        _handlers.clear()


def raise_event(evclass: EventClass, **info: Any) -> Event:
    """Deliver an event to every matching handler, then (for failures)
    to the errhandler of every live communicator containing the rank."""
    ev = Event(evclass, info)
    SPC.record(f"ft_events_{evclass.value}")
    with _lock:
        targets = [h for c, h in _handlers.values() if c == evclass]
    for h in targets:
        try:
            h(ev)
        # user-callback dispatch: a handler may raise anything, and one
        # bad handler must not starve the rest
        except Exception:  # commlint: allow(broadexcept)
            logger.exception("event handler failed for %s", evclass)
    if evclass in (EventClass.PROC_FAILED, EventClass.DEVICE_ERROR):
        _route_to_errhandlers(ev)
    return ev


def _route_to_errhandlers(ev: Event) -> None:
    from ..communicator import live_comms

    world_rank = ev.info.get("world_rank")
    exc = ProcFailedError(
        f"process failure reported: {ev.info}"
    )
    for comm in list(live_comms):
        if comm._freed:
            continue
        if world_rank is not None and world_rank not in comm.group:
            continue
        try:
            comm._invoke_errhandler(exc)
        except ProcFailedError:
            # ERRORS_RETURN re-raises; routing must still reach the
            # remaining comms — the caller sees failures via handlers.
            pass
        # user errhandlers are arbitrary callbacks (see above)
        except Exception:  # commlint: allow(broadexcept)
            logger.exception("errhandler raised for %s", comm.name)


def inject(world_rank: int, **info: Any) -> Event:
    """Fault injection for tests (the reference's only injection is
    abort-style test programs, SURVEY §5.3)."""
    return raise_event(
        EventClass.PROC_FAILED, world_rank=world_rank, injected=True,
        **info,
    )


def check_devices(comm=None) -> list[int]:
    """Probe each rank-device with a trivial computation; returns the
    world ranks whose device failed the probe (raising PROC_FAILED for
    each). The active-probing analog of a PMIx heartbeat."""
    import jax
    import jax.numpy as jnp

    from .. import api

    comm = comm or api.world()
    failed = []
    for r, dev in enumerate(comm.devices):
        try:
            val = jax.device_put(jnp.ones((), jnp.int32), dev)
            if int(val) != 1:
                raise RuntimeError(f"bad probe result {val}")
        # the probe's whole job is converting ANY device failure mode
        # into a PROC_FAILED event
        except Exception as exc:  # commlint: allow(broadexcept)
            failed.append(r)
            raise_event(
                EventClass.PROC_FAILED,
                world_rank=comm.group.world_rank(r),
                rank=r,
                error=str(exc),
            )
    return failed

"""Checkpoint manager: step-numbered snapshots with quiesce + retention.

Ties the FT stack together the way the reference's opal_cr runtime +
opal-checkpoint tool drive CRS/CRCP (reference: opal/runtime/opal_cr.c,
SURVEY §5.3): quiesce the network (crcp), snapshot array state (crs),
raise CHECKPOINT/RESTART events, keep the last N snapshots.
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Any, Optional

from ..core import config
from ..core.logging import get_logger
from . import crcp, crs, events

logger = get_logger("ft.manager")

_keep = config.register(
    "ft", "manager", "keep", type=int, default=3,
    description="Snapshots retained per checkpoint directory",
)

_SNAP_RE = re.compile(r"^snap-(\d+)$")


class CheckpointManager:
    """Directory of `snap-<step>` snapshots (orbax-style layout)."""

    def __init__(self, directory: str, *, component: Optional[str] = None,
                 keep: Optional[int] = None) -> None:
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.crs = (
            crs.component(component) if component else crs.select()
        )
        self.keep = keep if keep is not None else _keep.value

    # -- inventory ---------------------------------------------------------

    def steps(self) -> list[int]:
        out = []
        for name in os.listdir(self.directory):
            m = _SNAP_RE.match(name)
            if m and os.path.isdir(os.path.join(self.directory, name)):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def path(self, step: int) -> str:
        return os.path.join(self.directory, f"snap-{step}")

    # -- save/restore ------------------------------------------------------

    def save(self, step: int, state: Any, *, comm=None,
             meta: Optional[dict] = None,
             quiesce_timeout: float = 5.0) -> str:
        """Quiesce (when a comm is given), snapshot, prune."""
        meta = dict(meta or {})
        meta["step"] = step
        if comm is not None:
            bm = crcp.quiesce(comm, timeout=quiesce_timeout)
            meta["quiesce_waits"] = bm.drained_waits
        events.raise_event(events.EventClass.CHECKPOINT, step=step)
        p = self.path(step)
        self.crs.save(p, state, meta)
        self._prune()
        logger.info("checkpoint step %d -> %s", step, p)
        return p

    def restore(self, step: Optional[int] = None, *, like: Any = None
                ) -> tuple[Any, dict]:
        if step is None:
            step = self.latest_step()
            if step is None:
                raise crs.CheckpointError(
                    f"{self.directory}: no snapshots"
                )
        state, meta = self.crs.load(self.path(step), like=like)
        events.raise_event(events.EventClass.RESTART, step=step)
        return state, meta

    def _prune(self) -> None:
        steps = self.steps()
        while self.keep > 0 and len(steps) > self.keep:
            victim = steps.pop(0)
            shutil.rmtree(self.path(victim), ignore_errors=True)
            logger.info("pruned snapshot step %d", victim)

"""faultline — deterministic, seeded fault injection at the comm
boundaries.

The ft/ layer carries the ULFM-style recovery surface (events,
``elastic.shrink/agree/respawn``, checkpoint manager, quiesce) but
nothing in the repo could *provoke* the failures those paths exist
for. faultline closes that loop: a **fault plan** — a seeded list of
fault specs — is armed process-wide, and pass-through wrappers at the
BTL (sm + dcn), PML, modex/KV, and collective-dispatch boundaries
consult it on every operation (the sanitizer's interpose-at-selection
pattern from ``analysis/sanitizer.py``: wrappers install when the
component stack is selected, delegate everything they don't fault).

Fault-plan grammar
------------------
A plan is ``;``-separated specs, each ``action@layer[:key=val,...]``::

    drop@btl_dcn:peer=1,tag=100-200,count=2
    delay@pml:op=send,ms=50,count=3
    duplicate@btl_dcn:op=send,count=1
    corrupt@btl_sm:count=1
    disconnect@btl_dcn:peer=0,link=1,count=1
    disconnect@coll:op=allreduce,algo=quant_ring,count=1
    rank_kill@coll:op=allreduce,after=2
    rank_kill@coll:op=allreduce,after=1,exit=17
    rank_kill@coll:op=allreduce,after_step=2,peer=3
    rank_kill@modex:op=get,peer=1
    drop@modex:key=dcn/3,count=1,prob=0.5
    wedge@coll:op=allreduce,algo=native,count=1
    wedge@btl_dcn:op=send,ms=500,count=1

Actions: ``drop`` (message vanishes on the wire — the sender still
completes, exactly like TCP loss), ``delay`` (``ms=`` sleep before the
operation), ``duplicate`` (the operation runs twice), ``corrupt``
(payload perturbed — bytes XOR 0xFF at the BTL, ``leaf + 1`` at the
PML), ``disconnect`` (kill one DCN link via the engine's
``dcn_kill_link``; at the coll layer: the named algorithm tier raises
``FaultInjected``, the kernel/transport-fault the circuit breaker
degrades on), ``rank_kill`` (raise ``FaultInjected`` — or ``os._exit``
when ``exit=`` is given — modelling a controller death mid-call),
``wedge`` (the operation STALLS — blocks until ``ms=`` elapses, or
indefinitely until ``disarm()`` releases it; the hang-not-fail mode
the health sentinel's stall deadlines exist for).

Scoping keys: ``op`` (operation name at the layer: send/recv at
pml/btl, get/put at modex, the collective name at coll), ``peer``
(int; at the coll layer it is not a filter but names the victim world
rank for ``rank_kill``), ``tag=N`` or ``tag=LO-HI`` (inclusive range),
``count`` (fire
at most N times, default 1; ``count=inf`` = every match), ``after``
(alias ``skip``: let the first N matching occurrences pass),
``after_step`` (coll only: fire once the chosen schedule reaches IR
step N — tuned probes ``coll_step`` per step of the dispatched
program, so ``rank_kill@coll:after_step=k`` kills a rank
mid-collective at step granularity), ``prob``
(fire with this probability, drawn from the plan's seeded RNG),
``ms`` (delay milliseconds), ``link`` (DCN link index), ``algo``
(collective algorithm tier), ``key`` (modex key substring), ``exit``
(process exit code for rank_kill).

Determinism: the only randomness is the plan's ``random.Random(seed)``
(used by ``prob`` draws), and every fired fault is appended to an
ordered log — ``plan.schedule()`` renders it and ``plan.digest()``
hashes it, so the same seed and workload produce a byte-identical
fault schedule across runs (the drill-reproducibility contract).

Usage::

    from ompi_tpu.ft import inject
    plan = inject.arm("drop@btl_dcn:peer=1,count=2", seed=7)
    ...                      # run the workload; faults fire
    print(plan.schedule())   # what fired, in order
    inject.disarm()

Arm **before** ``init()``/first communication: like the sanitizer, the
PML/coll wrappers interpose at component-selection time and cached
selections are not rewrapped retroactively. Subprocess drills arm via
the ``faultline_base_plan`` / ``faultline_base_seed`` cvars
(``OMPITPU_MCA_faultline_base_plan=...`` in the environment) and call
``inject.arm()`` with no arguments.
"""

from __future__ import annotations

import hashlib
import math
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from ..core import config
from ..core.counters import SPC
from ..core.errors import OmpiTpuError
from ..core.logging import get_logger

logger = get_logger("ft.inject")

LAYERS = ("btl_sm", "btl_dcn", "pml", "modex", "coll", "daemon")
ACTIONS = ("drop", "delay", "duplicate", "corrupt", "disconnect",
           "rank_kill", "wedge", "flood", "hog")

#: Which actions make sense at which boundary (parse-time validation —
#: a spec that could never fire is a plan bug, not a quiet no-op).
#: wedge is valid everywhere: any seam can stall indefinitely.
#: flood/hog are the adversarial-tenant primitives: they only make
#: sense at the daemon admission boundary, where the daemon amplifies
#: a fired spec into `rate=` synthetic submits or charges `bytes=` of
#: queue memory against the probing tenant's budget.
_VALID = {
    "btl_sm": {"drop", "delay", "corrupt", "wedge"},
    "btl_dcn": {"drop", "delay", "duplicate", "corrupt", "disconnect",
                "wedge"},
    "pml": {"drop", "delay", "duplicate", "corrupt", "wedge"},
    "modex": {"drop", "delay", "wedge", "rank_kill"},
    "coll": {"delay", "disconnect", "rank_kill", "wedge"},
    "daemon": {"delay", "wedge", "flood", "hog"},
}

_plan_var = config.register(
    "faultline", "base", "plan", type=str, default="",
    description="Fault plan grammar armed by inject.arm() when no "
    "explicit plan is given (';'-separated action@layer:k=v specs)",
)
_seed_var = config.register(
    "faultline", "base", "seed", type=int, default=0,
    description="Fault-plan RNG seed (same seed => byte-identical "
    "fault schedule)",
)


class FaultInjected(OmpiTpuError):
    """An injected fault surfaced as a failure (rank_kill / tier
    disconnect). Carries the spec that fired."""

    errclass = "ERR_INTERN"


class PlanError(OmpiTpuError):
    errclass = "ERR_ARG"


@dataclass
class FaultSpec:
    """One scoped fault: what to do, where, and how often."""

    action: str
    layer: str
    op: Optional[str] = None
    peer: Optional[int] = None
    tag_lo: Optional[int] = None
    tag_hi: Optional[int] = None
    count: float = 1          # max firings (inf = unlimited)
    skip: int = 0             # matching occurrences to let pass first
    after_step: Optional[int] = None  # coll schedule step to fire at
    prob: Optional[float] = None
    ms: float = 0.0           # delay milliseconds
    link: int = 0             # DCN link index for disconnect
    algo: Optional[str] = None
    key: Optional[str] = None  # modex key / daemon tenant substring
    exit_code: Optional[int] = None
    cid: Optional[int] = None  # communicator scope (coll/daemon probes)
    rate: int = 0             # flood: synthetic submits per firing
    nbytes: int = 0           # hog: queue-memory bytes per firing
    # runtime state
    seen: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def __post_init__(self) -> None:
        if self.action not in ACTIONS:
            raise PlanError(f"unknown action {self.action!r}; "
                            f"expected one of {ACTIONS}")
        if self.layer not in LAYERS:
            raise PlanError(f"unknown layer {self.layer!r}; "
                            f"expected one of {LAYERS}")
        if self.action not in _VALID[self.layer]:
            raise PlanError(
                f"{self.action}@{self.layer} is not a meaningful "
                f"fault; {self.layer} supports "
                f"{sorted(_VALID[self.layer])}"
            )
        if self.after_step is not None and self.layer != "coll":
            raise PlanError(
                f"after_step only scopes coll-layer specs "
                f"(got {self.action}@{self.layer})"
            )

    def scope_matches(self, layer: str, op: Optional[str],
                      peer: Optional[int], tag: Optional[int],
                      algo: Optional[str], key: Optional[str],
                      step: Optional[int] = None,
                      cid: Optional[int] = None) -> bool:
        if layer != self.layer:
            return False
        if self.op is not None and op != self.op:
            return False
        # cid= pins a spec to one communicator scope — how a drill
        # targets one tenant's session comm on a shared daemon without
        # perturbing its neighbours. Non-strict: an unscoped spec
        # still matches probes that carry a cid.
        if self.cid is not None and cid != self.cid:
            return False
        # For rank_kill (and all coll-layer specs) `peer=` is not a
        # scope filter: those probes carry no peer; the key instead
        # names the victim world rank (driver mode hosts every rank
        # in-process, so rank_kill@modex:peer=N kills rank N when the
        # modex op fires, it does not filter on a wire peer).
        if self.peer is not None and self.layer != "coll" \
                and self.action != "rank_kill" \
                and peer != self.peer:
            return False
        if self.tag_lo is not None:
            if tag is None or not self.tag_lo <= tag <= self.tag_hi:
                return False
        # algo scoping is strict both ways so the two coll probes stay
        # disjoint: the dispatch probe (algo=None, on_coll) never
        # advances tier-scoped specs and the tier probe (kernel_fault)
        # never advances dispatch-scoped ones — occurrence counts
        # (`after=`) would otherwise double-step per collective.
        if (self.algo is None) != (algo is None) or algo != self.algo:
            return False
        if self.key is not None and (key is None or self.key not in key):
            return False
        # step scoping is strict both ways like algo: the per-step
        # probe (coll_step) only advances after_step specs and the
        # dispatch probe (on_coll) never does — occurrence counts
        # would otherwise step once per IR step, not per collective.
        if (self.after_step is None) != (step is None):
            return False
        if self.after_step is not None and step != self.after_step:
            return False
        return True

    def describe(self) -> str:
        parts = [f"{self.action}@{self.layer}"]
        kv = []
        for name, val in (("op", self.op), ("peer", self.peer),
                          ("algo", self.algo), ("key", self.key),
                          ("cid", self.cid)):
            if val is not None:
                kv.append(f"{name}={val}")
        if self.tag_lo is not None:
            kv.append(f"tag={self.tag_lo}-{self.tag_hi}")
        if self.after_step is not None:
            kv.append(f"after_step={self.after_step}")
        if self.rate:
            kv.append(f"rate={self.rate}")
        if self.nbytes:
            kv.append(f"bytes={self.nbytes}")
        if kv:
            parts.append(":" + ",".join(kv))
        return "".join(parts)


def _parse_spec(text: str) -> FaultSpec:
    head, _, tail = text.strip().partition(":")
    action, at, layer = head.partition("@")
    if not at or not action or not layer:
        raise PlanError(f"spec {text!r}: expected action@layer[:k=v,..]")
    spec = FaultSpec(action=action.strip(), layer=layer.strip())
    if not tail:
        return spec
    for kv in tail.split(","):
        k, eq, v = kv.partition("=")
        k, v = k.strip(), v.strip()
        if not eq or not k or not v:
            raise PlanError(f"spec {text!r}: malformed key=value {kv!r}")
        if k == "op":
            spec.op = v
        elif k == "peer":
            spec.peer = int(v)
        elif k == "tag":
            lo, dash, hi = v.partition("-")
            spec.tag_lo = int(lo)
            spec.tag_hi = int(hi) if dash else spec.tag_lo
            if spec.tag_hi < spec.tag_lo:
                raise PlanError(f"spec {text!r}: empty tag range {v!r}")
        elif k == "count":
            spec.count = math.inf if v == "inf" else int(v)
        elif k in ("after", "skip"):
            spec.skip = int(v)
        elif k == "after_step":
            spec.after_step = int(v)
        elif k == "prob":
            spec.prob = float(v)
            if not 0.0 <= spec.prob <= 1.0:
                raise PlanError(f"spec {text!r}: prob out of [0,1]")
        elif k == "ms":
            spec.ms = float(v)
        elif k == "link":
            spec.link = int(v)
        elif k == "algo":
            spec.algo = v
        elif k == "key":
            spec.key = v
        elif k == "cid":
            spec.cid = int(v)
        elif k == "rate":
            spec.rate = int(v)
        elif k == "bytes":
            spec.nbytes = int(v)
        elif k == "exit":
            spec.exit_code = int(v)
        else:
            raise PlanError(f"spec {text!r}: unknown key {k!r}")
    if spec.after_step is not None and spec.layer != "coll":
        raise PlanError(
            f"spec {text!r}: after_step only scopes coll-layer specs"
        )
    if spec.action == "flood" and spec.rate <= 0:
        raise PlanError(f"spec {text!r}: flood needs rate=N>0")
    if spec.action == "hog" and spec.nbytes <= 0:
        raise PlanError(f"spec {text!r}: hog needs bytes=N>0")
    return spec


class FaultPlan:
    """A seeded, ordered set of fault specs plus the append-only log
    of every fault that fired. Thread-safe: the wrappers consult it
    from transport and progress threads."""

    def __init__(self, specs, *, seed: int = 0) -> None:
        if isinstance(specs, str):
            specs = [s for s in specs.split(";") if s.strip()]
        self.specs: list[FaultSpec] = [
            s if isinstance(s, FaultSpec) else _parse_spec(s)
            for s in specs
        ]
        self.seed = seed
        self._rng = random.Random(seed)
        self._mu = threading.Lock()
        self.fired: list[str] = []

    def decide(self, layer: str, op: Optional[str] = None, *,
               peer: Optional[int] = None, tag: Optional[int] = None,
               algo: Optional[str] = None, key: Optional[str] = None,
               step: Optional[int] = None,
               cid: Optional[int] = None) -> list[FaultSpec]:
        """All specs firing for this occurrence, in plan order. Each
        scope match advances the spec's occurrence counter (and the
        seeded RNG when ``prob`` is set) whether or not it fires, so
        the schedule is a pure function of (plan, workload)."""
        out: list[FaultSpec] = []
        with self._mu:
            for spec in self.specs:
                if not spec.scope_matches(layer, op, peer, tag, algo,
                                          key, step, cid):
                    continue
                spec.seen += 1
                if spec.seen <= spec.skip or spec.fired >= spec.count:
                    continue
                if spec.prob is not None \
                        and self._rng.random() >= spec.prob:
                    continue
                spec.fired += 1
                self.fired.append(
                    f"{len(self.fired)} {spec.describe()} "
                    f"op={op} peer={peer} tag={tag} occ={spec.seen}"
                )
                SPC.record("faultline_fired")
                # commtrace: every injected fault is tagged on the
                # timeline so drill traces distinguish injected from
                # organic failures (injected=True is the contract the
                # drill suite asserts).
                from ..trace import span as tspan

                tspan.instant(f"fault.{spec.action}", cat="fault",
                              injected=True, layer=layer, op=op,
                              peer=peer, tag=tag, algo=algo, key=key,
                              step=step, occ=spec.seen)
                logger.warning("faultline: %s fired (op=%s peer=%s "
                               "tag=%s occ=%d)", spec.describe(), op,
                               peer, tag, spec.seen)
                out.append(spec)
        return out

    def schedule(self) -> str:
        """The fired-fault log, one line per fault, in firing order."""
        with self._mu:
            return "\n".join(self.fired)

    def digest(self) -> str:
        """sha256 of the schedule — byte-identical for the same seed
        and workload (the drill-reproducibility check)."""
        return hashlib.sha256(self.schedule().encode()).hexdigest()


# -- module-level arming ------------------------------------------------

_PLAN: Optional[FaultPlan] = None


def armed() -> bool:
    return _PLAN is not None


def plan() -> Optional[FaultPlan]:
    return _PLAN


def arm(specs=None, *, seed: Optional[int] = None) -> FaultPlan:
    """Install a fault plan process-wide and drop cached component
    selections so the wrappers interpose on next use. With no
    arguments, reads the ``faultline_base_plan`` / ``_seed`` cvars
    (the env path subprocess drills use)."""
    global _PLAN
    if specs is None:
        specs = _plan_var.value or ""
    if seed is None:
        seed = _seed_var.value
    p = specs if isinstance(specs, FaultPlan) else \
        FaultPlan(specs, seed=seed)
    _WEDGE_EV.clear()  # wedges in this plan will park
    _PLAN = p
    _reset_selections()
    logger.info("faultline armed: %d spec(s), seed=%d", len(p.specs),
                p.seed)
    return p


def disarm() -> Optional[FaultPlan]:
    """Remove the plan; returns it (for schedule/digest inspection)."""
    global _PLAN
    p = _PLAN
    _PLAN = None
    _WEDGE_EV.set()  # release every wedged thread
    if p is not None:
        _reset_selections()
    return p


def _reset_selections() -> None:
    from ..pml import framework as pml_fw

    pml_fw.reset_selection()


# -- fault application helpers -----------------------------------------

def _apply_delay(spec: FaultSpec) -> None:
    if spec.ms > 0:
        time.sleep(spec.ms / 1000.0)


# Wedged operations park on this event, not a sleep: ``disarm()`` sets
# it, releasing every wedged thread at once — how a drill (or the
# bench) un-wedges the world after the sentinel has already abandoned
# the stalled workers. arm() re-arms it for the next plan.
_WEDGE_EV = threading.Event()


def _apply_wedge(spec: FaultSpec) -> None:
    """Stall the calling thread: for ``ms=`` when given, else until
    the plan is disarmed (the indefinite-hang injection the health
    sentinel's deadlines exist to catch). The stall is deliberately
    un-failing — a wedged tier hangs, it does not raise."""
    timeout = spec.ms / 1000.0 if spec.ms > 0 else None
    _WEDGE_EV.wait(timeout)


def _corrupt_bytes(data) -> bytes:
    buf = bytearray(bytes(data))
    if buf:
        buf[0] ^= 0xFF
    return bytes(buf)


def _corrupt_value(value):
    """Perturb an array/pytree payload detectably (leaf + 1)."""
    import jax

    try:
        return jax.tree.map(lambda l: l + 1, value)
    except TypeError:
        return value


def _rank_kill(spec: FaultSpec, where: str) -> None:
    if spec.exit_code is not None:
        logger.warning("faultline: rank_kill exiting process (%s, "
                       "code %d)", where, spec.exit_code)
        os._exit(spec.exit_code)
    from . import events

    # peer= names the rank that "dies" (driver mode hosts every rank
    # in one process, so the kill is modeled as a failure event for
    # that world rank — elastic tracking then excludes it).
    events.raise_event(events.EventClass.PROC_FAILED,
                       injected=True, where=where,
                       world_rank=spec.peer)
    raise FaultInjected(f"rank_kill injected at {where}")


# -- PML boundary (interposed in pml/framework.select_for_comm) --------

class FaultPml:
    """Pass-through PML applying pml-layer faults to send/isend (drop /
    delay / duplicate / corrupt) and delay to recv/irecv. Unknown
    attributes delegate to the host (sanitizer wrapper idiom)."""

    NAME = "faultline"

    def __init__(self, host) -> None:
        self.host = host

    def __getattr__(self, name):
        return getattr(self.host, name)

    def _sendish(self, fn, comm, value, dest, tag, source):
        p = _PLAN
        if p is not None:
            for spec in p.decide("pml", "send", peer=dest, tag=tag):
                if spec.action == "delay":
                    _apply_delay(spec)
                elif spec.action == "wedge":
                    _apply_wedge(spec)
                elif spec.action == "corrupt":
                    value = _corrupt_value(value)
                elif spec.action == "duplicate":
                    fn(comm, value, dest, tag, source=source)
                elif spec.action == "drop":
                    # message lost on the wire: sender-side success
                    from ..core.request import CompletedRequest

                    return CompletedRequest(value)
        return fn(comm, value, dest, tag, source=source)

    def send(self, comm, value, dest, tag, source=None):
        req = self._sendish(self.host.send, comm, value, dest, tag,
                            source)
        return req

    def isend(self, comm, value, dest, tag, source=None):
        return self._sendish(self.host.isend, comm, value, dest, tag,
                             source)

    def _recvish(self, comm, source, tag) -> None:
        p = _PLAN
        if p is not None:
            for spec in p.decide("pml", "recv", peer=source, tag=tag):
                if spec.action == "delay":
                    _apply_delay(spec)
                elif spec.action == "wedge":
                    _apply_wedge(spec)

    def recv(self, comm, source, tag, *, dest):
        self._recvish(comm, source, tag)
        return self.host.recv(comm, source, tag, dest=dest)

    def irecv(self, comm, source, tag, *, dest):
        self._recvish(comm, source, tag)
        return self.host.irecv(comm, source, tag, dest=dest)


def maybe_wrap_pml(selected):
    """pml/framework hook: interpose when a plan is armed (inside the
    sanitizer wrapper, so the sanitizer still sees the traffic as the
    application issued it)."""
    if _PLAN is None or selected is None:
        return selected
    return FaultPml(selected)


# -- BTL boundaries ----------------------------------------------------

# Fake send ids handed out for dropped DCN sends: far above any native
# msgid (those start at 1 and count up) so completion polling can't
# collide.
_FAKE_MSGID = 1 << 62
_fake_mu = threading.Lock()


def _next_fake_msgid() -> int:
    global _FAKE_MSGID
    with _fake_mu:
        _FAKE_MSGID += 1
        return _FAKE_MSGID


class FaultDcnEndpoint:
    """Pass-through DcnEndpoint applying btl_dcn faults on the send
    path (drop / delay / duplicate / corrupt / disconnect). Dropped
    sends complete locally — the bytes vanish on the wire, exactly the
    loss mode TCP gives a dead link."""

    NAME = "faultline"

    def __init__(self, host) -> None:
        self.host = host

    def __getattr__(self, name):
        return getattr(self.host, name)

    def send_bytes(self, peer: int, tag: int, data) -> int:
        p = _PLAN
        if p is not None:
            for spec in p.decide("btl_dcn", "send", peer=peer, tag=tag):
                if spec.action == "delay":
                    _apply_delay(spec)
                elif spec.action == "wedge":
                    _apply_wedge(spec)
                elif spec.action == "corrupt":
                    data = _corrupt_bytes(data)
                elif spec.action == "duplicate":
                    self.host.send_bytes(peer, tag, data)
                elif spec.action == "disconnect":
                    self.host.kill_link(peer, spec.link)
                elif spec.action == "drop":
                    msgid = _next_fake_msgid()
                    with self.host._send_mu:
                        self.host._pending_send_done.append(msgid)
                    return msgid
        return self.host.send_bytes(peer, tag, data)

    def connect(self, ip: str, port: int, **kw) -> int:
        p = _PLAN
        if p is not None:
            for spec in p.decide("btl_dcn", "connect", peer=None,
                                 tag=None):
                if spec.action == "delay":
                    _apply_delay(spec)
                elif spec.action == "wedge":
                    _apply_wedge(spec)
        return self.host.connect(ip, port, **kw)

    def close(self) -> None:
        self.host.close()


def maybe_wrap_dcn(endpoint):
    """btl/dcn hook: wrap an endpoint when a plan is armed (DcnBtl
    installs this at endpoint creation; drills wrap standalone
    endpoints the same way)."""
    if _PLAN is None or endpoint is None:
        return endpoint
    if isinstance(endpoint, FaultDcnEndpoint):
        return endpoint
    return FaultDcnEndpoint(endpoint)


class FaultSmBtl:
    """Pass-through sm BTL: drop (raises CommError — a torn shared
    segment), delay, corrupt on transfer()."""

    def __init__(self, host) -> None:
        self.host = host
        self.NAME = host.NAME
        self.PRIORITY = host.PRIORITY

    def __getattr__(self, name):
        return getattr(self.host, name)

    def transfer(self, value, src_proc, dst_proc):
        p = _PLAN
        if p is not None:
            dst = getattr(dst_proc, "process_index", None)
            for spec in p.decide("btl_sm", "transfer", peer=dst,
                                 tag=None):
                if spec.action == "delay":
                    _apply_delay(spec)
                elif spec.action == "wedge":
                    _apply_wedge(spec)
                elif spec.action == "corrupt":
                    value = _corrupt_value(value)
                elif spec.action == "drop":
                    from ..core.errors import CommError

                    raise CommError(
                        "faultline: sm transfer dropped (injected)"
                    )
        return self.host.transfer(value, src_proc, dst_proc)


def maybe_wrap_sm(component):
    if _PLAN is None or component is None:
        return component
    if isinstance(component, FaultSmBtl):
        return component
    return FaultSmBtl(component)


def on_fp_send(endpoint, peer: int, tag: Optional[int]) -> None:
    """btl/sm fastpath descriptor-post hook. ``corrupt@btl_sm:
    op=fp_send`` arms the endpoint's corrupt-next latch: the native
    sender posts the next descriptor with its CRC XORed, and the drill
    proves the receiver's validate path rejects and DROPS it (counted
    in sm_fp_crc_drops) instead of delivering garbage or wedging the
    ring. drop raises before the post (a torn lane); delay models a
    descheduled producer."""
    p = _PLAN
    if p is None:
        return
    for spec in p.decide("btl_sm", "fp_send", peer=peer, tag=tag):
        if spec.action == "corrupt":
            endpoint.fp_corrupt_next()
            SPC.record("faultline_fp_corrupts")
        elif spec.action == "delay":
            _apply_delay(spec)
        elif spec.action == "wedge":
            _apply_wedge(spec)
        elif spec.action == "drop":
            from ..core.errors import CommError

            raise CommError(
                "faultline: fp descriptor post dropped (injected)"
            )


# -- modex/KV boundary (hooked inside runtime/modex.py) ----------------

def on_modex(op: str, key: str) -> None:
    """modex.get/put entry hook: drop raises ModexError (the KV entry
    is unreachable), delay sleeps (models a slow coordinator)."""
    p = _PLAN
    if p is None:
        return
    for spec in p.decide("modex", op, key=key):
        if spec.action == "delay":
            _apply_delay(spec)
        elif spec.action == "wedge":
            _apply_wedge(spec)
        elif spec.action == "rank_kill":
            # a controller dying inside the business-card exchange —
            # the worst-moment variant drills arm for recover()
            _rank_kill(spec, f"modex {op} {key}")
        elif spec.action == "drop":
            from ..runtime.modex import ModexError

            raise ModexError(
                f"faultline: modex {op}({key!r}) dropped (injected)"
            )


# -- collective-dispatch boundary (coll/framework.select_for_comm) -----

def _wrap_coll_fn(opname: str, comp, fn):
    def faulted(comm, *args, **kw):
        on_coll(comm, opname)
        return fn(comm, *args, **kw)

    return comp, faulted


def maybe_wrap_coll(table: dict):
    """coll/framework hook: wrap every per-op entry of a comm's coll
    vtable when a plan is armed."""
    if _PLAN is None:
        return table
    return {
        opname: _wrap_coll_fn(opname, comp, fn)
        for opname, (comp, fn) in table.items()
    }


def on_coll(comm, opname: str) -> None:
    """Collective-dispatch entry: delay and rank_kill fire here (the
    algorithm-tier `disconnect` fires deeper, at tuned's dispatch,
    where the chosen tier is known — see kernel_fault)."""
    p = _PLAN
    if p is None:
        return
    for spec in p.decide("coll", opname, cid=comm.cid):
        if spec.action == "delay":
            _apply_delay(spec)
        elif spec.action == "wedge":
            _apply_wedge(spec)
        elif spec.action == "rank_kill":
            _rank_kill(spec, f"{opname} on {comm.name}")


def coll_step(comm, opname: str, step: int) -> None:
    """Per-IR-step probe: tuned walks the chosen schedule's steps
    (when a plan is armed — zero cost otherwise) and probes each, so
    ``rank_kill@coll:after_step=k`` fires mid-collective at step
    granularity. Driver-model honesty: the fused XLA program cannot be
    interrupted between device steps, so the kill lands between the
    dispatch-time step probes — the program for the remaining steps is
    never launched, which is exactly what a controller death after
    step k means for every rank it hosts."""
    p = _PLAN
    if p is None:
        return
    for spec in p.decide("coll", opname, step=step, cid=comm.cid):
        if spec.action == "rank_kill":
            _rank_kill(spec,
                       f"{opname} step {step} on {comm.name}")
        elif spec.action == "delay":
            _apply_delay(spec)
        elif spec.action == "wedge":
            _apply_wedge(spec)


def kernel_fault(opname: str, algo: str,
                 cid: Optional[int] = None) -> None:
    """tuned-dispatch hook: a `disconnect@coll:algo=X` spec makes tier
    X raise FaultInjected — the kernel/transport fault the circuit
    breaker (coll/breaker.py) degrades on. ``cid`` scopes the probe
    to the dispatching communicator so `cid=` specs can wedge one
    tenant's tier without touching a neighbour's."""
    p = _PLAN
    if p is None:
        return
    for spec in p.decide("coll", opname, algo=algo, cid=cid):
        if spec.action == "disconnect":
            raise FaultInjected(
                f"injected {opname} tier fault in {algo!r}"
            )
        if spec.action == "delay":
            _apply_delay(spec)
        elif spec.action == "wedge":
            # the tier STALLS (no raise): only a sentinel deadline —
            # or disarm() — gets the collective off this tier
            _apply_wedge(spec)


# -- daemon boundary (interposed in daemon/service request handlers) ----

def on_daemon(op: str, *, tenant: Optional[str] = None,
              cid: Optional[int] = None) -> list[FaultSpec]:
    """Daemon-boundary probe (``op`` is the request kind: attach /
    submit / dispatch / detach). ``key=`` scopes a spec to a tenant
    substring, ``cid=`` to one session comm. delay/wedge are applied
    in place; flood/hog specs are *returned* — the daemon amplifies a
    flood into ``rate=`` synthetic admission attempts and charges a
    hog's ``bytes=`` against the probing tenant's queue budget, so
    the adversarial pressure goes through the same admission path
    (counted, logged, never silent) as organic traffic."""
    p = _PLAN
    if p is None:
        return []
    out: list[FaultSpec] = []
    for spec in p.decide("daemon", op, key=tenant, cid=cid):
        if spec.action == "delay":
            _apply_delay(spec)
        elif spec.action == "wedge":
            _apply_wedge(spec)
        else:
            out.append(spec)
    return out

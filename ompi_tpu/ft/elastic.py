"""Elastic recovery: shrink, agreement, respawn.

The reference snapshot predates ULFM (SURVEY §5.3: "No ULFM
(comm revoke/shrink) in this snapshot"); its recovery story is
checkpoint/restart only. The TPU driver model makes the ULFM trio
cheap, so this module provides it — going past reference parity:

- **shrink(comm)**: a new communicator over the surviving ranks
  (MPI_Comm_shrink). Failures come from the ft.events registry
  (`ft/events.py` probes or injection).
- **agree(comm, values)**: fault-tolerant agreement (MPIX_Comm_agree's
  role): the controller sees every surviving rank's flag, so agreement
  is a reduction over survivors.
- **respawn(comm, manager)**: shrink + restore the latest checkpoint
  resharded onto the surviving devices — the "re-initialize mesh on
  respawn" loop (SURVEY §5.3) in one call.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ..core.counters import SPC
from ..core.errors import CommError
from ..core.logging import get_logger
from ..group import Group
from . import events

logger = get_logger("ft.elastic")

_failed: set[int] = set()  # world ranks reported dead
_lock = threading.Lock()
_handler_id: Optional[int] = None
_abandoned: list[Any] = []  # detached runtime handles kept alive (no dtors)


def recoverable() -> None:
    """Arm multi-controller survival BEFORE jax.distributed.initialize.

    The reference's failure semantics are that a peer death routes to
    the application's errhandler and the runtime never kills survivors
    (reference: ompi/runtime/ompi_mpi_init.c:524 — PMIx event
    registration feeds errhandlers, not exit()). JAX's coordination
    service defaults to the opposite: a missed-heartbeat on ANY task
    fatally terminates every other task ("Terminating process because
    the JAX distributed service detected fatal errors"). This flips the
    client into recoverable mode (`jax_enable_recoverability`) so that
    a dead peer is OUR event to handle — watch_dcn/shrink/respawn run
    to completion even after the coordination-service heartbeat fuse
    has fired. Must be called before jax.distributed.initialize; it is
    a no-op (with a warning) afterwards.
    """
    import jax

    from jax._src import distributed as jdist

    if jdist.global_state.client is not None:
        logger.warning(
            "recoverable() called after jax.distributed.initialize; "
            "the running client keeps its fatal failure handler"
        )
        return
    try:
        jax.config.update("jax_enable_recoverability", True)
    except AttributeError:
        # older jax: no recoverability knob. Degrade instead of dying
        # on import — recovery still works as long as it completes
        # inside the coordination-service heartbeat window.
        logger.warning(
            "jax %s lacks jax_enable_recoverability; survivors race "
            "the coordination heartbeat fuse", jax.__version__
        )
        return
    SPC.record("ft_recoverable_arms")


def detach() -> None:
    """Quiesce + leave the current jax.distributed job (idempotent).

    Called by a survivor once peer failure is confirmed: the doomed
    job's coordination client/service must not be re-entered by any
    later code path (barriers, preemption sync, atexit shutdown) while
    recovery re-wires the world over the live fabric. The handles are
    moved into a module-level abandon list — NOT destroyed — because
    their destructors perform blocking shutdown RPCs against a
    coordinator that is dead or dying. This is the "leave the job"
    step of the recovery protocol (the reference never needs it: its
    RTE continues around failures, ompi_mpi_init.c:524).
    """
    import ctypes

    from jax._src import distributed as jdist

    st = jdist.global_state
    left = False
    for name in ("preemption_sync_manager", "client", "service"):
        handle = getattr(st, name)
        if handle is None:
            continue
        # A module-level list is not enough: interpreter finalization
        # clears module globals, which would still run the handle's
        # destructor (a blocking shutdown RPC against the dead
        # coordinator). Pin the refcount permanently — the handle is
        # leaked on purpose; the process is exiting anyway.
        ctypes.pythonapi.Py_IncRef(ctypes.py_object(handle))
        _abandoned.append(handle)
        setattr(st, name, None)
        left = True
    if left:
        st.coordinator_address = None
        SPC.record("ft_detaches")
        logger.info("detached from jax.distributed job (handles abandoned)")


def _on_failure(ev: events.Event) -> None:
    wr = ev.info.get("world_rank")
    if wr is not None:
        with _lock:
            _failed.add(wr)


def enable() -> None:
    """Start tracking PROC_FAILED events (idempotent)."""
    global _handler_id
    with _lock:
        if _handler_id is None:
            _handler_id = events.register(
                events.EventClass.PROC_FAILED, _on_failure
            )


def clear_failures() -> None:
    """Forget recorded failures after a successful recovery; tracking
    STAYS enabled so the next failure is still caught."""
    with _lock:
        _failed.clear()


def disable() -> None:
    """Stop tracking entirely (test teardown)."""
    global _handler_id
    with _lock:
        if _handler_id is not None:
            events.deregister(_handler_id)
            _handler_id = None


def reset() -> None:
    """Full teardown: forget failures AND stop tracking."""
    clear_failures()
    disable()


def failed_ranks() -> set[int]:
    with _lock:
        return set(_failed)


def revive(ranks) -> list[int]:
    """Forget recorded failures for ``ranks`` (lazarus calls this when
    a warm spare passes admission): a revived rank re-enters agree's
    survivor set and shrink's keep list. Returns the ranks that were
    actually recorded dead, sorted — the deterministic evidence line
    lazarus logs."""
    revived = []
    with _lock:
        for wr in ranks:
            if int(wr) in _failed:
                _failed.discard(int(wr))
                revived.append(int(wr))
    return sorted(revived)


def watch_dcn(peer_world_ranks: dict) -> int:
    """Bridge DCN link-death detection to elastic recovery: when every
    TCP link to a peer endpoint dies, `DcnEndpoint.check_peer` raises a
    DEVICE_ERROR event carrying the dcn peer id (`btl/dcn.py`); this
    handler translates it into PROC_FAILED for each world rank that
    peer's controller owned — the PMIx failure-notification flow
    (reference: ompi_mpi_init.c:524 event registration routing peer
    failures into errhandlers). `peer_world_ranks` maps dcn peer ids
    (active AND passive ids both work) to the world ranks behind them.
    Returns a handler id for events.deregister."""
    enable()

    def on_device_error(ev: events.Event) -> None:
        if ev.info.get("transport") != "dcn":
            return
        ranks = peer_world_ranks.get(ev.info.get("peer"))
        if not ranks:
            return
        for wr in ranks:
            if wr not in failed_ranks():
                events.raise_event(
                    events.EventClass.PROC_FAILED, world_rank=wr,
                    via="dcn_liveness",
                )

    hid = events.register(events.EventClass.DEVICE_ERROR,
                          on_device_error)
    SPC.record("ft_dcn_watches")
    return hid


def shrink(comm, *, dead: Optional[set] = None) -> Any:
    """MPI_Comm_shrink: a new communicator over the ranks of `comm`
    whose world ranks are not known-failed. `dead` lets callers pin
    one failure snapshot across several derived computations."""
    if dead is None:
        dead = failed_ranks()
    survivors = [
        wr for wr in comm.group.world_ranks if wr not in dead
    ]
    if not survivors:
        raise CommError(f"{comm.name}: no surviving ranks")
    if len(survivors) == comm.size \
            and not getattr(comm, "_revoked", False):
        return comm.dup()
    # ULFM: shrink stays valid on a REVOKED communicator (it is the
    # recovery escape hatch), and revocation fans out to every comm
    # containing the dead rank — WORLD included — so the survivor comm
    # is constructed directly rather than through world.create()'s
    # liveness fence.
    from ..communicator import Communicator

    new = Communicator(
        Group(survivors), comm._world_procs,
        name=f"{comm.name}.shrunk", parent_cid=comm.cid,
    )
    SPC.record("ft_shrinks")
    logger.info(
        "shrink %s: %d -> %d ranks (failed: %s)",
        comm.name, comm.size, new.size, sorted(dead),
    )
    return new


def grow(comm, spares) -> Any:
    """The inverse of :func:`shrink`: a new communicator over
    ``comm``'s ranks PLUS ``spares`` (world ranks present in the
    retained world proc table but not in the current group). The
    caller — ``ft/lazarus.grow`` — owns admission (PROBATION walks)
    and the epoch bump; this is only the construction step. Like
    shrink, the grown comm is built directly over the retained
    ``_world_procs`` table rather than through ``world.create``'s
    liveness fence: growth usually happens right after a recovery,
    when WORLD is still revoked."""
    if getattr(comm, "_revoked", False):
        raise CommError(
            f"{comm.name}: cannot grow a revoked communicator — "
            f"recover (shrink) it first"
        )
    current = set(comm.group.world_ranks)
    joiners = sorted(int(s) for s in set(spares) - current)
    if not joiners:
        return comm.dup()
    nworld = len(comm._world_procs)
    bad = [wr for wr in joiners if not 0 <= wr < nworld]
    if bad:
        raise CommError(
            f"{comm.name}: spare ranks {bad} outside the retained "
            f"world proc table (0..{nworld - 1})"
        )
    # the grow fence is the caller's: lazarus bumps new.epoch past
    # comm.epoch and re-checks revocation before traffic flows
    from ..communicator import Communicator

    new = Communicator(
        Group(sorted(current | set(joiners))), comm._world_procs,
        name=f"{comm.name}.grown", parent_cid=comm.cid,
    )
    SPC.record("ft_grows_constructed")
    logger.info(
        "grow %s: %d -> %d ranks (joiners: %s)",
        comm.name, comm.size, new.size, joiners,
    )
    return new


def agree(comm, flags) -> bool:
    """MPIX_Comm_agree's role: logical AND over the SURVIVING ranks'
    flags (failed ranks cannot veto). Delegates to lifeboat's
    two-phase, failure-masking agreement (tree vote + confirm,
    re-rooted around the known-dead set) — this bool wrapper is the
    back-compat surface; new code should call ``lifeboat.agree``
    directly for the int flags."""
    from . import lifeboat

    return bool(lifeboat.agree(
        comm, [1 if bool(f) else 0 for f in flags]
    ))


def respawn(comm, manager, *, like: Any = None) -> tuple[Any, Any, dict]:
    """Recovery loop: shrink to survivors and restore the latest
    snapshot with every rank-major leaf resharded onto the surviving
    devices (failed ranks' blocks dropped). Returns (new_comm, state,
    meta). `like` is the ORIGINAL state template (as saved) and gives
    the restored state its pytree structure; without it the arrays-CRS
    flat {keypath: array} dict is resharded in place. The failure set
    is snapshotted once so a failure arriving mid-recovery cannot
    desynchronize the survivor list from the resharding."""
    import jax

    dead = failed_ranks()
    new_comm = shrink(comm, dead=dead)
    keep = [
        i for i, wr in enumerate(comm.group.world_ranks)
        if wr not in dead
    ]
    # manager.restore raises the RESTART event itself
    state, meta = manager.restore(like=like)

    def reshard(value):
        import numpy as np

        arr = np.asarray(value)
        if arr.ndim >= 1 and arr.shape[0] == comm.size:
            return new_comm.put_rank_major(arr[keep])
        return value

    # works for any pytree: the caller's structure (like=...) or the
    # arrays-CRS flat dict (dicts are pytrees)
    state = jax.tree.map(reshard, state)
    SPC.record("ft_respawns")
    return new_comm, state, meta

"""Auto-tuner: measure the algorithm space, emit a tuned rules file.

TPU-native equivalent of generating coll/tuned's dynamic-rules input
(reference: coll_tuned_dynamic_file.c consumes rules files that HPC
sites produce by sweeping; the fixed rules in
coll_tuned_decision_fixed.c:45-87 are the shipped defaults). This tool
closes the loop on-device: time every registered algorithm per
(operation, message size) on the actual hardware, pick winners, and
write the JSON that `coll_tuned_rules_file` consumes — per-system
tuning without touching code.

    python -m ompi_tpu.tools.tune --out rules.json --max-bytes 1048576
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Callable

import numpy as np


def _time_plan(comm, key: tuple, per_rank: Callable, x, iters: int,
               check_vma: bool = True) -> float:
    import jax

    from ..coll.framework import compile_plan

    plan = compile_plan(comm, key, per_rank, check_vma=check_vma)
    jax.block_until_ready(plan(x))  # warmup/compile
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(plan(x))
        best = min(best, time.perf_counter() - t0)
    return best


def sweep_op(comm, opname: str, algos: dict, min_bytes: int,
             max_bytes: int, iters: int) -> list[dict]:
    """Time each algorithm per size; return winner rules sorted by
    size band (first-match format of coll/tuned's Rules)."""
    from ..coll.tuned import is_pallas_algo
    from ..ops import lookup as op_lookup

    op = op_lookup("sum")
    n = comm.size
    winners: list[tuple[int, str, dict]] = []
    size = min_bytes
    while size <= max_bytes:
        elems = max(1, size // 4)
        if opname in ("alltoall", "reduce_scatter", "scatter"):
            # Per-destination layout (ranks, dests/rows, chunk). The
            # decide_* functions for these ops consult rules with the
            # PER-CHUNK byte count, so the emitted band must be keyed
            # by the chunk size actually measured — not the total —
            # or the rules would select winners measured at n-times-
            # larger messages.
            chunk = max(1, elems // n)
            data = np.ones((n, n, chunk), np.float32)
            band = chunk * 4
        else:
            data = np.ones((n, elems), np.float32)
            band = size
        x = comm.put_rank_major(data)
        times = {}
        for name, fn in algos.items():
            key = ("tune", opname, name, x.shape, str(x.dtype))
            try:
                if opname in ("allreduce", "reduce_scatter", "scan",
                              "exscan"):
                    per_rank = lambda b, f=fn: f(b, "ranks", op)
                elif opname == "reduce":
                    per_rank = lambda b, f=fn: f(b, "ranks", op, root=0)
                elif opname in ("bcast", "gather", "scatter"):
                    per_rank = lambda b, f=fn: f(b, "ranks", root=0)
                else:
                    per_rank = lambda b, f=fn: f(b, "ranks")
                times[name] = _time_plan(
                    comm, key, per_rank, x, iters,
                    check_vma=not is_pallas_algo(name),
                )
            except Exception:
                continue  # algorithm invalid for this shape/rank count
        if times:
            best = min(times, key=times.get)
            winners.append((band, best, times))
        size *= 4
    # collapse consecutive same-winner bands into max_bytes rules
    rules: list[dict] = []
    for size, best, times in winners:
        if rules and rules[-1]["algorithm"] == best:
            rules[-1]["max_bytes"] = size
        else:
            rules.append({"max_bytes": size, "algorithm": best})
    if rules:
        del rules[-1]["max_bytes"]  # last band is open-ended
    return rules


def tune(comm, ops=None, min_bytes: int = 256,
         max_bytes: int = 1 << 20, iters: int = 5) -> dict:
    from ..coll.tuned import (
        ALLGATHER_ALGOS,
        ALLREDUCE_ALGOS,
        ALLTOALL_ALGOS,
        BCAST_ALGOS,
        GATHER_ALGOS,
        REDUCE_ALGOS,
        REDUCE_SCATTER_ALGOS,
        SCAN_ALGOS,
        EXSCAN_ALGOS,
        SCATTER_ALGOS,
        _pallas_algos,
    )

    _pallas_algos()  # pallas-vs-xla selection from measurement
    spaces = {
        "allreduce": {
            k: v for k, v in ALLREDUCE_ALGOS.items()
            if k not in ("gather_reduce", "ring_segmented")
        },
        "allgather": ALLGATHER_ALGOS,
        "alltoall": ALLTOALL_ALGOS,
        "bcast": BCAST_ALGOS,
        "reduce": REDUCE_ALGOS,
        "reduce_scatter": REDUCE_SCATTER_ALGOS,
        "gather": GATHER_ALGOS,
        "scatter": SCATTER_ALGOS,
        "scan": SCAN_ALGOS,
        "exscan": EXSCAN_ALGOS,
    }
    ops = ops or list(spaces)
    out = {}
    for opname in ops:
        out[opname] = sweep_op(
            comm, opname, spaces[opname], min_bytes, max_bytes, iters
        )
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ompi_tpu.tools.tune")
    ap.add_argument("--out", required=True)
    ap.add_argument("--ops", default="allreduce,allgather,alltoall,bcast,"
                                     "reduce,reduce_scatter,gather,"
                                     "scatter,scan,exscan")
    ap.add_argument("--min-bytes", type=int, default=256)
    ap.add_argument("--max-bytes", type=int, default=1 << 20)
    ap.add_argument("--iters", type=int, default=5)
    args = ap.parse_args(argv)

    import ompi_tpu

    comm = ompi_tpu.init()
    rules = tune(
        comm, [o.strip() for o in args.ops.split(",")],
        args.min_bytes, args.max_bytes, args.iters,
    )
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(rules, f, indent=2)
    print(f"wrote {args.out}; activate with "
          f"OMPITPU_MCA_coll_tuned_rules_file={args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""commlint CLI — static communication-correctness analysis.

Usage:
    python -m ompi_tpu.tools.lint <path> [<path> ...]
    python -m ompi_tpu.tools.lint ompi_tpu --baseline \\
        ompi_tpu/analysis/selfcheck_baseline.json
    python -m ompi_tpu.tools.lint ompi_tpu --write-baseline
    python -m ompi_tpu.tools.lint --changed
    python -m ompi_tpu.tools.lint --rules

``--changed`` scopes the run to .py files the git worktree touches
(diff vs HEAD plus untracked) — the fast pre-commit/CI path.  Note the
whole-program rules see only the changed files in this mode; the tree
run remains the authoritative self-check.

Exit codes: 0 clean (or within baseline), 1 findings at error severity /
baseline regressions, 2 the run itself failed (unreadable files,
crashing rule).

The baseline is a ratchet (analysis/report.Baseline): per-(rule, file)
finding counts, failures only on increases. ``--write-baseline``
regenerates it after debt is paid down; review the diff — counts must
only go down.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from ..analysis.lint import Linter
from ..analysis.report import Baseline, Severity

DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "analysis", "selfcheck_baseline.json",
)


def changed_py_files(cwd: str | None = None) -> list[str]:
    """Worktree-changed .py files: ``git diff --name-only HEAD`` plus
    untracked, repo-root-relative and deduplicated.  Raises
    RuntimeError outside a git checkout."""
    def run(*args: str) -> list[str]:
        proc = subprocess.run(
            ["git", *args], cwd=cwd, capture_output=True, text=True,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"git {' '.join(args)} failed: "
                f"{proc.stderr.strip() or proc.returncode}"
            )
        return [ln for ln in proc.stdout.splitlines() if ln.strip()]

    root = run("rev-parse", "--show-toplevel")[0]
    names = run("diff", "--name-only", "HEAD") \
        + run("ls-files", "--others", "--exclude-standard")
    out, seen = [], set()
    for name in names:
        if not name.endswith(".py") or name in seen:
            continue
        seen.add(name)
        path = os.path.join(root, name)
        if os.path.exists(path):   # deleted files have nothing to lint
            out.append(path)
    return sorted(out)


def _list_rules() -> str:
    from ..analysis.rules import COMMLINT, ensure_rules

    ensure_rules()
    lines = ["commlint rules:"]
    for comp in COMMLINT.select_all():
        lines.append(
            f"  {comp.NAME:<14} prio={comp.priority:<4} "
            f"{comp.DESCRIPTION}"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ompi_tpu.tools.lint",
        description="static communication-correctness linter",
    )
    ap.add_argument("paths", nargs="*", help="files or directories")
    ap.add_argument("--select", default=None,
                    help="rule filter, e.g. 'reqlife,parttags' or "
                         "'^broadexcept' (the commlint_select cvar)")
    ap.add_argument("--base", default=None,
                    help="root findings are keyed relative to "
                         "(default: the common parent of PATHS)")
    ap.add_argument("--baseline", default=None,
                    help="ratchet file to enforce (counts may not grow)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate the ratchet from this run "
                         "(default target: the self-check baseline)")
    ap.add_argument("--changed", action="store_true",
                    help="lint only .py files changed in the git "
                         "worktree (diff vs HEAD + untracked)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings")
    ap.add_argument("--rules", action="store_true",
                    help="list registered rules and exit")
    args = ap.parse_args(argv)

    if args.rules:
        print(_list_rules())
        return 0
    if args.changed:
        if args.paths:
            ap.error("--changed takes no explicit paths")
        try:
            args.paths = changed_py_files()
        except RuntimeError as exc:
            print(f"commlint: --changed: {exc}", file=sys.stderr)
            return 2
        if not args.paths:
            print("commlint: no changed .py files")
            return 0
    if not args.paths:
        ap.error("no paths given (or use --rules / --changed)")

    base = args.base
    if base is None:
        dirs = [p if os.path.isdir(p) else os.path.dirname(p) or "."
                for p in args.paths]
        base = os.path.commonpath([os.path.abspath(d) for d in dirs])
    linter = Linter(select=args.select, base=base)
    report = linter.lint_paths(args.paths)

    if args.as_json:
        payload = report.to_dict()
        payload["files_checked"] = linter.files_checked
        payload["elapsed_ms"] = round(linter.elapsed_ms, 3)
        payload["errors"] = linter.errors
        print(json.dumps(payload, indent=2))
    else:
        print(report.render())
        print(
            f"({linter.files_checked} file(s), "
            f"{len(linter.rules)} rule(s), "
            f"{linter.elapsed_ms:.0f} ms)"
        )
    for err in linter.errors:
        print(f"commlint: run error: {err}", file=sys.stderr)

    if args.write_baseline:
        target = args.baseline or DEFAULT_BASELINE
        Baseline.from_report(report).save(target)
        print(f"commlint: baseline written to {target}")
        return 2 if linter.errors else 0

    if args.baseline:
        baseline = Baseline.load(args.baseline)
        regressions = baseline.regressions(report)
        for line in regressions:
            print(f"commlint: regression: {line}", file=sys.stderr)
        improvements = baseline.improvements(report)
        if improvements:
            print(
                "commlint: %d bucket(s) improved — tighten the "
                "baseline with --write-baseline" % len(improvements)
            )
        if linter.errors:
            return 2
        return 1 if regressions else 0

    if linter.errors:
        return 2
    if report.max_severity() >= Severity.ERROR:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

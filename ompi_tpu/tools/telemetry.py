"""telemetry — scrape, tail, and diff telescope snapshots.

Offline/operator counterpart of the live telemetry plane
(``ompi_tpu/telemetry``):

- ``scrape``: GET a running process's localhost exporter (``/metrics``
  Prometheus text, ``/json`` snapshot, ``/fleet`` merged view) and
  print or save it.
- ``tail``: poll the ``/json`` endpoint and print the counters that
  changed between polls — ``watch`` for pvars.
- ``diff``: compare two saved JSON snapshots (scalar counter deltas,
  histogram count/percentile drift, health-state changes).
- ``dump``: render THIS process's registries to a file (mostly for
  tests and one-shot captures; live processes use the endpoint).

Usage::

    python -m ompi_tpu.tools.telemetry scrape --port 9464
    python -m ompi_tpu.tools.telemetry scrape --port 9464 --json
    python -m ompi_tpu.tools.telemetry tail --port 9464 --count 10
    python -m ompi_tpu.tools.telemetry diff before.json after.json
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request


def _url(args, path: str) -> str:
    if args.url:
        return args.url.rstrip("/") + path
    return f"http://127.0.0.1:{args.port}{path}"


def _get(url: str, timeout: float = 5.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def cmd_scrape(args) -> int:
    path = "/fleet" if args.fleet else ("/json" if args.json
                                        else "/metrics")
    body = _get(_url(args, path)).decode()
    if args.output:
        with open(args.output, "w") as f:
            f.write(body)
        print(f"wrote {len(body)} bytes -> {args.output}")
    else:
        sys.stdout.write(body)
    return 0


def cmd_tail(args) -> int:
    prev: dict = {}
    for i in range(args.count) if args.count else iter(int, 1):
        snap = json.loads(_get(_url(args, "/json")).decode())
        now = snap.get("counters", {})
        changed = {
            k: now[k] - prev.get(k, 0)
            for k in sorted(now) if now[k] != prev.get(k, 0)
        }
        stamp = snap.get("t_unix_ns", 0) // 1_000_000_000
        cols = " ".join(f"{k}=+{v:g}" for k, v in changed.items())
        print(f"[{stamp}] seq-deltas: {cols or '(idle)'}")
        prev = now
        if not args.count or i < args.count - 1:
            time.sleep(args.interval)
    return 0


def _load_snapshot(path: str) -> dict:
    with open(path) as f:
        d = json.load(f)
    if not str(d.get("format", "")).startswith("ompi_tpu.telemetry"):
        raise SystemExit(f"{path}: not an ompi_tpu telemetry snapshot "
                         f"(format={d.get('format')!r})")
    return d


def cmd_diff(args) -> int:
    a = _load_snapshot(args.a)
    b = _load_snapshot(args.b)
    rows = []
    ca, cb = a.get("counters", {}), b.get("counters", {})
    for name in sorted(set(ca) | set(cb)):
        d = cb.get(name, 0) - ca.get(name, 0)
        if d:
            rows.append((name, f"{ca.get(name, 0):g}",
                         f"{cb.get(name, 0):g}", f"{d:+g}"))
    ha, hb = a.get("hists", {}), b.get("hists", {})
    for name in sorted(set(ha) | set(hb)):
        sa, sb = ha.get(name, {}), hb.get(name, {})
        dcount = sb.get("count", 0) - sa.get("count", 0)
        if not dcount and sa.get("p50") == sb.get("p50"):
            continue
        rows.append((
            f"{name} [hist]",
            f"n={sa.get('count', 0):g} p50={sa.get('p50', 0):.2e}",
            f"n={sb.get('count', 0):g} p50={sb.get('p50', 0):.2e}",
            f"{dcount:+g}",
        ))
    for key in sorted(set(a.get("health", {})) | set(b.get("health", {}))):
        sa_state = a.get("health", {}).get(key, "healthy")
        sb_state = b.get("health", {}).get(key, "healthy")
        if sa_state != sb_state:
            rows.append((f"{key} [health]", sa_state, sb_state, ""))
    if not rows:
        print("no differences")
        return 0
    w = max(len(r[0]) for r in rows)
    print(f"{'pvar'.ljust(w)}  {'a'.rjust(24)}  {'b'.rjust(24)}  delta")
    for name, va, vb, d in rows:
        print(f"{name.ljust(w)}  {va.rjust(24)}  {vb.rjust(24)}  {d}")
    return 0


def cmd_dump(args) -> int:
    from ..telemetry import export

    if args.prometheus:
        path = export.write_prometheus(args.output)
    else:
        path = export.write_json(args.output)
    print(f"wrote {path}")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ompi_tpu.tools.telemetry",
        description="Scrape, tail, and diff telescope telemetry.",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    sc = sub.add_parser("scrape", help="GET a running exporter")
    sc.add_argument("--url", default=None,
                    help="full exporter base URL (overrides --port)")
    sc.add_argument("--port", type=int, default=9464,
                    help="localhost exporter port (default: %(default)s)")
    sc.add_argument("--json", action="store_true",
                    help="scrape /json instead of /metrics")
    sc.add_argument("--fleet", action="store_true",
                    help="scrape the rank-0 merged /fleet view")
    sc.add_argument("-o", "--output", default=None,
                    help="save to a file instead of stdout")
    sc.set_defaults(fn=cmd_scrape)

    tl = sub.add_parser("tail", help="poll /json, print counter deltas")
    tl.add_argument("--url", default=None)
    tl.add_argument("--port", type=int, default=9464)
    tl.add_argument("--interval", type=float, default=1.0,
                    help="poll interval seconds (default: %(default)s)")
    tl.add_argument("--count", type=int, default=0,
                    help="stop after N polls (0 = forever)")
    tl.set_defaults(fn=cmd_tail)

    df = sub.add_parser("diff", help="compare two JSON snapshots")
    df.add_argument("a")
    df.add_argument("b")
    df.set_defaults(fn=cmd_diff)

    dp = sub.add_parser("dump", help="render this process's registries")
    dp.add_argument("-o", "--output", required=True)
    dp.add_argument("--prometheus", action="store_true",
                    help="Prometheus text instead of JSON")
    dp.set_defaults(fn=cmd_dump)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

"""benchgate CLI — the enforced perf ratchet over the bench trajectory.

The BENCH_r*.json / MULTICHIP_r*.json files record every round's rows;
until now they were archaeology. This gate makes them a contract:
given a *current* set of rows (a fresh ``bench.py`` run, the live
partial file, or a round file), every (row, metric) with history must
not regress past the best trajectory value by more than the allowance.

Usage::

    python -m ompi_tpu.tools.benchgate [--root DIR] [--current FILE]
        [--allowance PCT] [--dry-run] [--self] [--json]
    python bench.py --gate [--dry-run | --current FILE ...]

Semantics:

- **Baselines** are the best-ever value per (row, metric) across the
  trajectory, direction-aware: throughput-shaped metrics (``gbps``,
  ``busbw``, ``hit_rate``, ``speedup``...) ratchet upward, latency-
  shaped ones (``*_us``, ``*_ms``, ``p50``/``p99``/``rtt``,
  ``overhead_pct``...) downward. Metrics that match neither shape are
  ignored — the gate never guesses a direction.
- **Degraded rows are excused, not silent**: a row tagged
  ``degraded=true`` (bench ran inside a quarantine window) or coming
  from a round whose ``rc != 0`` (the device tunnel was down) is
  reported but never fails the gate — the per-row allowance the
  trajectory's r03-r05 host-only era needs.
- ``--dry-run`` only validates/loads the trajectory (the tier-1 seam:
  malformed round files fail fast with exit 2, before a 25-minute
  bench run would trip over them).
- ``--self`` replays the trajectory: each round gated against the
  rounds before it (the newest-round regression check).

Exit codes: 0 pass, 1 ratchet break, 2 malformed trajectory / run
failure — the lint CLI's contract.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Optional

#: Metric-name fragments that mark a higher-is-better series.
_HIGHER = ("gbps", "busbw", "gb_s", "hit_rate", "speedup", "ratio_x",
           "overlap_pct", "ticks_sampled", "_per_s", "ag_elided")
#: Fragments that mark a lower-is-better series. ``overhead_pct``
#: rides the _pct absolute-slack path in _is_regression.
_LOWER = ("p50", "p99", "_us", "_ms", "rtt", "latency", "detect_ms",
          "overhead_pct", "tune_ms", "restore_ms", "degradation_pct",
          "convergence_ticks", "rejoin_steps", "blip")

DEFAULT_ALLOWANCE = 0.25


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def direction(metric: str) -> Optional[str]:
    """'higher' / 'lower' / None (ignored) for a metric name. Checked
    lower-first so ``overhead_pct`` never reads as throughput."""
    m = metric.lower()
    if any(t in m for t in _LOWER):
        return "lower"
    if any(t in m for t in _HIGHER):
        return "higher"
    return None


class GateError(Exception):
    """Malformed trajectory / unusable input (exit 2)."""


def _load_doc(path: str) -> dict:
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as exc:
        raise GateError(f"{path}: unreadable ({exc})") from exc
    if not isinstance(doc, dict):
        raise GateError(f"{path}: expected a JSON object, got "
                        f"{type(doc).__name__}")
    return doc


def _round_rows(doc: dict, path: str) -> dict[str, dict]:
    """{row_name: {metric: value, ..., "degraded": bool}} for one
    trajectory round. Tolerates the MULTICHIP shape (rc=0 but no
    parsed detail) by contributing nothing."""
    parsed = doc.get("parsed")
    if parsed is None:
        return {}
    if not isinstance(parsed, dict):
        raise GateError(f"{path}: 'parsed' is not an object")
    detail = parsed.get("detail")
    if detail is None:
        return {}
    if not isinstance(detail, dict):
        raise GateError(f"{path}: 'parsed.detail' is not an object")
    round_failed = doc.get("rc", 0) != 0
    rows: dict[str, dict] = {}

    def _take(name: str, row) -> None:
        if not isinstance(row, dict) or "error" in row:
            return
        metrics = {k: float(v) for k, v in row.items()
                   if isinstance(v, (int, float))
                   and not isinstance(v, bool)}
        if not metrics:
            return
        metrics["degraded"] = bool(row.get("degraded")) or round_failed
        rows[name] = metrics

    for name, row in detail.items():
        if name in ("error", "phase", "partial"):
            continue
        _take(name, row)
    partial = detail.get("partial")
    if isinstance(partial, dict):
        for name, row in partial.items():
            _take(name, row)
    return rows


def load_trajectory(root: str) -> list[tuple[str, dict[str, dict]]]:
    """[(path, rows)] for every trajectory file under ``root``, in
    round order. Raises GateError on a malformed file."""
    paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))) + \
        sorted(glob.glob(os.path.join(root, "MULTICHIP_r*.json")))
    if not paths:
        raise GateError(f"no BENCH_r*/MULTICHIP_r* files under {root}")
    return [(p, _round_rows(_load_doc(p), p)) for p in paths]


def baselines(rounds: list[tuple[str, dict]]) -> dict:
    """{(row, metric): best value} over the trajectory (direction-
    aware; metrics with no direction never enter)."""
    best: dict[tuple[str, str], float] = {}
    for _path, rows in rounds:
        for rname, metrics in rows.items():
            for metric, value in metrics.items():
                if metric == "degraded":
                    continue
                d = direction(metric)
                if d is None:
                    continue
                k = (rname, metric)
                if k not in best:
                    best[k] = value
                elif d == "higher":
                    best[k] = max(best[k], value)
                else:
                    best[k] = min(best[k], value)
    return best


def _is_regression(metric: str, cur: float, base: float,
                   allowance: float) -> bool:
    d = direction(metric)
    if d is None:
        return False
    if metric.lower().endswith("_pct"):
        # percentage-point rows hover near zero where relative slack
        # degenerates; use absolute points
        slack = max(2.0, abs(base) * allowance)
    else:
        slack = abs(base) * allowance
    if d == "lower":
        return cur > base + slack
    return cur < base - slack


def gate_rows(current: dict[str, dict], best: dict,
              allowance: float) -> tuple[list[dict], list[dict]]:
    """(breaks, excused) comparing current rows to the baselines."""
    breaks: list[dict] = []
    excused: list[dict] = []
    for rname in sorted(current):
        metrics = current[rname]
        if not isinstance(metrics, dict):
            continue
        degraded = bool(metrics.get("degraded"))
        for metric in sorted(metrics):
            value = metrics[metric]
            if metric == "degraded" or isinstance(value, bool) \
                    or not isinstance(value, (int, float)):
                continue
            base = best.get((rname, metric))
            if base is None:
                continue
            if _is_regression(metric, float(value), base, allowance):
                item = {"row": rname, "metric": metric,
                        "current": float(value), "best": base,
                        "direction": direction(metric)}
                (excused if degraded else breaks).append(item)
    return breaks, excused


def _current_rows(path: str) -> dict[str, dict]:
    """Rows from a 'current' file, accepting any of the shapes the
    repo produces: a round file (``parsed.detail``), the live partial
    dump (``{"phase", "rows"}``), or a bare ``{row: {metric: v}}``."""
    doc = _load_doc(path)
    if "parsed" in doc:
        return _round_rows(doc, path)
    rows = doc.get("rows") if isinstance(doc.get("rows"), dict) else doc
    out: dict[str, dict] = {}
    for name, row in rows.items():
        if isinstance(row, dict) and "error" not in row:
            metrics = {k: float(v) for k, v in row.items()
                       if isinstance(v, (int, float))
                       and not isinstance(v, bool)}
            if metrics:
                metrics["degraded"] = bool(row.get("degraded"))
                out[name] = metrics
    return out


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="benchgate",
        description="gate bench rows against the BENCH_r*/MULTICHIP_r* "
                    "trajectory")
    ap.add_argument("--root", default=repo_root(),
                    help="directory holding the trajectory files")
    ap.add_argument("--current",
                    help="rows to gate (round file, live partial dump, "
                         "or bare row dict); default: "
                         "docs/BENCH_PARTIAL_LIVE.json when present")
    ap.add_argument("--allowance", type=float,
                    default=DEFAULT_ALLOWANCE * 100,
                    help="regression allowance in percent "
                         "(default %(default)s)")
    ap.add_argument("--dry-run", action="store_true",
                    help="validate/load the trajectory only")
    ap.add_argument("--self", dest="self_check", action="store_true",
                    help="replay: gate each round against the rounds "
                         "before it")
    ap.add_argument("--json", dest="as_json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)
    allowance = max(0.0, args.allowance) / 100.0

    try:
        rounds = load_trajectory(args.root)
    except GateError as exc:
        print(f"benchgate: {exc}", file=sys.stderr)
        return 2

    report: dict = {
        "rounds": [os.path.basename(p) for p, _ in rounds],
        "tracked_series": len(baselines(rounds)),
        "allowance_pct": allowance * 100,
        "breaks": [],
        "excused": [],
    }

    if args.dry_run:
        report["mode"] = "dry-run"
        print(json.dumps(report, indent=1) if args.as_json else
              f"benchgate: trajectory ok — {len(rounds)} round file(s),"
              f" {report['tracked_series']} tracked series")
        return 0

    if args.self_check:
        report["mode"] = "self"
        for i in range(1, len(rounds)):
            best = baselines(rounds[:i])
            breaks, excused = gate_rows(rounds[i][1], best, allowance)
            tag = os.path.basename(rounds[i][0])
            for b in breaks:
                b["round"] = tag
            for e in excused:
                e["round"] = tag
            report["breaks"].extend(breaks)
            report["excused"].extend(excused)
    else:
        report["mode"] = "gate"
        current_path = args.current or os.path.join(
            args.root, "docs", "BENCH_PARTIAL_LIVE.json")
        if not os.path.exists(current_path):
            print(f"benchgate: no current rows at {current_path} "
                  "(run bench.py, or pass --current)", file=sys.stderr)
            return 2
        try:
            current = _current_rows(current_path)
        except GateError as exc:
            print(f"benchgate: {exc}", file=sys.stderr)
            return 2
        best = baselines(rounds)
        report["breaks"], report["excused"] = gate_rows(
            current, best, allowance)
        report["current"] = os.path.basename(current_path)
        report["rows_checked"] = len(current)

    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        for e in report["excused"]:
            print(f"benchgate: excused (degraded) {e['row']}."
                  f"{e['metric']}: {e['current']:g} vs best "
                  f"{e['best']:g}")
        for b in report["breaks"]:
            print(f"benchgate: RATCHET BREAK {b['row']}.{b['metric']}: "
                  f"{b['current']:g} vs best {b['best']:g} "
                  f"({b['direction']} is better)")
        if not report["breaks"]:
            print(f"benchgate: pass ({report['tracked_series']} "
                  f"series, {len(report['excused'])} excused)")
    return 1 if report["breaks"] else 0


if __name__ == "__main__":
    sys.exit(main())

"""Checkpoint inspection/management CLI (opal-checkpoint/restart analog).

Reference: opal/tools/opal-checkpoint and opal-restart drive the CRS
(SURVEY §2.5). The array-state analog is snapshot-directory management:

    python -m ompi_tpu.tools.ckpt list <dir>
    python -m ompi_tpu.tools.ckpt show <dir> [--step N]
    python -m ompi_tpu.tools.ckpt prune <dir> --keep N
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _manager(directory: str, keep=None):
    from ..ft.manager import CheckpointManager

    return CheckpointManager(directory, keep=keep)


def cmd_list(args) -> int:
    m = _manager(args.dir)
    steps = m.steps()
    if not steps:
        print(f"{args.dir}: no snapshots")
        return 1
    for s in steps:
        meta_path = os.path.join(m.path(s), "meta.json")
        extra = ""
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                doc = json.load(f)
            extra = f"  [{doc.get('format', '?')}]"
        mark = " (latest)" if s == steps[-1] else ""
        print(f"snap-{s}{extra}{mark}")
    return 0


def cmd_show(args) -> int:
    m = _manager(args.dir)
    step = args.step if args.step is not None else m.latest_step()
    if step is None:
        print(f"{args.dir}: no snapshots", file=sys.stderr)
        return 1
    meta_path = os.path.join(m.path(step), "meta.json")
    with open(meta_path) as f:
        doc = json.load(f)
    print(json.dumps(doc, indent=2, default=str))
    return 0


def cmd_prune(args) -> int:
    m = _manager(args.dir, keep=args.keep)
    before = m.steps()
    m._prune()
    after = m.steps()
    print(f"pruned {len(before) - len(after)} snapshots, kept {after}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ompi_tpu.tools.ckpt")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("list")
    p.add_argument("dir")
    p.set_defaults(fn=cmd_list)
    p = sub.add_parser("show")
    p.add_argument("dir")
    p.add_argument("--step", type=int, default=None)
    p.set_defaults(fn=cmd_show)
    p = sub.add_parser("prune")
    p.add_argument("dir")
    p.add_argument("--keep", type=int, required=True)
    p.set_defaults(fn=cmd_prune)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())

"""ompi_tpu_info: dump frameworks, components, config vars, counters.

TPU-native equivalent of ompi_info (reference: ompi/tools/ompi_info —
dumps every framework/component/MCA var) plus the MPI_T introspection
surface (cvars = the config registry, pvars = the SPC counters).

Usage: python -m ompi_tpu.tools.info [--all] [--json] [--param FW]
"""

from __future__ import annotations

import argparse
import json
import sys


def collect(include_internal: bool = False) -> dict:
    # Import for their registration side effects.
    from .. import _version
    from ..coll import framework as coll_fw
    from ..pml import framework as pml_fw
    from ..btl import framework as btl_fw  # noqa: F401
    from ..io import fbtl, fcoll, fs, sharedfp  # noqa: F401
    from ..ft import crs  # noqa: F401
    from ..hook import framework as hook_fw  # noqa: F401
    from ..pml import mtl  # noqa: F401
    from ..part import framework as part_fw
    from ..core import config
    from ..core.component import MCA
    from ..core.counters import SPC

    coll_fw.ensure_components()
    pml_fw.ensure_components()
    part_fw.ensure_components()

    frameworks = {}
    for name in MCA.names():
        fw = MCA.framework(name)
        comps = {}
        for cname in fw.component_names():
            comp = fw.component(cname)
            comps[cname] = {
                "priority": comp.priority,
                "description": comp.DESCRIPTION,
            }
        frameworks[name] = comps

    return {
        "version": _version.__version__,
        "frameworks": frameworks,
        "config_vars": config.VARS.dump(include_internal),
        "counters": SPC.dump(),
    }


def render_text(info: dict, param_filter: str = "") -> str:
    lines = [f"ompi_tpu version: {info['version']}", ""]
    lines.append("Frameworks and components:")
    for fw, comps in sorted(info["frameworks"].items()):
        lines.append(f"  {fw}:")
        for cname, meta in sorted(
            comps.items(), key=lambda kv: -kv[1]["priority"]
        ):
            lines.append(
                f"    {cname:<12} priority {meta['priority']:>4}  "
                f"{meta['description']}"
            )
    lines.append("")
    lines.append("Config vars (cvars):")
    for var in info["config_vars"]:
        if param_filter and not var["name"].startswith(param_filter):
            continue
        lines.append(
            f"  {var['name']:<40} = {var['value']!r:<16} "
            f"[{var['source']}] {var['description']}"
        )
    if info["counters"]:
        lines.append("")
        lines.append("Performance counters (pvars):")
        for c in info["counters"]:
            lines.append(f"  {c['name']:<40} {c['value']} {c['unit']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ompi_tpu_info")
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument("--all", action="store_true",
                    help="include internal vars")
    ap.add_argument("--param", default="",
                    help="filter config vars by prefix (e.g. coll_tuned)")
    args = ap.parse_args(argv)
    info = collect(include_internal=args.all)
    if args.json:
        print(json.dumps(info, indent=2, default=str))
    else:
        print(render_text(info, args.param))
    return 0


if __name__ == "__main__":
    sys.exit(main())

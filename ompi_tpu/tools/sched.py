"""Schedule-cache CLI: list, warm, and dump compiled schedules.

Front-end for coll/sched — the schedule compiler's operational
surface:

    # what winners does the cache hold (and for which topology)?
    python -m ompi_tpu.tools.sched list

    # warm the cache offline (model mode: no devices needed) so the
    # fleet's first collective dispatches a tuned winner instead of
    # paying first-call tune cost
    python -m ompi_tpu.tools.sched warm --nranks 8

    # print a schedule's step program (the IR the lowering compiles)
    python -m ompi_tpu.tools.sched dump --name ring --nranks 8
"""

from __future__ import annotations

import argparse
import json
import os


def _cmd_list(args) -> int:
    from ..coll.sched import cache

    if args.file:
        n = cache.CACHE.load(args.file)
        print(f"loaded {n} entr{'y' if n == 1 else 'ies'} from "
              f"{args.file}")
    else:
        d = cache.cache_dir()
        if not os.path.isdir(d):
            print(f"no schedule cache at {d}")
            return 0
        for name in sorted(os.listdir(d)):
            if name.endswith(".json"):
                cache.CACHE.load(os.path.join(d, name))
    entries = cache.CACHE.entries()
    if not entries:
        print("schedule cache is empty")
        return 0
    print(f"{len(entries)} cached schedule(s) "
          f"(digest {cache.CACHE.digest()[:16]}):")
    for key in sorted(entries):
        e = entries[key]
        extra = f" [{e['schedule']}]" if e.get("schedule") else ""
        print(f"  {key:<48} -> {e['algorithm']}{extra} "
              f"({e.get('source', '?')})")
    return 0


def _cmd_warm(args) -> int:
    from ..coll.sched import autotune

    res = autotune.tune(
        args.nranks, mode=args.mode,
        seed=args.seed, save=not args.dry_run,
        topo_fp=args.topo or None,
    )
    print(f"tuned {len(res['winners'])} key(s) in "
          f"{res['tune_ms']:.1f} ms (mode={res['mode']})")
    if res["skipped"]:
        print(f"skipped (quarantined tier): {', '.join(res['skipped'])}")
    if res["path"]:
        print(f"saved {res['path']}")
    print(f"digest {res['digest']}")
    if args.json:
        print(json.dumps({k: v for k, v in res.items()
                          if k != "times"}, indent=2, sort_keys=True))
    return 0


def _cmd_dump(args) -> int:
    from ..coll.sched import ir

    params = {}
    if args.segments is not None:
        params["segments"] = args.segments
    if args.wire:
        params["wire"] = args.wire
    sched = ir.generate(args.name, args.nranks, **params)
    print(sched.render())
    print(f"# digest {sched.digest()}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ompi_tpu.tools.sched")
    sub = ap.add_subparsers(dest="cmd", required=True)

    ls = sub.add_parser("list", help="show cached schedule winners")
    ls.add_argument("--file", default="",
                    help="load one cache file instead of scanning the "
                         "cache directory")
    ls.set_defaults(fn=_cmd_list)

    wm = sub.add_parser("warm", help="run the autotuner, persist "
                                     "winners (offline-capable)")
    wm.add_argument("--nranks", type=int, required=True)
    wm.add_argument("--mode", choices=("model", "measure"),
                    default="model")
    wm.add_argument("--seed", type=int, default=None)
    wm.add_argument("--topo", default="",
                    help="topology fingerprint override (default: "
                         "this machine's)")
    wm.add_argument("--dry-run", action="store_true",
                    help="tune but do not write the cache file")
    wm.add_argument("--json", action="store_true",
                    help="also print the full result as JSON")
    wm.set_defaults(fn=_cmd_warm)

    dp = sub.add_parser("dump", help="print a schedule's step program")
    dp.add_argument("--name", required=True,
                    help="generator name (ring, recursive_doubling, "
                         "segmented_ring, hierarchical, quantized_wire)")
    dp.add_argument("--nranks", type=int, required=True)
    dp.add_argument("--segments", type=int, default=None)
    dp.add_argument("--wire", default="")
    dp.set_defaults(fn=_cmd_dump)

    args = ap.parse_args(argv)
    if args.cmd == "warm" and args.mode == "measure":
        import ompi_tpu

        comm = ompi_tpu.init()
        from ..coll.sched import autotune

        res = autotune.tune(args.nranks, comm=comm, mode="measure",
                            save=not args.dry_run,
                            topo_fp=args.topo or None)
        print(f"tuned {len(res['winners'])} key(s) in "
              f"{res['tune_ms']:.1f} ms (mode=measure)")
        if res["path"]:
            print(f"saved {res['path']}")
        print(f"digest {res['digest']}")
        return 0
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())

"""mpisync — cross-process clock-offset measurement for trace alignment.

TPU-native equivalent of ompi/tools/mpisync (reference: sync.c +
mpigclock.c — measures each rank's clock offset against rank 0 with a
min-RTT ping filter so traces from different hosts can be merged on one
timeline). Two forms here:

- `measure_dcn(a, peer, ...)`: the real cross-host path — ping/pong of
  dss-packed timestamps over a DCN endpoint pair, offset estimated from
  the minimum-RTT sample (Cristian's algorithm, as mpigclock does).
- `measure_devices(comm)`: per-device dispatch-latency profile on one
  host (TPU device timelines are host-synchronous, so the interesting
  number is enqueue→ready latency per device).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from ..core import dss
from ..core.logging import get_logger

logger = get_logger("mpisync")

SYNC_TAG = 0x53594E43  # "SYNC"


@dataclass
class OffsetEstimate:
    offset_s: float  # remote_clock - local_clock
    rtt_s: float  # best round-trip observed
    samples: int


def serve_dcn(endpoint, n_requests: int, timeout: float = 30.0) -> None:
    """Responder: echo each ping with our receive/send timestamps
    (the server side of mpigclock's exchange)."""
    for _ in range(n_requests):
        peer, tag, payload = endpoint.recv_bytes(timeout=timeout)
        if tag != SYNC_TAG:
            continue
        t_recv = time.time()
        (t_client,) = dss.unpack(payload)
        endpoint.send_bytes(
            peer, SYNC_TAG, dss.pack(t_client, t_recv, time.time())
        )


def measure_dcn(endpoint, peer: int, samples: int = 32,
                timeout: float = 10.0) -> OffsetEstimate:
    """Requester: estimate the responder's clock offset. Uses the
    minimum-RTT sample — congestion only ever inflates RTT, so the
    smallest RTT gives the tightest offset bound (mpigclock.c's
    filtering)."""
    best_rtt = float("inf")
    best_offset = 0.0
    for _ in range(samples):
        t0 = time.time()
        endpoint.send_bytes(peer, SYNC_TAG, dss.pack(t0))
        _, tag, payload = endpoint.recv_bytes(timeout=timeout)
        t3 = time.time()
        t_client, t_recv, t_send = dss.unpack(payload)
        rtt = (t3 - t0) - (t_send - t_recv)
        if rtt < best_rtt:
            best_rtt = rtt
            # midpoint assumption: remote clock read at t0 + rtt/2
            best_offset = t_recv - (t0 + rtt / 2)
    return OffsetEstimate(best_offset, best_rtt, samples)


def measure_devices(comm, samples: int = 16) -> dict[int, float]:
    """Per-rank device dispatch→ready latency (seconds, min over
    samples): the on-host timeline skew that matters for aligning
    per-device profiler traces."""
    import jax
    import jax.numpy as jnp

    out = {}
    for r, dev in enumerate(comm.devices):
        best = float("inf")
        x = jax.device_put(jnp.ones((8,), jnp.float32), dev)
        for _ in range(samples):
            t0 = time.perf_counter()
            y = x + 1
            jax.block_until_ready(y)
            best = min(best, time.perf_counter() - t0)
        out[r] = best
    return out


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="ompi_tpu.tools.mpisync")
    ap.add_argument("--samples", type=int, default=16)
    args = ap.parse_args(argv)
    from .. import api

    comm = api.world()
    lat = measure_devices(comm, samples=args.samples)
    for r, s in sorted(lat.items()):
        print(f"rank {r}: dispatch->ready {s * 1e6:.1f} us")
    return 0


if __name__ == "__main__":
    import ompi_tpu

    ompi_tpu.init()
    raise SystemExit(main())

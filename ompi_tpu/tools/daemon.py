"""bulkhead daemon CLI: status, sessions, evict, drain.

Operational surface for ompi_tpu/daemon. The daemon is a long-lived
in-process service; the CLI talks to it through its state file — the
daemon (with ``daemon_base_state_path`` set) atomically rewrites a
JSON status snapshot every pump and consumes commands appended to
``<state_path>.cmd``:

    # what is the daemon doing right now?
    python -m ompi_tpu.tools.daemon status --state /run/bulkhead.json

    # per-session queue depths and states
    python -m ompi_tpu.tools.daemon sessions --state /run/bulkhead.json

    # deterministically evict a tenant (revoke -> quiesce -> detach
    # every session, GC its ledger namespace)
    python -m ompi_tpu.tools.daemon evict --state /run/bulkhead.json \\
        --tenant acme

    # ask the daemon to drain all queues
    python -m ompi_tpu.tools.daemon drain --state /run/bulkhead.json

``evict``/``drain`` append a command line and return immediately; the
daemon executes it on its next pump and the following ``status`` shows
the effect. When this process itself hosts the daemon (tests, single-
controller deployments), the same subcommands act on it directly via
``ompi_tpu.daemon.current()``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load_status(state_path: str):
    """The daemon's snapshot, preferring the live in-process instance
    over the (possibly one-pump-stale) state file."""
    from .. import daemon as daemon_mod

    d = daemon_mod.current()
    if d is not None:
        return d.status(), d
    try:
        with open(state_path, "r", encoding="utf-8") as fh:
            return json.load(fh), None
    except FileNotFoundError:
        print(f"no daemon state at {state_path!r} (is the daemon "
              f"running with daemon_base_state_path set?)",
              file=sys.stderr)
        return None, None
    except ValueError as exc:
        print(f"daemon state {state_path!r} unreadable: {exc}",
              file=sys.stderr)
        return None, None


def _append_cmd(state_path: str, cmd: dict) -> None:
    path = state_path + ".cmd"
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(cmd, sort_keys=True) + "\n")


def _cmd_status(args) -> int:
    st, _d = _load_status(args.state)
    if st is None:
        return 1
    if args.json:
        print(json.dumps(st, indent=2, sort_keys=True))
        return 0
    print(f"daemon {st['name']} (protocol v{st['version']}, "
          f"lane={st['lane']}, seed={st['seed']}, "
          f"slot={st['slot']})")
    print(f"decision-log digest {st['digest']}")
    tenants = st.get("tenants", {})
    if not tenants:
        print("no tenants")
        return 0
    for name in sorted(tenants):
        m = tenants[name]
        print(f"  {name:<16} class={m.get('qos', '?'):<10} "
              f"sessions={m.get('sessions', 0)} "
              f"admitted={m.get('admitted', 0)} "
              f"rejected={m.get('rejected', 0)} "
              f"bytes={m.get('bytes', 0)} "
              f"slo_viol_min={m.get('slo_violation_minutes', 0)}")
    return 0


def _cmd_sessions(args) -> int:
    st, _d = _load_status(args.state)
    if st is None:
        return 1
    sessions = st.get("sessions", [])
    if args.json:
        print(json.dumps(sessions, indent=2, sort_keys=True))
        return 0
    if not sessions:
        print("no attached sessions")
        return 0
    for s in sessions:
        print(f"  sid={s['sid']:<4} tenant={s['tenant']:<16} "
              f"class={s['qos']:<10} cid={s['cid']} "
              f"epoch={s['epoch']} state={s['state']} "
              f"queued={s['queued']} bytes={s['queued_bytes']}")
    return 0


def _cmd_evict(args) -> int:
    from .. import daemon as daemon_mod

    d = daemon_mod.current()
    if d is not None:
        rep = d.evict(args.tenant, cause="cli")
        print(f"evicted {args.tenant}: answered={rep['answered']} "
              f"released={rep['released']}")
        return 0
    _append_cmd(args.state, {"cmd": "evict", "tenant": args.tenant})
    print(f"eviction of {args.tenant!r} queued at "
          f"{args.state + '.cmd'} (applied on the daemon's next pump)")
    return 0


def _cmd_drain(args) -> int:
    from .. import daemon as daemon_mod

    d = daemon_mod.current()
    if d is not None:
        served = d.drain(timeout=args.timeout)
        print(f"drained: {served} request(s) served")
        return 0
    _append_cmd(args.state, {"cmd": "drain"})
    print(f"drain queued at {args.state + '.cmd'} (applied on the "
          f"daemon's next pump)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ompi_tpu.tools.daemon")
    sub = ap.add_subparsers(dest="cmd", required=True)

    def _with_state(p):
        p.add_argument(
            "--state",
            default=os.environ.get("OMPI_TPU_DAEMON_STATE",
                                   "bulkhead.json"),
            help="daemon state file (the daemon's "
                 "daemon_base_state_path; default "
                 "$OMPI_TPU_DAEMON_STATE or ./bulkhead.json)")
        return p

    st = _with_state(sub.add_parser(
        "status", help="daemon + per-tenant summary"))
    st.add_argument("--json", action="store_true")
    st.set_defaults(fn=_cmd_status)

    se = _with_state(sub.add_parser(
        "sessions", help="per-session queue state"))
    se.add_argument("--json", action="store_true")
    se.set_defaults(fn=_cmd_sessions)

    ev = _with_state(sub.add_parser(
        "evict", help="evict a tenant (revoke -> quiesce -> detach, "
                      "GC scopes)"))
    ev.add_argument("--tenant", required=True)
    ev.set_defaults(fn=_cmd_evict)

    dr = _with_state(sub.add_parser(
        "drain", help="serve every queued request"))
    dr.add_argument("--timeout", type=float, default=30.0)
    dr.set_defaults(fn=_cmd_drain)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())

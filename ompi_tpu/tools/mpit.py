"""MPI_T-style tool interface: cvars, pvars, categories.

TPU-native equivalent of ompi/mpi/tool (reference: the MPI_T API over
the mca_base_var registry (cvars, mca_base_var.c) and SPC/monitoring
pvars (mca_base_pvar.c); ompi_spc.c exports counters as pvars). Tools
use this module instead of reaching into internals:

    from ompi_tpu.tools import mpit
    for cv in mpit.cvar_list(): ...
    h = mpit.pvar_session(); ...; h.read()
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..core import config, counters


@dataclass
class CvarInfo:
    name: str
    value: Any
    default: Any
    type: str
    source: str
    description: str


def cvar_list(prefix: str = "") -> list[CvarInfo]:
    """Enumerate control variables (every registered config var)."""
    out = []
    for var in config.VARS.all_vars():
        if prefix and not var.full_name.startswith(prefix):
            continue
        out.append(
            CvarInfo(
                name=var.full_name,
                value=var.value,
                default=var.default,
                type=var.type.__name__,
                source=var.source.name,
                description=var.description,
            )
        )
    return sorted(out, key=lambda c: c.name)


def cvar_read(name: str) -> Any:
    return config.get(name)


def cvar_write(name: str, value: Any) -> None:
    """MPI_T_cvar_write: runtime override (the OVERRIDE source)."""
    config.set(name, value)


def pvar_list(prefix: str = "") -> list[dict]:
    """Enumerate performance variables (the SPC registry)."""
    return [
        d for d in counters.SPC.dump()
        if not prefix or d["name"].startswith(prefix)
    ]


def pvar_read(name: str) -> float:
    return counters.SPC.snapshot().get(name, 0.0)


def pvar_session() -> counters.PvarSession:
    """A pvar session: reads are deltas since session start (MPI_T
    pvar handle semantics — each tool sees its own baseline)."""
    return counters.PvarSession()


def categories() -> dict[str, list[str]]:
    """Group cvars by framework (MPI_T categories = MCA frameworks)."""
    cats: dict[str, list[str]] = {}
    for cv in cvar_list():
        fw = cv.name.split("_", 1)[0]
        cats.setdefault(fw, []).append(cv.name)
    return cats

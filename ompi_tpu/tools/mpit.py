"""MPI_T-style tool interface: cvars, pvars, categories, watches.

TPU-native equivalent of ompi/mpi/tool (reference: the MPI_T API over
the mca_base_var registry (cvars, mca_base_var.c) and SPC/monitoring
pvars (mca_base_pvar.c); ompi_spc.c exports counters as pvars). Tools
use this module instead of reaching into internals:

    from ompi_tpu.tools import mpit
    for cv in mpit.cvar_list(): ...
    h = mpit.pvar_session(); ...; h.read()

Pvars span the MPI_T classes: scalar **counter** / **watermark** /
**timer** variables (the SPC counter registry, class derived from the
unit) and **histogram** variables (the log-bucketed latency
distributions — ``CounterRegistry.histogram_snapshots``). A histogram
pvar's scalar value is its sample count; ``pvar_read("name:p50")``
addresses an individual field.

``pvar_watch`` is the MPI_T event-callback analog
(MPI_T_event_handle_alloc): register a callback against a pvar and a
threshold; ``check_watches()`` — called from the telemetry sampler's
tick, or by any polling tool — fires the callback on every observed
*rise* while the value sits at/above the threshold. The telemetry
straggler detector subscribes through this mechanism rather than a
bespoke path.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..core import config, counters


@dataclass
class CvarInfo:
    name: str
    value: Any
    default: Any
    type: str
    source: str
    description: str


def cvar_list(prefix: str = "") -> list[CvarInfo]:
    """Enumerate control variables (every registered config var)."""
    out = []
    for var in config.VARS.all_vars():
        if prefix and not var.full_name.startswith(prefix):
            continue
        out.append(
            CvarInfo(
                name=var.full_name,
                value=var.value,
                default=var.default,
                type=var.type.__name__,
                source=var.source.name,
                description=var.description,
            )
        )
    return sorted(out, key=lambda c: c.name)


def cvar_read(name: str) -> Any:
    return config.get(name)


def cvar_write(name: str, value: Any) -> None:
    """MPI_T_cvar_write: runtime override (the OVERRIDE source)."""
    config.set(name, value)


# -- pvars (both classes) ---------------------------------------------------

def pvar_list(prefix: str = "") -> list[dict]:
    """Enumerate performance variables: the scalar SPC counters plus
    the histogram-class pvars, every entry tagged with its MPI_T class
    (counter / watermark / timer / histogram). Histogram entries carry
    the percentile snapshot; their scalar ``value`` is the sample
    count."""
    out = []
    for d in counters.SPC.dump():
        if prefix and not d["name"].startswith(prefix):
            continue
        d["class"] = counters.pvar_class_of(d["unit"])
        out.append(d)
    for h in counters.SPC.histogram_dump():
        if prefix and not h["name"].startswith(prefix):
            continue
        h["class"] = counters.PVAR_HISTOGRAM
        h["value"] = h["snapshot"]["count"]
        out.append(h)
    return sorted(out, key=lambda d: d["name"])


def pvar_read(name: str) -> Any:
    """Read one pvar. Scalar counters return their value; a histogram
    name returns its snapshot dict; ``"name:field"`` (e.g.
    ``"pml_send:p99"``) returns one histogram field as a float."""
    base, _, fieldname = name.partition(":")
    h = counters.SPC.get_histogram(base)
    if h is not None:
        snap = h.snapshot()
        return snap[fieldname] if fieldname else snap
    if fieldname:
        raise KeyError(f"no histogram pvar {base!r}")
    return counters.SPC.snapshot().get(name, 0.0)


def pvar_session() -> counters.PvarSession:
    """A pvar session: reads are deltas since session start (MPI_T
    pvar handle semantics — each tool sees its own baseline). Scalar
    deltas via ``read()``, histogram-class deltas via
    ``read_histograms()``."""
    return counters.PvarSession()


def categories() -> dict[str, dict[str, list[str]]]:
    """Group cvars AND pvars by framework (MPI_T categories = MCA
    frameworks; a pvar's framework is its subsystem name prefix).
    Each category maps to ``{"cvars": [...], "pvars": [...]}``."""
    cats: dict[str, dict[str, list[str]]] = {}

    def bucket(fw: str) -> dict[str, list[str]]:
        return cats.setdefault(fw, {"cvars": [], "pvars": []})

    for cv in cvar_list():
        bucket(cv.name.split("_", 1)[0])["cvars"].append(cv.name)
    for pv in pvar_list():
        bucket(pv["name"].split("_", 1)[0])["pvars"].append(pv["name"])
    return cats


# -- pvar watches (MPI_T event-callback analog) -----------------------------

@dataclass
class WatchHandle:
    """One registered watch. ``fired`` counts callback invocations;
    ``cancel()`` (or falling out of the registry via
    ``clear_watches``) retires it."""

    name: str
    threshold: float
    cb: Callable[[str, float], None]
    fired: int = 0
    last: Optional[float] = field(default=None, repr=False)
    _active: bool = field(default=True, repr=False)

    def cancel(self) -> None:
        self._active = False
        with _watch_lock:
            if self in _watches:
                _watches.remove(self)


_watches: list[WatchHandle] = []
_watch_lock = threading.Lock()


def pvar_watch(name: str, threshold: float,
               cb: Callable[[str, float], None]) -> WatchHandle:
    """Register ``cb(name, value)`` to fire when the pvar rises to (or
    above) ``threshold``. ``name`` accepts the same forms as
    ``pvar_read`` — a scalar counter, ``"hist:p99"`` for a histogram
    field, or a bare histogram name (watched as its sample count, the
    histogram's scalar value in ``pvar_list``). Evaluation is
    pull-based: nothing fires until
    ``check_watches()`` runs (the telemetry sampler calls it every
    tick). Semantics: fires on every observed increase while the value
    is at/above the threshold — a counter that keeps climbing past the
    threshold fires once per check that saw a rise, a gauge parked at
    a high value fires once."""
    h = WatchHandle(name=name, threshold=threshold, cb=cb)
    with _watch_lock:
        _watches.append(h)
    return h


def check_watches() -> list[str]:
    """Evaluate every registered watch against current pvar values;
    returns the names that fired. Callback exceptions are swallowed
    (a broken tool must not take the sampler down) but counted in the
    ``mpit_watch_errors`` pvar."""
    with _watch_lock:
        active = list(_watches)
    fired = []
    for h in active:
        if not h._active:
            continue
        try:
            raw = pvar_read(h.name)
            if isinstance(raw, dict):  # bare histogram: watch count
                raw = raw.get("count", 0)
            value = float(raw)
        except (KeyError, TypeError, ValueError):
            continue
        rose = h.last is None or value > h.last
        h.last = value
        if value >= h.threshold and rose:
            h.fired += 1
            fired.append(h.name)
            try:
                h.cb(h.name, value)
            except Exception:  # commlint: allow(broadexcept)
                counters.SPC.record("mpit_watch_errors")
    return fired


def watches() -> list[WatchHandle]:
    with _watch_lock:
        return list(_watches)


def clear_watches() -> None:
    """Retire every watch (tests / teardown)."""
    with _watch_lock:
        for h in _watches:
            h._active = False
        _watches.clear()

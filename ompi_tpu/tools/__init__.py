"""Tools (reference: ompi/tools — ompi_info, wrappers, mpisync)."""

"""armada CLI — run, replay, and diff fleet-simulator scenarios.

Usage:
    python -m ompi_tpu.tools.sim run <scenario.json> [--json out.json]
    python -m ompi_tpu.tools.sim run --ranks 1024 --duration 20 \\
        --tenants 32 --seed 7 --fault "3.0:host_loss@fleet:host=9"
    python -m ompi_tpu.tools.sim replay <scenario.json> \\
        [--reference report.json]
    python -m ompi_tpu.tools.sim diff <report_a.json> <report_b.json>

``run`` executes a scenario (from a file, or assembled from flags)
through the real control planes under virtual time and prints the
report; ``--json`` also writes it to a file a later ``replay
--reference`` can verify against. ``replay`` re-runs the scenario and
checks the merged decision-log digest is byte-identical (running the
scenario twice when no reference report is given). ``diff`` compares
two saved reports subsystem-by-subsystem.

Exit codes: 0 ok (replay matched / reports agree), 1 digest mismatch,
2 the run itself failed.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..sim.engine import Scenario
from ..sim.replay import diff, load_scenario, replay, run_scenario


def _fault(spec: str) -> dict:
    """Parse ``AT:action@layer:k=v`` into a scenario fault entry."""
    at, sep, rest = spec.partition(":")
    if not sep:
        raise argparse.ArgumentTypeError(
            f"fault {spec!r}: expected AT:action@layer:k=v")
    try:
        return {"at": float(at), "spec": rest}
    except ValueError as exc:
        raise argparse.ArgumentTypeError(
            f"fault {spec!r}: bad fire time {at!r}") from exc


def _scenario_from_args(args) -> Scenario:
    if args.scenario:
        sc = load_scenario(args.scenario)
        if args.seed is not None:
            sc.seed = args.seed
        return sc
    return Scenario(
        name=args.name,
        seed=args.seed if args.seed is not None else 0,
        nranks=args.ranks,
        duration_s=args.duration,
        tenants=args.tenants,
        base_rps=args.rps,
        faults=[dict(f) for f in args.fault],
    )


def _emit(report: dict, path: str | None) -> None:
    blob = json.dumps(report, indent=1, sort_keys=True)
    if path:
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(blob + "\n")
    print(blob)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ompi_tpu.tools.sim",
        description="armada fleet-simulator scenarios over the real "
                    "control planes")
    sub = ap.add_subparsers(dest="cmd", required=True)

    run_p = sub.add_parser("run", help="run a scenario, print report")
    rep_p = sub.add_parser("replay",
                           help="re-run + verify decision-log digest")
    for p in (run_p, rep_p):
        p.add_argument("scenario", nargs="?", default=None,
                       help="scenario JSON file (omit to build from "
                            "flags)")
        p.add_argument("--seed", type=int, default=None)
        p.add_argument("--name", default="cli")
        p.add_argument("--ranks", type=int, default=64)
        p.add_argument("--duration", type=float, default=10.0)
        p.add_argument("--tenants", type=int, default=8)
        p.add_argument("--rps", type=float, default=100.0)
        p.add_argument("--fault", action="append", type=_fault,
                       default=[],
                       help="AT:action@layer:k=v (repeatable)")
        p.add_argument("--json", dest="json_out", default=None,
                       help="also write the report/result here")
    rep_p.add_argument("--reference", default=None,
                       help="saved report to verify the digest "
                            "against (default: run twice)")

    diff_p = sub.add_parser("diff",
                            help="compare two saved reports' digests")
    diff_p.add_argument("report_a")
    diff_p.add_argument("report_b")

    args = ap.parse_args(argv)

    if args.cmd == "diff":
        with open(args.report_a, encoding="utf-8") as fh:
            a = json.load(fh)
        with open(args.report_b, encoding="utf-8") as fh:
            b = json.load(fh)
        mismatch = diff(a, b)
        _emit({"ok": not mismatch, "mismatch": mismatch}, None)
        return 0 if not mismatch else 1

    try:
        sc = _scenario_from_args(args)
    except (OSError, ValueError) as exc:
        print(f"sim: bad scenario: {exc}", file=sys.stderr)
        return 2

    if args.cmd == "run":
        _emit(run_scenario(sc), args.json_out)
        return 0

    reference = None
    if args.reference:
        with open(args.reference, encoding="utf-8") as fh:
            reference = json.load(fh)
    res = replay(sc, reference)
    _emit({"ok": res["ok"], "digest": res["digest"],
           "reference_digest": res["reference_digest"],
           "mismatch": res["mismatch"]}, args.json_out)
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

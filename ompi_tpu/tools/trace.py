"""trace — merge per-rank commtrace dumps into one Perfetto timeline.

Offline counterpart of the finalize-time modex gather (trace/__init__
``at_finalize``): each rank leaves ``ompi_tpu-trace-rank<r>.json`` in
``trace_base_dir``; this tool loads any number of them, aligns their
clocks with the mpisync offsets stamped in each dump, and writes one
Chrome/Perfetto trace_event JSON. Open the result at ui.perfetto.dev
(or chrome://tracing). ``--timeline`` additionally prints the
per-collective cross-rank text timeline on stdout.

Usage::

    python -m ompi_tpu.tools.trace rank0.json rank1.json -o merged.json
    python -m ompi_tpu.tools.trace --dir /tmp/traces --timeline
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from ..trace import export


def load_dump(path: str) -> dict:
    with open(path) as f:
        d = json.load(f)
    if d.get("format") != "ompi_tpu-trace-v1":
        raise SystemExit(f"{path}: not an ompi_tpu trace dump "
                         f"(format={d.get('format')!r})")
    return d


def find_dumps(directory: str) -> list[str]:
    pats = (os.path.join(directory, "ompi_tpu-trace-rank*.json"),)
    found: list[str] = []
    for pat in pats:
        found.extend(sorted(glob.glob(pat)))
    return found


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ompi_tpu.tools.trace",
        description="Merge per-rank commtrace dumps into one "
        "Perfetto trace_event JSON.",
    )
    ap.add_argument("dumps", nargs="*",
                    help="per-rank dump files (ompi_tpu-trace-rank*.json)")
    ap.add_argument("--dir", default=None,
                    help="scan a directory for rank dumps")
    ap.add_argument("-o", "--output", default="trace-merged.json",
                    help="merged Perfetto JSON path "
                    "(default: %(default)s)")
    ap.add_argument("--no-align", action="store_true",
                    help="skip mpisync clock alignment (raw per-rank "
                    "monotonic clocks)")
    ap.add_argument("--timeline", action="store_true",
                    help="also print the per-collective cross-rank "
                    "timeline")
    args = ap.parse_args(argv)

    paths = list(args.dumps)
    if args.dir:
        paths.extend(find_dumps(args.dir))
    if not paths:
        ap.error("no dump files given (pass paths or --dir)")
    # de-dup while keeping order (a path may be both explicit and
    # found by --dir)
    seen: set[str] = set()
    paths = [p for p in paths if not (p in seen or seen.add(p))]

    dumps = [load_dump(p) for p in paths]
    align = not args.no_align
    merged = export.perfetto(dumps, align=align)
    with open(args.output, "w") as f:
        json.dump(merged, f)
    ranks = sorted(d.get("rank", 0) for d in dumps)
    print(f"merged {len(dumps)} rank dump(s) (ranks {ranks}) -> "
          f"{args.output}: {len(merged['traceEvents'])} events")
    if args.timeline:
        print("per-collective timeline:")
        for line in export.timeline(dumps, align=align).splitlines():
            print(" ", line)
    return 0


if __name__ == "__main__":
    sys.exit(main())

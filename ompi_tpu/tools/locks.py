"""locksmith CLI — inspect the whole-program lock model.

Usage:
    python -m ompi_tpu.tools.locks [<root>]            # summary tables
    python -m ompi_tpu.tools.locks --graph             # order edges
    python -m ompi_tpu.tools.locks --dot > locks.dot   # GraphViz export
    python -m ompi_tpu.tools.locks --json              # machine-readable

The default root is the ompi_tpu package itself.  Output sections:

- **inventory**: every ``threading.Lock/RLock/Condition`` bound to a
  module global or ``self.`` attribute, with creation site and owner
  (a ``Condition(self._mu)`` shows as an alias of the underlying
  lock);
- **threads**: every ``threading.Thread(target=...)`` spawn site with
  the resolved target;
- **holders/waiters**: per lock, which functions acquire it directly,
  and which order edges *wait* on it while holding something else;
- **graph/cycles**: the lock-order edges with their witness chains;
  cycles (potential deadlocks) render with the full chain and exit 1.

Exit codes: 0 clean, 1 lock-order cycles found, 2 run failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

DEFAULT_ROOT = os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))


def _analysis(root: str):
    from ..analysis.index import ProjectIndex

    index = ProjectIndex.build(root)
    return index, index.locksmith()


def _render_inventory(index) -> list[str]:
    lines = [f"lock inventory ({len(index.locks)}):"]
    for key in sorted(index.locks):
        li = index.locks[key]
        alias = f" (alias of {li.alias_of})" if li.alias_of else ""
        lines.append(f"  {li.kind:<10} {key}  "
                     f"[{li.relpath}:{li.line}]{alias}")
    lines.append(f"thread spawns ({len(index.threads)}):")
    for t in index.threads:
        lines.append(f"  {t.relpath}:{t.line}  target="
                     f"{t.target or '<unresolved>'} ({t.target_text})")
    return lines


def _render_holders(an) -> list[str]:
    lines = ["holders (functions acquiring each lock directly):"]
    for lock, fns in an.holders().items():
        lines.append(f"  {lock}:")
        for fn in fns:
            lines.append(f"    {fn}")
    waiters = an.waiters()
    lines.append("waiters (acquired while another lock is held):")
    if not waiters:
        lines.append("  (none)")
    for lock, edges in waiters.items():
        lines.append(f"  {lock}:")
        for e in edges:
            lines.append(f"    while holding {e.src}  "
                         f"[{e.witness[0].render()}]")
    return lines


def _render_graph(an) -> list[str]:
    lines = [f"lock-order edges ({len(an.edges)}):"]
    for key in sorted(an.edges):
        lines.append(f"  {an.edges[key].render()}")
    if an.cycles:
        lines.append(f"CYCLES ({len(an.cycles)}) — potential deadlocks:")
        for cyc in an.cycles:
            locks = [e.src for e in cyc] + [cyc[0].src]
            lines.append(f"  {' -> '.join(locks)}")
            for e in cyc:
                lines.append(f"    {e.render()}")
    else:
        lines.append("no cycles")
    return lines


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ompi_tpu.tools.locks",
        description="whole-program lock inventory, order graph, and "
                    "deadlock-cycle report",
    )
    ap.add_argument("root", nargs="?", default=DEFAULT_ROOT,
                    help="package directory to analyze "
                         "(default: the ompi_tpu package)")
    ap.add_argument("--graph", action="store_true",
                    help="order edges + cycles only")
    ap.add_argument("--dot", action="store_true",
                    help="GraphViz digraph on stdout")
    ap.add_argument("--json", dest="as_json", action="store_true",
                    help="machine-readable dump")
    args = ap.parse_args(argv)

    try:
        index, an = _analysis(args.root)
    except (OSError, ValueError) as exc:
        print(f"locks: {exc}", file=sys.stderr)
        return 2

    if args.dot:
        print(an.to_dot())
        return 1 if an.cycles else 0

    if args.as_json:
        print(json.dumps({
            "locks": {
                k: {"kind": li.kind, "site": f"{li.relpath}:{li.line}",
                    "owner": li.owner, "alias_of": li.alias_of}
                for k, li in sorted(index.locks.items())
            },
            "threads": [
                {"site": f"{t.relpath}:{t.line}", "target": t.target,
                 "target_text": t.target_text}
                for t in index.threads
            ],
            "edges": [
                {"src": e.src, "dst": e.dst,
                 "witness": [f.render() for f in e.witness]}
                for _, e in sorted(an.edges.items())
            ],
            "cycles": [
                [{"src": e.src, "dst": e.dst} for e in cyc]
                for cyc in an.cycles
            ],
            "findings": [
                {"rule": f.rule, "severity": f.severity.name,
                 "where": f"{f.path}:{f.line}", "message": f.message}
                for f in an.findings
            ],
        }, indent=2))
        return 1 if an.cycles else 0

    lines: list[str] = []
    if not args.graph:
        lines += _render_inventory(index)
        lines += _render_holders(an)
    lines += _render_graph(an)
    print("\n".join(lines))
    return 1 if an.cycles else 0


if __name__ == "__main__":
    sys.exit(main())

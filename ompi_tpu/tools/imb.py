"""IMB-MPI1-style benchmark harness.

The reference delegates perf measurement to the Intel MPI Benchmarks
(reference: NEWS:249 lists IMB among the external suites; BASELINE.md's
target metric is "IMB-MPI1 Allreduce GB/s + p50 latency vs message size
4B-1GB"). This is that harness for ompi_tpu: sweep message sizes per
collective, report p50/min latency and effective bandwidth.

    python -m ompi_tpu.tools.imb --ops allreduce,bcast --max-bytes 4194304

Timing notes: each (op, size) is run `--iters` times after a warmup
call that triggers plan compilation; latency includes the full
framework dispatch path (what a user sees per call). On tunneled
single-chip setups the constant RPC round-trip dominates small sizes —
use bench.py's chained-iteration method for pure device throughput.
"""

from __future__ import annotations

import argparse
import json
import time
from dataclasses import dataclass

import numpy as np

OPS = ("allreduce", "bcast", "reduce", "allgather",
       "reduce_scatter_block", "alltoall", "barrier",
       "gather", "scatter", "scan", "exscan")


@dataclass
class Row:
    op: str
    nbytes: int
    p50_us: float
    min_us: float
    gbps: float


def _buffer(comm, op: str, nbytes: int):
    n = comm.size
    elems = max(1, nbytes // 4)
    if op in ("alltoall", "reduce_scatter_block"):
        data = np.ones((n, n, max(1, elems // n)), np.float32)
    else:
        data = np.ones((n, elems), np.float32)
    return comm.put_rank_major(data)


def _traffic_bytes(op: str, nbytes: int, n: int) -> float:
    """Algorithmic bus bytes per rank (IMB conventions)."""
    if op == "allreduce":
        return 2 * (n - 1) / n * nbytes
    if op in ("bcast", "reduce"):
        return nbytes
    if op in ("allgather", "alltoall"):
        return (n - 1) / n * nbytes
    if op == "reduce_scatter_block":
        return (n - 1) / n * nbytes
    if op in ("gather", "scatter", "scan", "exscan"):
        return nbytes
    return 0.0


def run_one(comm, op: str, nbytes: int, iters: int) -> Row:
    import jax

    x = None if op == "barrier" else _buffer(comm, op, nbytes)

    def call():
        if op == "barrier":
            comm.barrier()
            return None
        if op in ("gather", "scatter"):
            return getattr(comm, op)(x, root=0)
        return getattr(comm, op)(x)

    out = call()  # warmup/compile
    if out is not None:
        jax.block_until_ready(out)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = call()
        if out is not None:
            jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    p50 = float(np.median(times))
    tmin = float(np.min(times))
    traffic = _traffic_bytes(op, nbytes, comm.size)
    gbps = traffic / tmin / 1e9 if traffic else 0.0
    return Row(op, nbytes, p50 * 1e6, tmin * 1e6, gbps)


def sweep(comm, ops, min_bytes: int, max_bytes: int, iters: int
          ) -> list[Row]:
    rows = []
    for op in ops:
        if op == "barrier":
            rows.append(run_one(comm, op, 0, iters))
            continue
        size = min_bytes
        while size <= max_bytes:
            rows.append(run_one(comm, op, size, iters))
            size *= 4
    return rows


def render(rows: list[Row]) -> str:
    lines = [
        f"{'op':>22} {'bytes':>12} {'p50 us':>10} {'min us':>10} "
        f"{'GB/s':>8}"
    ]
    for r in rows:
        lines.append(
            f"{r.op:>22} {r.nbytes:>12} {r.p50_us:>10.1f} "
            f"{r.min_us:>10.1f} {r.gbps:>8.2f}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ompi_tpu.tools.imb")
    ap.add_argument("--ops", default="allreduce,bcast,alltoall,barrier")
    ap.add_argument("--min-bytes", type=int, default=4)
    ap.add_argument("--max-bytes", type=int, default=1 << 22)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    ops = [o.strip() for o in args.ops.split(",") if o.strip()]
    bad = [o for o in ops if o not in OPS]
    if bad:
        raise SystemExit(f"unknown ops {bad}; known: {OPS}")

    import ompi_tpu

    comm = ompi_tpu.init()
    rows = sweep(comm, ops, args.min_bytes, args.max_bytes, args.iters)
    if args.json:
        print(json.dumps([r.__dict__ for r in rows]))
    else:
        print(f"# ompi_tpu IMB-style sweep, {comm.size} ranks")
        print(render(rows))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

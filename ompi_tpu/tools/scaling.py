"""Launch-scaling probe: init time + memory footprint vs rank count.

TPU-native equivalent of contrib/scaling (reference: scaling.pl +
mpi_no_op.c + mpi_memprobe.c — measure launch wall time and per-proc
memory at increasing scale, SURVEY §4 "Scale/launch tests"). Driver
form: subprocesses with growing virtual device counts measure
init→world→barrier→finalize wall time and peak RSS.

    python -m ompi_tpu.tools.scaling --ranks 1,2,4,8
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys

_PROBE = r"""
import os, resource, time, sys
n = int(sys.argv[1])
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={n}"
)
import jax
jax.config.update("jax_platforms", "cpu")
t0 = time.perf_counter()
import ompi_tpu
comm = ompi_tpu.init()
t_init = time.perf_counter() - t0
assert comm.size == n, (comm.size, n)
t1 = time.perf_counter()
comm.barrier()
t_barrier = time.perf_counter() - t1
ompi_tpu.finalize()
rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
print(__import__("json").dumps(
    {"ranks": n, "init_s": round(t_init, 3),
     "first_barrier_s": round(t_barrier, 3),
     "peak_rss_mb": round(rss_mb, 1)}
))
"""


def probe(n: int, timeout: float = 300.0) -> dict:
    out = subprocess.run(
        [sys.executable, "-c", _PROBE, str(n)],
        capture_output=True, text=True, timeout=timeout,
    )
    if out.returncode != 0:
        raise RuntimeError(
            f"scaling probe n={n} failed:\n{out.stderr[-2000:]}"
        )
    return json.loads(out.stdout.strip().splitlines()[-1])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ompi_tpu.tools.scaling")
    ap.add_argument("--ranks", default="1,2,4,8")
    args = ap.parse_args(argv)
    print(f"{'ranks':>6} {'init s':>8} {'barrier s':>10} {'rss MB':>8}")
    for n in (int(x) for x in args.ranks.split(",")):
        r = probe(n)
        print(
            f"{r['ranks']:>6} {r['init_s']:>8.3f} "
            f"{r['first_barrier_s']:>10.3f} {r['peak_rss_mb']:>8.1f}"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

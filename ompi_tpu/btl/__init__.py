"""Inter-device transfer layer (reference: opal/mca/btl)."""

from .framework import BTL, Bml, BtlComponent

__all__ = ["BTL", "Bml", "BtlComponent"]

"""Inter-device transfer layer (reference: opal/mca/btl)."""

from .framework import BTL, Bml, BtlComponent
from . import dcn, sm, template  # noqa: F401 - register components

__all__ = ["BTL", "Bml", "BtlComponent", "dcn", "sm", "template"]

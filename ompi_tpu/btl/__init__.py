"""Inter-device transfer layer (reference: opal/mca/btl)."""

from .framework import BTL, Bml, BtlComponent
from . import dcn, template  # noqa: F401 - register btl/dcn, btl/template

__all__ = ["BTL", "Bml", "BtlComponent", "dcn", "template"]

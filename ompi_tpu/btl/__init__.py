"""Inter-device transfer layer (reference: opal/mca/btl)."""

from .framework import BTL, Bml, BtlComponent
from . import dcn  # noqa: F401 - registers btl/dcn

__all__ = ["BTL", "Bml", "BtlComponent", "dcn"]

"""BTL — byte/buffer transfer layer between rank-devices.

TPU-native equivalent of opal/mca/btl (reference: btl.h:1210-1219 module
struct with eager/rndv/max-send limits; btl/self, btl/sm, btl/smcuda,
btl/tcp) plus the BML multiplexer choosing a BTL per peer (reference:
bml/r2, bml_r2.c:131-148 latency/bandwidth-weighted endpoint arrays).

On TPU the "byte transfer" is an array transfer between devices:

- ``self``: same device — no movement (reference: btl/self loopback).
- ``ici``: devices on the same host/slice — jax.device_put rides the
  ICI/DMA path with device-resident buffers end to end (reference
  analog: btl/sm + btl/smcuda's CUDA-IPC device-to-device path).
- ``dcn`` (future): devices owned by different host processes — the
  btl/tcp analog over DCN sockets.

Each BTL advertises `eager_limit`: payloads at or below it are shipped
immediately on send (possibly before the recv is posted — "unexpected"
delivery buffered at the destination); larger payloads use the PML's
rendezvous protocol and move only once the recv is matched (reference:
ob1's eager/rndv split, pml_ob1_sendreq.h:385-455).
"""

from __future__ import annotations

from typing import Any, Optional

from ..core import component as mca
from ..core import config
from ..core.errors import CommError

BTL = mca.framework("btl", "inter-device transfer layer")


class BtlComponent(mca.Component):
    """A transfer method between a pair of rank-devices."""

    #: bytes at/below which sends ship immediately (reference default
    #: lineage: tcp 64KiB, sm 32KiB — btl_tcp_component.c:322,
    #: btl_sm_component.c:243)
    EAGER_LIMIT = 64 * 1024

    def __init__(self, framework: mca.Framework) -> None:
        super().__init__(framework)
        self._eager_var = config.register(
            framework.name,
            self.NAME,
            "eager_limit",
            type=int,
            default=self.EAGER_LIMIT,
            description=f"Eager-send byte limit for btl/{self.NAME}",
        )

    @property
    def eager_limit(self) -> int:
        return self._eager_var.value

    def can_reach(self, src_proc, dst_proc) -> bool:
        raise NotImplementedError

    def transfer(self, value: Any, src_proc, dst_proc) -> Any:
        """Move a device value to dst's device (async; returns the new
        array immediately, completion = array readiness)."""
        raise NotImplementedError

    def wire_label(self, comm, src_rank: int, dst_rank: int) -> str:
        """comm_method detail string for this pair. Components that mux
        several mechanisms behind one name (sm: descriptor fastpath,
        CMA pull, eager rings) append the negotiated lanes, e.g.
        "sm/fp+cma". Base: just the component name."""
        return self.NAME


@BTL.register
class SelfBtl(BtlComponent):
    """Loopback: source and destination are the same device."""

    NAME = "self"
    PRIORITY = 100
    EAGER_LIMIT = 1 << 62  # no copy, no reason to delay

    def can_reach(self, src_proc, dst_proc) -> bool:
        return src_proc.device == dst_proc.device

    def transfer(self, value, src_proc, dst_proc):
        return value


@BTL.register
class IciBtl(BtlComponent):
    """Device-to-device transfer within one host process (ICI/DMA path)."""

    NAME = "ici"
    PRIORITY = 50
    EAGER_LIMIT = 64 * 1024

    def can_reach(self, src_proc, dst_proc) -> bool:
        return src_proc.process_index == dst_proc.process_index

    def transfer(self, value, src_proc, dst_proc):
        import jax

        return jax.device_put(value, dst_proc.device)


class Bml:
    """Per-communicator endpoint table: the chosen BTL per peer pair
    (reference: bml/r2 building per-proc endpoint arrays)."""

    def __init__(self, comm) -> None:
        self._comm = comm
        self._cache: dict[tuple[int, int], BtlComponent] = {}

    def btl_for(self, src_rank: int, dst_rank: int) -> BtlComponent:
        key = (src_rank, dst_rank)
        btl = self._cache.get(key)
        if btl is None:
            src = self._comm.procs[src_rank]
            dst = self._comm.procs[dst_rank]
            for cand in BTL.select_all():
                if cand.can_reach(src, dst):
                    btl = cand
                    break
            if btl is None:
                raise CommError(
                    f"no btl reaches rank {src_rank}->{dst_rank} "
                    f"({src.device} -> {dst.device})"
                )
            # faultline interposes at BML selection (sanitizer
            # pattern): sm transfers consult the armed plan.
            if btl.NAME == "sm":
                from ..ft import inject

                btl = inject.maybe_wrap_sm(btl)
            # once per pair: record which wire won the reachability
            # race (the hook_comm_method story, now on the timeline)
            from ..trace import span as tspan

            tspan.instant("btl.select", cat="btl", src=src_rank,
                          dst=dst_rank, btl=btl.NAME)
            self._cache[key] = btl
        return btl

    def wire_label(self, src_rank: int, dst_rank: int) -> str:
        """The selected BTL's lane-qualified label for this pair
        (reference: hook_comm_method printing the chosen mechanism)."""
        btl = self.btl_for(src_rank, dst_rank)
        return btl.wire_label(self._comm, src_rank, dst_rank)

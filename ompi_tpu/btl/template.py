"""btl/template — scaffold + test-double transport.

TPU-native equivalent of opal/mca/btl/template (reference: the scaffold
for writing a new BTL, 1,436 LoC of commented stubs) crossed with the
reference test strategy of using scaffolds as mocks (SURVEY §4). Copy
this file to start a new transport; registered but disabled by default
(priority 0, available() False unless the test flag is set). When
enabled it records every transfer so tests can assert on traffic.
"""

from __future__ import annotations

from typing import Any

from ..core import config
from .framework import BTL, BtlComponent

_enable = config.register(
    "btl", "template", "enable", type=bool, default=False,
    description="Enable the template/test-double BTL",
)


@BTL.register
class TemplateBtl(BtlComponent):
    NAME = "template"
    PRIORITY = 0
    EAGER_LIMIT = 4 * 1024
    DESCRIPTION = "scaffold transport (test double)"

    def __init__(self, framework) -> None:
        super().__init__(framework)
        #: every transfer as (src_device, dst_device, nbytes)
        self.transfers: list[tuple] = []

    def available(self, **ctx: Any) -> bool:
        return _enable.value

    def can_reach(self, src_proc, dst_proc) -> bool:
        # reach everything — tests drive exact routing through config
        return True

    def transfer(self, value, src_proc, dst_proc):
        import jax

        nbytes = sum(
            getattr(l, "nbytes", 0) for l in jax.tree.leaves(value)
        )
        self.transfers.append(
            (str(src_proc.device), str(dst_proc.device), nbytes)
        )
        return jax.device_put(value, dst_proc.device)

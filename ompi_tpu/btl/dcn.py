"""btl/dcn — inter-host transport over the native TCP engine.

TPU-native equivalent of opal/mca/btl/tcp (reference:
btl_tcp_component.c eager 64K / max-send 128K, btl_tcp_endpoint.c
connection FSM, multi-link striping). The compiled engine
(native/src/dcn.cc) owns sockets, framing, the eager/rndv protocol and
an epoll progress thread; this module is the endpoint/bytes API plus
the BTL component that plugs it into the BML.

Role in the TPU design (SURVEY §5.8): ICI moves device buffers inside a
slice (btl/ici); DCN is the btl/tcp domain *between* host processes —
arrays stage through the host pool, cross the wire, and are re-placed
on the destination's devices. Within one driver process the component
stays idle (ici wins); `DcnEndpoint` is also usable standalone as the
multi-host wire (the modex analog exchanges host:port pairs).
"""

from __future__ import annotations

import contextlib
import time
from collections import deque
from typing import Any, Optional

import numpy as np

from ..core import config
from ..core.counters import SPC
from ..core.errors import CommError, OmpiTpuError
from ..core.logging import get_logger
from ..native import build, mempool
from .framework import BTL, BtlComponent

logger = get_logger("btl.dcn")

_links = config.register(
    "btl", "dcn", "links", type=int, default=2,
    description="TCP links per peer for striping (reference tcp multi-link)",
)
_connect_timeout = config.register(
    "btl", "dcn", "connect_timeout_ms", type=int, default=5000,
    description="Per-link connect timeout (reference tcp connect FSM)",
)
_send_retry = config.register(
    "btl", "dcn", "send_retry_ms", type=int, default=200,
    description="How long a failed send retries with backoff before "
    "escalating (rides out in-flight link failover)",
)


class DcnError(OmpiTpuError):
    errclass = "ERR_OTHER"


class DcnEndpoint:
    """One process's DCN presence: a listener plus per-peer links."""

    def __init__(self, bind_ip: str = "127.0.0.1", port: int = 0) -> None:
        self._lib = build.get_lib()
        if self._lib is None or not hasattr(self._lib, "dcn_create"):
            raise DcnError("native DCN engine unavailable")
        import ctypes

        actual = ctypes.c_int(0)
        self._ctx = self._lib.dcn_create(
            bind_ip.encode(), port, ctypes.byref(actual)
        )
        if not self._ctx:
            raise DcnError(f"cannot bind DCN listener on {bind_ip}:{port}")
        self.address = (bind_ip, actual.value)
        self.listeners: list[tuple[str, int]] = [self.address]
        # One knob for the eager/rndv split: the framework-registered
        # btl_dcn_eager_limit var (what the BML/PML layers also read).
        self._lib.dcn_set_eager(
            self._ctx,
            config.get("btl_dcn_eager_limit", DcnBtl.EAGER_LIMIT),
        )
        self._pool = mempool.shared_pool()
        # Zero-copy send pins: msgid -> buffer, released at completion.
        self._send_refs: dict[int, Any] = {}
        # Lossless: ids already drained from the engine must stay
        # claimable by explicit pollers (an int per unclaimed send —
        # negligible next to the payloads; cleared on close()).
        self._pending_send_done: deque[int] = deque()
        import threading

        self._inflight_waits = 0  # threads inside a native blocking wait
        self._wait_mu = threading.Lock()  # guards the count + closing
        # Serializes native send + pin registration against completion
        # polling so a completion id can't be claimed between
        # dcn_send_ref returning and the pin landing in _send_refs
        # (which would leave the payload pinned until close()).
        self._send_mu = threading.Lock()
        self._closed = False
        # Link-failover bookkeeping: last observed live-link count per
        # peer, so heal_links can tell "lost a link, survivors remain"
        # (re-stripe) from "endpoint dead" (escalate).
        self._peer_links_seen: dict[int, int] = {}

    @contextlib.contextmanager
    def _native_call(self, *, what: str):
        """Guard for any data-plane entry into the native Ctx: bounce
        when closed (DcnError on enter) and count the call in
        _inflight_waits so close()'s drain loop waits for it before
        dcn_destroy (otherwise the _closed check is a TOCTOU and the
        native call can run on a freed context)."""
        with self._wait_mu:
            if self._closed:
                raise DcnError(f"endpoint closed during {what}")
            self._inflight_waits += 1
        try:
            yield
        finally:
            with self._wait_mu:
                self._inflight_waits -= 1

    # -- wiring ------------------------------------------------------------

    def listen_on(self, ip: str, port: int = 0) -> tuple[str, int]:
        """Bind an ADDITIONAL listener on a specific local interface
        address (reference: btl/tcp opens a listening endpoint per
        usable interface and publishes them all). Returns (ip, port)
        and records it in `self.listeners`."""
        actual = int(self._lib.dcn_listen_add(self._ctx, ip.encode(),
                                              port))
        if actual < 0:
            raise DcnError(f"cannot bind extra DCN listener on {ip}")
        self.listeners.append((ip, actual))
        return (ip, actual)

    def connect_pairs(self, pairs, *, cookie: int,
                      timeout_ms: Optional[int] = None) -> int:
        """Open one link per (local_ip | None, remote_ip, remote_port)
        pair, all grouped under ONE peer (the multi-NIC endpoint:
        distinct (local if, remote if) socket pairs, reference
        btl_tcp_proc.c address matching). Returns the peer id."""
        if not pairs:
            raise DcnError("connect_pairs needs at least one pair")
        if cookie <= 0:
            raise DcnError("cookie must be > 0")
        tmo = timeout_ms if timeout_ms is not None \
            else _connect_timeout.value
        from ..core.backoff import Backoff

        # One retry budget shared by the whole call (cold-start race:
        # the peer's listeners may come up late) — refused pairs back
        # off and retry until the budget runs out, then each remaining
        # pair still gets its single attempt.
        bo = Backoff(initial=0.02, maximum=0.25, timeout=tmo / 1000.0)
        peer = -1
        failed = []
        for local_ip, ip, port in pairs:
            while True:
                got = self._lib.dcn_connect_from(
                    self._ctx, peer,
                    (local_ip or "").encode(), ip.encode(), port, 1,
                    cookie, max(1, int(min(tmo, bo.remaining() * 1000))),
                )
                if got >= 0 or not bo.sleep():
                    break
                SPC.record("dcn_connect_retries")
            if got < 0:
                # CQ scores are heuristics, not reachability probes: a
                # failed pair degrades the peer to fewer links instead
                # of aborting (and orphaning) the connected ones
                failed.append((local_ip, ip, port))
                continue
            peer = got
        if peer < 0:
            raise DcnError(f"all link pairs failed: {failed}")
        if failed:
            logger.warning("multi-NIC peer degraded: %d/%d pairs "
                           "failed (%s)", len(failed), len(pairs),
                           failed)
        self._peer_links_seen[int(peer)] = self.peer_links(int(peer))
        return int(peer)

    def link_addrs(self, peer: int) -> list[tuple[str, str]]:
        """(local 'ip:port', remote 'ip:port') per live link of a peer
        — striping/multi-NIC observability."""
        import ctypes

        out = []
        idx = 0
        while True:
            lo = ctypes.create_string_buffer(64)
            ro = ctypes.create_string_buffer(64)
            rc = self._lib.dcn_link_addr(self._ctx, peer, idx, lo, ro,
                                         64)
            if rc != 0:
                break
            out.append((lo.value.decode(), ro.value.decode()))
            idx += 1
        return out

    def connect(self, ip: str, port: int, *, cookie: int,
                nlinks: Optional[int] = None,
                timeout_ms: Optional[int] = None) -> int:
        """Open striped links to a peer listener; returns the local peer
        id. `cookie` must be globally unique per connecting endpoint
        (the modex rank works) so the passive side can group links.

        Refused connections retry with exponential backoff until
        `connect_timeout` — at job start the peer's listener may simply
        not be up yet (the cold-start race between controllers; the
        reference's connect FSM retries the same way)."""
        if cookie <= 0:
            raise DcnError("cookie must be > 0")
        n = nlinks if nlinks is not None else max(1, _links.value)
        tmo = timeout_ms if timeout_ms is not None \
            else _connect_timeout.value
        from ..core.backoff import Backoff

        bo = Backoff(initial=0.02, maximum=0.25, timeout=tmo / 1000.0)
        while True:
            remaining_ms = max(1, int(bo.remaining() * 1000))
            peer = self._lib.dcn_connect(
                self._ctx, ip.encode(), port, n, cookie, remaining_ms,
            )
            if peer >= 0:
                self._peer_links_seen[int(peer)] = \
                    self.peer_links(int(peer))
                return int(peer)
            if not bo.sleep():
                raise DcnError(
                    f"connect to {ip}:{port} failed after "
                    f"{bo.attempts + 1} attempt(s) over {tmo} ms"
                )
            SPC.record("dcn_connect_retries")

    # -- data --------------------------------------------------------------

    def send_bytes(self, peer: int, tag: int, data) -> int:
        buf = np.ascontiguousarray(np.frombuffer(data, np.uint8))
        self.heal_links(peer)
        bo = None
        while True:
            with self._native_call(what="send"), self._send_mu:
                msgid = self._lib.dcn_send_ref(
                    self._ctx, peer, tag, buf.ctypes.data, buf.nbytes
                )
                if msgid >= 0:
                    # Zero-copy contract: the engine references `buf`
                    # directly for rendezvous payloads; pin it until
                    # the completion id pops. Registration happens
                    # under _send_mu so a concurrent poll_send_complete
                    # can't claim the id first. Every send also drains
                    # finished completions so non-polling callers don't
                    # keep flushed payloads pinned; drained ids are
                    # preserved losslessly for explicit pollers.
                    self._send_refs[int(msgid)] = buf
                    while True:
                        done = int(self._lib.dcn_poll_send(self._ctx))
                        if not done:
                            break
                        self._send_refs.pop(done, None)
                        self._pending_send_done.append(done)
                    SPC.record("dcn_send_bytes", buf.nbytes)
                    return int(msgid)
            # Send refused: the peer is unknown, or every link dropped
            # in-flight. Retry briefly with backoff — the passive side
            # of a failover may still be re-establishing links — then
            # escalate through check_peer (DEVICE_ERROR only when the
            # whole endpoint is dead, keeping elastic.watch_dcn
            # semantics).
            if peer not in self._peer_links_seen:
                raise DcnError(f"send to unknown peer {peer}")
            if bo is None:
                from ..core.backoff import Backoff

                bo = Backoff(initial=0.005, maximum=0.05,
                             timeout=_send_retry.value / 1000.0)
            if not bo.sleep():
                self.check_peer(peer, what="send to peer")
                raise DcnError(f"send to peer {peer} failed")
            SPC.record("dcn_send_retries")

    def _consume_receipt(self, msgid: int, peer, tag, length
                         ) -> tuple[int, int, bytes]:
        try:
            block = self._pool.alloc(max(1, length.value))
        except mempool.PoolExhausted:
            # Oversized/late message: fall back to a one-off buffer —
            # the receipt must be consumed either way or it leaks.
            block = mempool.Block(
                self._pool, -1, np.empty(max(1, length.value), np.uint8)
            )
        with block:
            got = self._lib.dcn_read(
                self._ctx, msgid, block.view.ctypes.data, length.value
            )
            if got != length.value:
                raise DcnError(
                    f"short read {got} != {length.value} for msg {msgid}"
                )
            payload = block.view[:length.value].tobytes()
        SPC.record("dcn_recv_bytes", length.value)
        return int(peer.value), int(tag.value), payload

    def poll_recv(self) -> Optional[tuple[int, int, bytes]]:
        """(peer, tag, payload) of one completed message, or None."""
        import ctypes

        peer = ctypes.c_int(0)
        tag = ctypes.c_longlong(0)
        length = ctypes.c_longlong(0)
        msgid = self._lib.dcn_poll_recv(
            self._ctx, ctypes.byref(peer), ctypes.byref(tag),
            ctypes.byref(length),
        )
        if msgid == 0:
            return None
        return self._consume_receipt(msgid, peer, tag, length)

    def recv_bytes(self, timeout: float = 10.0) -> tuple[int, int, bytes]:
        """Blocking receive: parks on the engine's completion condition
        variable (in <=100 ms slices so Ctrl-C stays responsive) instead
        of burning a core busy-polling."""
        import ctypes

        deadline = time.monotonic() + timeout
        peer = ctypes.c_int(0)
        tag = ctypes.c_longlong(0)
        length = ctypes.c_longlong(0)
        while True:
            remaining = deadline - time.monotonic()
            slice_ms = max(1, min(100, int(remaining * 1000)))
            with self._native_call(what="recv"):
                msgid = self._lib.dcn_wait_recv(
                    self._ctx, slice_ms, ctypes.byref(peer),
                    ctypes.byref(tag), ctypes.byref(length),
                )
            if msgid:
                return self._consume_receipt(msgid, peer, tag, length)
            if time.monotonic() >= deadline:
                raise DcnError("recv timeout")

    def wait_event(self, timeout: float) -> bool:
        """Park until ANY engine completion (recv/send/matched) is
        pending or up to ~200 ms lapse (each call parks one bounded
        slice so close() can drain waiters promptly — loop for longer
        waits), consuming nothing. True when something fired."""
        ms = max(1, min(200, int(timeout * 1000)))
        try:
            with self._native_call(what="wait_event"):
                return bool(self._lib.dcn_wait_event(self._ctx, ms))
        except DcnError:
            return False  # closed

    def notify(self) -> None:
        """Wake a parked wait_event waiter (the progress engine pokes
        this when a non-DCN completion fires elsewhere). Guarded like
        every data-plane native call so close()'s drain also covers a
        thread mid-dcn_notify."""
        try:
            with self._native_call(what="notify"):
                self._lib.dcn_notify(self._ctx)
        except DcnError:
            pass  # closed: nothing to wake

    def poll_send_complete(self) -> Optional[int]:
        try:
            with self._native_call(what="poll_send"), self._send_mu:
                if self._pending_send_done:
                    return self._pending_send_done.popleft()
                msgid = int(self._lib.dcn_poll_send(self._ctx))
                if not msgid:
                    return None
                self._send_refs.pop(msgid, None)
                return msgid
        except DcnError:
            return None  # closed: nothing left to poll

    def set_link_weights(self, peer: int, weights) -> None:
        """Per-link FRAG striping proportions for a peer (reference:
        bml_r2's bandwidth-weighted scheduling, bml_r2.c:131-148).
        Empty/None restores uniform round-robin."""
        import ctypes

        ws = list(weights or [])
        arr = (ctypes.c_double * max(len(ws), 1))(*(ws or [0.0]))
        rc = self._lib.dcn_set_link_weights(
            self._ctx, peer, arr, len(ws)
        )
        if rc != 0:
            raise DcnError(f"set_link_weights: unknown peer {peer}")

    def link_frags(self, peer: int, idx: int) -> int:
        """FRAGs scheduled onto link `idx` of `peer` (striping
        observability)."""
        return int(self._lib.dcn_link_frags(self._ctx, peer, idx))

    def peer_links(self, peer: int) -> int:
        """Live TCP links to a peer; 0 means the peer is unreachable
        (every link died — the btl_tcp endpoint-failed state)."""
        return int(self._lib.dcn_peer_links(self._ctx, peer))

    def kill_link(self, peer: int, idx: int = 0) -> int:
        """Deterministically sever link `idx` to `peer` (faultline's
        injection primitive and the drill suite's link-failure lever).
        Frames still queued on the dying link salvage onto survivors
        inside the engine. Returns the surviving link count."""
        left = int(self._lib.dcn_kill_link(self._ctx, int(peer),
                                           int(idx)))
        if left < 0:
            raise DcnError(f"kill_link: unknown peer {peer}")
        SPC.record("dcn_links_killed")
        logger.warning(
            "dcn peer %d: link %d severed, %d link(s) surviving",
            peer, idx, left,
        )
        return left

    def heal_links(self, peer: int) -> int:
        """Failover: notice links lost since the last look and
        re-stripe traffic uniformly over the survivors (any configured
        bandwidth weights were sized for the full link set). Returns
        the live link count (-1 = unknown peer). DEVICE_ERROR is NOT
        raised here — partial link loss is a degraded-but-healthy
        state; only check_peer escalates, and only when every link is
        gone."""
        peer = int(peer)
        live = self.peer_links(peer)
        seen = self._peer_links_seen.get(peer)
        if seen is not None and 0 < live < seen:
            try:
                self.set_link_weights(peer, None)
            except DcnError:
                pass
            SPC.record("dcn_restripes")
            from ..trace import span as tspan

            tspan.instant("dcn.restripe", cat="btl", peer=peer,
                          lost=seen - live, survivors=live)
            logger.warning(
                "dcn peer %d: %d link(s) down, re-striped over %d "
                "survivor(s)", peer, seen - live, live,
            )
        if live > 0:
            self._peer_links_seen[peer] = live
        return live

    # -- tag-matching offload (reference: mtl.h:418-421) -------------------

    def enable_matching(self, dcn_tag: int) -> None:
        """Divert completed messages carrying `dcn_tag` into the
        engine's matching thread (-1 disables)."""
        self._lib.dcn_enable_matching(self._ctx, dcn_tag)

    def post_recv(self, handle: int, cid: int, src: int, dst: int,
                  tag: int) -> Optional[bytes]:
        """Post a receive to the engine (src/tag < 0 = wildcard).
        Returns the payload immediately when an unexpected message
        already matches; None when queued for the transport thread."""
        receipt = self._lib.dcn_post_recv(
            self._ctx, handle, cid, src, dst, tag
        )
        if receipt == 0:
            return None
        return self._read_receipt(int(receipt))

    def poll_matched(self) -> Optional[tuple[int, bytes]]:
        """(handle, payload) of one match made by the transport thread,
        or None."""
        import ctypes

        handle = ctypes.c_longlong(0)
        receipt = self._lib.dcn_poll_matched(
            self._ctx, ctypes.byref(handle)
        )
        if receipt == 0:
            return None
        return int(handle.value), self._read_receipt(int(receipt))

    def match_probe(self, cid: int, src: int, dst: int, tag: int
                    ) -> Optional[tuple[int, int, int]]:
        """(src, tag, nbytes) of the first compatible unexpected
        message, without consuming it (MPI_Iprobe)."""
        import ctypes

        o_src = ctypes.c_int(0)
        o_tag = ctypes.c_int(0)
        o_len = ctypes.c_longlong(0)
        hit = self._lib.dcn_match_probe(
            self._ctx, cid, src, dst, tag, ctypes.byref(o_src),
            ctypes.byref(o_tag), ctypes.byref(o_len),
        )
        if not hit:
            return None
        return int(o_src.value), int(o_tag.value), int(o_len.value)

    def match_stat(self, what: int) -> int:
        """0=posted depth, 1=unexpected depth, 2=matches, 3=unexpected
        arrivals."""
        return int(self._lib.dcn_match_stat(self._ctx, what))

    def _read_receipt(self, receipt: int) -> bytes:
        length = int(self._lib.dcn_receipt_len(self._ctx, receipt))
        if length < 0:
            raise DcnError(f"unknown matched receipt {receipt}")
        buf = np.empty(max(1, length), np.uint8)
        got = self._lib.dcn_read(
            self._ctx, receipt, buf.ctypes.data, length
        )
        if got != length:
            raise DcnError(f"short matched read {got} != {length}")
        return buf[:length].tobytes()

    def peer_alive(self, peer: int) -> bool:
        return self.peer_links(peer) > 0

    def check_peer(self, peer: int, *, what: str = "peer") -> None:
        """Raise (and report a failure event) if the peer is dead.
        Partial link loss re-stripes silently (heal_links); only a
        fully dead endpoint escalates to DEVICE_ERROR."""
        if self.heal_links(peer) <= 0:
            from ..ft import events

            events.raise_event(
                events.EventClass.DEVICE_ERROR,
                transport="dcn", peer=peer,
            )
            raise DcnError(
                f"{what} {peer}: all DCN links are down "
                "(connection lost)"
            )

    def stats(self) -> dict:
        names = ("bytes_sent", "bytes_recv", "eager_sends", "rndv_sends",
                 "frags_sent", "links", "restriped_frames")
        return {
            n: int(self._lib.dcn_stat(self._ctx, i))
            for i, n in enumerate(names)
        }

    def close(self) -> None:
        # Order matters: flag first under the lock (new waiters bounce,
        # a racing close returns), wake parked ones (the C-side drain
        # handles threads already inside), then wait for in-flight
        # native calls to return before freeing.
        with self._wait_mu:
            if self._closed:
                return
            self._closed = True
        try:
            self._lib.dcn_notify(self._ctx)
        except Exception:
            pass
        # Every wait parks in bounded slices (<=200 ms), so this drain
        # deadline is real; if a waiter still hasn't returned, LEAK the
        # native context instead of freeing memory under its feet.
        deadline = time.monotonic() + 5.0
        remaining = 1
        while time.monotonic() < deadline:
            with self._wait_mu:
                remaining = self._inflight_waits
            if remaining == 0:
                break
            time.sleep(0.001)
        if remaining:
            logger.warning(
                "dcn close: %d native wait(s) did not drain; leaking "
                "the context rather than freeing it mid-call", remaining,
            )
            return
        self._lib.dcn_destroy(self._ctx)
        with self._send_mu:
            self._send_refs.clear()
            self._pending_send_done.clear()

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass


def register_health_probe(endpoint, peer_ids: dict) -> None:
    """Wire the dcn tier canary to a live endpoint: per-peer link ping
    (heal_links re-counts live sockets; zero links to any peer is a
    dead tier). Weakref only — a closed endpoint retires its probe
    (health/prober contract; called at both wire-up seams)."""
    import weakref

    from ..health import prober as health_prober

    ref = weakref.ref(endpoint)
    peers = dict(peer_ids)

    def _dcn_canary() -> None:
        ep = ref()
        if ep is None:
            # torn-down endpoint verified nothing: retire the probe
            # instead of reporting a success on zero evidence
            raise health_prober.ProbeRetired("dcn endpoint retired")
        ep.stats()  # native round trip: raises on a dead context
        dead = [idx for idx, pid in sorted(peers.items())
                if ep.heal_links(pid) <= 0]
        if dead:
            raise RuntimeError(f"dcn peer(s) linkless: {dead}")

    health_prober.register_probe(
        "dcn", _dcn_canary,
        description="per-link peer ping (heal_links live-socket count)")


@BTL.register
class DcnBtl(BtlComponent):
    """BML-pluggable DCN transport: array payloads stage host-side,
    cross the wire, and land on the destination device. Reaches peers in
    a different host process; idle inside one driver (ici wins there)."""

    NAME = "dcn"
    PRIORITY = 10
    EAGER_LIMIT = 64 * 1024

    def __init__(self, framework) -> None:
        super().__init__(framework)
        self._endpoint: Optional[DcnEndpoint] = None
        self._peer_ids: dict[int, int] = {}  # process_index -> peer id

    def available(self, **ctx: Any) -> bool:
        lib = build.get_lib()
        return lib is not None and hasattr(lib, "dcn_create")

    def can_reach(self, src_proc, dst_proc) -> bool:
        return src_proc.process_index != dst_proc.process_index

    def endpoint(self) -> DcnEndpoint:
        if self._endpoint is None:
            self._endpoint = DcnEndpoint()
        # faultline interposes at the endpoint boundary (sanitizer
        # pattern): a no-op passthrough unless a fault plan is armed.
        from ..ft import inject

        return inject.maybe_wrap_dcn(self._endpoint)

    def wire_up(self, peer_addrs: dict[int, tuple[str, int]],
                my_index: int,
                peer_records: Optional[dict[int, dict]] = None) -> None:
        """Modex: connect to every peer process's listener (reference:
        PMIx modex exchanging btl/tcp addresses, ompi_mpi_init.c:642).
        When full business cards are supplied (`peer_records`), the
        remote address is chosen by weighted reachability over the
        peer's interface list (reference: btl_tcp_proc.c address
        matching + reachable/weighted scoring)."""
        from ..runtime import interfaces

        ep = self.endpoint()
        locals_ = interfaces.usable_interfaces()
        for idx, (ip, port) in sorted(peer_addrs.items()):
            if idx == my_index or idx in self._peer_ids:
                continue
            rec = (peer_records or {}).get(idx) or {}
            # Multi-NIC: when the peer published several listeners,
            # open links across distinct (local if, remote if) socket
            # pairs by CQ score and stripe by the scores
            # (reference: btl_tcp_proc.c pairing + bml_r2 weights).
            listeners = [
                l for l in rec.get("listeners", [])
                if l.get("ip") and l["ip"] != "0.0.0.0"
            ]
            if len(listeners) > 1:
                nlinks = max(1, _links.value)
                pairs = interfaces.choose_link_pairs(
                    locals_, listeners, nlinks)
                if pairs:
                    try:
                        pid = ep.connect_pairs(
                            [(lip, rip, rport)
                             for lip, rip, rport, _ in pairs],
                            cookie=my_index + 1,
                        )
                    except DcnError as exc:
                        # every pair failed: fall back to the single
                        # best-address path below
                        logger.warning(
                            "multi-NIC wiring to process %d failed "
                            "(%s); falling back to single address",
                            idx, exc)
                    else:
                        links = ep.peer_links(pid)
                        weights = [q for _, _, _, q in pairs][:links]
                        total = sum(weights) or 1.0
                        ep.set_link_weights(
                            pid, [q / total for q in weights])
                        self._peer_ids[idx] = pid
                        SPC.record("dcn_multinic_peers")
                        continue
            best_ip, best_q = ip, -1.0
            # Interface alternatives are reachable only when the peer's
            # listener binds every interface; a single-address listener
            # is authoritative.
            candidates = (
                rec.get("ifaces", []) if ip == "0.0.0.0" else []
            )
            for riface in candidates:
                # A REMOTE loopback address points at the local host —
                # never a valid cross-process target (and it would win
                # the same-network tier against the real NIC pair).
                if not riface.get("ip") or riface.get("loopback"):
                    continue
                q = max(
                    (interfaces.connection_quality(
                        li, riface["ip"], riface.get("speed", 0))
                     for li in locals_),
                    default=0.0,
                )
                if q > best_q:
                    best_ip, best_q = riface["ip"], q
            if best_ip == "0.0.0.0":
                # listen-all peer with no scorable non-loopback NIC
                # (single-host setups): any published address reaches it
                best_ip = next(
                    (r["ip"] for r in rec.get("ifaces", [])
                     if r.get("ip")), "127.0.0.1",
                )
            self._peer_ids[idx] = ep.connect(
                best_ip, port, cookie=my_index + 1
            )
        if self._peer_ids:
            register_health_probe(self._endpoint, self._peer_ids)

    def transfer(self, value, src_proc, dst_proc):
        # Cross-process delivery needs the full MPI envelope + matching
        # on the receiving controller — that is pml/fabric's job (it
        # serializes treedef/dtypes/shapes and reassembles remotely).
        # A bare BTL transfer cannot return the remote value locally,
        # so rather than silently returning the un-transferred input
        # (round-1 behavior), fail with the right pointer.
        raise CommError(
            f"DcnBtl.transfer cannot deliver to process "
            f"{dst_proc.process_index} directly: cross-process p2p goes "
            "through the PML fabric (ompi_tpu.pml.fabric.wire_up); "
            "byte-level DCN sends are available via DcnEndpoint"
        )

"""btl/sm — intra-host shared-memory transport.

TPU-native equivalent of opal/mca/btl/sm (reference: btl_sm_fbox.h:22-60
per-peer lock-free fastboxes; btl_sm_component.c:200,243-245 — 4 KiB
fastbox / 32 KiB eager regime; btl_sm_module.c FIFO queues). The native
engine (native/src/shm.cc) owns the POSIX segment, the per-peer-pair
fastbox + eager SPSC rings, chunked bulk streaming and futex parking;
this module is the endpoint/bytes API plus the BTL component that makes
the selection visible to the BML/comm_method layers.

Role in the TPU design (SURVEY §5.8): same-host controller processes
previously exchanged ALL traffic over TCP loopback through the kernel
(~1 ms small-message p50 on 1-core hosts — VERDICT r3 missing #1);
this engine keeps the entire same-host path in user space. Peers are
addressed by their global process index; the modex publishes
(segment prefix, hostname) and `pml/fabric.wire_up` connects co-located
peers here while inter-host peers stay on DCN.
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import threading
import time
from typing import Any, Optional

import numpy as np

from ..core import config
from ..core.backoff import Backoff
from ..core.counters import SPC
from ..core.errors import OmpiTpuError
from ..core.logging import get_logger
from ..native import build
from .framework import BTL, BtlComponent

logger = get_logger("btl.sm")

_fbox_var = config.register(
    "btl", "sm", "fbox_size", type=int, default=4096,
    description="Per-peer fastbox ring bytes (reference: btl/sm 4 KiB "
                "fastbox, btl_sm_component.c:200)",
)
_ring_var = config.register(
    "btl", "sm", "ring_size", type=int, default=1 << 20,
    description="Per-peer eager/bulk ring bytes (reference: btl/sm FIFO)",
)
_max_peers_var = config.register(
    "btl", "sm", "max_peers", type=int, default=32,
    description="Sender slots in this process's shared segment",
)
_enable_var = config.register(
    "btl", "sm", "enable", type=bool, default=True,
    description="Use shared memory for same-host cross-process traffic "
                "(off: such traffic rides DCN TCP loopback)",
)
_eager_limit_var = config.register(
    "btl", "sm", "eager_limit", type=int, default=32 * 1024,
    description="Whole-message-inline limit for the shm eager ring; "
                "larger payloads chunk-stream (reference: btl/sm "
                "32 KiB eager, btl_sm_component.c:243)",
)
_cma_var = config.register(
    "btl", "sm", "use_cma", type=bool, default=True,
    description="Single-copy bulk transfers via process_vm_readv when "
                "the kernel allows it (probed per peer at connect; "
                "reference: btl/sm CMA get, btl_sm_get.c:69, mechanism "
                "selection btl_sm_component.c:453-478). Off or denied: "
                "bulk chunk-streams through the shared rings.",
)
_cma_min_var = config.register(
    "btl", "sm", "cma_min", type=int, default=256 * 1024,
    description="Smallest payload that takes the single-copy CMA path. "
                "CMA is a rendezvous (the sender parks until the "
                "receiver reads the message); below this, bulk keeps "
                "the buffered chunk tier and completes on return.",
)
_fp_enable_var = config.register(
    "btl", "sm", "fp_enable", type=bool, default=True,
    description="Use the fastpath shared-ring doorbell lane "
                "(native/src/fastpath.cc) for small messages: SPSC "
                "descriptor rings with inline payload <=256 B and slab "
                "frames above; full rings spill to the general engine",
)
_fp_ring_entries_var = config.register(
    "btl", "sm", "fp_ring_entries", type=int, default=64,
    description="Descriptors per fastpath ring (power of two). 64 x "
                "320 B descriptors = one 20 KiB ring per peer pair",
)
_fp_slab_frames_var = config.register(
    "btl", "sm", "fp_slab_frames", type=int, default=32,
    description="Slab frames per fastpath peer pair (payloads between "
                "256 B inline and fp_frame_size ride these; exhaustion "
                "spills to the general engine)",
)
_fp_frame_size_var = config.register(
    "btl", "sm", "fp_frame_size", type=int, default=64 * 1024,
    description="Bytes per fastpath slab frame — the fast lane's upper "
                "payload bound; larger messages always take the "
                "eager/chunk/CMA tiers",
)
_fp_spin_us_var = config.register(
    "btl", "sm", "fp_spin_us", type=int, default=20,
    description="Bounded spin budget (us) a fastpath/doorbell waiter "
                "burns (sched_yield loop) before parking on the futex. "
                "On few-core hosts the yield IS the handoff to the "
                "producer; 0 parks immediately",
)


class ShmError(OmpiTpuError):
    errclass = "ERR_OTHER"


class ShmPullError(ShmError):
    """A single-copy CMA pull failed mid-receive (sender exited or the
    kernel withdrew permission). If the sender is alive it re-sends the
    payload through the chunk tier, so waiters should keep waiting;
    the progress pump converts this into a DEVICE_ERROR event."""


def _declare(lib) -> None:
    import ctypes

    if getattr(lib, "_shm_declared", False):
        return
    LL = ctypes.c_longlong
    P = ctypes.c_void_p
    lib.shm_create.restype = P
    lib.shm_create.argtypes = [ctypes.c_char_p, ctypes.c_int,
                               ctypes.c_int, LL, LL, LL, ctypes.c_int,
                               LL]
    lib.shm_connect.restype = ctypes.c_int
    lib.shm_connect.argtypes = [P, ctypes.c_int, ctypes.c_int]
    lib.shm_send.restype = LL
    lib.shm_send.argtypes = [P, ctypes.c_int, LL, ctypes.c_void_p, LL]
    lib.shm_send2.restype = LL
    lib.shm_send2.argtypes = [P, ctypes.c_int, LL, ctypes.c_void_p, LL,
                              ctypes.c_void_p, LL]
    lib.shm_poll_recv.restype = LL
    lib.shm_poll_recv.argtypes = [
        P, ctypes.POINTER(ctypes.c_int), ctypes.POINTER(LL),
        ctypes.POINTER(LL),
    ]
    lib.shm_wait_recv.restype = LL
    lib.shm_wait_recv.argtypes = [
        P, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(LL), ctypes.POINTER(LL),
    ]
    lib.shm_wait_event.restype = ctypes.c_int
    lib.shm_wait_event.argtypes = [P, ctypes.c_int]
    lib.shm_notify.restype = None
    lib.shm_notify.argtypes = [P]
    lib.shm_read.restype = LL
    lib.shm_read.argtypes = [P, LL, ctypes.c_void_p, LL]
    lib.shm_requeue.restype = None
    lib.shm_requeue.argtypes = [P, LL]
    lib.shm_stat.restype = LL
    lib.shm_stat.argtypes = [P, ctypes.c_int]
    lib.shm_peer_alive.restype = ctypes.c_int
    lib.shm_peer_alive.argtypes = [P, ctypes.c_int]
    lib.shm_peer_cma.restype = ctypes.c_int
    lib.shm_peer_cma.argtypes = [P, ctypes.c_int]
    lib.shm_destroy.restype = None
    lib.shm_destroy.argtypes = [P]
    lib.cma_read.restype = ctypes.c_int
    lib.cma_read.argtypes = [LL, ctypes.c_ulonglong, ctypes.c_void_p, LL]
    lib.cma_write.restype = ctypes.c_int
    lib.cma_write.argtypes = [LL, ctypes.c_ulonglong, ctypes.c_void_p,
                              LL]
    lib.winseg_open.restype = P
    lib.winseg_open.argtypes = [ctypes.c_char_p, LL, ctypes.c_int]
    lib.winseg_close.restype = None
    lib.winseg_close.argtypes = [P, LL, ctypes.c_char_p, ctypes.c_int]
    lib.winseg_cas.restype = ctypes.c_int
    lib.winseg_cas.argtypes = [P, LL, ctypes.c_int, ctypes.c_int]
    lib.winseg_load.restype = ctypes.c_int
    lib.winseg_load.argtypes = [P, LL]
    lib.winseg_store.restype = None
    lib.winseg_store.argtypes = [P, LL, ctypes.c_int]
    lib.winseg_add.restype = ctypes.c_int
    lib.winseg_add.argtypes = [P, LL, ctypes.c_int]
    lib.winseg_wait.restype = ctypes.c_int
    lib.winseg_wait.argtypes = [P, LL, ctypes.c_int, ctypes.c_int]
    lib.winseg_wake.restype = None
    lib.winseg_wake.argtypes = [P, LL]
    lib.shm_enable_matching.restype = None
    lib.shm_enable_matching.argtypes = [P, LL]
    lib.shm_post_recv.restype = LL
    lib.shm_post_recv.argtypes = [P, LL, ctypes.c_int, ctypes.c_int,
                                  ctypes.c_int, ctypes.c_int]
    lib.shm_poll_matched.restype = LL
    lib.shm_poll_matched.argtypes = [P, ctypes.POINTER(LL)]
    lib.shm_match_probe.restype = ctypes.c_int
    lib.shm_match_probe.argtypes = [
        P, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(LL),
    ]
    lib.shm_msg_len.restype = LL
    lib.shm_msg_len.argtypes = [P, LL]
    lib.shm_wait_matched.restype = LL
    lib.shm_wait_matched.argtypes = [P, LL, ctypes.c_int]
    lib._shm_declared = True


class WinSyncSeg:
    """Shared 32-bit word array for one RMA window's same-host sync:
    word 0 is a modification counter, words 1..n per-rank
    readers-writer lock words (0 free, -1 exclusive, k>0 shared) —
    the osc/sm passive-target state, CPU atomics + futex parking
    (reference: osc_sm_passive_target.c)."""

    def __init__(self, name: str, n_words: int, create: bool) -> None:
        lib = build.get_lib()
        if lib is None or not hasattr(lib, "winseg_open"):
            raise ShmError("native winseg unavailable")
        _declare(lib)
        self._lib = lib
        self.name = name
        self.n_words = n_words
        self.creator = create
        self._base = lib.winseg_open(name.encode(), n_words,
                                     int(create))
        if not self._base:
            raise ShmError(f"cannot {'create' if create else 'attach'} "
                           f"window sync segment {name}")

    def cas(self, idx: int, expect: int, desired: int) -> int:
        return self._lib.winseg_cas(self._base, idx, expect, desired)

    def load(self, idx: int) -> int:
        return self._lib.winseg_load(self._base, idx)

    def store(self, idx: int, value: int) -> None:
        self._lib.winseg_store(self._base, idx, value)

    def add(self, idx: int, delta: int) -> int:
        return self._lib.winseg_add(self._base, idx, delta)

    def wait(self, idx: int, while_value: int, timeout_ms: int) -> int:
        return self._lib.winseg_wait(self._base, idx, while_value,
                                     timeout_ms)

    def wake(self, idx: int) -> None:
        self._lib.winseg_wake(self._base, idx)

    def close(self) -> None:
        if self._base:
            self._lib.winseg_close(self._base, self.n_words,
                                   self.name.encode(),
                                   int(self.creator))
            self._base = None


def cma_read_into(pid: int, addr: int, arr: np.ndarray) -> None:
    """Pull arr.nbytes from (pid, addr) into `arr` (contiguous) — the
    osc/sm direct-get data plane."""
    lib = build.get_lib()
    _declare(lib)
    rc = lib.cma_read(pid, addr, arr.ctypes.data, arr.nbytes)
    if rc != 0:
        raise ShmError(f"cma_read from pid {pid} failed")


def cma_write_from(pid: int, addr: int, arr: np.ndarray) -> None:
    """Push `arr` (contiguous) into (pid, addr) — the osc/sm direct-put
    data plane."""
    lib = build.get_lib()
    _declare(lib)
    rc = lib.cma_write(pid, addr, arr.ctypes.data, arr.nbytes)
    if rc != 0:
        raise ShmError(f"cma_write to pid {pid} failed")


_STAT_NAMES = (
    "bytes_sent", "bytes_recv", "fbox_sends", "ring_sends",
    "chunk_msgs", "msgs_recvd", "send_stalls", "fbox_recvs", "peers",
    "ns_stalled", "ns_sweep", "cma_sends", "cma_bytes_pulled",
    "cma_fails", "proto_errors", "offload_matches",
    "offload_unexpected",
)

_inject_mod = None


def _inject():
    """Lazy ft.inject handle (the ft package pulls in pml.framework at
    module scope, so a top-level import here would be circular)."""
    global _inject_mod
    if _inject_mod is None:
        from ..ft import inject as m

        _inject_mod = m
    return _inject_mod


_FP_STAT_NAMES = (
    "sends_inline", "sends_frame", "ring_full", "slab_full", "recvs",
    "crc_drops", "futex_parks", "bytes_sent", "bytes_recv",
)


class ShmEndpoint:
    """One process's shared-memory presence: its own segment plus maps
    of each connected peer's. Peers are global process indices (the
    slot-owner table in the segment records them)."""

    def __init__(self, prefix: str, my_rank: int) -> None:
        lib = build.get_lib()
        if lib is None or not hasattr(lib, "shm_create"):
            raise ShmError("native shm engine unavailable")
        _declare(lib)
        self._lib = lib
        self.prefix = prefix
        self.my_rank = my_rank
        self._ctx = lib.shm_create(
            prefix.encode(), my_rank, _max_peers_var.value,
            _fbox_var.value, _ring_var.value,
            _eager_limit_var.value, int(_cma_var.value),
            _cma_min_var.value,
        )
        if not self._ctx:
            raise ShmError(
                f"cannot create shm segment /{prefix}_{my_rank}"
            )
        spin_us = max(0, _fp_spin_us_var.value)
        lib.shm_set_spin(self._ctx, spin_us)
        # The fastpath lane: a second, minimal segment of SPSC
        # descriptor rings + slab frame pools. Optional — every caller
        # falls back to the general engine when it is absent or full.
        self._fp = None
        if _fp_enable_var.value and hasattr(lib, "fp_attach"):
            self._fp = lib.fp_attach(
                prefix.encode(), my_rank, _max_peers_var.value,
                _fp_ring_entries_var.value, _fp_slab_frames_var.value,
                _fp_frame_size_var.value, spin_us,
            ) or None
        self.fp_peers: set[int] = set()
        self._fp_tls = threading.local()
        self._mu = threading.Lock()
        self._drained = threading.Condition(self._mu)
        self._inflight = 0
        self._closed = False
        self.peers: set[int] = set()

    def _begin(self, what: str) -> None:
        """Hot-path guard entry (the contextmanager variant costs ~3 us
        per call in generator machinery — real money at fastbox rates).
        Pair with _end() in a finally block."""
        with self._mu:
            if self._closed:
                raise ShmError(f"endpoint closed during {what}")
            self._inflight += 1

    def _end(self) -> None:
        with self._mu:
            self._inflight -= 1
            if self._closed and self._inflight == 0:
                self._drained.notify_all()  # close() waits on this

    @contextlib.contextmanager
    def _native_call(self, *, what: str):
        with self._mu:
            if self._closed:
                raise ShmError(f"endpoint closed during {what}")
            self._inflight += 1
        try:
            yield
        finally:
            self._end()

    def connect(self, peer_rank: int, timeout_s: float = 30.0) -> None:
        with self._native_call(what="connect"):
            rc = self._lib.shm_connect(
                self._ctx, peer_rank, int(timeout_s * 1000)
            )
        if rc != 0:
            raise ShmError(
                f"cannot attach peer {peer_rank}'s shm segment "
                f"(/{self.prefix}_{peer_rank})"
            )
        self.peers.add(peer_rank)
        # The fastpath lane rides along: claim a producer slot in the
        # peer's fp segment. Failure is non-fatal (sends spill to the
        # general engine just attached above).
        if self._fp is not None:
            with self._native_call(what="fp_connect"):
                rc = self._lib.fp_connect(
                    self._fp, peer_rank, int(timeout_s * 1000)
                )
            if rc == 0:
                self.fp_peers.add(peer_rank)
            else:
                logger.debug("fp_connect to %d failed rc=%d (fastpath "
                             "disabled toward this peer)", peer_rank, rc)

    @staticmethod
    def _as_ptr(data):
        """(address, nbytes, keepalive) for a bytes-like or array
        source with NO copy: ctypes reads the object's buffer in
        place (the engine's tiers never write through it)."""
        if isinstance(data, bytes):
            return (ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p),
                    len(data), data)
        arr = np.frombuffer(data, np.uint8)  # bytearray/memoryview/array
        return arr.ctypes.data, arr.nbytes, arr

    def _check_send_rc(self, rc, peer_rank: int, nbytes: int) -> int:
        if rc == -1:
            raise ShmError(f"send to unconnected shm peer {peer_rank}")
        if rc == -2:
            raise ShmError(f"shm peer {peer_rank} is dead")
        SPC.record("sm_send_bytes", nbytes)
        return 0  # copy/rendezvous semantics: complete on return

    def send_bytes(self, peer_rank: int, tag: int, data) -> int:
        ptr, n, keep = self._as_ptr(data)
        self._begin("send")
        try:
            rc = self._lib.shm_send(self._ctx, peer_rank, tag, ptr, n)
        finally:
            self._end()
        del keep
        return self._check_send_rc(rc, peer_rank, n)

    def send_bytes2(self, peer_rank: int, tag: int, hdr, payload) -> int:
        """Framed send (header + payload) with no Python-side
        concatenation: both buffers go to the engine as a gather pair;
        the receiver sees ONE message of len(hdr)+len(payload) bytes."""
        hp, hn, hkeep = self._as_ptr(hdr)
        pp, pn, pkeep = self._as_ptr(payload)
        self._begin("send2")
        try:
            rc = self._lib.shm_send2(
                self._ctx, peer_rank, tag, hp, hn, pp, pn
            )
        finally:
            self._end()
        del hkeep, pkeep
        return self._check_send_rc(rc, peer_rank, hn + pn)

    # -- fastpath lane (native/src/fastpath.cc): per-peer SPSC
    # descriptor rings with inline payload + slab frames. Strictly
    # opportunistic — every entry point spills to the general engine
    # when the lane is absent, unconnected, or full, so callers keep
    # the v2 tiers' delivery guarantees. ------------------------------

    def fp_available(self, peer_rank: Optional[int] = None) -> bool:
        if self._fp is None:
            return False
        return peer_rank is None or peer_rank in self.fp_peers

    def fp_send(self, peer_rank: int, tag: int, data) -> bool:
        """Post one descriptor on the fast lane. True when posted
        (delivery complete from the sender's view — copy semantics);
        False when the caller must spill to send_bytes (lane missing,
        ring/slab full, payload larger than a slab frame)."""
        if self._fp is None or peer_rank not in self.fp_peers:
            return False
        inj = _inject()
        if inj.armed():
            inj.on_fp_send(self, peer_rank, tag)
        ptr, n, keep = self._as_ptr(data)
        self._begin("fp_send")
        try:
            rc = self._lib.fp_send(self._fp, peer_rank, tag, ptr, n)
        finally:
            self._end()
        del keep
        if rc == 0:
            SPC.record("sm_send_bytes", n)
            return True
        if rc == -2:
            raise ShmError(f"shm peer {peer_rank} is dead")
        SPC.record("sm_fp_spills")
        return False

    def send_small(self, peer_rank: int, tag: int, data) -> int:
        """Small-message send: fastpath descriptor post when the lane
        has room, general-engine send otherwise. Always completes on
        return (both lanes have copy semantics)."""
        if self.fp_send(peer_rank, tag, data):
            return 0
        return self.send_bytes(peer_rank, tag, data)

    def fp_send_many(self, peer_rank: int, msgs) -> int:
        """Coalesced post: msgs is a sequence of (tag, bytes). All
        descriptors land under ONE native call and one doorbell ring;
        whatever does not fit spills to the general engine here.
        Returns how many rode the fast lane."""
        if self._fp is None or peer_rank not in self.fp_peers:
            posted = 0
        else:
            n = len(msgs)
            tags = (ctypes.c_longlong * n)(*(t for t, _ in msgs))
            lens = (ctypes.c_longlong * n)(*(len(p) for _, p in msgs))
            blob = b"".join(bytes(p) for _, p in msgs)
            self._begin("fp_send_many")
            try:
                posted = int(self._lib.fp_send_many(
                    self._fp, peer_rank, n, tags, lens, blob
                ))
            finally:
                self._end()
            if posted < 0:
                posted = 0
            if posted:
                SPC.record("sm_send_bytes",
                           int(sum(lens[:posted])))
        for tag, payload in msgs[posted:]:
            SPC.record("sm_fp_spills")
            self.send_bytes(peer_rank, tag, payload)
        return posted

    def send_many(self, peer_rank: int, msgs) -> None:
        """Coalesced v2-lane post: msgs is a sequence of (tag, bytes).
        Fastbox-tier messages land under ONE native call and one
        doorbell ring (shm_send_many); whatever does not batch (bulk
        tiers, ring stalls) ships per-message here. Copy semantics
        throughout — every message is delivered or raised on return."""
        n = len(msgs)
        if n == 0:
            return
        if n == 1 or not hasattr(self._lib, "shm_send_many"):
            for tag, payload in msgs:
                self.send_bytes(peer_rank, tag, payload)
            return
        tags = (ctypes.c_longlong * n)(*(t for t, _ in msgs))
        lens = (ctypes.c_longlong * n)(*(len(p) for _, p in msgs))
        blob = b"".join(bytes(p) for _, p in msgs)
        self._begin("send_many")
        try:
            posted = int(self._lib.shm_send_many(
                self._ctx, peer_rank, n, tags, lens, blob
            ))
        finally:
            self._end()
        if posted == -1:
            raise ShmError(f"shm peer {peer_rank} not connected")
        if posted == -2:
            raise ShmError(f"shm peer {peer_rank} is dead")
        SPC.record("sm_send_bytes", int(sum(lens[:posted])))
        if posted:
            SPC.record("sm_batched_sends", posted)
        for tag, payload in msgs[posted:]:
            self.send_bytes(peer_rank, tag, payload)

    def _fp_wait(self, src: int, deadline: float, native_fn, *cells):
        """Shared fp receive loop: <=100 ms native slices (the drain
        discipline — close() must observe _inflight within one slice),
        CRC-rejected descriptors counted and skipped."""
        while True:
            rem_us = int((deadline - time.monotonic()) * 1e6)
            if rem_us <= 0:
                raise ShmError("fp recv timeout")
            self._begin("fp_recv")
            try:
                rc = native_fn(self._fp, src, min(rem_us, 100_000),
                               *cells)
            finally:
                self._end()
            if rc >= 0:
                return rc
            if rc == -5:
                SPC.record("sm_fp_crc_drops")
                continue
            if rc != -3:
                raise ShmError(f"fastpath recv error rc={rc}")

    def _fp_scratch(self) -> np.ndarray:
        """Per-thread landing buffer for the copy-out fp receives.
        Both users (fp_recv, fp_sendrecv) copy the payload out before
        returning, so one frame-sized buffer per thread is safe and
        saves a 64 KiB allocation per call — measurable against a
        ~3 us wire RTT."""
        buf = getattr(self._fp_tls, "buf", None)
        if buf is None or buf.nbytes < _fp_frame_size_var.value:
            buf = np.empty(_fp_frame_size_var.value, np.uint8)
            self._fp_tls.buf = buf
        return buf

    def fp_recv(self, src: int, timeout: float = 10.0):
        """Next fast-lane message from `src` as (tag, bytes). Single
        consumer per source ring (the fabric progress thread or the
        collective leader — never both)."""
        if self._fp is None:
            raise ShmError("fastpath lane unavailable")
        buf = self._fp_scratch()
        tag = ctypes.c_longlong(0)
        n = self._fp_wait(
            src, time.monotonic() + timeout, self._lib.fp_recv,
            buf.ctypes.data, buf.nbytes, ctypes.byref(tag),
        )
        SPC.record("sm_recv_bytes", n)
        return int(tag.value), buf[:n].tobytes()

    def fp_sendrecv(self, peer_rank: int, tag: int, data, src: int,
                    timeout: float = 10.0):
        """Combined post + reap in ONE native transition — the
        ping-pong hop primitive. Falls back to send_small + fp_recv
        when the post spills."""
        if self._fp is None or peer_rank not in self.fp_peers:
            self.send_small(peer_rank, tag, data)
            return self.fp_recv(src, timeout)
        ptr, n, keep = self._as_ptr(data)
        buf = self._fp_scratch()
        rtag = ctypes.c_longlong(0)
        deadline = time.monotonic() + timeout
        self._begin("fp_sendrecv")
        try:
            rc = self._lib.fp_sendrecv(
                self._fp, peer_rank, tag, ptr, n, src,
                min(int(timeout * 1e6), 100_000), buf.ctypes.data,
                buf.nbytes, ctypes.byref(rtag),
            )
        finally:
            self._end()
        del keep
        if rc <= -20:  # send side failed: spill and recv separately
            SPC.record("sm_fp_spills")
            self.send_bytes(peer_rank, tag, data)
            return self.fp_recv(src, max(0.001,
                                         deadline - time.monotonic()))
        SPC.record("sm_send_bytes", n)
        while rc < 0:  # recv side: timeout slice or CRC drop — retry
            if rc == -5:
                SPC.record("sm_fp_crc_drops")
            rc = self._fp_wait(
                src, deadline, self._lib.fp_recv,
                buf.ctypes.data, buf.nbytes, ctypes.byref(rtag),
            )
        SPC.record("sm_recv_bytes", rc)
        return int(rtag.value), buf[:rc].tobytes()

    def fp_echo(self, src: int, count: int, timeout: float = 10.0) -> int:
        """Bench/drill responder: bounce `count` fast-lane messages from
        `src` straight back, entirely in native code (the initiator's
        measured round trip never includes interpreter turnaround).
        Returns echoes completed."""
        if self._fp is None or src not in self.fp_peers:
            raise ShmError("fastpath lane unavailable")
        with self._native_call(what="fp_echo"):
            return int(self._lib.fp_echo(
                self._fp, src, count, int(timeout * 1e6)))

    def fp_pingpong(self, peer_rank: int, nbytes: int, iters: int,
                    timeout: float = 10.0) -> np.ndarray:
        """Bench initiator: `iters` native ping-pong round trips of
        `nbytes` against a peer sitting in fp_echo. Returns per-round
        wall seconds (float64 array of the rounds completed)."""
        if self._fp is None or peer_rank not in self.fp_peers:
            raise ShmError("fastpath lane unavailable")
        ns = np.zeros(iters, np.int64)
        with self._native_call(what="fp_pingpong"):
            done = int(self._lib.fp_pingpong(
                self._fp, peer_rank, peer_rank, nbytes, iters,
                int(timeout * 1e6),
                ns.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong)),
            ))
        if done < 0:
            raise ShmError(f"fp_pingpong error rc={done}")
        SPC.record("sm_send_bytes", nbytes * done)
        return ns[:done].astype(np.float64) * 1e-9

    def fp_recv_view(self, src: int, timeout: float = 10.0):
        """Zero-copy receive: (tag, uint8 array aliasing the payload
        IN the shared segment, release_token). The view is valid until
        fp_release(token) (token -1: inline payload in a ctx-local
        scratch, nothing to release — but the NEXT fp_recv_view
        overwrites it, so consume before re-polling). This is the
        PiP-style reduction plane: smcoll accumulates straight out of
        the peer's frame."""
        if self._fp is None:
            raise ShmError("fastpath lane unavailable")
        ptr = ctypes.c_void_p(0)
        tag = ctypes.c_longlong(0)
        tok = ctypes.c_longlong(-1)
        n = self._fp_wait(
            src, time.monotonic() + timeout, self._lib.fp_recv_view,
            ctypes.byref(ptr), ctypes.byref(tag), ctypes.byref(tok),
        )
        SPC.record("sm_recv_bytes", n)
        if n == 0:
            arr = np.empty(0, np.uint8)
        else:
            arr = np.ctypeslib.as_array(
                ctypes.cast(ptr, ctypes.POINTER(ctypes.c_ubyte)),
                shape=(n,),
            )
        return int(tag.value), arr, int(tok.value)

    def fp_try_recv_view(self, src: int):
        """Nonblocking fp_recv_view: ONE native poll, None when the
        ring is empty (a CRC-rejected descriptor is dropped, counted
        and also reported as empty — the retry is the caller's next
        poll). This is the demux primitive: coll/sm's router drains a
        source ring under its own lock without committing to a wait."""
        if self._fp is None:
            return None
        ptr = ctypes.c_void_p(0)
        tag = ctypes.c_longlong(0)
        tok = ctypes.c_longlong(-1)
        self._begin("fp_recv")
        try:
            rc = self._lib.fp_recv_view(
                self._fp, src, 0, ctypes.byref(ptr),
                ctypes.byref(tag), ctypes.byref(tok),
            )
        finally:
            self._end()
        if rc == -3:
            return None
        if rc == -5:
            SPC.record("sm_fp_crc_drops")
            return None
        if rc < 0:
            raise ShmError(f"fastpath recv error rc={rc}")
        SPC.record("sm_recv_bytes", rc)
        if rc == 0:
            arr = np.empty(0, np.uint8)
        else:
            arr = np.ctypeslib.as_array(
                ctypes.cast(ptr, ctypes.POINTER(ctypes.c_ubyte)),
                shape=(int(rc),),
            )
        return int(tag.value), arr, int(tok.value)

    def fp_release(self, token: int) -> None:
        """Return a fp_recv_view slab frame to the sender's pool."""
        if token < 0 or self._fp is None:
            return
        with self._native_call(what="fp_release"):
            self._lib.fp_release(self._fp, token)

    def fp_drain_views(self, src: int, max_msgs: int = 16) -> list:
        """Batched demux drain — the daemon ingest primitive: up to
        ``max_msgs`` nonblocking polls of ``src``'s ring, returning
        (tag, view, release_token) triples. Frame-backed views
        (token >= 0) alias the shared slab and stay valid until
        fp_release(token); inline payloads (token -1) live in a
        ctx-local scratch the NEXT poll overwrites, so they are
        materialized here — the only copy on the ingest path, and
        only for ≤ 256 B control frames."""
        out: list = []
        for _ in range(max_msgs):
            got = self.fp_try_recv_view(src)
            if got is None:
                break
            tag, view, tok = got
            if tok < 0:
                view = view.copy()
            out.append((tag, view, tok))
        return out

    def fp_corrupt_next(self) -> None:
        """Faultline drill hook: the next fp_send posts a descriptor
        with a deliberately wrong CRC; the receiver must reject it."""
        if self._fp is None:
            return
        with self._native_call(what="fp_corrupt_next"):
            self._lib.fp_corrupt_next(self._fp)

    def fp_stats(self) -> dict:
        if self._fp is None:
            return {}
        with self._native_call(what="fp_stats"):
            return {
                n: int(self._lib.fp_stat(self._fp, i))
                for i, n in enumerate(_FP_STAT_NAMES)
            }

    def poll_recv(self) -> Optional[tuple[int, int, Any]]:
        """One completed message as (peer, tag, payload) or None.
        Payload is `bytes` up to 64 KiB and a read-only memoryview
        above (zero-copy delivery of single-copy CMA pulls); both
        support len/slice/==/np.frombuffer. A failed CMA pull (sender
        vanished mid-rendezvous) raises ShmPullError — progress pumps
        convert it to a DEVICE_ERROR event and keep polling."""
        peer = ctypes.c_int(0)
        tag = ctypes.c_longlong(0)
        length = ctypes.c_longlong(0)
        # Only the closed-endpoint race (guard entry) maps to "no
        # message"; a _consume failure after the native side already
        # popped the message must propagate, not silently drop it.
        try:
            self._begin("poll")
        except ShmError:
            return None  # closed
        try:
            msgid = self._lib.shm_poll_recv(
                self._ctx, ctypes.byref(peer), ctypes.byref(tag),
                ctypes.byref(length),
            )
            if not msgid:
                return None
            return self._consume(msgid, peer, tag, length)
        finally:
            self._end()

    def poll_recv_many(self, max_msgs: int = 16) -> list:
        """Batched completion reap: up to max_msgs completed messages
        as [(peer, tag, payload), ...] out of ONE native sweep + lock
        cycle (shm_poll_recv_many). The pml progress loop uses this so
        a burst of N small messages costs one Python->C transition for
        the reap instead of N+1 polls."""
        try:
            self._begin("poll_many")
        except ShmError:
            return []  # closed
        try:
            if not hasattr(self._lib, "shm_poll_recv_many"):
                out1 = self.poll_recv()
                return [out1] if out1 is not None else []
            LL = ctypes.c_longlong
            ids = (LL * max_msgs)()
            peers = (ctypes.c_int * max_msgs)()
            tags = (LL * max_msgs)()
            lens = (LL * max_msgs)()
            n = int(self._lib.shm_poll_recv_many(
                self._ctx, max_msgs, ids, peers, tags, lens
            ))
            out = []
            for i in range(n):
                try:
                    payload = self._read_payload(int(ids[i]),
                                                 int(lens[i]))
                except ShmPullError as exc:
                    # Same absorption the pml does for the single-poll
                    # path: an alive sender re-delivers via the chunk
                    # tier, so the rest of the batch must still land.
                    SPC.record("sm_pull_failures")
                    logger.warning("shm pull failure in batch "
                                   "absorbed: %s", exc)
                    continue
                out.append((int(peers[i]), int(tags[i]), payload))
            return out
        finally:
            self._end()

    def _read_payload(self, msgid: int, n: int):
        """shm_read msgid into a fresh buffer; payload typed per the
        poll_recv contract (bytes <= 64 KiB, read-only memoryview
        above). Caller holds the _begin/_end guard."""
        buf = np.empty(max(1, n), np.uint8)
        got = self._lib.shm_read(self._ctx, msgid, buf.ctypes.data, n)
        if got == -3:
            # If the sender is alive it re-sends via the chunk tier —
            # this message id is gone but the payload is not.
            raise ShmPullError("shm CMA pull failed (peer gone?)")
        if got != n:
            raise ShmError(f"short shm read {got} != {n}")
        SPC.record("sm_recv_bytes", n)
        if n <= 65536:
            return buf[:n].tobytes()
        # Bulk: a .tobytes() here would re-copy what may have just
        # arrived as a SINGLE process_vm_readv into `buf`. The array
        # is exclusively ours — hand out a read-only view.
        return buf[:n].data.toreadonly()

    def _consume(self, msgid, peer, tag, length):
        payload = self._read_payload(msgid, length.value)
        return int(peer.value), int(tag.value), payload

    def _wait_msg(self, deadline, what):
        """Shared park-until-message loop; returns (msgid, peer, tag,
        length) ctypes cells, or raises ShmError on timeout."""
        peer = ctypes.c_int(0)
        tag = ctypes.c_longlong(0)
        length = ctypes.c_longlong(0)
        while True:
            remaining = deadline - time.monotonic()
            slice_ms = max(1, min(100, int(remaining * 1000)))
            self._begin(what)
            try:
                msgid = self._lib.shm_wait_recv(
                    self._ctx, slice_ms, ctypes.byref(peer),
                    ctypes.byref(tag), ctypes.byref(length),
                )
            finally:
                self._end()
            if msgid:
                return msgid, peer, tag, length
            if time.monotonic() >= deadline:
                raise ShmError("shm recv timeout")

    def recv_into(self, out, timeout: float = 10.0) -> tuple[int, int, int]:
        """Deliver the next message's payload into `out` (a writable
        buffer-protocol object, e.g. a reused numpy array — warm pages
        make the single-copy pull run at kernel-copy speed). Returns
        (peer, tag, nbytes). If `out` is too small the message is
        REQUEUED (front of the queue) and ShmError raised: retry with a
        larger buffer; nothing is lost and the sender stays parked."""
        dst = np.frombuffer(out, np.uint8)
        deadline = time.monotonic() + timeout
        msgid, peer, tag, length = self._wait_msg(deadline, "recv_into")
        with self._native_call(what="recv_into"):
            if length.value > dst.nbytes:
                self._lib.shm_requeue(self._ctx, msgid)
                raise ShmError(
                    f"recv_into buffer too small "
                    f"({dst.nbytes} < {length.value}); message requeued"
                )
            got = self._lib.shm_read(
                self._ctx, msgid, dst.ctypes.data, dst.nbytes
            )
        if got == -3:
            raise ShmPullError(
                f"shm CMA pull from peer {peer.value} failed"
            )
        if got != length.value:
            raise ShmError(f"short shm read {got} != {length.value}")
        SPC.record("sm_recv_bytes", length.value)
        return int(peer.value), int(tag.value), int(got)

    def recv_bytes(self, timeout: float = 10.0) -> tuple[int, int, Any]:
        """Next message as (peer, tag, payload); payload type follows
        poll_recv's contract (bytes <= 64 KiB, read-only memoryview
        above)."""
        deadline = time.monotonic() + timeout
        msgid, peer, tag, length = self._wait_msg(deadline, "recv")
        with self._native_call(what="recv"):
            return self._consume(msgid, peer, tag, length)

    def wait_event(self, timeout: float) -> bool:
        ms = max(1, min(200, int(timeout * 1000)))
        try:
            with self._native_call(what="wait_event"):
                return bool(self._lib.shm_wait_event(self._ctx, ms))
        except ShmError:
            return False  # closed

    def notify(self) -> None:
        try:
            with self._native_call(what="notify"):
                self._lib.shm_notify(self._ctx)
        except ShmError:
            pass

    def poll_send_complete(self) -> Optional[int]:
        return None  # sends complete synchronously (copy semantics)

    def peer_alive(self, peer_rank: int) -> bool:
        try:
            with self._native_call(what="peer_alive"):
                return bool(
                    self._lib.shm_peer_alive(self._ctx, peer_rank)
                )
        except ShmError:
            return False

    # -- tag-matching offload (reference: mtl.h:418-421; mirrors the
    # DcnEndpoint surface so the MTL muxes both engines) -------------------

    def enable_matching(self, wire_tag: int) -> None:
        """Divert completed messages carrying `wire_tag` into the
        engine's matcher (-1 disables)."""
        self._begin("enable_matching")
        try:
            self._lib.shm_enable_matching(self._ctx, wire_tag)
        finally:
            self._end()

    def _read_matched_locked(self, msgid: int):
        """Matched-message delivery; caller holds the guard (the read
        must not race close()'s destroy — _inflight is the drain
        barrier before the segment unmaps)."""
        n = self._lib.shm_msg_len(self._ctx, msgid)
        if n < 0:
            raise ShmError(f"unknown matched message {msgid}")
        return self._read_payload(msgid, n)

    def post_recv(self, handle: int, cid: int, src: int, dst: int,
                  tag: int):
        """Post a receive (src/tag < 0 wildcard). Returns the payload
        immediately when an unexpected message already matches; None
        when queued for the sweep."""
        self._begin("post_recv")
        try:
            msgid = self._lib.shm_post_recv(
                self._ctx, handle, cid, src, dst, tag
            )
            if not msgid:
                return None
            return self._read_matched_locked(msgid)
        finally:
            self._end()

    def wait_matched(self, handle: int, timeout: float):
        """Block NATIVELY until `handle`'s posted recv matches (sweep +
        doorbell futex in C — no Python progress per message); returns
        the payload, or None on timeout. Other handles' matches are
        left for their own collectors. Parks in <=100 ms slices per
        guard entry (same discipline as _wait_msg) so a concurrent
        close() observes the drain within one slice instead of
        stalling its 5 s deadline and leaking the mapping."""
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            slice_ms = max(1, min(100, int(remaining * 1000)))
            self._begin("wait_matched")
            try:
                msgid = self._lib.shm_wait_matched(
                    self._ctx, handle, slice_ms
                )
                if msgid:
                    return self._read_matched_locked(msgid)
            finally:
                self._end()

    def poll_matched(self):
        """(handle, payload) of one sweep-side match, or None."""
        handle = ctypes.c_longlong(0)
        self._begin("poll_matched")
        try:
            msgid = self._lib.shm_poll_matched(
                self._ctx, ctypes.byref(handle)
            )
            if not msgid:
                return None
            return int(handle.value), self._read_matched_locked(msgid)
        finally:
            self._end()

    def match_probe(self, cid: int, src: int, dst: int, tag: int):
        """(src, tag, nbytes) of the first compatible unexpected
        message without consuming it (MPI_Iprobe)."""
        o_src = ctypes.c_int(0)
        o_tag = ctypes.c_int(0)
        o_len = ctypes.c_longlong(0)
        self._begin("match_probe")
        try:
            hit = self._lib.shm_match_probe(
                self._ctx, cid, src, dst, tag, ctypes.byref(o_src),
                ctypes.byref(o_tag), ctypes.byref(o_len),
            )
        finally:
            self._end()
        if not hit:
            return None
        return int(o_src.value), int(o_tag.value), int(o_len.value)

    def peer_cma(self, peer_rank: int) -> bool:
        """True when bulk sends to this peer use the single-copy
        process_vm_readv path (probed at connect, may withdraw at
        runtime if the kernel starts denying the pull)."""
        try:
            with self._native_call(what="peer_cma"):
                return self._lib.shm_peer_cma(self._ctx, peer_rank) == 1
        except ShmError:
            return False

    def stats(self) -> dict:
        with self._native_call(what="stats"):
            return {
                n: int(self._lib.shm_stat(self._ctx, i))
                for i, n in enumerate(_STAT_NAMES)
            }

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            self._closed = True
        # Wake parked waiters, then drain in-flight native calls before
        # unmapping (same discipline as DcnEndpoint.close).
        try:
            self._lib.shm_notify(self._ctx)
        except (OSError, AttributeError) as exc:
            # the segment may already be torn down on the other side;
            # waiters fall back to their poll timeout
            from ..core.logging import warn_once

            warn_once("btl.sm", "shm close: wake notify failed: %s", exc)
        # Drain: _end() notifies _drained when the last in-flight
        # native call returns, so this parks instead of polling; the
        # timed wait (Backoff schedule, bounded by the 5 s deadline)
        # only guards a missed notify or a call wedged in its <=100 ms
        # futex slice.
        bo = Backoff(timeout=5.0, initial=0.001, maximum=0.05)
        with self._mu:
            while self._inflight and not bo.expired:
                self._drained.wait(
                    timeout=max(0.001, min(bo.next_delay(), 0.1))
                )
            remaining = self._inflight
        if remaining:
            logger.warning(
                "shm close: %d native call(s) did not drain; leaking "
                "the segment mapping rather than unmapping mid-call",
                remaining,
            )
            return
        if self._fp is not None:
            self._lib.fp_detach(self._fp)
            self._fp = None
        self._lib.shm_destroy(self._ctx)

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:  # commlint: allow(broadexcept)
            pass  # interpreter shutdown: nothing sane to do or log


def engine_available() -> bool:
    """True when the native shm engine is usable and enabled."""
    if not _enable_var.value:
        return False
    lib = build.get_lib()
    return lib is not None and hasattr(lib, "shm_create")


def register_health_probes(shm, peers) -> None:
    """Wire the shm + fastpath tier canaries to a live endpoint (the
    health/prober registration contract — called from fabric wire-up,
    the same selection seam the fault wrappers interpose at). The
    canaries hold only a weakref: a torn-down endpoint quietly retires
    its probes instead of keeping the segment mapped."""
    import weakref

    from ..health import prober as health_prober

    ref = weakref.ref(shm)
    peer_list = sorted(peers)

    def _shm_canary() -> None:
        ep = ref()
        if ep is None:
            # torn-down endpoint: no evidence either way — retire the
            # probe rather than report a success that would restore a
            # quarantined tier with no live endpoint behind it
            raise health_prober.ProbeRetired("shm endpoint retired")
        ep.stats()  # segment round trip: raises on a torn mapping
        dead = [p for p in peer_list if not ep.peer_alive(p)]
        if dead:
            raise RuntimeError(f"shm peer(s) dead: {dead}")

    def _fp_canary() -> None:
        ep = ref()
        if ep is None:
            raise health_prober.ProbeRetired("fp endpoint retired")
        if not ep.fp_available():
            raise RuntimeError("fastpath lane lost")
        ep.fp_stats()  # ring walk: raises when the fp segment is torn

    health_prober.register_probe(
        "shm", _shm_canary,
        description="shm v2 segment stat + peer liveness")
    if shm.fp_available():
        health_prober.register_probe(
            "fastpath", _fp_canary,
            description="fp lane availability + ring stats")


def host_identity() -> dict:
    """Same-machine identity for the modex business card: hostname can
    collide across containers, so pair it with the kernel boot id."""
    import socket

    boot = ""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip()
    except OSError:
        pass
    return {"host": socket.gethostname(), "boot": boot}


def new_prefix() -> str:
    """Job-unique segment prefix (rank 0 generates, the modex shares
    it): uid keeps parallel users on one box apart."""
    import uuid

    return f"ompitpu{os.getuid()}_{uuid.uuid4().hex[:10]}"


@BTL.register
class SmBtl(BtlComponent):
    """Same-host cross-process transport (shared memory). Outranks DCN
    for co-located peers (reference: btl/sm priority over tcp) — the
    actual byte path lives in the fabric's endpoint mux; this component
    makes the selection visible to the BML and comm_method."""

    NAME = "sm"
    PRIORITY = 40  # below self/ici (in-process), above dcn (10)
    EAGER_LIMIT = 32 * 1024  # btl_sm_component.c:243

    def available(self, **ctx: Any) -> bool:
        return engine_available()

    def can_reach(self, src_proc, dst_proc) -> bool:
        if src_proc.process_index == dst_proc.process_index:
            return False  # in-process: self/ici win
        from ..pml.framework import PML

        from ..core.errors import ComponentError

        try:
            ob1 = PML.component("ob1")
        except ComponentError:
            return False
        eng = getattr(ob1, "_fabric", None)
        if eng is None:
            return False
        shm_peers = getattr(eng, "shm_peers", set())
        import jax

        me = jax.process_index()
        return all(
            idx == me or idx in shm_peers
            for idx in (src_proc.process_index, dst_proc.process_index)
        )

    def wire_label(self, comm, src_rank: int, dst_rank: int) -> str:
        """comm_method detail: the negotiated sm lanes for this pair —
        "fp" when small messages toward the remote side ride the
        shared-ring descriptor fastpath, "cma" when bulk rides the
        single-copy pull. Renders "sm/fp+cma", "sm/fp", "sm/cma", or
        plain "sm" (mirrors the reference printing the sm mechanism).
        Local view only: pairs not involving this process render plain
        "sm" even if those two processes negotiated lanes between
        themselves — their mechanism is not observable from here."""
        from ..pml.framework import PML

        from ..core.errors import ComponentError

        try:
            eng = getattr(PML.component("ob1"), "_fabric", None)
        except ComponentError:
            return self.NAME
        if eng is None or eng.shm is None:
            return self.NAME
        import jax

        me = jax.process_index()
        indices = {comm.procs[src_rank].process_index,
                   comm.procs[dst_rank].process_index}
        if me not in indices:
            return self.NAME  # not our pair: mechanism unobservable
        remote = [idx for idx in indices if idx != me]
        lanes = []
        if remote and all(eng.shm.fp_available(idx) for idx in remote):
            lanes.append("fp")
        if remote and all(eng.shm.peer_cma(idx) for idx in remote):
            lanes.append("cma")
        if lanes:
            return f"{self.NAME}/{'+'.join(lanes)}"
        return self.NAME

    def transfer(self, value, src_proc, dst_proc):
        from ..core.errors import CommError

        raise CommError(
            "SmBtl.transfer: cross-process p2p goes through the PML "
            "fabric (ompi_tpu.pml.fabric.wire_up routes co-located "
            "peers over the shm endpoint); byte-level sends are "
            "available via ShmEndpoint"
        )

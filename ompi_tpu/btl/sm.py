"""btl/sm — intra-host shared-memory transport.

TPU-native equivalent of opal/mca/btl/sm (reference: btl_sm_fbox.h:22-60
per-peer lock-free fastboxes; btl_sm_component.c:200,243-245 — 4 KiB
fastbox / 32 KiB eager regime; btl_sm_module.c FIFO queues). The native
engine (native/src/shm.cc) owns the POSIX segment, the per-peer-pair
fastbox + eager SPSC rings, chunked bulk streaming and futex parking;
this module is the endpoint/bytes API plus the BTL component that makes
the selection visible to the BML/comm_method layers.

Role in the TPU design (SURVEY §5.8): same-host controller processes
previously exchanged ALL traffic over TCP loopback through the kernel
(~1 ms small-message p50 on 1-core hosts — VERDICT r3 missing #1);
this engine keeps the entire same-host path in user space. Peers are
addressed by their global process index; the modex publishes
(segment prefix, hostname) and `pml/fabric.wire_up` connects co-located
peers here while inter-host peers stay on DCN.
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import threading
import time
from typing import Any, Optional

import numpy as np

from ..core import config
from ..core.counters import SPC
from ..core.errors import OmpiTpuError
from ..core.logging import get_logger
from ..native import build
from .framework import BTL, BtlComponent

logger = get_logger("btl.sm")

_fbox_var = config.register(
    "btl", "sm", "fbox_size", type=int, default=4096,
    description="Per-peer fastbox ring bytes (reference: btl/sm 4 KiB "
                "fastbox, btl_sm_component.c:200)",
)
_ring_var = config.register(
    "btl", "sm", "ring_size", type=int, default=1 << 20,
    description="Per-peer eager/bulk ring bytes (reference: btl/sm FIFO)",
)
_max_peers_var = config.register(
    "btl", "sm", "max_peers", type=int, default=32,
    description="Sender slots in this process's shared segment",
)
_enable_var = config.register(
    "btl", "sm", "enable", type=bool, default=True,
    description="Use shared memory for same-host cross-process traffic "
                "(off: such traffic rides DCN TCP loopback)",
)
_eager_limit_var = config.register(
    "btl", "sm", "eager_limit", type=int, default=32 * 1024,
    description="Whole-message-inline limit for the shm eager ring; "
                "larger payloads chunk-stream (reference: btl/sm "
                "32 KiB eager, btl_sm_component.c:243)",
)
_cma_var = config.register(
    "btl", "sm", "use_cma", type=bool, default=True,
    description="Single-copy bulk transfers via process_vm_readv when "
                "the kernel allows it (probed per peer at connect; "
                "reference: btl/sm CMA get, btl_sm_get.c:69, mechanism "
                "selection btl_sm_component.c:453-478). Off or denied: "
                "bulk chunk-streams through the shared rings.",
)
_cma_min_var = config.register(
    "btl", "sm", "cma_min", type=int, default=256 * 1024,
    description="Smallest payload that takes the single-copy CMA path. "
                "CMA is a rendezvous (the sender parks until the "
                "receiver reads the message); below this, bulk keeps "
                "the buffered chunk tier and completes on return.",
)


class ShmError(OmpiTpuError):
    errclass = "ERR_OTHER"


class ShmPullError(ShmError):
    """A single-copy CMA pull failed mid-receive (sender exited or the
    kernel withdrew permission). If the sender is alive it re-sends the
    payload through the chunk tier, so waiters should keep waiting;
    the progress pump converts this into a DEVICE_ERROR event."""


def _declare(lib) -> None:
    import ctypes

    if getattr(lib, "_shm_declared", False):
        return
    LL = ctypes.c_longlong
    P = ctypes.c_void_p
    lib.shm_create.restype = P
    lib.shm_create.argtypes = [ctypes.c_char_p, ctypes.c_int,
                               ctypes.c_int, LL, LL, LL, ctypes.c_int,
                               LL]
    lib.shm_connect.restype = ctypes.c_int
    lib.shm_connect.argtypes = [P, ctypes.c_int, ctypes.c_int]
    lib.shm_send.restype = LL
    lib.shm_send.argtypes = [P, ctypes.c_int, LL, ctypes.c_void_p, LL]
    lib.shm_send2.restype = LL
    lib.shm_send2.argtypes = [P, ctypes.c_int, LL, ctypes.c_void_p, LL,
                              ctypes.c_void_p, LL]
    lib.shm_poll_recv.restype = LL
    lib.shm_poll_recv.argtypes = [
        P, ctypes.POINTER(ctypes.c_int), ctypes.POINTER(LL),
        ctypes.POINTER(LL),
    ]
    lib.shm_wait_recv.restype = LL
    lib.shm_wait_recv.argtypes = [
        P, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(LL), ctypes.POINTER(LL),
    ]
    lib.shm_wait_event.restype = ctypes.c_int
    lib.shm_wait_event.argtypes = [P, ctypes.c_int]
    lib.shm_notify.restype = None
    lib.shm_notify.argtypes = [P]
    lib.shm_read.restype = LL
    lib.shm_read.argtypes = [P, LL, ctypes.c_void_p, LL]
    lib.shm_requeue.restype = None
    lib.shm_requeue.argtypes = [P, LL]
    lib.shm_stat.restype = LL
    lib.shm_stat.argtypes = [P, ctypes.c_int]
    lib.shm_peer_alive.restype = ctypes.c_int
    lib.shm_peer_alive.argtypes = [P, ctypes.c_int]
    lib.shm_peer_cma.restype = ctypes.c_int
    lib.shm_peer_cma.argtypes = [P, ctypes.c_int]
    lib.shm_destroy.restype = None
    lib.shm_destroy.argtypes = [P]
    lib.cma_read.restype = ctypes.c_int
    lib.cma_read.argtypes = [LL, ctypes.c_ulonglong, ctypes.c_void_p, LL]
    lib.cma_write.restype = ctypes.c_int
    lib.cma_write.argtypes = [LL, ctypes.c_ulonglong, ctypes.c_void_p,
                              LL]
    lib.winseg_open.restype = P
    lib.winseg_open.argtypes = [ctypes.c_char_p, LL, ctypes.c_int]
    lib.winseg_close.restype = None
    lib.winseg_close.argtypes = [P, LL, ctypes.c_char_p, ctypes.c_int]
    lib.winseg_cas.restype = ctypes.c_int
    lib.winseg_cas.argtypes = [P, LL, ctypes.c_int, ctypes.c_int]
    lib.winseg_load.restype = ctypes.c_int
    lib.winseg_load.argtypes = [P, LL]
    lib.winseg_store.restype = None
    lib.winseg_store.argtypes = [P, LL, ctypes.c_int]
    lib.winseg_add.restype = ctypes.c_int
    lib.winseg_add.argtypes = [P, LL, ctypes.c_int]
    lib.winseg_wait.restype = ctypes.c_int
    lib.winseg_wait.argtypes = [P, LL, ctypes.c_int, ctypes.c_int]
    lib.winseg_wake.restype = None
    lib.winseg_wake.argtypes = [P, LL]
    lib.shm_enable_matching.restype = None
    lib.shm_enable_matching.argtypes = [P, LL]
    lib.shm_post_recv.restype = LL
    lib.shm_post_recv.argtypes = [P, LL, ctypes.c_int, ctypes.c_int,
                                  ctypes.c_int, ctypes.c_int]
    lib.shm_poll_matched.restype = LL
    lib.shm_poll_matched.argtypes = [P, ctypes.POINTER(LL)]
    lib.shm_match_probe.restype = ctypes.c_int
    lib.shm_match_probe.argtypes = [
        P, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(LL),
    ]
    lib.shm_msg_len.restype = LL
    lib.shm_msg_len.argtypes = [P, LL]
    lib.shm_wait_matched.restype = LL
    lib.shm_wait_matched.argtypes = [P, LL, ctypes.c_int]
    lib._shm_declared = True


class WinSyncSeg:
    """Shared 32-bit word array for one RMA window's same-host sync:
    word 0 is a modification counter, words 1..n per-rank
    readers-writer lock words (0 free, -1 exclusive, k>0 shared) —
    the osc/sm passive-target state, CPU atomics + futex parking
    (reference: osc_sm_passive_target.c)."""

    def __init__(self, name: str, n_words: int, create: bool) -> None:
        lib = build.get_lib()
        if lib is None or not hasattr(lib, "winseg_open"):
            raise ShmError("native winseg unavailable")
        _declare(lib)
        self._lib = lib
        self.name = name
        self.n_words = n_words
        self.creator = create
        self._base = lib.winseg_open(name.encode(), n_words,
                                     int(create))
        if not self._base:
            raise ShmError(f"cannot {'create' if create else 'attach'} "
                           f"window sync segment {name}")

    def cas(self, idx: int, expect: int, desired: int) -> int:
        return self._lib.winseg_cas(self._base, idx, expect, desired)

    def load(self, idx: int) -> int:
        return self._lib.winseg_load(self._base, idx)

    def store(self, idx: int, value: int) -> None:
        self._lib.winseg_store(self._base, idx, value)

    def add(self, idx: int, delta: int) -> int:
        return self._lib.winseg_add(self._base, idx, delta)

    def wait(self, idx: int, while_value: int, timeout_ms: int) -> int:
        return self._lib.winseg_wait(self._base, idx, while_value,
                                     timeout_ms)

    def wake(self, idx: int) -> None:
        self._lib.winseg_wake(self._base, idx)

    def close(self) -> None:
        if self._base:
            self._lib.winseg_close(self._base, self.n_words,
                                   self.name.encode(),
                                   int(self.creator))
            self._base = None


def cma_read_into(pid: int, addr: int, arr: np.ndarray) -> None:
    """Pull arr.nbytes from (pid, addr) into `arr` (contiguous) — the
    osc/sm direct-get data plane."""
    lib = build.get_lib()
    _declare(lib)
    rc = lib.cma_read(pid, addr, arr.ctypes.data, arr.nbytes)
    if rc != 0:
        raise ShmError(f"cma_read from pid {pid} failed")


def cma_write_from(pid: int, addr: int, arr: np.ndarray) -> None:
    """Push `arr` (contiguous) into (pid, addr) — the osc/sm direct-put
    data plane."""
    lib = build.get_lib()
    _declare(lib)
    rc = lib.cma_write(pid, addr, arr.ctypes.data, arr.nbytes)
    if rc != 0:
        raise ShmError(f"cma_write to pid {pid} failed")


_STAT_NAMES = (
    "bytes_sent", "bytes_recv", "fbox_sends", "ring_sends",
    "chunk_msgs", "msgs_recvd", "send_stalls", "fbox_recvs", "peers",
    "ns_stalled", "ns_sweep", "cma_sends", "cma_bytes_pulled",
    "cma_fails", "proto_errors", "offload_matches",
    "offload_unexpected",
)


class ShmEndpoint:
    """One process's shared-memory presence: its own segment plus maps
    of each connected peer's. Peers are global process indices (the
    slot-owner table in the segment records them)."""

    def __init__(self, prefix: str, my_rank: int) -> None:
        lib = build.get_lib()
        if lib is None or not hasattr(lib, "shm_create"):
            raise ShmError("native shm engine unavailable")
        _declare(lib)
        self._lib = lib
        self.prefix = prefix
        self.my_rank = my_rank
        self._ctx = lib.shm_create(
            prefix.encode(), my_rank, _max_peers_var.value,
            _fbox_var.value, _ring_var.value,
            _eager_limit_var.value, int(_cma_var.value),
            _cma_min_var.value,
        )
        if not self._ctx:
            raise ShmError(
                f"cannot create shm segment /{prefix}_{my_rank}"
            )
        self._mu = threading.Lock()
        self._inflight = 0
        self._closed = False
        self.peers: set[int] = set()

    def _begin(self, what: str) -> None:
        """Hot-path guard entry (the contextmanager variant costs ~3 us
        per call in generator machinery — real money at fastbox rates).
        Pair with _end() in a finally block."""
        with self._mu:
            if self._closed:
                raise ShmError(f"endpoint closed during {what}")
            self._inflight += 1

    def _end(self) -> None:
        with self._mu:
            self._inflight -= 1

    @contextlib.contextmanager
    def _native_call(self, *, what: str):
        with self._mu:
            if self._closed:
                raise ShmError(f"endpoint closed during {what}")
            self._inflight += 1
        try:
            yield
        finally:
            with self._mu:
                self._inflight -= 1

    def connect(self, peer_rank: int, timeout_s: float = 30.0) -> None:
        with self._native_call(what="connect"):
            rc = self._lib.shm_connect(
                self._ctx, peer_rank, int(timeout_s * 1000)
            )
        if rc != 0:
            raise ShmError(
                f"cannot attach peer {peer_rank}'s shm segment "
                f"(/{self.prefix}_{peer_rank})"
            )
        self.peers.add(peer_rank)

    @staticmethod
    def _as_ptr(data):
        """(address, nbytes, keepalive) for a bytes-like or array
        source with NO copy: ctypes reads the object's buffer in
        place (the engine's tiers never write through it)."""
        if isinstance(data, bytes):
            return (ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p),
                    len(data), data)
        arr = np.frombuffer(data, np.uint8)  # bytearray/memoryview/array
        return arr.ctypes.data, arr.nbytes, arr

    def _check_send_rc(self, rc, peer_rank: int, nbytes: int) -> int:
        if rc == -1:
            raise ShmError(f"send to unconnected shm peer {peer_rank}")
        if rc == -2:
            raise ShmError(f"shm peer {peer_rank} is dead")
        SPC.record("sm_send_bytes", nbytes)
        return 0  # copy/rendezvous semantics: complete on return

    def send_bytes(self, peer_rank: int, tag: int, data) -> int:
        ptr, n, keep = self._as_ptr(data)
        self._begin("send")
        try:
            rc = self._lib.shm_send(self._ctx, peer_rank, tag, ptr, n)
        finally:
            self._end()
        del keep
        return self._check_send_rc(rc, peer_rank, n)

    def send_bytes2(self, peer_rank: int, tag: int, hdr, payload) -> int:
        """Framed send (header + payload) with no Python-side
        concatenation: both buffers go to the engine as a gather pair;
        the receiver sees ONE message of len(hdr)+len(payload) bytes."""
        hp, hn, hkeep = self._as_ptr(hdr)
        pp, pn, pkeep = self._as_ptr(payload)
        self._begin("send2")
        try:
            rc = self._lib.shm_send2(
                self._ctx, peer_rank, tag, hp, hn, pp, pn
            )
        finally:
            self._end()
        del hkeep, pkeep
        return self._check_send_rc(rc, peer_rank, hn + pn)

    def poll_recv(self) -> Optional[tuple[int, int, Any]]:
        """One completed message as (peer, tag, payload) or None.
        Payload is `bytes` up to 64 KiB and a read-only memoryview
        above (zero-copy delivery of single-copy CMA pulls); both
        support len/slice/==/np.frombuffer. A failed CMA pull (sender
        vanished mid-rendezvous) raises ShmPullError — progress pumps
        convert it to a DEVICE_ERROR event and keep polling."""
        peer = ctypes.c_int(0)
        tag = ctypes.c_longlong(0)
        length = ctypes.c_longlong(0)
        # Only the closed-endpoint race (guard entry) maps to "no
        # message"; a _consume failure after the native side already
        # popped the message must propagate, not silently drop it.
        try:
            self._begin("poll")
        except ShmError:
            return None  # closed
        try:
            msgid = self._lib.shm_poll_recv(
                self._ctx, ctypes.byref(peer), ctypes.byref(tag),
                ctypes.byref(length),
            )
            if not msgid:
                return None
            return self._consume(msgid, peer, tag, length)
        finally:
            self._end()

    def _read_payload(self, msgid: int, n: int):
        """shm_read msgid into a fresh buffer; payload typed per the
        poll_recv contract (bytes <= 64 KiB, read-only memoryview
        above). Caller holds the _begin/_end guard."""
        buf = np.empty(max(1, n), np.uint8)
        got = self._lib.shm_read(self._ctx, msgid, buf.ctypes.data, n)
        if got == -3:
            # If the sender is alive it re-sends via the chunk tier —
            # this message id is gone but the payload is not.
            raise ShmPullError("shm CMA pull failed (peer gone?)")
        if got != n:
            raise ShmError(f"short shm read {got} != {n}")
        SPC.record("sm_recv_bytes", n)
        if n <= 65536:
            return buf[:n].tobytes()
        # Bulk: a .tobytes() here would re-copy what may have just
        # arrived as a SINGLE process_vm_readv into `buf`. The array
        # is exclusively ours — hand out a read-only view.
        return buf[:n].data.toreadonly()

    def _consume(self, msgid, peer, tag, length):
        payload = self._read_payload(msgid, length.value)
        return int(peer.value), int(tag.value), payload

    def _wait_msg(self, deadline, what):
        """Shared park-until-message loop; returns (msgid, peer, tag,
        length) ctypes cells, or raises ShmError on timeout."""
        peer = ctypes.c_int(0)
        tag = ctypes.c_longlong(0)
        length = ctypes.c_longlong(0)
        while True:
            remaining = deadline - time.monotonic()
            slice_ms = max(1, min(100, int(remaining * 1000)))
            self._begin(what)
            try:
                msgid = self._lib.shm_wait_recv(
                    self._ctx, slice_ms, ctypes.byref(peer),
                    ctypes.byref(tag), ctypes.byref(length),
                )
            finally:
                self._end()
            if msgid:
                return msgid, peer, tag, length
            if time.monotonic() >= deadline:
                raise ShmError("shm recv timeout")

    def recv_into(self, out, timeout: float = 10.0) -> tuple[int, int, int]:
        """Deliver the next message's payload into `out` (a writable
        buffer-protocol object, e.g. a reused numpy array — warm pages
        make the single-copy pull run at kernel-copy speed). Returns
        (peer, tag, nbytes). If `out` is too small the message is
        REQUEUED (front of the queue) and ShmError raised: retry with a
        larger buffer; nothing is lost and the sender stays parked."""
        dst = np.frombuffer(out, np.uint8)
        deadline = time.monotonic() + timeout
        msgid, peer, tag, length = self._wait_msg(deadline, "recv_into")
        with self._native_call(what="recv_into"):
            if length.value > dst.nbytes:
                self._lib.shm_requeue(self._ctx, msgid)
                raise ShmError(
                    f"recv_into buffer too small "
                    f"({dst.nbytes} < {length.value}); message requeued"
                )
            got = self._lib.shm_read(
                self._ctx, msgid, dst.ctypes.data, dst.nbytes
            )
        if got == -3:
            raise ShmPullError(
                f"shm CMA pull from peer {peer.value} failed"
            )
        if got != length.value:
            raise ShmError(f"short shm read {got} != {length.value}")
        SPC.record("sm_recv_bytes", length.value)
        return int(peer.value), int(tag.value), int(got)

    def recv_bytes(self, timeout: float = 10.0) -> tuple[int, int, Any]:
        """Next message as (peer, tag, payload); payload type follows
        poll_recv's contract (bytes <= 64 KiB, read-only memoryview
        above)."""
        deadline = time.monotonic() + timeout
        msgid, peer, tag, length = self._wait_msg(deadline, "recv")
        with self._native_call(what="recv"):
            return self._consume(msgid, peer, tag, length)

    def wait_event(self, timeout: float) -> bool:
        ms = max(1, min(200, int(timeout * 1000)))
        try:
            with self._native_call(what="wait_event"):
                return bool(self._lib.shm_wait_event(self._ctx, ms))
        except ShmError:
            return False  # closed

    def notify(self) -> None:
        try:
            with self._native_call(what="notify"):
                self._lib.shm_notify(self._ctx)
        except ShmError:
            pass

    def poll_send_complete(self) -> Optional[int]:
        return None  # sends complete synchronously (copy semantics)

    def peer_alive(self, peer_rank: int) -> bool:
        try:
            with self._native_call(what="peer_alive"):
                return bool(
                    self._lib.shm_peer_alive(self._ctx, peer_rank)
                )
        except ShmError:
            return False

    # -- tag-matching offload (reference: mtl.h:418-421; mirrors the
    # DcnEndpoint surface so the MTL muxes both engines) -------------------

    def enable_matching(self, wire_tag: int) -> None:
        """Divert completed messages carrying `wire_tag` into the
        engine's matcher (-1 disables)."""
        self._begin("enable_matching")
        try:
            self._lib.shm_enable_matching(self._ctx, wire_tag)
        finally:
            self._end()

    def _read_matched_locked(self, msgid: int):
        """Matched-message delivery; caller holds the guard (the read
        must not race close()'s destroy — _inflight is the drain
        barrier before the segment unmaps)."""
        n = self._lib.shm_msg_len(self._ctx, msgid)
        if n < 0:
            raise ShmError(f"unknown matched message {msgid}")
        return self._read_payload(msgid, n)

    def post_recv(self, handle: int, cid: int, src: int, dst: int,
                  tag: int):
        """Post a receive (src/tag < 0 wildcard). Returns the payload
        immediately when an unexpected message already matches; None
        when queued for the sweep."""
        self._begin("post_recv")
        try:
            msgid = self._lib.shm_post_recv(
                self._ctx, handle, cid, src, dst, tag
            )
            if not msgid:
                return None
            return self._read_matched_locked(msgid)
        finally:
            self._end()

    def wait_matched(self, handle: int, timeout: float):
        """Block NATIVELY until `handle`'s posted recv matches (sweep +
        doorbell futex in C — no Python progress per message); returns
        the payload, or None on timeout. Other handles' matches are
        left for their own collectors. Parks in <=100 ms slices per
        guard entry (same discipline as _wait_msg) so a concurrent
        close() observes the drain within one slice instead of
        stalling its 5 s deadline and leaking the mapping."""
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            slice_ms = max(1, min(100, int(remaining * 1000)))
            self._begin("wait_matched")
            try:
                msgid = self._lib.shm_wait_matched(
                    self._ctx, handle, slice_ms
                )
                if msgid:
                    return self._read_matched_locked(msgid)
            finally:
                self._end()

    def poll_matched(self):
        """(handle, payload) of one sweep-side match, or None."""
        handle = ctypes.c_longlong(0)
        self._begin("poll_matched")
        try:
            msgid = self._lib.shm_poll_matched(
                self._ctx, ctypes.byref(handle)
            )
            if not msgid:
                return None
            return int(handle.value), self._read_matched_locked(msgid)
        finally:
            self._end()

    def match_probe(self, cid: int, src: int, dst: int, tag: int):
        """(src, tag, nbytes) of the first compatible unexpected
        message without consuming it (MPI_Iprobe)."""
        o_src = ctypes.c_int(0)
        o_tag = ctypes.c_int(0)
        o_len = ctypes.c_longlong(0)
        self._begin("match_probe")
        try:
            hit = self._lib.shm_match_probe(
                self._ctx, cid, src, dst, tag, ctypes.byref(o_src),
                ctypes.byref(o_tag), ctypes.byref(o_len),
            )
        finally:
            self._end()
        if not hit:
            return None
        return int(o_src.value), int(o_tag.value), int(o_len.value)

    def peer_cma(self, peer_rank: int) -> bool:
        """True when bulk sends to this peer use the single-copy
        process_vm_readv path (probed at connect, may withdraw at
        runtime if the kernel starts denying the pull)."""
        try:
            with self._native_call(what="peer_cma"):
                return self._lib.shm_peer_cma(self._ctx, peer_rank) == 1
        except ShmError:
            return False

    def stats(self) -> dict:
        with self._native_call(what="stats"):
            return {
                n: int(self._lib.shm_stat(self._ctx, i))
                for i, n in enumerate(_STAT_NAMES)
            }

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            self._closed = True
        # Wake parked waiters, then drain in-flight native calls before
        # unmapping (same discipline as DcnEndpoint.close).
        try:
            self._lib.shm_notify(self._ctx)
        except (OSError, AttributeError) as exc:
            # the segment may already be torn down on the other side;
            # waiters fall back to their poll timeout
            from ..core.logging import warn_once

            warn_once("btl.sm", "shm close: wake notify failed: %s", exc)
        deadline = time.monotonic() + 5.0
        remaining = 1
        while time.monotonic() < deadline:
            with self._mu:
                remaining = self._inflight
            if remaining == 0:
                break
            time.sleep(0.001)
        if remaining:
            logger.warning(
                "shm close: %d native call(s) did not drain; leaking "
                "the segment mapping rather than unmapping mid-call",
                remaining,
            )
            return
        self._lib.shm_destroy(self._ctx)

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:  # commlint: allow(broadexcept)
            pass  # interpreter shutdown: nothing sane to do or log


def engine_available() -> bool:
    """True when the native shm engine is usable and enabled."""
    if not _enable_var.value:
        return False
    lib = build.get_lib()
    return lib is not None and hasattr(lib, "shm_create")


def host_identity() -> dict:
    """Same-machine identity for the modex business card: hostname can
    collide across containers, so pair it with the kernel boot id."""
    import socket

    boot = ""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip()
    except OSError:
        pass
    return {"host": socket.gethostname(), "boot": boot}


def new_prefix() -> str:
    """Job-unique segment prefix (rank 0 generates, the modex shares
    it): uid keeps parallel users on one box apart."""
    import uuid

    return f"ompitpu{os.getuid()}_{uuid.uuid4().hex[:10]}"


@BTL.register
class SmBtl(BtlComponent):
    """Same-host cross-process transport (shared memory). Outranks DCN
    for co-located peers (reference: btl/sm priority over tcp) — the
    actual byte path lives in the fabric's endpoint mux; this component
    makes the selection visible to the BML and comm_method."""

    NAME = "sm"
    PRIORITY = 40  # below self/ici (in-process), above dcn (10)
    EAGER_LIMIT = 32 * 1024  # btl_sm_component.c:243

    def available(self, **ctx: Any) -> bool:
        return engine_available()

    def can_reach(self, src_proc, dst_proc) -> bool:
        if src_proc.process_index == dst_proc.process_index:
            return False  # in-process: self/ici win
        from ..pml.framework import PML

        from ..core.errors import ComponentError

        try:
            ob1 = PML.component("ob1")
        except ComponentError:
            return False
        eng = getattr(ob1, "_fabric", None)
        if eng is None:
            return False
        shm_peers = getattr(eng, "shm_peers", set())
        import jax

        me = jax.process_index()
        return all(
            idx == me or idx in shm_peers
            for idx in (src_proc.process_index, dst_proc.process_index)
        )

    def wire_label(self, comm, src_rank: int, dst_rank: int) -> str:
        """comm_method detail: "sm/cma" when bulk toward the remote
        side of this pair rides the single-copy pull, plain "sm"
        otherwise (mirrors the reference printing the sm mechanism).
        Local view only: pairs not involving this process render plain
        "sm" even if those two processes negotiated CMA between
        themselves — their mechanism is not observable from here."""
        from ..pml.framework import PML

        from ..core.errors import ComponentError

        try:
            eng = getattr(PML.component("ob1"), "_fabric", None)
        except ComponentError:
            return self.NAME
        if eng is None or eng.shm is None:
            return self.NAME
        import jax

        me = jax.process_index()
        indices = {comm.procs[src_rank].process_index,
                   comm.procs[dst_rank].process_index}
        if me not in indices:
            return self.NAME  # not our pair: mechanism unobservable
        remote = [idx for idx in indices if idx != me]
        if remote and all(eng.shm.peer_cma(idx) for idx in remote):
            return f"{self.NAME}/cma"
        return self.NAME

    def transfer(self, value, src_proc, dst_proc):
        from ..core.errors import CommError

        raise CommError(
            "SmBtl.transfer: cross-process p2p goes through the PML "
            "fabric (ompi_tpu.pml.fabric.wire_up routes co-located "
            "peers over the shm endpoint); byte-level sends are "
            "available via ShmEndpoint"
        )

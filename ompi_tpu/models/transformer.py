"""Flagship model: a 3-D-parallel MoE transformer LM built entirely on
ompi_tpu's collective substrate.

This is the framework's "one model running end-to-end" (SURVEY §7 step 4
analog, extended to every §2.6 parallelism row):

- **dp**: batch sharded over the 'dp' mesh axis; gradients psum'd
  (parallel/dp).
- **pp**: transformer blocks split into stages over 'pp'; activations
  hop stages through ppermute edge channels in a GPipe schedule
  (parallel/pp).
- **tp**: Megatron column/row-sharded MLPs with sequence-parallel
  allgather / reduce_scatter transitions (parallel/tp).
- **sp**: the sequence dimension lives sharded over the 'tp' axis
  between blocks; attention is exact causal *ring attention* — KV blocks
  circulate the tp ring (parallel/sp).
- **ep**: alternating blocks use MoE MLPs whose experts are sharded over
  the same axis, dispatched by capacity-based all_to_all (parallel/ep).

Gradient synchronization rules (encoded in `_sync_grads`):
- every param: mean over dp;
- tp-replicated params (attn, norms, router, embed/head): psum over tp
  (each tp rank saw only its sequence shard);
- tp-sharded params (MLP shards, experts): no tp sync — each rank owns
  its slice;
- stage-stacked params: no pp sync; embed/head/final-norm (used by one
  stage, stored replicated): psum over pp.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import jax_compat
from ..parallel import dp as dp_mod
from ..parallel import overlap as overlap_mod

jax_compat.ensure()
from ..parallel import ep as ep_mod
from ..parallel import pp as pp_mod
from ..parallel import sp as sp_mod
from ..parallel import tp as tp_mod
from ..parallel.mesh_utils import factorize, make_mesh


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 4
    head_dim: int = 16
    d_ff: int = 128
    layers_per_stage: int = 2
    seq_len: int = 64
    n_experts: int = 4  # total experts (0 = dense-only)
    expert_ff: int = 64
    moe_every: int = 2  # every k-th layer is MoE (0 = never)
    capacity_factor: float = 1.25
    microbatches: int = 2
    lr: float = 1e-2
    dtype: Any = jnp.float32

    @property
    def qkv_dim(self) -> int:
        return self.n_heads * self.head_dim


# ---------------------------------------------------------------------------
# Parameter construction
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: ModelConfig, pp_size: int) -> dict:
    """Global (unsharded) parameter pytree; block params stacked over
    (stage, layer). Sharding is applied by the mesh specs at jit time."""
    k = jax.random.split(rng, 16)
    D, V, S = cfg.d_model, cfg.vocab, cfg.seq_len
    L, Pn = cfg.layers_per_stage, pp_size
    QKV, F = cfg.qkv_dim, cfg.d_ff
    E, Fe = max(cfg.n_experts, 1), cfg.expert_ff

    def norm(key, *shape, scale=0.02):
        return (jax.random.normal(key, shape) * scale).astype(cfg.dtype)

    return {
        "embed": norm(k[0], V, D),
        "pos": norm(k[1], S, D),
        "head": norm(k[2], D, V),
        "ln_f": jnp.ones((D,), cfg.dtype),
        "blocks": {
            "ln1": jnp.ones((Pn, L, D), cfg.dtype),
            "wq": norm(k[3], Pn, L, D, QKV),
            "wk": norm(k[4], Pn, L, D, QKV),
            "wv": norm(k[5], Pn, L, D, QKV),
            "wo": norm(k[6], Pn, L, QKV, D),
            "ln2": jnp.ones((Pn, L, D), cfg.dtype),
            "w1": norm(k[7], Pn, L, D, F),
            "w2": norm(k[8], Pn, L, F, D),
            "router": norm(k[9], Pn, L, D, E),
            "we1": norm(k[10], Pn, L, E, D, Fe),
            "we2": norm(k[11], Pn, L, E, Fe, D),
        },
    }


def param_specs(cfg: ModelConfig) -> dict:
    """PartitionSpecs: stage axis over 'pp'; Megatron shards over 'tp';
    experts sharded over 'tp' (= the ep axis)."""
    return {
        "embed": P(),
        "pos": P(),
        "head": P(),
        "ln_f": P(),
        "blocks": {
            "ln1": P("pp"),
            "wq": P("pp"),
            "wk": P("pp"),
            "wv": P("pp"),
            "wo": P("pp"),
            "ln2": P("pp"),
            "w1": P("pp", None, None, "tp"),
            "w2": P("pp", None, "tp", None),
            "router": P("pp"),
            "we1": P("pp", None, "tp", None, None),
            "we2": P("pp", None, "tp", None, None),
        },
    }


# Leaves whose gradients need a tp psum (saw only a sequence shard).
_TP_REPLICATED = {"ln1", "wq", "wk", "wv", "wo", "ln2", "router"}
# Leaves used by a single pipeline stage but stored replicated over pp.
_PP_REPLICATED_TOP = {"embed", "pos", "head", "ln_f"}


# ---------------------------------------------------------------------------
# Model math (per-rank block code, runs inside shard_map)
# ---------------------------------------------------------------------------

def _rmsnorm(x, scale):
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + 1e-6) * scale


def _attention(x, wq, wk, wv, wo, cfg: ModelConfig):
    """Ring attention over the tp axis; x is (B, T_local, D)."""
    B, T, D = x.shape
    H, Dh = cfg.n_heads, cfg.head_dim
    q = (x @ wq).reshape(B, T, H, Dh)
    kk = (x @ wk).reshape(B, T, H, Dh)
    v = (x @ wv).reshape(B, T, H, Dh)
    attn = jax.vmap(
        lambda qq, kkk, vv: sp_mod.ring_attention(
            qq, kkk, vv, axis_name="tp", causal=True
        )
    )(q, kk, v)
    return attn.reshape(B, T, H * Dh) @ wo


def _dense_mlp(x, w1, w2):
    """Megatron TP MLP with sequence-parallel transitions; x (B,T,D)."""
    B = x.shape[0]
    flat = x.reshape(-1, x.shape[-1])  # (B*T_local, D)
    out = tp_mod.tp_mlp(flat, w1, w2, axis_name="tp")
    return out.reshape(x.shape)


def _moe_mlp(x, router, we1, we2, cfg: ModelConfig):
    """Expert-parallel MoE over the tp(=ep) axis; x (B,T,D)."""
    n_local = we1.shape[0]  # experts this rank owns (E_total/ntp)
    flat = x.reshape(-1, x.shape[-1])
    logits = flat @ router

    def expert_fn(e, toks):
        h = jax.nn.gelu(toks @ we1[e])
        return h @ we2[e]

    out = ep_mod.moe_dispatch_combine(
        flat, logits, expert_fn, n_local, axis_name="tp",
        capacity_factor=cfg.capacity_factor,
    )
    return out.reshape(x.shape)


def _block(x, bp, layer: int, cfg: ModelConfig, use_moe: bool):
    g = lambda leaf: leaf[layer]
    h = x + _attention(
        _rmsnorm(x, g(bp["ln1"])), g(bp["wq"]), g(bp["wk"]), g(bp["wv"]),
        g(bp["wo"]), cfg,
    )
    norm2 = _rmsnorm(h, g(bp["ln2"]))
    if use_moe:
        return h + _moe_mlp(
            norm2, g(bp["router"]), g(bp["we1"]), g(bp["we2"]), cfg
        )
    return h + _dense_mlp(norm2, g(bp["w1"]), g(bp["w2"]))


def _stage_fn(stage_blocks, x, cfg: ModelConfig):
    """Apply this stage's layers_per_stage blocks to (B, T_local, D).

    Each block's input carries a grad_marker: its backward rule fires
    once every gradient inside the block has been produced, so the
    captured order is the true per-layer backprop tile schedule
    (parallel/overlap replays it for tile-granular Pready firing)."""
    for layer in range(cfg.layers_per_stage):
        use_moe = (
            cfg.n_experts > 0
            and cfg.moe_every > 0
            and (layer % cfg.moe_every) == (cfg.moe_every - 1)
        )
        x = overlap_mod.grad_marker(x, f"blk{layer}")
        x = _block(x, stage_blocks, layer, cfg, use_moe)
    return x


# ---------------------------------------------------------------------------
# The SPMD training step
# ---------------------------------------------------------------------------

def _forward_loss(params, tokens, targets, cfg: ModelConfig):
    """Per-rank forward+loss. tokens/targets: (B_local, S) replicated
    over pp/tp; returns global-mean scalar loss (same on every rank)."""
    B, S = tokens.shape
    ntp = lax.axis_size("tp")
    T = S // ntp  # local sequence shard

    # Embed + positional, then shard the sequence over tp. The marker's
    # backward rule fires last — embed/pos grads close the backprop.
    x = overlap_mod.grad_marker(
        params["embed"][tokens] + params["pos"][None, :S], "embed"
    )
    tp_idx = lax.axis_index("tp")
    x = lax.dynamic_slice_in_dim(x, tp_idx * T, T, axis=1)  # (B, T, D)

    # Microbatch split for the pipeline.
    M = cfg.microbatches
    mb = B // M
    micro = x.reshape(M, mb, T, x.shape[-1])

    # params["blocks"] is already this rank's stage slice (shard_map
    # delivered the 'pp'-sharded leading axis, squeezed by the wrapper).
    outs = pp_mod.pipeline(
        lambda bp, h: _stage_fn(bp, h, cfg), params["blocks"], micro,
        axis_name="pp",
    )  # (M, mb, T, D), valid on last pp stage

    h = outs.reshape(B, T, -1)
    h = _rmsnorm(h, params["ln_f"])
    logits = h @ params["head"]  # (B, T, V)

    tgt = lax.dynamic_slice_in_dim(targets, tp_idx * T, T, axis=1)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    local_sum = jnp.sum(nll)

    npp = lax.axis_size("pp")
    stage = lax.axis_index("pp")
    ndp = lax.axis_size("dp")
    # Only the last stage's activations are real; mask the others. This
    # is the LOCAL loss share: no collective here — differentiating a
    # psum under shard_map (rep-checking off) multiplies cotangents by
    # the group size, so the cross-rank reduction of both loss and
    # grads happens explicitly outside the grad (_sync_grads / the
    # caller's psum), keeping per-rank cotangents exactly 1.
    local_sum = jnp.where(stage == npp - 1, local_sum, 0.0)
    ntokens_global = B * S * ndp
    return local_sum / ntokens_global


def _sync_grads(grads, cfg: ModelConfig):
    """Apply the gradient synchronization rules (module docstring).

    The local loss already carries the 1/(global tokens) normalization,
    so every cross-rank combination is a SUM: over dp for all params
    (each dp rank saw a batch shard), over tp for tp-replicated params
    (each tp rank saw a sequence shard), over pp for the stage-shared
    top-level params (only one stage's copy received gradient).

    The tp/pp sums are few and stay per-leaf; the dp sum — every
    parameter, the DDP-style gradient reduction — goes through the
    bucket coalescer (parallel/dp.allreduce_gradients): leaves fuse
    into size-capped flat buckets with one collective per bucket, so
    tuned scheduling and the quantized wire tier apply at bucket
    granularity.  Values match the per-leaf psums exactly — an
    elementwise sum of a concatenation is the concatenation of the
    sums.
    """
    from ..parallel import dp as _dp

    pre = {}
    for name in ("embed", "pos", "head", "ln_f"):
        g = grads[name]
        g = lax.psum(g, "tp")
        pre[name] = lax.psum(g, "pp")
    pre["blocks"] = {
        name: lax.psum(g, "tp") if name in _TP_REPLICATED else g
        for name, g in grads["blocks"].items()
    }
    # Capture the readiness schedule of the exact tree handed to the dp
    # reduction — the tile order parallel/overlap's mark_ready replays.
    pre = overlap_mod.capture_ready_schedule(pre)
    return _dp.allreduce_gradients(pre, "dp")


def build_train_step(cfg: ModelConfig, mesh):
    """Compile the full SPMD training step over a ('dp','pp','tp') mesh.

    Returns step(params, tokens, targets) -> (loss, new_params); params
    enter/leave sharded per param_specs.
    """
    specs = param_specs(cfg)

    def per_rank(params, tokens, targets):
        local_loss, grads = jax.value_and_grad(
            lambda p: _forward_loss(p, tokens, targets, cfg)
        )(params)
        grads = _sync_grads(grads, cfg)
        # Reported loss: sum the local shares OUTSIDE the grad.
        loss = lax.psum(
            lax.psum(lax.psum(local_loss, "tp"), "pp"), "dp"
        )
        new_params = jax.tree.map(
            lambda p, g: (p - cfg.lr * g).astype(p.dtype), params, grads
        )
        return loss, new_params

    # shard_map hands each rank a (1, L, ...) slice of every
    # 'pp'-sharded blocks leaf; squeeze that stage axis so the block code
    # sees its own stage's (L, ...) params directly, and restore it on
    # the way out.
    def per_rank_wrapped(params, tokens, targets):
        params = dict(params)
        params["blocks"] = jax.tree.map(
            lambda l: l[0], params["blocks"]
        )
        loss, new_params = per_rank(params, tokens, targets)
        new_params["blocks"] = jax.tree.map(
            lambda l: l[None], new_params["blocks"]
        )
        return loss, new_params

    in_specs = (
        specs,
        P("dp"),  # tokens: batch sharded over dp
        P("dp"),
    )
    out_specs = (P(), specs)

    fn = jax.shard_map(
        per_rank_wrapped,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(fn, donate_argnums=(0,))


def build_forward(cfg: ModelConfig, mesh):
    """Compile the forward+loss only (no grad, no donation) — the
    compile-check entry point."""
    specs = param_specs(cfg)

    def per_rank(params, tokens, targets):
        params = dict(params)
        params["blocks"] = jax.tree.map(lambda l: l[0], params["blocks"])
        local = _forward_loss(params, tokens, targets, cfg)
        return lax.psum(lax.psum(lax.psum(local, "tp"), "pp"), "dp")

    fn = jax.shard_map(
        per_rank,
        mesh=mesh,
        in_specs=(specs, P("dp"), P("dp")),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(fn)


def sharded_init(cfg: ModelConfig, mesh, seed: int = 0):
    """Init params and place them according to param_specs."""
    pp_size = mesh.shape["pp"]
    params = init_params(jax.random.PRNGKey(seed), cfg, pp_size)
    specs = param_specs(cfg)
    # PartitionSpec is itself a pytree (tuple), so flatten the spec tree
    # with specs-as-leaves and zip against the param leaves.
    leaves, treedef = jax.tree.flatten(params)
    spec_leaves = jax.tree.leaves(
        specs, is_leaf=lambda s: isinstance(s, P)
    )
    placed = [
        jax.device_put(x, NamedSharding(mesh, s))
        for x, s in zip(leaves, spec_leaves)
    ]
    return jax.tree.unflatten(treedef, placed)


def demo_mesh(n_devices: Optional[int] = None, devices=None):
    """A (dp, pp, tp) mesh factorizing the available devices."""
    import jax as _jax

    if devices is None:
        devices = _jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    dims = factorize(n, 3)
    return make_mesh(
        {"dp": dims[0], "pp": dims[1], "tp": dims[2]}, devices
    )


def make_batch(cfg: ModelConfig, batch: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab, size=(batch, cfg.seq_len))
    targets = np.roll(tokens, -1, axis=1)
    return jnp.asarray(tokens, jnp.int32), jnp.asarray(targets, jnp.int32)

"""Demonstration models exercising the full parallelism stack."""

from . import transformer

__all__ = ["transformer"]

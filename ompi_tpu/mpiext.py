"""Extensions — the mpiext mechanism's TPU-native forms.

TPU-native equivalent of ompi/mpiext (reference: affinity — rank
binding report; cuda — MPIX_Query_cuda_support; pcollreq — persistent
collectives; shortfloat — half-precision types). Each extension maps to
its platform-native answer:

- `query_device_support()` ≈ MPIX_Query_cuda_support: are collectives
  operating on device-resident (TPU/accelerator) buffers?
- `affinity_str(comm)` ≈ MPIX_Affinity_str: per-rank placement report
  (device, platform, host process, ICI coords).
- persistent collectives (pcollreq) live on the communicator
  (`allreduce_init` / `bcast_init`).
- shortfloat ≈ bfloat16/float16 datatypes, first-class in the dtype
  table (the MXU's native precision — better than the reference's
  add-on short floats).
"""

from __future__ import annotations


def query_device_support() -> bool:
    """True when rank buffers live on accelerator devices (the
    MPIX_Query_cuda_support analog: 'can I pass device pointers?' —
    here device arrays are the native currency, so this is False only
    on CPU-emulated meshes)."""
    from . import api

    comm = api.world()
    return any(p.platform == "tpu" for p in comm.procs)


def affinity_str(comm=None) -> str:
    """Per-rank placement table (reference: mpiext/affinity's
    OMPI_Affinity_str)."""
    from . import api

    comm = comm or api.world()
    lines = []
    for r, proc in enumerate(comm.procs):
        dev = proc.device
        coords = getattr(dev, "coords", None)
        lines.append(
            f"rank {r}: device={dev} platform={proc.platform} "
            f"process={proc.process_index}"
            + (f" coords={tuple(coords)}" if coords else "")
        )
    return "\n".join(lines)

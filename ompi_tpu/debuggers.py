"""Parallel-debugger attach interface (MPIR analog).

TPU-native equivalent of ompi/debuggers (reference:
ompi_debuggers.c:84-129 — the MPIR spec's `MPIR_proctable` describing
every rank for TotalView/DDT, plus the `MPIR_debug_gate` the launcher
releases once the debugger attached). The driver analog: one process
per host, ranks are devices — the proctable maps rank → (host pid,
device, platform, coords) so a tools process can find everything, and
the gate is an env-controlled barrier before init returns.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

from .core.logging import get_logger

logger = get_logger("debuggers")

#: set to "1" by an attaching tool to release the gate
GATE_ENV = "OMPITPU_DEBUG_GATE"
#: set to "1" in the job env to make init wait for an attach
WAIT_ENV = "OMPITPU_WAIT_FOR_DEBUGGER"


@dataclass
class ProcEntry:
    rank: int
    pid: int
    device: str
    platform: str
    process_index: int
    coords: tuple = ()


@dataclass
class Proctable:
    entries: list = field(default_factory=list)
    being_debugged: bool = False


def build_proctable(comm) -> Proctable:
    """The MPIR_proctable analog for a communicator."""
    pt = Proctable(being_debugged=os.environ.get(WAIT_ENV) == "1")
    for r, proc in enumerate(comm.procs):
        dev = proc.device
        pt.entries.append(
            ProcEntry(
                rank=r,
                pid=os.getpid(),
                device=str(dev),
                platform=getattr(proc, "platform", "?"),
                process_index=proc.process_index,
                coords=tuple(getattr(dev, "coords", ()) or ()),
            )
        )
    return pt


def wait_for_debugger(poll_s: float = 0.1, timeout: float = 600.0) -> bool:
    """The MPIR_debug_gate: when WAIT_ENV is set, block until a tool
    sets GATE_ENV (reference: debugger spins on MPIR_debug_gate,
    ompi_debuggers.c:129). Returns True if gated."""
    if os.environ.get(WAIT_ENV) != "1":
        return False
    logger.info(
        "waiting for debugger (release: set %s=1 in this process)",
        GATE_ENV,
    )
    deadline = time.monotonic() + timeout
    while os.environ.get(GATE_ENV) != "1":
        if time.monotonic() >= deadline:
            raise TimeoutError("debugger gate never released")
        time.sleep(poll_s)
    return True

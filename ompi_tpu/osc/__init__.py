"""One-sided communication (reference: ompi/mca/osc)."""

from .fabric_window import FabricWindow
from .window import (
    LOCK_EXCLUSIVE,
    LOCK_SHARED,
    DynamicWindow,
    SyncType,
    Window,
    WindowResult,
    allocate_window,
    create_dynamic_window,
    create_window,
)

__all__ = [
    "DynamicWindow", "FabricWindow", "LOCK_EXCLUSIVE", "LOCK_SHARED", "SyncType",
    "Window", "WindowResult", "allocate_window",
    "create_dynamic_window", "create_window",
]

"""One-sided communication (reference: ompi/mca/osc)."""

from .window import (
    LOCK_EXCLUSIVE,
    LOCK_SHARED,
    SyncType,
    Window,
    WindowResult,
    allocate_window,
    create_window,
)

__all__ = [
    "LOCK_EXCLUSIVE", "LOCK_SHARED", "SyncType", "Window",
    "WindowResult", "allocate_window", "create_window",
]

"""Cross-process one-sided communication over the fabric.

TPU-native equivalent of osc/rdma's network path (reference:
osc_rdma_comm.c put/get over btl; osc_rdma_accumulate.c's
active-message fallback when the btl has no native atomics;
osc_rdma_sync.h:24-30 epoch state machine; osc_rdma_lock.h passive
locks). There is no RDMA into another controller's HBM, so every
remote RMA op is an ACTIVE MESSAGE: the origin ships op descriptors
over the parent comm's p2p (pml/fabric over DCN) and the TARGET's
controller applies them to its device-resident blocks — exactly the
reference's fallback mode, with the epoch close as the completion
point.

Window layout on a spanning comm: each controller holds the rank-major
blocks of its LOCAL ranks (an inner `Window` over the auto-wired local
sub-communicator, so the apply machinery — compiled scatter/gather
epochs — is shared with the single-controller path).

Synchronization:
- **fence**: origin flushes per-target-process batches (one message per
  peer controller, empty allowed), the passive handler applies arrivals
  and answers each batch with a reply (get/fetch results + ack), and a
  spanning barrier (coll/hier) closes the epoch.
- **lock/unlock**: a lock manager at the target's controller grants
  shared/exclusive access per local rank (request/grant messages — the
  reference uses remote atomics, osc_rdma_lock.h); unlock ships the
  batch and completes on the reply.
- **passive-side application**: the handler is registered with the
  progress engine, so ANY blocking call on the target's controller
  applies pending remote ops (the same progress-dependent guarantee the
  reference's active-message mode has).
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Optional

import numpy as np

from ..core import progress as _progress
from ..core.counters import SPC
from ..core.errors import RMASyncError, WinError
from ..ops import lookup as op_lookup
from .window import (
    LOCK_EXCLUSIVE,
    LOCK_SHARED,
    SyncType,
    Window,
    WindowResult,
    _PendingOp,
)

#: Tag band above the hier epochs' windows (those top out below
#: 0x5900_0000); 8 sub-tags per window id.
_TAG_BASE = 0x60000000
_T_BATCH = 0   # op batch (fence flush or unlock flush)
_T_REPLY = 1   # per-batch reply: get/fetch results + application ack
_T_LOCK = 2    # lock request
_T_GRANT = 3   # lock grant
_T_POST = 4    # PSCW exposure-epoch notification (post -> origins)
_T_XCHG = 5    # osc/sm direct-mode mirror exchange at creation

def _enc_index(idx) -> Any:
    """dss-able encoding of a window index (None | int | slice |
    integer index array | tuple of those) — the datatype story of the
    RMA wire. Index arrays carry SHMEM's strided/element-offset ops
    (iput/iget unravel to coordinate arrays for multi-dim blocks)."""
    import numpy as _np

    if idx is None or isinstance(idx, (int, _np.integer)):
        return int(idx) if idx is not None else None
    if isinstance(idx, slice):
        return ("s", idx.start, idx.stop, idx.step)
    if isinstance(idx, _np.ndarray) and idx.dtype.kind in "iu":
        return ("a", idx.dtype.str, idx.tolist())
    if isinstance(idx, tuple):
        return ("t",) + tuple(_enc_index(i) for i in idx)
    raise WinError(f"unsupported remote RMA index {idx!r}")


def _dec_index(enc) -> Any:
    import numpy as _np

    if enc is None or isinstance(enc, int):
        return enc
    if isinstance(enc, (tuple, list)):
        if enc[0] == "s":
            return slice(enc[1], enc[2], enc[3])
        if enc[0] == "a":
            return _np.asarray(enc[2], dtype=_np.dtype(enc[1]))
        if enc[0] == "t":
            return tuple(_dec_index(i) for i in enc[1:])
    raise WinError(f"bad remote RMA index encoding {enc!r}")


class FabricWindow:
    """An RMA window over a process-spanning communicator."""

    RESULT_KINDS = ("get", "get_acc", "fetch_op", "cswap")

    def __init__(self, comm, buffer, *, name: str = "") -> None:
        import jax.numpy as jnp

        from ..coll.hier import comm_slice

        self.comm = comm
        self.h = comm_slice(comm)
        # Window creation is collective over the comm, so a per-comm
        # counter yields the SAME id on every controller (tags derive
        # from it); a process-global counter would diverge when the
        # controllers hold different comm sets.
        if not hasattr(comm, "_win_counter"):
            comm._win_counter = itertools.count(0)
        self.win_id = next(comm._win_counter)
        self.name = name or f"fwin{comm.cid}.{self.win_id}"
        arr = jnp.asarray(buffer)
        n_local = self.h.comm.size
        if arr.shape[0] != n_local:
            raise WinError(
                f"{self.name}: spanning-comm window buffer carries this "
                f"controller's LOCAL blocks; leading dim must be "
                f"{n_local}, got {arr.shape[0]}"
            )
        self._inner = Window(self.h.comm, arr, name=f"{self.name}.local")
        self._inner.fence()  # persistent inner epoch; we own outer sync
        self._sync = SyncType.NONE
        self._epoch = 0
        self._remote_pending: dict[int, list[dict]] = {}  # slice -> ops
        self._result_slots: dict[int, list[list]] = {}    # slice -> slots
        self._locks: dict[int, str] = {}
        # lock manager for MY local ranks: rank -> (mode, holders,
        # waitq of (origin_slice, mode))
        self._lock_state: dict[int, list] = {}
        self._lock_mu = threading.RLock()
        # fence arrival accounting (driven by the handler)
        self._got_batches: set[int] = set()
        # PSCW accounting: counters, not sets — back-to-back epochs
        # from the same peer must not coalesce
        self._pscw_done: dict[int, int] = {}    # origin -> completions
        self._post_tokens: dict[int, int] = {}  # target -> posts seen
        self._pscw_origins: list[int] = []
        self._pscw_posted = False
        self._held: list = []  # future-epoch messages
        self._in_handler = False
        self._in_close = False
        self._arming = False
        self._freed = False
        # osc/sm direct data plane (reference: osc/sm maps the window
        # into every same-node process and does loads/stores,
        # osc_sm_component.c / osc_sm_passive_target.c:269). Here each
        # controller exposes a HOST MIRROR of its local blocks; when
        # every peer controller is same-host with CMA reach, put/get
        # against contiguous spans go straight at the target's mirror
        # with process_vm_writev/readv — no op batch, no reply round.
        # Accumulates/cswaps stay on the active-message path (the
        # target controller applies them, giving element-atomicity),
        # but apply to the mirror. The device array re-lands lazily at
        # epoch boundaries (.array materializes it on demand).
        self._direct = False
        self._mirror: Optional[np.ndarray] = None
        self._mirror_dirty = False
        self._peer_mirrors: dict[int, tuple[int, int]] = {}
        self._slice_ranks: dict[int, list[int]] = {}
        _progress.register(self._handle_arrivals)
        self._try_direct_mode()

    # -- accessors ---------------------------------------------------------

    @property
    def array(self):
        """This controller's local blocks (rank-major over local
        ranks). Direct mode re-lands the host mirror onto the local
        devices lazily — once per epoch with remote writes, not per
        access."""
        if self._direct:
            mod = self._winseg.load(0)
            if self._mirror_dirty or mod != self._seen_mod:
                self._inner._set_array(self._mirror)
                # epoch ordering, not the segment lock, guards this:
                # remote writers only flip the flag inside an exposure
                # epoch, and .array reads outside one
                self._mirror_dirty = False  # commlint: allow(unguardedwrite)
                self._seen_mod = mod
        return self._inner.array

    @property
    def block_shape(self):
        return self._inner.block_shape

    def _set_array(self, arr) -> None:
        """Replace this controller's LOCAL blocks (SHMEM collectives
        deliver local rank-major results on spanning comms)."""
        if self._direct:
            np.copyto(self._mirror, np.asarray(arr))
            self._mirror_dirty = True
            return
        self._inner._set_array(arr)

    def _local_idx_or_raise(self, pe: int) -> int:
        pe = self.comm.check_rank(pe)
        if self.h.rank_slice[pe] != self.h.slice_id:
            raise WinError(
                f"{self.name}: PE {pe} lives on another controller; "
                "use get()/put() for remote symmetric access"
            )
        return self._local_idx(pe)

    def _tag(self, sub: int) -> int:
        return _TAG_BASE + (self.win_id % 0xFFFF) * 8 + sub

    def _slice_of(self, target: int) -> int:
        return self.h.rank_slice[self.comm.check_rank(target)]

    def _local_idx(self, target: int) -> int:
        return self.h.local_ranks.index(target)

    def _leader(self, slice_id: int) -> int:
        return self.h.leaders[slice_id]

    def _my_leader(self) -> int:
        return self.h.leaders[self.h.slice_id]

    def _check_alive(self):
        if self._freed:
            raise WinError(f"{self.name} has been freed")

    def _check_epoch(self, target: Optional[int] = None):
        if self._sync == SyncType.NONE:
            raise RMASyncError(
                f"{self.name}: RMA op outside an access epoch"
            )
        if self._sync == SyncType.LOCK and target is not None:
            if target not in self._locks:
                raise RMASyncError(
                    f"{self.name}: target {target} is not locked"
                )
        if self._sync == SyncType.PSCW and target is not None:
            if target not in self._pscw_targets:
                raise RMASyncError(
                    f"{self.name}: target {target} is outside the "
                    f"start() group {self._pscw_targets}"
                )

    # -- osc/sm direct data plane ------------------------------------------

    def _try_direct_mode(self) -> None:
        """Collective capability exchange: direct mode arms only when
        EVERY controller sees every peer over shm with CMA (the
        reference's osc/sm selects only for single-node comms,
        osc_sm_component.c query)."""
        self._arming = True
        try:
            self._try_direct_mode_inner()
        finally:
            self._arming = False
            self._release_held()

    def _try_direct_mode_inner(self) -> None:
        import os

        from ..pml.framework import PML

        try:
            eng = getattr(PML.component("ob1"), "_fabric", None)
        except Exception:
            return
        peers = [s for s in range(self.h.n_slices)
                 if s != self.h.slice_id]
        leader_idx = {
            s: self.comm.procs[self._leader(s)].process_index
            for s in peers
        }
        if (eng is None or eng.shm is None
                or not all(idx in eng.shm_peers
                           for idx in leader_idx.values())):
            return  # not same-host-complete: no exchange (symmetric
                    # knowledge — shm_peers comes from the modex)
        from ..btl.sm import ShmError, WinSyncSeg

        my_ok = all(eng.shm.peer_cma(idx)
                    for idx in leader_idx.values())
        # Lock-word segment (word 0 = modification counter, 1..size =
        # per-rank rw-lock words; reference: osc_sm_passive_target.c).
        # The CREATOR builds it BEFORE phase 1, so by the time any
        # attacher acts, a stale same-name segment from a crashed run
        # has already been unlinked and replaced — attach can never
        # land on the old one.
        seg_name = (f"/{eng.shm.prefix}_w{self.comm.cid % 0xFFFF}_"
                    f"{self.win_id % 0xFFFF}")
        creator = self.h.slice_id == 0
        winseg = None
        if creator and my_ok:
            try:
                winseg = WinSyncSeg(seg_name, 1 + self.comm.size,
                                    create=True)
            except ShmError:
                my_ok = False
        # explicit copy: np.asarray over a jax array is a READ-ONLY
        # view and ascontiguousarray would pass it through unchanged
        self._mirror = np.array(self._inner.array, copy=True)
        me = self._my_leader()
        # phase 1: capabilities + mirror addresses
        for s in peers:
            self._send_msg(s, _T_XCHG, {
                "win": self.win_id, "cma": my_ok,
                "pid": os.getpid(), "addr": self._mirror.ctypes.data,
            })
        ok = my_ok
        for s in peers:
            rec = self.comm.recv(source=self._leader(s),
                                 tag=self._tag(_T_XCHG), dest=me)
            if rec.get("win") != self.win_id:
                raise WinError(f"{self.name}: foreign mirror exchange")
            ok = ok and bool(rec.get("cma"))
            self._peer_mirrors[s] = (int(rec["pid"]), int(rec["addr"]))
        # attach only once phase 1 proved the creator built the segment
        if ok and not creator:
            try:
                winseg = WinSyncSeg(seg_name, 1 + self.comm.size,
                                    create=False)
            except ShmError:
                ok = False
        # phase 2: confirm — ANY rank's failure (winseg attach,
        # /dev/shm pressure) disarms EVERY rank, or the data planes
        # would diverge mid-window
        for s in peers:
            self._send_msg(s, _T_XCHG, {"win": self.win_id, "ok": ok})
        final = ok
        for s in peers:
            rec = self.comm.recv(source=self._leader(s),
                                 tag=self._tag(_T_XCHG), dest=me)
            final = final and bool(rec.get("ok"))
        if not final:
            if winseg is not None:
                winseg.close()
            self._mirror = None
            self._peer_mirrors.clear()
            return
        for s in range(self.h.n_slices):
            self._slice_ranks[s] = [
                r for r in range(self.comm.size)
                if self.h.rank_slice[r] == s
            ]
        self._winseg = winseg
        self._seen_mod = self._winseg.load(0)
        self._direct = True
        SPC.record("osc_sm_direct_windows")

    def _direct_span(self, index) -> Optional[tuple[int, tuple]]:
        """(byte offset, shape) of a contiguous span of one block, or
        None when the index needs the general apply path (step slices,
        index arrays, tuples)."""
        bshape = tuple(self._mirror.shape[1:])
        itemsize = self._mirror.dtype.itemsize
        if index is None:
            return 0, bshape
        if not bshape:
            return None  # scalar blocks: only whole-block access
        row = itemsize
        for d in bshape[1:]:
            row *= int(d)
        if isinstance(index, (int, np.integer)):
            i = int(index)
            if not -bshape[0] <= i < bshape[0]:
                return None
            return (i % bshape[0]) * row, bshape[1:]
        if isinstance(index, slice):
            if index.step not in (None, 1):
                return None
            start, stop, _ = index.indices(bshape[0])
            if stop <= start:
                return None
            return start * row, (stop - start,) + bshape[1:]
        return None

    def _mirror_addr(self, s: int, target: int, off: int) -> tuple[int, int]:
        """(pid, absolute address) of byte `off` within `target`'s
        block inside slice s's mirror."""
        pid, base = self._peer_mirrors[s]
        lidx = self._slice_ranks[s].index(target)
        return pid, base + lidx * self._mirror[0].nbytes + off

    def _host_apply(self, kind: str, lidx: int, index, value, op,
                    compare) -> Optional[np.ndarray]:
        """Apply one RMA op to the local mirror (host-side twin of
        Window._apply_pending's device semantics; ops use their
        np_reduce host path)."""
        from ..ops.op import NO_OP, REPLACE

        block = self._mirror[lidx]
        idx = index if index is not None else Ellipsis
        if kind == "put":
            self._mirror_dirty = True
            block[idx] = value
            return None
        cur = np.copy(block[idx])
        if kind == "get":
            return cur  # pure read: device copy stays fresh
        self._mirror_dirty = True
        if op is not None and not hasattr(op, "np_reduce"):
            op = op_lookup(op)
        val = (None if value is None
               else np.asarray(value, dtype=block.dtype))
        if kind == "acc":
            block[idx] = val if op is REPLACE else op.np_reduce(cur, val)
            return None
        if kind == "get_acc":
            if op is not NO_OP:
                block[idx] = (val if op is REPLACE
                              else op.np_reduce(cur, val))
            return cur
        if kind == "cswap":
            eq = cur == np.asarray(compare, dtype=block.dtype)
            block[idx] = np.where(eq, val, cur)
            return cur
        raise WinError(f"unknown RMA op {kind}")

    # -- RMA operations ----------------------------------------------------

    def _queue_remote(self, kind: str, target: int, value, index,
                      op=None, compare=None) -> Optional[WindowResult]:
        s = self._slice_of(target)
        desc = {
            "k": kind, "t": target, "i": _enc_index(index),
            "v": None if value is None else np.asarray(value),
        }
        if op is not None:
            desc["o"] = op.name if hasattr(op, "name") else str(op)
        if compare is not None:
            desc["c"] = np.asarray(compare)
        self._remote_pending.setdefault(s, []).append(desc)
        SPC.record("osc_fabric_remote_ops")
        if kind in self.RESULT_KINDS:
            slot: list = []
            self._result_slots.setdefault(s, []).append(slot)
            return WindowResult(slot, self)
        return None

    def put(self, value, target: int, index=None) -> None:
        self._check_alive()
        self._check_epoch(target)
        s = self._slice_of(target)
        if self._direct:
            if s == self.h.slice_id:
                self._host_apply("put", self._local_idx(target), index,
                                 np.asarray(value), None, None)
                return
            # Direct writes are immediate, which fits passive/PSCW
            # epochs (the target has ceded the memory: lock held, or
            # post() promised no local access). FENCE-epoch puts ride
            # the batch — the AM epoch gate is what keeps them from
            # landing before the target even enters the epoch. A
            # queued AM batch to this slice also pins ordering.
            span = self._direct_span(index)
            if (span is not None
                    and self._sync in (SyncType.LOCK, SyncType.LOCK_ALL,
                                       SyncType.PSCW)
                    and not self._remote_pending.get(s)):
                from ..btl import sm as _sm

                off, shp = span
                val = np.ascontiguousarray(np.broadcast_to(
                    np.asarray(value, self._mirror.dtype), shp))
                pid, addr = self._mirror_addr(s, target, off)
                _sm.cma_write_from(pid, addr, val)
                SPC.record("osc_sm_direct_puts")
                return
            self._queue_remote("put", target, value, index)
            return
        if s == self.h.slice_id:
            self._inner.put(value, self._local_idx(target), index)
            return
        self._queue_remote("put", target, value, index)

    def get(self, target: int, index=None) -> WindowResult:
        self._check_alive()
        self._check_epoch(target)
        s = self._slice_of(target)
        if self._direct:
            import jax

            if s == self.h.slice_id:
                out = self._host_apply("get", self._local_idx(target),
                                       index, None, None, None)
                return WindowResult([jax.device_put(out)], self)
            # Direct gets complete IMMEDIATELY, which fits passive and
            # PSCW epochs (osc/sm's load path); fence-epoch gets keep
            # the apply-at-close contract (they observe the whole
            # epoch's accumulates) and ride the batch.
            span = self._direct_span(index)
            if (span is not None
                    and self._sync in (SyncType.LOCK, SyncType.LOCK_ALL,
                                       SyncType.PSCW)
                    and not self._remote_pending.get(s)):
                from ..btl import sm as _sm

                off, shp = span
                out = np.empty(shp, self._mirror.dtype)
                pid, addr = self._mirror_addr(s, target, off)
                _sm.cma_read_into(pid, addr, out)
                SPC.record("osc_sm_direct_gets")
                return WindowResult([jax.device_put(out)], self)
            return self._queue_remote("get", target, None, index)
        if s == self.h.slice_id:
            return self._inner.get(self._local_idx(target), index)
        return self._queue_remote("get", target, None, index)

    def accumulate(self, value, target: int, op="sum", index=None) -> None:
        self._check_alive()
        self._check_epoch(target)
        op = op_lookup(op)
        if self._slice_of(target) == self.h.slice_id:
            if self._direct:
                self._host_apply("acc", self._local_idx(target), index,
                                 np.asarray(value), op, None)
                return
            self._inner.accumulate(value, self._local_idx(target),
                                   op, index)
            return
        self._queue_remote("acc", target, value, index, op=op)

    def get_accumulate(self, value, target: int, op="sum", index=None
                       ) -> WindowResult:
        self._check_alive()
        self._check_epoch(target)
        op = op_lookup(op)
        if self._slice_of(target) == self.h.slice_id:
            if self._direct:
                import jax

                out = self._host_apply(
                    "get_acc", self._local_idx(target), index,
                    None if value is None else np.asarray(value), op,
                    None)
                return WindowResult([jax.device_put(out)], self)
            return self._inner.get_accumulate(
                value, self._local_idx(target), op, index)
        return self._queue_remote("get_acc", target, value, index, op=op)

    def fetch_and_op(self, value, target: int, op="sum", index=None
                     ) -> WindowResult:
        return self.get_accumulate(value, target, op, index)

    def compare_and_swap(self, value, compare, target: int, index=None
                         ) -> WindowResult:
        self._check_alive()
        self._check_epoch(target)
        if self._slice_of(target) == self.h.slice_id:
            if self._direct:
                import jax

                out = self._host_apply(
                    "cswap", self._local_idx(target), index,
                    np.asarray(value), None, compare)
                return WindowResult([jax.device_put(out)], self)
            return self._inner.compare_and_swap(
                value, compare, self._local_idx(target), index)
        return self._queue_remote("cswap", target, value, index,
                                  compare=compare)

    # -- wire helpers ------------------------------------------------------

    def _send_msg(self, slice_id: int, sub: int, msg: dict) -> None:
        self.comm.rank(self._my_leader()).send(
            msg, dest=self._leader(slice_id), tag=self._tag(sub)
        )

    def _flush_slice(self, s: int, ep: int) -> None:
        """Ship slice `s`'s batch (possibly empty). `ep` is the fence
        epoch, or -1 for lock-epoch flushes (applied immediately at the
        passive target)."""
        ops = self._remote_pending.pop(s, [])
        self._send_msg(s, _T_BATCH, {
            "win": self.win_id, "ep": ep,
            "org": self.h.slice_id, "ops": ops,
        })
        SPC.record("osc_fabric_batches_sent")

    def _pump_until(self, cond, what: str, timeout: float = 60.0) -> None:
        ok = _progress.ENGINE.progress_until(cond, timeout)
        if not ok:
            raise RMASyncError(f"{self.name}: timeout waiting for {what}")

    # -- passive handler ---------------------------------------------------

    def _handle_arrivals(self) -> int:
        """Progress callback: apply arrived batches to local blocks and
        answer lock traffic (the passive side of osc/rdma's active
        message mode). Reentrancy-guarded — improbe pumps progress."""
        if self._in_handler or self._freed:
            return 0
        self._in_handler = True
        n = 0
        try:
            pml = self.comm.pml
            me = self._my_leader()
            for sub in (_T_BATCH, _T_LOCK, _T_POST):
                while True:
                    m = pml.improbe(self.comm, -1, self._tag(sub),
                                    dest=me)
                    if m is None:
                        break
                    msg = m.mrecv()
                    self._dispatch(sub, msg)
                    n += 1
        finally:
            self._in_handler = False
        return n

    def _dispatch(self, sub: int, msg: dict) -> None:
        if msg.get("win") != self.win_id:
            # another window's traffic shares no tags; this is a bug
            raise WinError(f"{self.name}: foreign window message {msg}")
        if self._arming:
            # Window creation is collective but NOT a barrier: a fast
            # peer can finish its side of the mirror exchange and send
            # ops while we are still arming — and our exchange recv
            # pumps progress. Applying now would pick the WRONG data
            # plane (the _direct decision isn't made yet); park until
            # arming resolves.
            self._held.append((sub, msg))
            return
        if sub == _T_BATCH:
            if msg["ep"] not in (-1, -2):
                if msg["ep"] != self._epoch:
                    self._held.append((sub, msg))  # future fence epoch
                    return
                if self._direct and not self._in_close:
                    # direct mode: local ops hit the mirror immediately
                    # instead of queueing, so a fence batch applied by
                    # an early pump would reorder against local ops
                    # still being issued — park it until OUR close
                    self._held.append((sub, msg))
                    return
            self._apply_batch(msg)
        elif sub == _T_LOCK:
            self._handle_lock_req(msg)
        elif sub == _T_POST:
            org = msg["org"]
            with self._lock_mu:
                self._post_tokens[org] = (
                    self._post_tokens.get(org, 0) + 1)

    def _apply_batch(self, msg: dict) -> None:
        org = msg["org"]
        results: list = []
        if self._direct:
            # direct mode: the mirror is the epoch-time store — AM ops
            # (accumulates, fancy-index put/get) apply host-side so
            # they compose with peers' direct writes on the same memory
            for d in msg["ops"]:
                kind = {"fetch_op": "get_acc"}.get(d["k"], d["k"])
                res = self._host_apply(
                    kind, self._local_idx(d["t"]), _dec_index(d["i"]),
                    d.get("v"), d.get("o"), d.get("c"))
                if d["k"] in self.RESULT_KINDS:
                    results.append([res])
        else:
            for d in msg["ops"]:
                lidx = self._local_idx(d["t"])
                idx = _dec_index(d["i"])
                kind = d["k"]
                opname = d.get("o")
                op = op_lookup(opname) if opname else None
                pending = _PendingOp(
                    kind={"fetch_op": "get_acc"}.get(kind, kind),
                    target=lidx, value=d.get("v"), index=idx, op=op,
                    compare=d.get("c"),
                    result_slot=[] if kind in self.RESULT_KINDS else None,
                )
                self._inner._pending.append(pending)
                if pending.result_slot is not None:
                    results.append(pending.result_slot)
            self._inner._apply_pending()
        SPC.record("osc_fabric_batches_applied")
        vals = [np.asarray(r[0]) if r else None for r in results]
        self._send_msg(org, _T_REPLY, {
            "win": self.win_id, "ep": msg["ep"],
            "org": self.h.slice_id, "vals": vals,
        })
        if msg["ep"] == -2:
            # PSCW completion marker: the origin's access epoch closed
            with self._lock_mu:
                self._pscw_done[org] = self._pscw_done.get(org, 0) + 1
        elif msg["ep"] != -1:
            self._got_batches.add(org)

    # -- lock manager (targets owned by this controller) -------------------

    def _handle_lock_req(self, msg: dict) -> None:
        rank, mode, org, unlock = (msg["rank"], msg["mode"], msg["org"],
                                   msg.get("unlock", False))
        with self._lock_mu:
            st = self._lock_state.setdefault(rank, ["", set(), []])
            if unlock:
                st[1].discard(org)
                if not st[1]:
                    st[0] = ""
                if self._direct:
                    self._mirror_dirty = True  # origin's epoch closed
                self._grant_waiters(rank, st)
                return
            if self._lock_compatible(st, mode):
                st[0] = mode
                st[1].add(org)
                self._send_msg(org, _T_GRANT, {
                    "win": self.win_id, "ep": -1, "rank": rank,
                })
            else:
                st[2].append((org, mode))

    @staticmethod
    def _lock_compatible(st, mode: str) -> bool:
        if not st[1]:
            return True
        return st[0] == LOCK_SHARED and mode == LOCK_SHARED

    def _grant_waiters(self, rank: int, st) -> None:
        while st[2]:
            org, mode = st[2][0]
            if not self._lock_compatible(st, mode):
                break
            st[2].pop(0)
            st[0] = mode
            st[1].add(org)
            self._send_msg(org, _T_GRANT, {
                "win": self.win_id, "ep": -1, "rank": rank,
            })
            if mode == LOCK_EXCLUSIVE:
                break

    # -- synchronization ---------------------------------------------------

    def fence(self) -> None:
        self._check_alive()
        if self._sync not in (SyncType.NONE, SyncType.FENCE):
            raise RMASyncError(
                f"{self.name}: fence inside {self._sync.value} epoch"
            )
        if self._sync == SyncType.FENCE:
            self._close_fence()
        self._sync = SyncType.FENCE
        self._epoch += 1
        self._release_held()
        SPC.record("osc_fence_calls")

    def fence_end(self) -> None:
        self._check_alive()
        if self._sync != SyncType.FENCE:
            raise RMASyncError(f"{self.name}: fence_end outside fence")
        self._close_fence()
        self._sync = SyncType.NONE
        self._epoch += 1
        self._release_held()

    def _release_held(self) -> None:
        held, self._held = self._held, []
        for sub, msg in held:
            self._dispatch(sub, msg)

    def _close_fence(self) -> None:
        # local ops complete on the device
        self._inner._apply_pending()
        # ship one batch per peer controller (empty counts as "none"),
        # then wait until every peer's batch was applied here and every
        # reply to OUR batches (get results + acks) came back
        peers = [s for s in range(self.h.n_slices)
                 if s != self.h.slice_id]
        self._in_close = True
        try:
            self._release_held()  # direct mode parks same-epoch batches
            for s in peers:
                self._flush_slice(s, self._epoch)
            self._collect_replies(peers, self._epoch)
            self._pump_until(
                lambda: all(s in self._got_batches for s in peers),
                "peer fence batches",
            )
        finally:
            self._in_close = False
        self._got_batches.clear()
        self.comm.barrier()
        if self._direct:
            # peers' direct writes into our mirror are invisible to us:
            # after the closing barrier they are complete — mark the
            # device copy stale
            self._mirror_dirty = True

    def _collect_replies(self, slices, ep: int) -> None:
        """Receive one reply per outstanding batch, filling result
        slots in issue order."""
        me = self._my_leader()
        for s in slices:
            slots = self._result_slots.pop(s, [])
            rep = self.comm.recv(source=self._leader(s),
                                 tag=self._tag(_T_REPLY), dest=me)
            if rep.get("ep") != ep or rep.get("org") != s:
                raise WinError(
                    f"{self.name}: reply epoch mismatch {rep.get('ep')}"
                    f" != {ep}"
                )
            vals = rep["vals"]
            if len(vals) != len(slots):
                raise WinError(
                    f"{self.name}: {len(vals)} results for "
                    f"{len(slots)} slots"
                )
            import jax

            for slot, v in zip(slots, vals):
                slot.append(jax.device_put(v) if v is not None else None)
            SPC.record("osc_fabric_replies")

    # passive target ------------------------------------------------------

    def lock(self, target: int, lock_type: str = LOCK_SHARED) -> None:
        self._check_alive()
        if self._sync in (SyncType.FENCE, SyncType.PSCW):
            raise RMASyncError(
                f"{self.name}: lock inside {self._sync.value} epoch"
            )
        target = self.comm.check_rank(target)
        if self._direct:
            # one CAS on the shared lock word (0 free / -1 exclusive /
            # k>0 shared holders); contended acquires park on the futex
            # between progress pumps
            word = 1 + target
            want_excl = lock_type == LOCK_EXCLUSIVE

            def _try():
                cur = self._winseg.load(word)
                if want_excl:
                    if cur != 0:
                        self._winseg.wait(word, cur, 2)
                        return False
                    return self._winseg.cas(word, 0, -1) == 0
                while cur >= 0:
                    if self._winseg.cas(word, cur, cur + 1) == cur:
                        return True
                    cur = self._winseg.load(word)
                self._winseg.wait(word, cur, 2)
                return False

            self._pump_until(_try, f"shared lock word for {target}")
            self._locks[target] = lock_type
            self._sync = SyncType.LOCK
            SPC.record("osc_lock_calls")
            return
        s = self._slice_of(target)
        if s == self.h.slice_id:
            # local target: same lock manager, no messages (the inner
            # Window lives in a permanent fence epoch and cannot host
            # lock state itself)
            def _try_local():
                with self._lock_mu:
                    st = self._lock_state.setdefault(target,
                                                     ["", set(), []])
                    if self._lock_compatible(st, lock_type):
                        st[0] = lock_type
                        st[1].add(self.h.slice_id)
                        return True
                return False

            self._pump_until(_try_local, f"local lock on {target}")
        else:
            self._send_msg(s, _T_LOCK, {
                "win": self.win_id, "ep": -1, "rank": target,
                "mode": lock_type, "org": self.h.slice_id,
            })
            granted: list = []

            def _check():
                m = self.comm.pml.improbe(
                    self.comm, self._leader(s), self._tag(_T_GRANT),
                    dest=self._my_leader(),
                )
                if m is not None:
                    granted.append(m.mrecv())
                return bool(granted)

            self._pump_until(_check, f"lock grant on rank {target}")
        self._locks[target] = lock_type
        self._sync = SyncType.LOCK
        SPC.record("osc_lock_calls")

    def unlock(self, target: int) -> None:
        self._check_alive()
        target = self.comm.check_rank(target)
        if target not in self._locks:
            raise RMASyncError(f"{self.name}: rank {target} not locked")
        s = self._slice_of(target)
        if self._direct:
            # complete outstanding AM ops (accumulates, fancy indices)
            # for this slice, then drop the shared lock word and bump
            # the window modification counter (the target re-lands its
            # device copy when it observes the bump)
            if s != self.h.slice_id and (
                    s in self._remote_pending or s in self._result_slots):
                self._flush_slice(s, -1)
                self._collect_replies([s], -1)
            word = 1 + target
            # bump the modification counter BEFORE releasing the lock
            # word: the next holder's very first win.array read must
            # already see mod != seen and re-land — release-then-bump
            # would let it run in the gap and serve stale device data
            self._winseg.add(0, 1)
            if self._locks[target] == LOCK_EXCLUSIVE:
                self._winseg.store(word, 0)
            else:
                self._winseg.add(word, -1)
            self._winseg.wake(word)
            del self._locks[target]
            if not self._locks:
                self._sync = SyncType.NONE
            return
        if s == self.h.slice_id:
            self._inner._apply_pending(self._local_idx(target))
            with self._lock_mu:
                st = self._lock_state.setdefault(target, ["", set(), []])
                st[1].discard(self.h.slice_id)
                if not st[1]:
                    st[0] = ""
                self._grant_waiters(target, st)
        else:
            self._flush_slice(s, -1)
            self._collect_replies([s], -1)
            self._send_msg(s, _T_LOCK, {
                "win": self.win_id, "ep": -1, "rank": target,
                "mode": self._locks[target], "org": self.h.slice_id,
                "unlock": True,
            })
        del self._locks[target]
        if not self._locks:
            self._sync = SyncType.NONE

    # generalized active target (PSCW) -------------------------------------

    def start(self, group) -> None:
        """Open an access epoch to the ranks in `group`
        (MPI_Win_start; reference: osc_rdma PSCW sync,
        osc_rdma_sync.h:24-30)."""
        self._check_alive()
        if self._sync != SyncType.NONE:
            raise RMASyncError(
                f"{self.name}: start inside {self._sync.value} epoch"
            )
        self._pscw_targets = [self.comm.check_rank(r)
                              for r in self._group_ranks(group)]
        # MPI_Win_start may not access the window before the matching
        # MPI_Win_post: consume one post token per remote target slice
        # (tokens are counters, so repeated epochs pair up correctly)
        for s in sorted({self._slice_of(t) for t in self._pscw_targets
                         if self._slice_of(t) != self.h.slice_id}):

            def _take(s=s):
                # consume atomically vs the handler's increment (which
                # runs on whichever thread pumps progress)
                with self._lock_mu:
                    if self._post_tokens.get(s, 0) > 0:
                        self._post_tokens[s] -= 1
                        return True
                return False

            self._pump_until(_take, f"post() from slice {s}")
        self._sync = SyncType.PSCW
        SPC.record("osc_pscw_starts")

    def complete(self) -> None:
        """Close the access epoch: local ops apply, remote ops ship as
        PSCW batches (applied immediately at the passive target and
        counted by its wait())."""
        self._check_alive()
        if self._sync != SyncType.PSCW:
            raise RMASyncError(f"{self.name}: complete without start")
        self._inner._apply_pending()
        slices = sorted({
            self._slice_of(t) for t in self._pscw_targets
            if self._slice_of(t) != self.h.slice_id
        })
        for s in slices:
            self._flush_slice(s, -2)  # ep=-2: the PSCW marker
        self._collect_replies(slices, -2)
        self._sync = SyncType.NONE
        self._pscw_targets = []

    def post(self, group) -> None:
        """Expose the window to `group`'s origins (MPI_Win_post)."""
        self._check_alive()
        if self._pscw_posted:
            raise RMASyncError(
                f"{self.name}: post() with an un-waited exposure epoch"
            )
        # NOTE: do not clear _pscw_done here — a fast origin's
        # complete() marker may land before the exposure side posts
        self._pscw_origins = sorted({
            self._slice_of(self.comm.check_rank(r))
            for r in self._group_ranks(group)
        } - {self.h.slice_id})
        for s in self._pscw_origins:
            self._send_msg(s, _T_POST, {
                "win": self.win_id, "ep": -2, "org": self.h.slice_id,
            })
        self._pscw_posted = True

    def wait(self) -> None:
        """Exposure-side wait: every posted origin's complete() batch
        has arrived and been applied."""
        self._check_alive()
        if not self._pscw_posted:
            raise RMASyncError(f"{self.name}: wait() without post()")
        expected = self._pscw_origins

        def _all_done():
            with self._lock_mu:
                if not all(self._pscw_done.get(s, 0) > 0
                           for s in expected):
                    return False
                # consume this epoch's markers (repeated epochs pair up)
                for s in expected:
                    self._pscw_done[s] -= 1
                return True

        self._pump_until(_all_done, "PSCW origin completions")
        if self._direct:
            self._mirror_dirty = True  # exposure epoch closed
        self._pscw_origins = []
        self._pscw_posted = False

    def _group_ranks(self, group):
        """Comm ranks of a PSCW group (a Group of world ranks or a
        plain iterable of comm ranks)."""
        if hasattr(group, "world_ranks"):
            comm_wr = list(self.comm.group.world_ranks)
            return [comm_wr.index(w) for w in group.world_ranks]
        return list(group)

    def lock_all(self) -> None:
        """Shared lock on every rank (MPI_Win_lock_all) — the SHMEM
        standing epoch. Grants are acquired per remote rank through the
        same lock manager as lock()."""
        self._check_alive()
        if self._sync != SyncType.NONE:
            raise RMASyncError(f"{self.name}: lock_all inside epoch")
        for r in range(self.comm.size):
            self._sync = SyncType.NONE  # let lock() see a clean state
            self.lock(r, LOCK_SHARED)
        self._sync = SyncType.LOCK_ALL

    def unlock_all(self) -> None:
        self._check_alive()
        if self._sync != SyncType.LOCK_ALL:
            raise RMASyncError(
                f"{self.name}: unlock_all without lock_all")
        self._sync = SyncType.LOCK
        for r in list(self._locks):
            self.unlock(r)
        self._sync = SyncType.NONE

    def flush(self, target: Optional[int] = None) -> None:
        self._check_alive()
        if self._sync not in (SyncType.LOCK, SyncType.LOCK_ALL):
            raise RMASyncError(f"{self.name}: flush outside lock epoch")
        targets = ([target] if target is not None
                   else list(self._locks))
        slices = sorted({
            self._slice_of(t) for t in targets
            if self._slice_of(t) != self.h.slice_id
        })
        self._inner._apply_pending()
        for s in slices:
            if s in self._remote_pending or s in self._result_slots:
                self._flush_slice(s, -1)
                self._collect_replies([s], -1)

    def free(self) -> None:
        if self._freed:
            return  # idempotent: a second free must not re-enter the
                    # collective barrier (no peer would match it)
        pending = bool(self._remote_pending
                       or any(self._result_slots.values()))
        # MPI_Win_free is collective WITH barrier semantics: every
        # controller must stay alive (and pumping) until its peers'
        # final epoch-release requests are serviced — without this, the
        # first controller to finish its own unlocks exits and a peer's
        # in-flight unlock waits on a dead process (a shutdown race hit
        # by the 2-process SHMEM drill). The barrier rides p2p, so
        # waiting in it services peers' remaining window traffic.
        # Participate in the barrier even on the pending-ops error path:
        # raising BEFORE it would leave every peer blocked against a
        # rank that never arrives — one rank's usage error must surface
        # locally, not as a distributed hang.
        self.comm.barrier()
        # Tear down unconditionally: the barrier has completed, so no
        # peer will ever match another one — a retried free() after the
        # pending-ops error below must hit the idempotency guard, not
        # re-enter an unmatchable barrier.
        _progress.unregister(self._handle_arrivals)
        self._freed = True
        if self._direct:
            # drop direct mode BEFORE closing the segment: a post-free
            # .array access must fall through to the (harmless) inner
            # array, not winseg_load a NULL base
            self._direct = False
            self._winseg.close()
        self._inner._pending.clear()
        self._inner._sync = SyncType.NONE
        self._inner.free()
        if pending:
            raise RMASyncError(
                f"{self.name}: free with pending remote ops"
            )

    def __repr__(self) -> str:
        return (
            f"<FabricWindow {self.name} local_blocks="
            f"{self.h.comm.size}x{self.block_shape} "
            f"sync={self._sync.value}>"
        )

"""One-sided communication: RMA windows.

TPU-native equivalent of ompi/mca/osc (reference: osc/rdma — sync state
machine osc_rdma_sync.h:24-30 {NONE, LOCK, FENCE, PSCW}, put/get over
btl RDMA osc_rdma_comm.c, accumulate via remote atomics or active
message osc_rdma_accumulate.c, dynamic windows osc_rdma_dynamic.c).

Driver-model mapping: a window is a rank-major device buffer (block i =
rank i's exposed memory, resident on device i). One-sided operations
are *epoch-buffered*: puts/gets/accumulates enqueue against the target
block and the queue is applied as compiled scatter/gather programs when
the epoch closes (fence / unlock / complete) — which is exactly the MPI
completion contract (RMA ops are only guaranteed at synchronization),
and lets XLA fuse a whole epoch's updates into few kernels. The
reference instead issues NIC RDMA per op and tracks completion counts;
on TPU the "NIC" is the ICI transfer inside the compiled update.

Accumulate ordering: ops apply in issue order per target (the reference
guarantees same-origin ordered accumulates).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from ..core.counters import SPC
from ..core.errors import (
    ArgumentError,
    HasErrhandler,
    RMASyncError,
    WinError,
)
from ..ops import NO_OP, REPLACE, Op, lookup as op_lookup


class SyncType(enum.Enum):
    NONE = "none"
    FENCE = "fence"
    LOCK = "lock"
    LOCK_ALL = "lock_all"
    PSCW = "pscw"


LOCK_EXCLUSIVE = "exclusive"
LOCK_SHARED = "shared"


@dataclass
class _PendingOp:
    kind: str  # put | get | acc | get_acc | fetch_op | cswap
    target: int
    value: Any
    index: Any  # slice/index into the target block (None = whole)
    op: Optional[Op] = None
    result_slot: Optional[list] = None  # filled at epoch close
    compare: Any = None


class Window(HasErrhandler):
    """An RMA window over a rank-major device buffer."""

    def __init__(self, comm, buffer, *, name: str = "") -> None:
        import jax.numpy as jnp

        arr = jnp.asarray(buffer)
        if arr.shape[0] != comm.size:
            raise ArgumentError(
                f"window buffer leading dim {arr.shape[0]} != comm size "
                f"{comm.size}"
            )
        self.comm = comm
        self._array = comm.put_rank_major(arr)
        self.name = name or f"win{comm.cid}"
        self._sync = SyncType.NONE
        self._pending: list[_PendingOp] = []
        self._locks: dict[int, str] = {}  # target -> lock type
        self._pscw_group = None
        self._freed = False

    # -- accessors --------------------------------------------------------

    @property
    def array(self):
        """The current window contents (rank-major device array)."""
        return self._array

    @property
    def block_shape(self):
        return self._array.shape[1:]

    def _set_array(self, arr) -> None:
        """Replace the window contents wholesale (SHMEM collectives);
        keeps the rank-major sharding."""
        self._array = self.comm.put_rank_major(arr)

    def _check_alive(self):
        if self._freed:
            raise WinError(f"{self.name} has been freed")

    def _check_epoch(self, target: Optional[int] = None):
        if self._sync == SyncType.NONE:
            raise RMASyncError(
                f"{self.name}: RMA op outside an access epoch "
                "(fence/lock/lock_all/start first)"
            )
        if self._sync == SyncType.LOCK and target is not None:
            if target not in self._locks:
                raise RMASyncError(
                    f"{self.name}: target {target} is not locked"
                )

    # -- synchronization --------------------------------------------------

    def fence(self) -> None:
        """Close the current fence epoch (applying pending ops) and open
        a new one. First call opens only."""
        self._check_alive()
        if self._sync not in (SyncType.NONE, SyncType.FENCE):
            raise RMASyncError(
                f"{self.name}: fence inside {self._sync.value} epoch"
            )
        self._apply_pending()
        self.comm.barrier()
        self._sync = SyncType.FENCE
        SPC.record("osc_fence_calls")

    def fence_end(self) -> None:
        """Close the fence epoch without opening another (the
        MPI_MODE_NOSUCCEED fence)."""
        self._apply_pending()
        self.comm.barrier()
        self._sync = SyncType.NONE

    def lock(self, target: int, lock_type: str = LOCK_SHARED) -> None:
        self._check_alive()
        if self._sync in (SyncType.FENCE, SyncType.PSCW):
            raise RMASyncError(
                f"{self.name}: lock inside {self._sync.value} epoch"
            )
        self.comm.check_rank(target)
        if target in self._locks:
            raise RMASyncError(f"{self.name}: target {target} already locked")
        self._locks[target] = lock_type
        self._sync = SyncType.LOCK
        SPC.record("osc_lock_calls")

    def unlock(self, target: int) -> None:
        self._check_alive()
        if target not in self._locks:
            raise RMASyncError(f"{self.name}: target {target} not locked")
        self._apply_pending(target_filter=target)
        del self._locks[target]
        if not self._locks:
            self._sync = SyncType.NONE

    def lock_all(self) -> None:
        self._check_alive()
        if self._sync != SyncType.NONE:
            raise RMASyncError(f"{self.name}: lock_all inside epoch")
        self._sync = SyncType.LOCK_ALL

    def unlock_all(self) -> None:
        if self._sync != SyncType.LOCK_ALL:
            raise RMASyncError(f"{self.name}: unlock_all without lock_all")
        self._apply_pending()
        self._sync = SyncType.NONE

    def flush(self, target: Optional[int] = None) -> None:
        """Complete pending ops without ending the epoch (btl flush
        analog, reference btl.h:1205)."""
        self._check_epoch()
        self._apply_pending(target_filter=target)

    # PSCW (generalized active target)
    def post(self, group) -> None:
        """Expose the window to the group (exposure epoch)."""
        self._check_alive()

    def start(self, group) -> None:
        if self._sync != SyncType.NONE:
            raise RMASyncError(f"{self.name}: start inside epoch")
        self._sync = SyncType.PSCW
        self._pscw_group = group

    def complete(self) -> None:
        if self._sync != SyncType.PSCW:
            raise RMASyncError(f"{self.name}: complete without start")
        self._apply_pending()
        self._sync = SyncType.NONE
        self._pscw_group = None

    def wait(self) -> None:
        """Exposure-side wait; driver-mode ops are already applied at the
        origin's complete()."""
        self.comm.barrier()

    # -- one-sided operations ---------------------------------------------

    def put(self, value, target: int, index=None) -> None:
        self._check_alive()
        self.comm.check_rank(target)
        self._check_epoch(target)
        self._pending.append(_PendingOp("put", target, value, index))
        SPC.record("osc_put_calls")
        from ..monitoring import MONITOR

        if MONITOR.enabled:
            # nbytes without forcing a device→host transfer: jax arrays
            # expose it directly; only host data goes through asarray.
            nbytes = getattr(value, "nbytes", None)
            if nbytes is None:
                nbytes = int(getattr(np.asarray(value), "nbytes", 0))
            MONITOR.record_osc(self.comm.cid, target, "put", int(nbytes))

    def get(self, target: int, index=None) -> "WindowResult":
        self._check_alive()
        self.comm.check_rank(target)
        self._check_epoch(target)
        slot: list = []
        self._pending.append(
            _PendingOp("get", target, None, index, result_slot=slot)
        )
        SPC.record("osc_get_calls")
        return WindowResult(slot, self)

    def accumulate(self, value, target: int, op="sum", index=None) -> None:
        self._check_alive()
        self.comm.check_rank(target)
        self._check_epoch(target)
        self._pending.append(
            _PendingOp("acc", target, value, index, op=op_lookup(op))
        )
        SPC.record("osc_accumulate_calls")

    def get_accumulate(self, value, target: int, op="sum", index=None
                       ) -> "WindowResult":
        self._check_alive()
        self.comm.check_rank(target)
        self._check_epoch(target)
        slot: list = []
        self._pending.append(
            _PendingOp(
                "get_acc", target, value, index, op=op_lookup(op),
                result_slot=slot,
            )
        )
        return WindowResult(slot, self)

    def fetch_and_op(self, value, target: int, op="sum", index=None
                     ) -> "WindowResult":
        return self.get_accumulate(value, target, op, index)

    def compare_and_swap(self, value, compare, target: int, index=None
                         ) -> "WindowResult":
        self._check_alive()
        self.comm.check_rank(target)
        self._check_epoch(target)
        slot: list = []
        self._pending.append(
            _PendingOp(
                "cswap", target, value, index, result_slot=slot,
                compare=compare,
            )
        )
        return WindowResult(slot, self)

    # -- epoch application -------------------------------------------------

    def _apply_pending(self, target_filter: Optional[int] = None) -> None:
        """Apply queued ops in issue order as per-block functional
        updates, each computed ON the target rank's device, then
        reassemble the rank-major array from the single-device blocks —
        no host staging, and no cross-device scatter (which jax rejects
        outright under multi-process device sets)."""
        import jax
        import jax.numpy as jnp

        remaining = []
        arr = self._array
        blocks: dict[int, Any] = {}  # target -> committed block view
        dirty: set[int] = set()      # targets actually written

        def load(t: int):
            if t not in blocks:
                blocks[t] = jax.device_put(arr[t], self.comm.devices[t])
            return blocks[t]

        def place(t: int, v):
            return jax.device_put(jnp.asarray(v), self.comm.devices[t])

        for op in self._pending:
            if target_filter is not None and op.target != target_filter:
                remaining.append(op)
                continue
            t = op.target
            block = load(t)
            idx = op.index if op.index is not None else Ellipsis
            if op.kind == "put":
                blocks[t] = block.at[idx].set(place(t, op.value))
                dirty.add(t)
            elif op.kind == "get":
                op.result_slot.append(block[idx])
            elif op.kind == "acc":
                cur = block[idx]
                if op.op is REPLACE:
                    upd = place(t, op.value)
                else:
                    upd = op.op.combine(cur, place(t, op.value))
                blocks[t] = block.at[idx].set(upd)
                dirty.add(t)
            elif op.kind == "get_acc":
                cur = block[idx]
                op.result_slot.append(cur)
                if op.op is NO_OP:
                    pass
                else:
                    if op.op is REPLACE:
                        upd = place(t, op.value)
                    else:
                        upd = op.op.combine(cur, place(t, op.value))
                    blocks[t] = block.at[idx].set(upd)
                    dirty.add(t)
            elif op.kind == "cswap":
                cur = block[idx]
                eq = cur == place(t, op.compare)
                op.result_slot.append(cur)
                blocks[t] = block.at[idx].set(
                    jnp.where(eq, place(t, op.value), cur)
                )
                dirty.add(t)
            else:  # pragma: no cover
                raise WinError(f"unknown RMA op {op.kind}")
        self._pending = remaining
        if dirty:  # read-only epochs skip the reassembly entirely
            n = self.comm.size
            parts = [
                blocks[i] if i in blocks
                else jax.device_put(arr[i], self.comm.devices[i])
                for i in range(n)
            ]
            self._array = jax.make_array_from_single_device_arrays(
                (n,) + tuple(self.block_shape),
                self.comm.rank_sharding(),
                [p[None] for p in parts],
            )

    def free(self) -> None:
        if self._pending:
            raise RMASyncError(
                f"{self.name}: free with {len(self._pending)} pending ops "
                "(close the epoch first)"
            )
        self._freed = True

    def __repr__(self) -> str:
        return (
            f"<Window {self.name} blocks={self.comm.size}x"
            f"{self.block_shape} sync={self._sync.value}>"
        )


class WindowResult:
    """Deferred result of get/get_accumulate/compare_and_swap: defined
    after the epoch closes (MPI completion semantics)."""

    def __init__(self, slot: list, win: Window) -> None:
        self._slot = slot
        self._win = win

    @property
    def ready(self) -> bool:
        return bool(self._slot)

    def value(self):
        if not self._slot:
            raise RMASyncError(
                "RMA result read before epoch completion (fence/unlock/"
                "flush first)"
            )
        return self._slot[0]


class DynamicWindow:
    """MPI_Win_create_dynamic (reference: osc_rdma_dynamic.c — a window
    with no initial memory; regions attach/detach at runtime and RMA
    targets name a region). Each attached region is its own rank-major
    Window sharing this handle's epoch calls; the region handle plays
    the role the attached base address plays in the reference."""

    def __init__(self, comm, *, name: str = "") -> None:
        self.comm = comm
        self.name = name or f"dynwin{comm.cid}"
        self._regions: dict[int, Window] = {}
        self._next_region = 0
        self._epoch: Optional[str] = None  # None | "fence" | "lock_all"
        self._freed = False

    def attach(self, buffer) -> int:
        """Attach a rank-major buffer; returns the region handle.
        Legal at any time (MPI_Win_attach): a region attached inside an
        open epoch joins it."""
        if self._freed:
            raise WinError(f"{self.name} has been freed")
        rid = self._next_region
        self._next_region += 1
        win = Window(self.comm, buffer, name=f"{self.name}.r{rid}")
        if self._epoch == "fence":
            win.fence()
        elif self._epoch == "lock_all":
            win.lock_all()
        self._regions[rid] = win
        SPC.record("osc_dynamic_attaches")
        return rid

    def detach(self, region: int) -> None:
        win = self._regions.get(region)
        if win is None:
            raise WinError(
                f"{self.name}: region {region} is not attached"
            )
        # free first: if it raises (pending RMA ops), the region stays
        # attached so the caller can close the epoch and retry
        win.free()
        del self._regions[region]

    def region(self, region: int) -> Window:
        win = self._regions.get(region)
        if win is None:
            raise WinError(
                f"{self.name}: RMA on unattached region {region} "
                "(the reference segfaults; we raise)"
            )
        return win

    # epoch calls fan out to every attached region; the dynamic window
    # remembers the epoch so late attaches join it
    def fence(self) -> None:
        self._epoch = "fence"
        for win in self._regions.values():
            win.fence()

    def fence_end(self) -> None:
        for win in self._regions.values():
            win.fence_end()
        self._epoch = None

    def lock_all(self) -> None:
        self._epoch = "lock_all"
        for win in self._regions.values():
            win.lock_all()

    def unlock_all(self) -> None:
        for win in self._regions.values():
            win.unlock_all()
        self._epoch = None

    def put(self, value, target: int, *, region: int, index=None) -> None:
        self.region(region).put(value, target, index)

    def get(self, target: int, *, region: int, index=None):
        return self.region(region).get(target, index)

    def accumulate(self, value, target: int, *, region: int, op="sum",
                   index=None) -> None:
        self.region(region).accumulate(value, target, op, index)

    def free(self) -> None:
        for win in self._regions.values():
            win.free()
        self._regions.clear()
        self._freed = True


def _spans_processes(comm) -> bool:
    from ..runtime.proc import spans_processes

    return spans_processes(comm)


def create_window(comm, buffer, *, name: str = ""):
    """MPI_Win_create equivalent (collective over comm). Spanning comms
    get the fabric-backed window (active-message RMA across
    controllers; reference: osc/rdma's network path)."""
    if _spans_processes(comm):
        from .fabric_window import FabricWindow

        return FabricWindow(comm, buffer, name=name)
    return Window(comm, buffer, name=name)


def create_dynamic_window(comm, *, name: str = "") -> DynamicWindow:
    """MPI_Win_create_dynamic equivalent."""
    return DynamicWindow(comm, name=name)


def allocate_window(comm, block_shape, dtype="float32", *, name: str = ""
                    ):
    """MPI_Win_allocate: the window owns freshly zeroed memory (local
    blocks only on spanning comms)."""
    import jax.numpy as jnp

    if _spans_processes(comm):
        from .fabric_window import FabricWindow

        n_local = sum(1 for p in comm.procs if p.is_local)
        buf = jnp.zeros((n_local,) + tuple(block_shape), dtype)
        return FabricWindow(comm, buf, name=name)
    buf = jnp.zeros((comm.size,) + tuple(block_shape), dtype)
    return Window(comm, buf, name=name)

"""Fused ring-attention Pallas kernel: compute/DMA overlap on ICI.

SURVEY §5.7's plan realized: "ring send-recv as a Pallas kernel with
double-buffered ICI DMA + per-step compute callback". The XLA-level
ring attention (parallel/sp.py) circulates KV blocks with ppermute and
*hopes* XLA overlaps the hop with the flash compute; this kernel
GUARANTEES the overlap — each step starts the remote DMA shipping the
current KV block to the right neighbor, runs the online-softmax block
update on the MXU/VPU while the block is in flight, then waits the DMA.

The communication protocol is the capacity-credit double-buffered ring
of coll/pallas_ring (reference lineage: the ring pass of
coll_base_allreduce.c:341 plus btl_sm_fbox.h:22-60-style flow control):
credits flow from each receiver to its upstream sender, granting reuse
of a KV slot only after the slot was both computed on and forwarded.

Shape constraints (compiled mode): T divisible by the dtype sublane
tile, Dh divisible by 128 — the wrapper falls back to the XLA
implementation otherwise. The whole (2*T, H, Dh) KV slot pair plus the
f32 accumulators must fit VMEM; long-context shards beyond that use
the XLA path (which streams through HBM).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_ring import _interpret, _sublane

_NEG = -1e30


def _ring_attn_kernel(axis_name: str, n: int, causal: bool, scale: float,
                      nheads: int, tq: int,
                      q_ref, k_ref, v_ref, o_ref,
                      kv_buf, m_scr, l_scr, o_scr,
                      send_sem, recv_sem, cap_sem):
    me = jax.lax.axis_index(axis_name)
    right = jax.lax.rem(me + 1, n)
    left = jax.lax.rem(me - 1 + n, n)

    # Seed slot 0 with the local KV block (K stacked over V).
    kv_buf[0, :tq] = k_ref[:]
    kv_buf[0, tq:] = v_ref[:]
    # Initial credit: my buf[1] is free — grant my upstream neighbor
    # its step-0 send (credits are about MY slots, granted to LEFT;
    # the ones I wait on come from RIGHT about ITS slots).
    if n > 1:
        pltpu.semaphore_signal(cap_sem.at[1], inc=1, device_id=left,
                               device_id_type=pltpu.DeviceIdType.LOGICAL)

    # Online-softmax accumulators (f32).
    m_scr[...] = jnp.full_like(m_scr, _NEG)
    l_scr[...] = jnp.zeros_like(l_scr)
    o_scr[...] = jnp.zeros_like(o_scr)

    row = jax.lax.broadcasted_iota(jnp.int32, (tq, tq), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (tq, tq), 1)

    def compute(slot: int, src):
        """Fold the KV block in `slot` (originally rank `src`'s) into
        the accumulators — the per-step compute that overlaps the DMA."""
        kb = kv_buf[slot, :tq]   # (T, H, Dh)
        vb = kv_buf[slot, tq:]
        for h in range(nheads):
            qh = q_ref[:, h, :].astype(jnp.float32)       # (Tq, Dh)
            kh = kb[:, h, :].astype(jnp.float32)          # (Tk, Dh)
            vh = vb[:, h, :].astype(jnp.float32)
            scores = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                     # (Tq, Tk)
            if causal:
                mask = (me * tq + row) >= (src * tq + col)
                scores = jnp.where(mask, scores, _NEG)
            mh = m_scr[h]                                 # (Tq,)
            blk_max = jnp.max(scores, axis=-1)
            m_new = jnp.maximum(mh, blk_max)
            corr = jnp.exp(mh - m_new)
            p = jnp.exp(scores - m_new[:, None])
            l_scr[h] = l_scr[h] * corr + jnp.sum(p, axis=-1)
            o_scr[h] = o_scr[h] * corr[:, None] + jax.lax.dot_general(
                p, vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_scr[h] = m_new

    for step in range(n):
        slot = step % 2
        nslot = (step + 1) % 2
        src = jax.lax.rem(me - step + n, n)
        rdma = None
        if step < n - 1:
            # Permission to write RIGHT's buf[nslot] (its credit).
            pltpu.semaphore_wait(cap_sem.at[nslot], 1)
            rdma = pltpu.make_async_remote_copy(
                src_ref=kv_buf.at[slot],
                dst_ref=kv_buf.at[nslot],
                send_sem=send_sem.at[slot],
                recv_sem=recv_sem.at[nslot],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
        compute(slot, src)            # overlaps the in-flight DMA
        if rdma is not None:
            rdma.wait()               # send drained + next block landed
            if step < n - 2:
                # buf[slot] fully consumed (computed + forwarded):
                # left may overwrite it at its step+1.
                pltpu.semaphore_signal(
                    cap_sem.at[slot], inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )

    for h in range(nheads):
        denom = jnp.maximum(l_scr[h], 1e-30)[:, None]
        o_ref[:, h, :] = (o_scr[h] / denom).astype(o_ref.dtype)


# Conservative VMEM budget for the kernel's working set (~16 MiB real
# VMEM minus headroom for Mosaic's own staging).
_VMEM_BUDGET = 12 << 20


def supported(q: jax.Array) -> bool:
    """Whether the fused kernel can take this shape in compiled mode:
    tile alignment (T on the dtype sublane, Dh on the 128-lane tile)
    AND the whole working set — double-buffered KV pair, q/output, f32
    accumulators — fitting the VMEM budget. Callers fall back to the
    streaming XLA implementation otherwise (also applied in interpret
    mode, where the constraints are moot, to keep path selection
    deterministic across backends)."""
    t, h, dh = q.shape
    if t % _sublane(q.dtype) != 0 or dh % 128 != 0:
        return False
    itemsize = jnp.dtype(q.dtype).itemsize
    working = (
        2 * 2 * t * h * dh * itemsize   # kv_buf double buffer
        + 4 * t * h * dh * itemsize     # q, k, v inputs + output
        + h * t * dh * 4                # o accumulator (f32)
        + 2 * h * t * 4                 # m, l accumulators (f32)
    )
    return working <= _VMEM_BUDGET


def ring_attention_block(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis_name: str, causal: bool = True
                         ) -> jax.Array:
    """Inside shard_map: (T, H, Dh) local q/k/v -> (T, H, Dh) outputs
    for this rank's query block, exact over the full ring."""
    n = jax.lax.axis_size(axis_name)
    t, h, dh = q.shape
    scale = 1.0 / float(dh) ** 0.5
    kernel = functools.partial(_ring_attn_kernel, axis_name, n,
                               bool(causal), scale, h, t)
    if n == 1:
        # no remote traffic: collective_id (the cross-device barrier)
        # must be absent on a 1-member ring
        params = pltpu.CompilerParams(has_side_effects=True)
    else:
        params = pltpu.CompilerParams(has_side_effects=True,
                                      collective_id=12)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((t, h, dh), q.dtype,
                                       vma=frozenset({axis_name})),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, 2 * t, h, dh), q.dtype),   # double-buffered KV
            pltpu.VMEM((h, t), jnp.float32),          # running max
            pltpu.VMEM((h, t), jnp.float32),          # running denom
            pltpu.VMEM((h, t, dh), jnp.float32),      # running output
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=params,
        interpret=_interpret(),
    )(q, k, v)

"""coll/sync — periodic barrier injection for flow control.

TPU-native equivalent of ompi/mca/coll/sync (reference: interposes on
rooted collectives and injects a barrier every N calls so one-sided
producers can't run unbounded ahead of consumers — the classic
bcast-flood flow-control fix). On TPU the analog hazard is the async
dispatch queue running far ahead of completion, ballooning live HBM
buffers; the injected barrier bounds the pipeline depth.
"""

from __future__ import annotations

from typing import Any

from ..core import config
from ..core.counters import SPC
from .framework import COLL
from .xla import XlaColl

_enable = config.register(
    "coll", "sync", "enable", type=bool, default=False,
    description="Enable periodic-barrier flow control",
)
_period = config.register(
    "coll", "sync", "barrier_before_nops", type=int, default=100,
    description="Inject a barrier every N rooted collectives "
    "(reference: coll_sync's barrier_before_nops)",
)


@COLL.register
class SyncColl(XlaColl):
    """XlaColl plus an injected barrier every N rooted ops. Selected
    only when enabled; priority must top every data component (tuned
    is 80) or the per-op merge silently bypasses the interposition."""

    NAME = "sync"
    PRIORITY = 90
    DESCRIPTION = "periodic barrier injection (reference coll/sync)"

    def __init__(self, framework) -> None:
        super().__init__(framework)
        self._counts: dict[int, int] = {}

    def available(self, **ctx: Any) -> bool:
        if not _enable.value:
            return False
        comm = ctx.get("comm")
        if comm is not None:
            from ..runtime.proc import spans_processes

            # the XlaColl lowering cannot cross controller processes;
            # spanning comms must keep coll/hier (priority 85 < 90)
            if spans_processes(comm):
                return False
        return True

    def _maybe_barrier(self, comm) -> None:
        n = self._counts.get(comm.cid, 0) + 1
        period = max(1, _period.value)
        if n >= period:
            n = 0
            token = super().barrier(comm)
            if token is not None:
                import jax

                jax.block_until_ready(token)
            SPC.record("coll_sync_barriers")
        self._counts[comm.cid] = n

    # the reference interposes on the rooted ops (bcast/reduce/
    # gather/scatter) — the ones that let a root run ahead
    def bcast(self, comm, x, root):
        self._maybe_barrier(comm)
        return super().bcast(comm, x, root)

    def reduce(self, comm, x, op, root):
        self._maybe_barrier(comm)
        return super().reduce(comm, x, op, root)

    def gather(self, comm, x, root):
        self._maybe_barrier(comm)
        return super().gather(comm, x, root)

    def scatter(self, comm, x, root):
        self._maybe_barrier(comm)
        return super().scatter(comm, x, root)

"""coll/nbc — nonblocking collectives as compiled round schedules.

TPU-native equivalent of ompi/mca/coll/libnbc (reference: every
nonblocking collective compiles into a *schedule* — rounds of
{SEND, RECV, OP, COPY} primitives, nbc_internal.h:149-155 — started by
NBC_Start (nbc.c:265) and advanced one round at a time by the progress
engine). This module is the "collective schedule compiler" SURVEY §2.3
calls the model for the TPU build: the same round/primitive IR, executed
over the ob1-style PML p2p stack (pml/ob1.py) with device-resident
payloads moved by the BTL (DMA between chips), and local reductions run
as jax ops on the owning device instead of CPU loops.

Relationship to the fabric components (coll/xla, coll/tuned): those
lower whole collectives to XLA programs — the device-optimal path. This
engine exists for what schedules uniquely give you (reference rationale
mirrored from libnbc):

- true overlap: start many collectives, advance them round-by-round
  from the progress engine, complete out of order;
- algorithm transparency: the round structure *is* the algorithm
  (binomial tree, dissemination, recursive doubling, ring), testable
  round by round;
- p2p-composed collectives for communicators whose peers are reached
  over different transports (the DCN path), where a single XLA program
  cannot span the job.

Algorithms compiled here follow libnbc's choices (reference files
ompi/mca/coll/libnbc/nbc_i{bcast,barrier,allreduce,reduce,allgather,
alltoall,gather,scatter,scan,exscan,reduce_scatter}.c): binomial bcast
and reduce, dissemination barrier, recursive-doubling allreduce with
the non-power-of-two fold, ring allgather, pairwise alltoall, linear
gather/scatter/scan.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
import numpy as np

from ..core import progress as _progress
from ..core.counters import SPC
from ..core.errors import ArgumentError
from ..core.request import Request, Status
from ..ops import lookup as op_lookup
from ..ops.op import Op

__all__ = [
    "Schedule", "NbcRequest",
    "ibcast", "ibarrier", "iallreduce", "ireduce", "iallgather",
    "ialltoall", "igather", "iscatter", "ireduce_scatter_block",
    "iscan", "iexscan",
]

# Internal tag space for schedule traffic, disjoint from user tags
# (reference: collective-decomposed traffic runs on negative tags,
# common_monitoring.c internal-tag split; our PML requires tags >= 0 so
# the internal window starts high instead).
_NBC_TAG_BASE = 1 << 20
_tag_counter = itertools.count()


# ---------------------------------------------------------------------------
# Schedule IR (reference: nbc_internal.h:149-155 — NBC_Fn_type
# {SEND, RECV, OP, COPY, UNPACK}; rounds delimited by barriers)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class _Send:
    src: int
    dst: int
    buf: str


@dataclass(frozen=True)
class _Recv:
    src: int
    dst: int
    buf: str  # destination buffer name on `dst`


@dataclass(frozen=True)
class _OpPrim:
    rank: int
    a: str
    b: str
    out: str


@dataclass(frozen=True)
class _Copy:
    rank: int
    src: str
    out: str


@dataclass
class Schedule:
    """Compiled collective: rounds of primitives for ALL ranks (the
    driver model issues every rank's operations, so one schedule holds
    the whole job's round structure rather than one rank's slice)."""

    name: str
    size: int
    rounds: list[list[Any]] = field(default_factory=list)
    _current: list[Any] = field(default_factory=list)

    # -- builder API (reference: NBC_Sched_send/recv/op/copy +
    #    NBC_Sched_barrier ends a round) --------------------------------
    def send(self, src: int, dst: int, buf: str) -> None:
        self._current.append(_Send(src, dst, buf))

    def recv(self, src: int, dst: int, buf: str) -> None:
        self._current.append(_Recv(src, dst, buf))

    def move(self, src: int, dst: int, sbuf: str, rbuf: str) -> None:
        """send+recv pair: sbuf@src -> rbuf@dst."""
        self.send(src, dst, sbuf)
        self.recv(src, dst, rbuf)

    def op(self, rank: int, a: str, b: str, out: str) -> None:
        self._current.append(_OpPrim(rank, a, b, out))

    def copy(self, rank: int, src: str, out: str) -> None:
        self._current.append(_Copy(rank, src, out))

    def barrier(self) -> None:
        """End the current round (reference: NBC_Sched_barrier)."""
        if self._current:
            self.rounds.append(self._current)
            self._current = []

    def commit(self) -> "Schedule":
        self.barrier()
        return self

    @property
    def n_rounds(self) -> int:
        return len(self.rounds)


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------

class NbcRequest(Request):
    """A started schedule (reference: NBC_Handle). One round advances
    per progress-engine tick (reference: NBC_Progress executes the
    current round's requests and only then moves to the next), so
    concurrently started collectives interleave their rounds."""

    def __init__(self, comm, sched: Schedule, env: dict, op: Optional[Op],
                 finish: Callable[[dict], Any]) -> None:
        super().__init__()
        self._comm = comm
        self._sched = sched
        self._env = env  # (rank, bufname) -> device value
        self._op = op
        self._finish = finish
        self._round = 0
        self._tag = _NBC_TAG_BASE + (next(_tag_counter) % (1 << 16))
        self._pending: list[tuple[Any, int, str]] = []  # (req, rank, buf)
        SPC.record("nbc_schedules_started")
        _progress.register(self._progress_cb)
        self._registered = True

    # -- round machinery --------------------------------------------------
    def _issue_round(self) -> None:
        """Round semantics: OP/COPY first (they consume the previous
        round's arrivals), then sends, then recvs — so a round reads
        "combine what arrived, then exchange". Sends precede recvs so
        every recv can match immediately (driver model: arrival order
        == issue order; the reference's frags race over the wire and
        need its matching engine instead)."""
        prims = self._sched.rounds[self._round]
        pml = self._comm.pml
        tag = self._tag + self._round
        for p in prims:
            if isinstance(p, _OpPrim):
                self._env[(p.rank, p.out)] = self._op.combine(
                    self._env[(p.rank, p.a)], self._env[(p.rank, p.b)]
                )
            elif isinstance(p, _Copy):
                self._env[(p.rank, p.out)] = self._env[(p.rank, p.src)]
        for p in prims:
            if isinstance(p, _Send):
                # in-process transport: matched by the irecv loop below
                pml.isend(  # commlint: allow(reqlife)
                    self._comm, self._env[(p.src, p.buf)], p.dst, tag,
                    source=p.src,
                )
        for p in prims:
            if isinstance(p, _Recv):
                req = pml.irecv(self._comm, p.src, tag, dest=p.dst)
                self._pending.append((req, p.dst, p.buf))

    def _round_done(self) -> bool:
        return all(r.done for r, _, _ in self._pending)

    def _retire_round(self) -> None:
        for req, rank, buf in self._pending:
            self._env[(rank, buf)] = req.result()
        self._pending = []
        self._round += 1
        SPC.record("nbc_rounds_progressed")

    def _progress_cb(self) -> int:
        """One tick: finish the in-flight round and/or start the next.
        Returns work count (progress-engine convention)."""
        if self.done:
            return 0
        if self._pending:
            if not self._round_done():
                return 0
            self._retire_round()
            return 1
        if self._round >= self._sched.n_rounds:
            self._complete(self._finish(self._env))
            self._unregister()
            return 1
        self._issue_round()
        if self._round_done():
            self._retire_round()
        return 1

    def _unregister(self) -> None:
        if self._registered:
            _progress.unregister(self._progress_cb)
            self._registered = False

    # -- Request interface -------------------------------------------------
    def _poll(self) -> bool:
        if not self.done and not self._registered:
            # A previous wait() timed out and detached us; re-attach so
            # global progress() sweeps advance this schedule again.
            _progress.register(self._progress_cb)
            self._registered = True
        self._progress_cb()
        return self.done

    def wait(self, timeout: float | None = None) -> Status:
        if not _progress.ENGINE.progress_until(self._poll, timeout):
            # Detach from the engine so an abandoned schedule doesn't
            # pin its device buffers or spin on every future tick;
            # _poll re-attaches if the caller retries.
            self._unregister()
            raise TimeoutError(
                f"nbc {self._sched.name} stuck at round "
                f"{self._round}/{self._sched.n_rounds}"
            )
        result = self._result
        if result is not None:
            jax.block_until_ready(result)
        return self.status

    @property
    def rounds_done(self) -> int:
        return self._round


# ---------------------------------------------------------------------------
# Schedule compilers (one per collective; cached per shape-independent
# key — the round structure depends only on (size, root), mirroring
# libnbc's schedule cache keyed on the argument tuple)
# ---------------------------------------------------------------------------

_sched_cache: dict[tuple, Schedule] = {}


def _cached(key: tuple, build: Callable[[], Schedule]) -> Schedule:
    s = _sched_cache.get(key)
    if s is None:
        s = _sched_cache[key] = build().commit()
        SPC.record("nbc_schedules_compiled")
    return s


def _vrank(rank: int, root: int, size: int) -> int:
    return (rank - root) % size


def _rank_of(vrank: int, root: int, size: int) -> int:
    return (vrank + root) % size


def sched_bcast_binomial(size: int, root: int) -> Schedule:
    """Binomial-tree broadcast (reference: nbc_ibcast.c binomial path).

    Round k: every vrank < 2^k holding the data sends to vrank + 2^k.
    """
    s = Schedule("ibcast", size)
    dist = 1
    while dist < size:
        for v in range(dist):
            peer = v + dist
            if peer < size:
                s.move(
                    _rank_of(v, root, size), _rank_of(peer, root, size),
                    "buf", "buf",
                )
        s.barrier()
        dist <<= 1
    return s


def sched_barrier_dissemination(size: int) -> Schedule:
    """Dissemination barrier (reference: nbc_ibarrier.c — log2(n) rounds,
    round k: rank r sends to (r + 2^k) % n and receives from
    (r - 2^k) % n)."""
    s = Schedule("ibarrier", size)
    dist = 1
    while dist < size:
        for r in range(size):
            s.move(r, (r + dist) % size, "tok", "tok")
        s.barrier()
        dist <<= 1
    return s


def sched_allreduce_recursive_doubling(size: int) -> Schedule:
    """Recursive doubling with the non-power-of-two pre/post fold
    (reference: nbc_iallreduce.c NBC_ARED_RDBL; same structure as
    coll_base_allreduce.c:130)."""
    s = Schedule("iallreduce", size)
    pow2 = 1
    while pow2 * 2 <= size:
        pow2 *= 2
    rem = size - pow2
    # Pre-fold: ranks [pow2, size) send into ranks [0, rem).
    if rem:
        for i in range(rem):
            s.move(pow2 + i, i, "buf", "tmp")
        s.barrier()
        for i in range(rem):
            s.op(i, "buf", "tmp", "buf")
    # Recursive doubling among the first pow2 ranks.
    dist = 1
    while dist < pow2:
        for r in range(pow2):
            s.move(r, r ^ dist, "buf", "tmp")
        s.barrier()
        for r in range(pow2):
            s.op(r, "buf", "tmp", "buf")
        dist <<= 1
    # Post-fold: results back out to the folded ranks.
    if rem:
        for i in range(rem):
            s.move(i, pow2 + i, "buf", "buf")
        s.barrier()
    return s


def sched_reduce_binomial(size: int, root: int) -> Schedule:
    """Binomial-tree reduce (reference: nbc_ireduce.c binomial path;
    assumes a commutative op, as the reference's binomial path does)."""
    s = Schedule("ireduce", size)
    dist = 1
    while dist < size:
        for v in range(0, size, dist * 2):
            peer = v + dist
            if peer < size:
                s.move(
                    _rank_of(peer, root, size), _rank_of(v, root, size),
                    "buf", "tmp",
                )
        s.barrier()
        for v in range(0, size, dist * 2):
            if v + dist < size:
                s.op(_rank_of(v, root, size), "buf", "tmp", "buf")
        dist <<= 1
    return s


def sched_allgather_ring(size: int) -> Schedule:
    """Ring allgather (reference: nbc_iallgather.c / the ring in
    coll_base_allgather.c): step k, rank r passes block (r - k) mod n
    to rank r+1."""
    s = Schedule("iallgather", size)
    for step in range(size - 1):
        for r in range(size):
            blk = (r - step) % size
            s.move(r, (r + 1) % size, f"blk{blk}", f"blk{blk}")
        s.barrier()
    return s


def sched_alltoall_pairwise(size: int) -> Schedule:
    """Pairwise-exchange alltoall (reference: nbc_ialltoall.c
    NBC_A2A_PAIRWISE; coll_base_alltoall.c pairwise): step k, rank r
    sends its block for (r + k) and receives from (r - k)."""
    s = Schedule("ialltoall", size)
    for step in range(1, size):
        for r in range(size):
            dst = (r + step) % size
            s.move(r, dst, f"out{dst}", f"in{r}")
        s.barrier()
    return s


def sched_gather_linear(size: int, root: int) -> Schedule:
    """Linear gather (reference: nbc_igather.c — one round, everyone
    sends to root)."""
    s = Schedule("igather", size)
    for r in range(size):
        if r != root:
            s.move(r, root, "buf", f"in{r}")
    return s


def sched_scatter_linear(size: int, root: int) -> Schedule:
    """Linear scatter (reference: nbc_iscatter.c)."""
    s = Schedule("iscatter", size)
    for r in range(size):
        if r != root:
            s.move(root, r, f"out{r}", "buf")
    return s


def sched_scan_linear(size: int, *, exclusive: bool) -> Schedule:
    """Linear scan chain (reference: nbc_iscan.c / nbc_iexscan.c — rank
    r receives the running prefix from r-1, combines, forwards)."""
    s = Schedule("iexscan" if exclusive else "iscan", size)
    if size == 1:
        return s
    # Rank r's forwarded value is the inclusive prefix through r; the
    # exclusive result at r is exactly what arrives from r-1. Combines
    # open the round AFTER the arrival (OP runs at round issue).
    s.copy(0, "buf", "acc")
    for r in range(1, size):
        s.move(r - 1, r, "acc", "prev")
        s.barrier()
        s.op(r, "prev", "buf", "acc")
        s.copy(r, "prev" if exclusive else "acc", "res")
    if not exclusive:
        s.copy(0, "buf", "res")
    return s


# ---------------------------------------------------------------------------
# Public API: rank-major input -> NbcRequest -> rank-major result
# ---------------------------------------------------------------------------

def _rank_blocks(comm, x, buf: str = "buf") -> dict:
    """Split a rank-major array into per-rank device blocks env.

    Device-resident fast path: a jax.Array already sharded rank-major
    over the comm's devices (put_rank_major layout) is split into its
    addressable shards with no host round-trip."""
    n = comm.size
    if isinstance(x, jax.Array):
        if x.ndim < 1 or x.shape[0] != n:
            raise ArgumentError(
                f"expected rank-major leading dim {n}, got {x.shape}"
            )
        shards = {}
        for s in x.addressable_shards:
            idx = s.index[0] if s.index else slice(0, 1)
            start = idx.start or 0
            if idx.stop is not None and idx.stop - start == 1:
                shards[(s.device, start)] = s.data
        if len(shards) == n:
            env = {}
            for r, p in enumerate(comm.procs):
                blk = shards.get((p.device, r))
                if blk is None:
                    break
                env[(r, buf)] = blk[0]  # squeeze the rank row, stays on device
            else:
                return env
        # layout mismatch (replicated, host array on one device, ...):
        # fall through to the host path
    arr = np.asarray(x)
    if arr.ndim < 1 or arr.shape[0] != n:
        raise ArgumentError(
            f"expected rank-major leading dim {n}, got {arr.shape}"
        )
    return {
        (r, buf): jax.device_put(arr[r], comm.procs[r].device)
        for r in range(n)
    }


def _rank_rows(comm, x, min_ndim: int = 1) -> list:
    """Per-rank rows of a rank-major buffer as device values (one per
    rank, on that rank's device); device-resident fast path via
    _rank_blocks, host fallback otherwise."""
    env = _rank_blocks(comm, x)
    rows = [env[(r, "buf")] for r in range(comm.size)]
    if rows[0].ndim < min_ndim:
        raise ArgumentError(
            f"expected rank blocks of ndim >= {min_ndim}, got "
            f"{rows[0].shape}"
        )
    return rows


def _assemble(comm, env, buf: str = "buf"):
    return comm.from_rank_values(
        [env[(r, buf)] for r in range(comm.size)]
    )


def ibcast(comm, x, root: int = 0) -> NbcRequest:
    root = comm.check_rank(root)
    n = comm.size
    sched = _cached(("bcast", n, root), lambda: sched_bcast_binomial(n, root))
    env = _rank_blocks(comm, x)
    return NbcRequest(comm, sched, env, None, lambda e: _assemble(comm, e))


def ibarrier(comm) -> NbcRequest:
    n = comm.size
    sched = _cached(("barrier", n), lambda: sched_barrier_dissemination(n))
    env = {
        (r, "tok"): jax.device_put(
            np.zeros((), np.int32), comm.procs[r].device
        )
        for r in range(n)
    }
    return NbcRequest(comm, sched, env, None, lambda e: None)


def iallreduce(comm, x, op="sum") -> NbcRequest:
    op = op_lookup(op)
    n = comm.size
    sched = _cached(
        ("allreduce", n), lambda: sched_allreduce_recursive_doubling(n)
    )
    env = _rank_blocks(comm, x)
    return NbcRequest(comm, sched, env, op, lambda e: _assemble(comm, e))


def ireduce(comm, x, op="sum", root: int = 0) -> NbcRequest:
    op = op_lookup(op)
    root = comm.check_rank(root)
    n = comm.size
    sched = _cached(
        ("reduce", n, root), lambda: sched_reduce_binomial(n, root)
    )
    env = _rank_blocks(comm, x)
    return NbcRequest(
        comm, sched, env, op, lambda e: e[(root, "buf")]
    )


def iallgather(comm, x) -> NbcRequest:
    n = comm.size
    sched = _cached(("allgather", n), lambda: sched_allgather_ring(n))
    rows = _rank_rows(comm, x)
    env = {(r, f"blk{r}"): rows[r] for r in range(n)}

    def finish(e):
        import jax.numpy as jnp

        return comm.from_rank_values([
            jnp.stack([e[(r, f"blk{i}")] for i in range(n)])
            for r in range(n)
        ])

    return NbcRequest(comm, sched, env, None, finish)


def ialltoall(comm, x) -> NbcRequest:
    n = comm.size
    sched = _cached(("alltoall", n), lambda: sched_alltoall_pairwise(n))
    rows = _rank_rows(comm, x, min_ndim=1)
    if rows[0].shape[0] != n:
        raise ArgumentError(
            f"expected [size, size, ...] blocks, got rank rows of "
            f"shape {rows[0].shape}"
        )
    env = {}
    for r in range(n):
        for d in range(n):
            env[(r, f"out{d}")] = rows[r][d]  # on-device slice
        env[(r, f"in{r}")] = env[(r, f"out{r}")]  # self block stays

    def finish(e):
        import jax.numpy as jnp

        return comm.from_rank_values([
            jnp.stack([e[(r, f"in{src}")] for src in range(n)])
            for r in range(n)
        ])

    return NbcRequest(comm, sched, env, None, finish)


def igather(comm, x, root: int = 0) -> NbcRequest:
    root = comm.check_rank(root)
    n = comm.size
    sched = _cached(("gather", n, root), lambda: sched_gather_linear(n, root))
    env = _rank_blocks(comm, x)
    env[(root, f"in{root}")] = env[(root, "buf")]

    def finish(e):
        import jax.numpy as jnp

        return jnp.stack([e[(root, f"in{r}")] for r in range(n)])

    return NbcRequest(comm, sched, env, None, finish)


def iscatter(comm, x, root: int = 0) -> NbcRequest:
    root = comm.check_rank(root)
    n = comm.size
    sched = _cached(
        ("scatter", n, root), lambda: sched_scatter_linear(n, root)
    )
    arr = np.asarray(x)
    if arr.ndim < 1 or arr.shape[0] != n:
        raise ArgumentError(
            f"expected [size, ...] blocks at root, got {arr.shape}"
        )
    env = {
        (root, f"out{r}"): jax.device_put(arr[r], comm.procs[root].device)
        for r in range(n)
    }
    env[(root, "buf")] = env[(root, f"out{root}")]
    return NbcRequest(comm, sched, env, None, lambda e: _assemble(comm, e))


def ireduce_scatter_block(comm, x, op="sum") -> NbcRequest:
    """Reduce+scatter composition (reference: nbc_ireduce_scatter.c uses
    a reduce-then-scatterv schedule)."""
    op = op_lookup(op)
    n = comm.size
    root = 0
    key = ("reduce_scatter_block", n)

    def build():
        s = sched_reduce_binomial(n, root)
        s.barrier()
        # Scatter row r of the reduced rank-major buffer to rank r.
        for r in range(n):
            if r != root:
                s.move(root, r, f"rsblk{r}", "rsout")
        return s

    sched = _cached(key, build)
    env = _rank_blocks(comm, x)
    if env[(0, "buf")].shape[0] != n:
        raise ArgumentError(
            f"expected [size, size, ...] blocks, got rank rows of "
            f"shape {env[(0, 'buf')].shape}"
        )

    def finish(e):
        reduced = e[(root, "buf")]  # [n, ...] reduced blocks at root
        out = [None] * n
        for r in range(n):
            out[r] = e[(r, "rsout")] if r != root else reduced[root]
        return comm.from_rank_values(out)

    # rsblk slices of root's reduced buffer only exist after the reduce
    # rounds; a lazy env materialises them (on device) when the scatter
    # round reads them. Built BEFORE the request so no progress tick can
    # observe the plain dict.
    class _LazyEnv(dict):
        def __getitem__(self, key):
            rank, buf = key
            if buf.startswith("rsblk") and key not in self:
                idx = int(buf[5:])
                self[key] = dict.__getitem__(self, (rank, "buf"))[idx]
            return dict.__getitem__(self, key)

    return NbcRequest(comm, sched, _LazyEnv(env), op, finish)


def iscan(comm, x, op="sum") -> NbcRequest:
    op = op_lookup(op)
    n = comm.size
    sched = _cached(("scan", n), lambda: sched_scan_linear(n, exclusive=False))
    env = _rank_blocks(comm, x)
    if n == 1:
        env[(0, "res")] = env[(0, "buf")]
    return NbcRequest(
        comm, sched, env, op, lambda e: _assemble(comm, e, "res")
    )


def iexscan(comm, x, op="sum") -> NbcRequest:
    """Exclusive scan; rank 0's result is op-identity-shaped zeros
    (MPI leaves it undefined; we define it as identity when known)."""
    op = op_lookup(op)
    n = comm.size
    sched = _cached(("exscan", n), lambda: sched_scan_linear(n, exclusive=True))
    env = _rank_blocks(comm, x)
    env[(0, "res")] = (
        op.identity_like(env[(0, "buf")])
        if op.has_identity
        else env[(0, "buf")]
    )
    return NbcRequest(
        comm, sched, env, op, lambda e: _assemble(comm, e, "res")
    )

"""coll/demo — scaffold + test-double collective component.

TPU-native equivalent of ompi/mca/coll/demo (reference: a scaffold
component that logs and forwards; the reference's test strategy uses
such scaffolds as mocks, SURVEY §4). Disabled unless selected; when
active it records each operation then delegates to the host-staged
basic algorithms, letting tests observe the per-comm selection and
call flow without faking a fabric.
"""

from __future__ import annotations

from typing import Any

from ..core import config
from .basic import BasicColl
from .framework import COLL

_enable = config.register(
    "coll", "demo", "enable", type=bool, default=False,
    description="Enable the demo/test-double coll component",
)


@COLL.register
class DemoColl(BasicColl):
    NAME = "demo"
    PRIORITY = 0
    DESCRIPTION = "scaffold collective component (test double)"

    def __init__(self, framework) -> None:
        super().__init__(framework)
        #: (operation, comm name) per dispatched call
        self.calls: list[tuple[str, str]] = []

    def available(self, **ctx: Any) -> bool:
        return _enable.value

    def _record(self, opname: str, comm) -> None:
        self.calls.append((opname, comm.name))

    def allreduce(self, comm, x, op):
        self._record("allreduce", comm)
        return super().allreduce(comm, x, op)

    def bcast(self, comm, x, root):
        self._record("bcast", comm)
        return super().bcast(comm, x, root)

    def barrier(self, comm):
        self._record("barrier", comm)
        return super().barrier(comm)

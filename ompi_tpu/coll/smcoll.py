"""coll/sm — same-host spanning collectives over shared memory.

TPU-native equivalent of ompi/mca/coll/sm (reference: coll_sm.h:35-120
— per-comm shm segment with fan-in/fan-out and in_use-flag flow
control; selected above the network paths for fully-intra-node comms).
Here the local phases already run device-resident on each controller's
slice (the hier design); what coll/sm contributes is the LEADER
exchange: when every process of a spanning communicator shares the
host, phase-2 traffic moves as raw frames through the btl/sm segment
— no MPI envelope, no matching queues, no per-hop request objects —
via a fabric byte channel (FabricEngine.open_channel).

Selection: priority 87 beats coll/hier (85) exactly when the comm is
same-host-complete (the reference's coll/sm outranks tuned/tcp for
intra-node comms and withdraws otherwise, coll_sm_module.c query).
All schedules (rd/ring/gather, v/w variants, neighborhood, prefix) are
inherited from HierColl — only the wire changes.
"""

from __future__ import annotations

import struct
import threading
import time
from collections import deque
from typing import Optional

from ..core import progress as _progress
from ..core.counters import SPC
from ..pml.fabric import COLL_SM_TAG
from .framework import COLL
from .hier import FabricSlice, HierColl, HierError, _fabric_wired

#: per-frame header: collective tag (q), source slice (i), comm cid (i)
_HDR = struct.Struct("<qii")


def _engine():
    from ..pml.framework import PML

    try:
        return getattr(PML.component("ob1"), "_fabric", None)
    except Exception:
        return None


class _Router:
    """Engine-wide demux of the coll/sm channel: frames land keyed by
    (cid, src_slice, tag) so interleaved collectives on different
    comms never steal each other's traffic. Locked — concurrent
    collectives on different comms drain from different threads."""

    def __init__(self, engine) -> None:
        self.engine = engine
        self.q = engine.open_channel(COLL_SM_TAG)
        self.stash: dict[tuple, deque] = {}
        self._mu = threading.Lock()

    def _drain_locked(self) -> None:
        while True:
            try:
                _src_idx, raw = self.q.popleft()
            except IndexError:
                break
            tag, src_slice, cid = _HDR.unpack_from(raw)
            self.stash.setdefault((cid, src_slice, tag),
                                  deque()).append(raw[_HDR.size:])

    def pop(self, key) -> Optional[bytes]:
        with self._mu:
            self._drain_locked()
            q = self.stash.get(key)
            if q:
                out = q.popleft()
                if not q:
                    del self.stash[key]
                return out
            return None

    def purge_window(self, cid: int, lo: int, hi: int) -> None:
        """Drop stashed frames of an aborted collective so the 4096-
        epoch tag-window recycle can never resurrect them as a later
        collective's data."""
        with self._mu:
            self._drain_locked()
            dead = [k for k in self.stash
                    if k[0] == cid and lo <= k[2] < hi]
            for k in dead:
                del self.stash[k]


def _router(engine) -> _Router:
    r = getattr(engine, "_coll_sm_router", None)
    if r is None:
        r = engine._coll_sm_router = _Router(engine)
    return r


class ShmSlice(FabricSlice):
    """FabricSlice whose leader exchange rides raw shm frames instead
    of MPI p2p: one segment write + one futex wake per hop (the
    fan-in/fan-out byte path of the reference's coll/sm, with the shm
    rings standing in for its in_use-flagged fragment segments)."""

    def __init__(self, parent) -> None:
        super().__init__(parent)
        eng = _engine()
        if eng is None or eng.shm is None:
            raise HierError("coll/sm needs the shm-wired fabric")
        self.engine = eng
        self.router = _router(eng)

    def send_bytes(self, peer_slice: int, tag: int, raw: bytes) -> None:
        dst_proc = self.slices[peer_slice]
        hdr = _HDR.pack(tag, self.slice_id, self.parent.cid)
        self.engine.shm.send_bytes(dst_proc, COLL_SM_TAG, hdr + raw)
        SPC.record("coll_sm_leader_sends")
        SPC.record("coll_sm_leader_bytes", len(raw))

    def recv_from(self, src_slice: int, tag: int,
                  timeout: float) -> bytes:
        key = (self.parent.cid, src_slice, tag)
        deadline = time.monotonic() + timeout
        spins = 0
        while True:
            out = self.router.pop(key)
            if out is not None:
                return out
            # liveness probe (a kill(pid,0) syscall) only every ~50th
            # pass — per-iteration it would tax the very latency path
            # this transport shortens
            spins += 1
            if spins % 50 == 0 and not self.engine.shm.peer_alive(
                    self.slices[src_slice]):
                raise HierError(
                    f"coll/sm: slice {src_slice}'s controller died "
                    "mid-collective"
                )
            if time.monotonic() >= deadline:
                raise HierError(
                    f"coll/sm: timeout waiting for {key}"
                )
            # pump the fabric (fills the channel), then park briefly on
            # the shm doorbell
            if _progress.progress() == 0:
                self.engine.shm.wait_event(0.002)

    def next_tag_base(self) -> int:
        self._window = super().next_tag_base()
        return self._window

    def finish(self) -> None:
        pass  # shm sends complete on return (copy semantics)

    def abort_pending(self) -> None:
        # Purge this collective's window from the engine stash: an
        # aborted exchange may have landed frames that the (mod-4096)
        # tag-window recycle would otherwise hand to a much-later
        # collective as data.
        w = getattr(self, "_window", None)
        if w is not None:
            self.router.purge_window(self.parent.cid, w, w + 0x10000)


@COLL.register
class SmColl(HierColl):
    NAME = "sm"
    PRIORITY = 87  # above hier (85): same wire family, fewer hops
    DESCRIPTION = ("same-host spanning collectives with the leader "
                   "exchange over the btl/sm segment (reference: "
                   "ompi/mca/coll/sm, coll_sm.h:35-120)")
    SLICE_FACTORY = ShmSlice
    SLICE_ATTR = "_coll_sm_slice"

    def available(self, comm=None, **_) -> bool:
        if comm is None or not _fabric_wired():
            return False
        import jax

        eng = _engine()
        if eng is None or eng.shm is None:
            return False
        try:
            idxs = {p.process_index for p in comm.procs}
        except Exception:
            return False
        me = jax.process_index()
        return (len(idxs) > 1 and me in idxs
                and all(i == me or i in eng.shm_peers for i in idxs))

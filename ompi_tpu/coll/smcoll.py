"""coll/sm — same-host spanning collectives over shared memory.

TPU-native equivalent of ompi/mca/coll/sm (reference: coll_sm.h:35-120
— per-comm shm segment with fan-in/fan-out and in_use-flag flow
control; selected above the network paths for fully-intra-node comms).
Here the local phases already run device-resident on each controller's
slice (the hier design); what coll/sm contributes is the LEADER
exchange: when every process of a spanning communicator shares the
host, phase-2 traffic moves as raw frames through the btl/sm segment
— no MPI envelope, no matching queues, no per-hop request objects —
via a fabric byte channel (FabricEngine.open_channel).

Selection: priority 87 beats coll/hier (85) exactly when the comm is
same-host-complete (the reference's coll/sm outranks tuned/tcp for
intra-node comms and withdraws otherwise, coll_sm_module.c query).
All schedules (rd/ring/gather, v/w variants, neighborhood, prefix) are
inherited from HierColl — only the wire changes.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from ..core import progress as _progress
from ..core.counters import SPC
from ..pml.fabric import COLL_SM_TAG
from .framework import COLL
from .hier import FabricSlice, HierColl, HierError, _fabric_wired, _fold

#: per-frame header: collective tag (q), source slice (i), comm cid (i)
#: — the v2 (spill) lane only; the fastpath lane carries the same
#: triple packed INTO the descriptor tag, so zero header bytes ride
#: the frame (see _fp_tag)
_HDR = struct.Struct("<qii")

#: fastpath descriptor-tag packing: cid (12 bits) | src_slice (8) |
#: collective tag (40). The hier tag window tops out near 2^28
#: (_HIER_TAG + 4096*0x10000), so 40 bits are lossless.
_FP_TAG_MASK = (1 << 40) - 1


def _fp_tag(cid: int, src_slice: int, tag: int) -> int:
    return (((cid & 0xFFF) << 48) | ((src_slice & 0xFF) << 40)
            | (tag & _FP_TAG_MASK))


def _engine():
    from ..pml.framework import PML

    try:
        return getattr(PML.component("ob1"), "_fabric", None)
    except Exception:
        return None


class _Router:
    """Engine-wide demux of the coll/sm channel: frames land keyed by
    (cid, src_slice, tag) so interleaved collectives on different
    comms never steal each other's traffic. Locked — concurrent
    collectives on different comms drain from different threads."""

    def __init__(self, engine) -> None:
        self.engine = engine
        self.q = engine.open_channel(COLL_SM_TAG)
        self.stash: dict[tuple, deque] = {}
        # fastpath frames that arrived for a different (comm,
        # collective) than the one draining the ring: copied out,
        # released, parked here — (src_proc, fp_tag) -> deque[bytes]
        self.fp_stash: dict[tuple, deque] = {}
        self._mu = threading.Lock()

    def _drain_locked(self) -> None:
        while True:
            try:
                _src_idx, raw = self.q.popleft()
            except IndexError:
                break
            tag, src_slice, cid = _HDR.unpack_from(raw)
            self.stash.setdefault((cid, src_slice, tag),
                                  deque()).append(raw[_HDR.size:])

    def pop(self, key) -> Optional[bytes]:
        with self._mu:
            self._drain_locked()
            q = self.stash.get(key)
            if q:
                out = q.popleft()
                if not q:
                    del self.stash[key]
                return out
            return None

    def fp_pop(self, src_proc: int, fptag: int):
        """Next fastpath frame from ``src_proc`` matching ``fptag``:
        ("view", arr, token) — zero-copy, the caller folds out of the
        sender's slab frame then fp_release(token) — when the ring
        head matches; ("bytes", raw, None) when a matching frame was
        stashed by an earlier drain; None when nothing matches yet.
        Head frames for OTHER (comm, collective) keys are copied out,
        released immediately (slab frames are a scarce pool) and
        stashed, so interleaved collectives never wedge each other.
        Locked: one ring consumer at a time (the SPSC contract)."""
        shm = self.engine.shm
        with self._mu:
            q = self.fp_stash.get((src_proc, fptag))
            if q:
                raw = q.popleft()
                if not q:
                    del self.fp_stash[(src_proc, fptag)]
                return ("bytes", raw, None)
            while True:
                got = shm.fp_try_recv_view(src_proc)
                if got is None:
                    return None
                tag, arr, token = got
                if tag == fptag:
                    if token < 0:
                        # inline scratch: only valid until the next
                        # poll on this ctx — hand out a copy
                        return ("bytes", arr.tobytes(), None)
                    return ("view", arr, token)
                self.fp_stash.setdefault(
                    (src_proc, tag), deque()).append(arr.tobytes())
                shm.fp_release(token)

    def purge_window(self, cid: int, lo: int, hi: int) -> None:
        """Drop stashed frames of an aborted collective so the 4096-
        epoch tag-window recycle can never resurrect them as a later
        collective's data (both lanes)."""
        with self._mu:
            self._drain_locked()
            dead = [k for k in self.stash
                    if k[0] == cid and lo <= k[2] < hi]
            for k in dead:
                del self.stash[k]
            deadfp = [
                k for k in self.fp_stash
                if (k[1] >> 48) & 0xFFF == cid & 0xFFF
                and lo <= (k[1] & _FP_TAG_MASK) < hi
            ]
            for k in deadfp:
                del self.fp_stash[k]


def _router(engine) -> _Router:
    r = getattr(engine, "_coll_sm_router", None)
    if r is None:
        r = engine._coll_sm_router = _Router(engine)
    return r


class ShmSlice(FabricSlice):
    """FabricSlice whose leader exchange rides raw shm frames instead
    of MPI p2p: one segment write + one futex wake per hop (the
    fan-in/fan-out byte path of the reference's coll/sm, with the shm
    rings standing in for its in_use-flagged fragment segments)."""

    def __init__(self, parent) -> None:
        super().__init__(parent)
        eng = _engine()
        if eng is None or eng.shm is None:
            raise HierError("coll/sm needs the shm-wired fabric")
        self.engine = eng
        self.router = _router(eng)

    def send_bytes(self, peer_slice: int, tag: int, raw: bytes) -> None:
        dst_proc = self.slices[peer_slice]
        shm = self.engine.shm
        # fastpath first: the (cid, slice, tag) triple rides packed in
        # the descriptor tag, so the frame is pure payload — no header
        # pack, no hdr+raw join. Spills (lane absent/full, frame-size
        # overflow) take the enveloped v2 channel.
        if shm.fp_send(dst_proc,
                       _fp_tag(self.parent.cid, self.slice_id, tag),
                       raw):
            SPC.record("coll_sm_fp_sends")
        else:
            hdr = _HDR.pack(tag, self.slice_id, self.parent.cid)
            shm.send_bytes(dst_proc, COLL_SM_TAG, hdr + raw)
        SPC.record("coll_sm_leader_sends")
        SPC.record("coll_sm_leader_bytes", len(raw))

    def _await_frame(self, src_slice: int, tag: int, timeout: float):
        """Wait for (cid, src_slice, tag) on EITHER lane. Returns
        ("view", arr, release_token) — payload aliasing the sender's
        slab frame — or ("bytes", raw, None)."""
        shm = self.engine.shm
        src_proc = self.slices[src_slice]
        fp_live = shm.fp_available()  # receive side: own lane attached
        fptag = _fp_tag(self.parent.cid, src_slice, tag)
        key = (self.parent.cid, src_slice, tag)
        now = time.monotonic()
        deadline = now + timeout
        # fastpath frames land in single-digit µs: a short yield-spin
        # before parking is the latency win; the park cap stays small
        # because fp doorbells ring the RING futex, not the v2 event
        # this thread parks on.
        spin_end = now + 0.0002
        probes = 0
        # deadline-bounded with its own peer-liveness probe: cannot
        # spin forever on a revoked comm
        while True:  # commlint: allow(revokecheck)
            if fp_live:
                hit = self.router.fp_pop(src_proc, fptag)
                if hit is not None:
                    return hit
            out = self.router.pop(key)
            if out is not None:
                return ("bytes", out, None)
            # liveness probe (a kill(pid,0) syscall) only every ~50th
            # pass — per-iteration it would tax the very latency path
            # this transport shortens
            probes += 1
            if probes % 50 == 0 and not shm.peer_alive(src_proc):
                raise HierError(
                    f"coll/sm: slice {src_slice}'s controller died "
                    "mid-collective"
                )
            now = time.monotonic()
            if now >= deadline:
                raise HierError(
                    f"coll/sm: timeout waiting for {key}"
                )
            if now < spin_end:
                os.sched_yield()
                continue
            # pump the fabric (fills the v2 channel), then park briefly
            # on the shm doorbell
            if _progress.progress() == 0:
                self.engine.shm.wait_event(0.0005)

    def recv_from(self, src_slice: int, tag: int,
                  timeout: float) -> bytes:
        kind, payload, token = self._await_frame(src_slice, tag, timeout)
        if kind == "view":
            raw = payload.tobytes()
            self.engine.shm.fp_release(token)
            return raw
        return payload

    def recv_reduce_into(self, src_slice: int, tag: int, timeout: float,
                         acc: np.ndarray, op) -> np.ndarray:
        """The single-copy reduction plane: fold the incoming block
        into ``acc`` straight OUT of the sender's slab frame — the
        only copy in the hop is the sender's post (PiP-style; the
        reference's coll/sm reduces out of the shared fragment
        segments the same way)."""
        kind, payload, token = self._await_frame(src_slice, tag, timeout)
        if kind == "view":
            try:
                if payload.nbytes != acc.nbytes:
                    raise HierError(
                        f"coll/sm: frame size {payload.nbytes} != "
                        f"accumulator {acc.nbytes}"
                    )
                incoming = payload.view(acc.dtype).reshape(acc.shape)
                out = _fold(acc, incoming, op)
            finally:
                self.engine.shm.fp_release(token)
            SPC.record("coll_sm_slab_folds")
            from ..trace import span as tspan

            tspan.instant("smcoll.fold", cat="coll", src=src_slice,
                          tag=tag, nbytes=acc.nbytes)
            return out
        incoming = np.frombuffer(payload, acc.dtype).reshape(acc.shape)
        return _fold(acc, incoming, op)

    def next_tag_base(self) -> int:
        self._window = super().next_tag_base()
        return self._window

    def finish(self) -> None:
        pass  # shm sends complete on return (copy semantics)

    def abort_pending(self) -> None:
        # Purge this collective's window from the engine stash: an
        # aborted exchange may have landed frames that the (mod-4096)
        # tag-window recycle would otherwise hand to a much-later
        # collective as data.
        w = getattr(self, "_window", None)
        if w is not None:
            self.router.purge_window(self.parent.cid, w, w + 0x10000)


@COLL.register
class SmColl(HierColl):
    NAME = "sm"
    PRIORITY = 87  # above hier (85): same wire family, fewer hops
    DESCRIPTION = ("same-host spanning collectives with the leader "
                   "exchange over the btl/sm segment (reference: "
                   "ompi/mca/coll/sm, coll_sm.h:35-120)")
    SLICE_FACTORY = ShmSlice
    SLICE_ATTR = "_coll_sm_slice"

    def available(self, comm=None, **_) -> bool:
        if comm is None or not _fabric_wired():
            return False
        import jax

        eng = _engine()
        if eng is None or eng.shm is None:
            return False
        try:
            idxs = {p.process_index for p in comm.procs}
        except Exception:
            return False
        me = jax.process_index()
        return (len(idxs) > 1 and me in idxs
                and all(i == me or i in eng.shm_peers for i in idxs))

"""coll/basic — host-staged linear algorithms (correctness fallback).

TPU-native equivalent of ompi/mca/coll/basic (reference: naive
linear/log algorithms as the always-available fallback) — and,
deliberately, of the coll/cuda staging pattern (reference:
coll_cuda_allreduce.c:44-69 — stage device buffers to host, run the host
algorithm, copy back). That staging is the anti-pattern the TPU build
eliminates on the fast path; it is kept here ONLY as the lowest-priority
oracle: it handles every op/dtype (via the ops' numpy combines), runs
without compiling a plan, and gives tests an independent reference for
the fabric components.
"""

from __future__ import annotations

import jax
import numpy as np

from ..core.errors import ArgumentError
from ..ops import lookup as op_lookup
from .framework import COLL, CollComponent


def _to_host(x):
    return jax.tree.map(lambda l: np.asarray(l), x)


@COLL.register
class BasicColl(CollComponent):
    NAME = "basic"
    PRIORITY = 10
    DESCRIPTION = "host-staged linear fallbacks (reference: coll/basic)"

    def _put_back(self, comm, arr):
        return comm.put_rank_major(arr)

    def allreduce(self, comm, x, op):
        op = op_lookup(op)
        host = _to_host(x)
        leaves = jax.tree.leaves(host)
        n = comm.size
        for leaf in leaves:
            if leaf.shape[0] != n:
                raise ArgumentError(
                    f"expected rank-major leading dim {n}, got {leaf.shape}"
                )
        acc = jax.tree.map(lambda l: l[0], host)
        for i in range(1, n):
            ith = jax.tree.map(lambda l, i=i: l[i], host)
            from ..ops.op import _is_joint

            if _is_joint(op):
                acc = op._combine(acc, ith)
            else:
                acc = jax.tree.map(op.np_reduce, acc, ith)
        stacked = jax.tree.map(
            lambda a: np.broadcast_to(np.asarray(a), (n,) + np.shape(a)), acc
        )
        return jax.tree.map(lambda s: self._put_back(comm, s), stacked)

    def bcast(self, comm, x, root):
        host = _to_host(x)
        out = jax.tree.map(
            lambda l: np.broadcast_to(l[root], l.shape), host
        )
        return jax.tree.map(lambda s: self._put_back(comm, s), out)

    def reduce(self, comm, x, op, root):
        red = self.allreduce(comm, x, op)
        return jax.tree.map(lambda l: l[root], red)

    def allgather(self, comm, x):
        host = np.asarray(_to_host(x))
        n = comm.size
        out = np.broadcast_to(host, (n,) + host.shape)
        return self._put_back(comm, np.ascontiguousarray(out))

    def reduce_scatter_block(self, comm, x, op):
        op = op_lookup(op)
        host = np.asarray(_to_host(x))
        n = comm.size
        if host.ndim < 2 or host.shape[0] != n or host.shape[1] != n:
            raise ArgumentError(
                f"reduce_scatter_block needs (size, size, ...), got "
                f"{host.shape}"
            )
        acc = host[0]
        for i in range(1, n):
            acc = op.np_reduce(acc, host[i])
        # acc[j] is rank j's block.
        return self._put_back(comm, acc)

    def alltoall(self, comm, x):
        host = np.asarray(_to_host(x))
        n = comm.size
        if host.ndim < 2 or host.shape[0] != n or host.shape[1] != n:
            raise ArgumentError(
                f"alltoall needs (size, size, ...), got {host.shape}"
            )
        return self._put_back(comm, np.ascontiguousarray(host.swapaxes(0, 1)))

    def gather(self, comm, x, root):
        host = np.asarray(_to_host(x))
        return jax.device_put(host, comm.devices[root])

    def scatter(self, comm, x, root):
        host = np.asarray(_to_host(x))
        if host.shape[0] != comm.size:
            raise ArgumentError(
                f"scatter needs (size, ...), got {host.shape}"
            )
        return self._put_back(comm, host)

    def scan(self, comm, x, op):
        op = op_lookup(op)
        host = np.asarray(_to_host(x))
        out = host.copy()
        for i in range(1, comm.size):
            out[i] = op.np_reduce(out[i - 1], host[i])
        return self._put_back(comm, out)

    def exscan(self, comm, x, op):
        op = op_lookup(op)
        host = np.asarray(_to_host(x))
        out = np.zeros_like(host)
        acc = host[0]
        for i in range(1, comm.size):
            out[i] = acc
            if i < comm.size - 1:
                acc = op.np_reduce(acc, host[i])
        return self._put_back(comm, out)

    def barrier(self, comm):
        return None

    # -- vector (ragged) variants -----------------------------------------
    # Driver-mode ragged convention: inputs are per-rank sequences of
    # arrays whose leading dims differ (the counts are carried by the
    # shapes, so no separate counts argument — reference alltoallv's
    # sendcounts/displs arrays collapse into the block list).

    @staticmethod
    def _ragged_in(comm, values) -> list[np.ndarray]:
        if len(values) != comm.size:
            raise ArgumentError(
                f"need one block per rank ({comm.size}), got {len(values)}"
            )
        return [np.asarray(_to_host(v)) for v in values]

    def allgatherv(self, comm, values):
        host = self._ragged_in(comm, values)
        cat = np.concatenate(host, axis=0)
        return jax.device_put(cat, comm.replicated_sharding())

    def gatherv(self, comm, values, root):
        host = self._ragged_in(comm, values)
        cat = np.concatenate(host, axis=0)
        return jax.device_put(cat, comm.devices[root])

    def scatterv(self, comm, blocks, root):
        host = self._ragged_in(comm, blocks)
        return [
            jax.device_put(b, comm.devices[r])
            for r, b in enumerate(host)
        ]

    def alltoallv(self, comm, blocks):
        """blocks[src][dst] = array for dst; returns out[dst] =
        concatenation over src of blocks[src][dst], on dst's device."""
        n = comm.size
        if len(blocks) != n:
            raise ArgumentError(f"need {n} send lists, got {len(blocks)}")
        out = []
        for dstr in range(n):
            pieces = [
                np.asarray(_to_host(blocks[src][dstr])) for src in range(n)
            ]
            out.append(
                jax.device_put(
                    np.concatenate(pieces, axis=0), comm.devices[dstr]
                )
            )
        return out

    def alltoallw(self, comm, blocks):
        """Like alltoallv but fully heterogeneous: no concatenation —
        out[dst][src] keeps each block's own shape/dtype (reference
        MPI_Alltoallw's per-block datatypes)."""
        n = comm.size
        if len(blocks) != n:
            raise ArgumentError(f"need {n} send lists, got {len(blocks)}")
        return [
            [
                jax.device_put(
                    np.asarray(_to_host(blocks[src][dst])),
                    comm.devices[dst],
                )
                for src in range(n)
            ]
            for dst in range(n)
        ]

    def reduce_scatter(self, comm, values, counts, op):
        """MPI_Reduce_scatter: element-wise reduce the per-rank (total,
        ...) buffers, then scatter piece r (counts[r] rows) to rank r."""
        op = op_lookup(op)
        host = self._ragged_in(comm, values)
        n = comm.size
        if len(counts) != n:
            raise ArgumentError(f"need {n} counts, got {len(counts)}")
        total = sum(counts)
        for h in host:
            if h.shape[0] != total:
                raise ArgumentError(
                    f"buffer rows {h.shape[0]} != sum(counts) {total}"
                )
        acc = host[0]
        for i in range(1, n):
            acc = op.np_reduce(acc, host[i])
        out, start = [], 0
        for r, c in enumerate(counts):
            out.append(
                jax.device_put(acc[start:start + c], comm.devices[r])
            )
            start += c
        return out

    # -- neighborhood collectives over the attached topology --------------

    def neighbor_allgather(self, comm, x):
        from ..topo import topology as topo_mod

        return topo_mod.neighbor_allgather(comm, x)

    def neighbor_alltoall(self, comm, sendblocks):
        from ..topo import topology as topo_mod

        return topo_mod.neighbor_alltoall(comm, sendblocks)

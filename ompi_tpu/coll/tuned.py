"""coll/tuned — algorithm decision layer.

TPU-native equivalent of ompi/mca/coll/tuned (reference:
coll_tuned_decision_fixed.c — fixed rules keyed on communicator size,
message size and op commutativity; coll_tuned_dynamic_file.c — rules
loadable from a file; per-op forced-algorithm MCA vars in
coll_tuned_*_decision.c).

The decision picks among the explicit algorithm space in coll/spmd plus
the XLA-native lowering. Defaults mirror the reference's fixed rules
(recursive doubling < 10 KB; ring ≤ 1 MB/rank; segmented ring above, 1 MB
segments — coll_tuned_decision_fixed.c:45-87) with one TPU-first change:
when the op maps onto the fabric's native reduction (`prefer_native`,
default on), XLA's own collective is used — it compiles to the ICI
schedule the explicit algorithms approximate.
"""

from __future__ import annotations

import json
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import config
from ..core.errors import ArgumentError
from ..core.logging import get_logger
from ..ops import Op, lookup as op_lookup
from ..ops.op import _is_joint
from . import spmd
from .framework import COLL, CollComponent, compile_plan, rank_major_check
from .xla import XlaColl, _dtype_key, _leaf_check

logger = get_logger("coll.tuned")

# Reference cutoffs (BASELINE.md): 10,000 B small-message cutoff, 1 MiB
# ring→segmented switch, 1 MiB segments.
_V = partial(config.register, "coll", "tuned")
_large = _V("bcast_large_cutoff", type=int, default=1 << 20,
            description="Bytes above which rooted ops take the "
                        "segmented pipeline tier (reference: 1MiB "
                        "segments, coll_tuned_decision_fixed.c:250-310)")
_small = _V("allreduce_small_cutoff", type=int, default=10_000,
            description="Allreduce: bytes/rank below which recursive "
                        "doubling is used (reference: 10000B)")
_ring_limit = _V("allreduce_ring_limit", type=int, default=1 << 20,
                 description="Allreduce: max bytes/rank for plain ring "
                             "before switching to segmented ring")
_seg_bytes = _V("segment_bytes", type=int, default=1 << 20,
                description="Segment size for segmented algorithms "
                            "(reference: 1MiB)")
_prefer_native = _V("prefer_native", type=bool, default=True,
                    description="Use XLA-native fabric collectives when "
                                "the op supports them")
_rules_file = _V("rules_file", type=str, default="",
                 description="JSON dynamic-rules file (reference: "
                             "coll_tuned_dynamic_file.c)")
_force_allreduce = _V("allreduce_algorithm", type=str, default="",
                      description="Force an allreduce algorithm by name")
_force_alltoall = _V("alltoall_algorithm", type=str, default="",
                     description="Force an alltoall algorithm by name")
_force_allgather = _V("allgather_algorithm", type=str, default="",
                      description="Force an allgather algorithm by name")
_force_bcast = _V("bcast_algorithm", type=str, default="",
                  description="Force a bcast algorithm by name")
_force_reduce = _V("reduce_algorithm", type=str, default="",
                   description="Force a reduce algorithm by name")
_force_scan = _V("scan_algorithm", type=str, default="",
                 description="Force the scan algorithm")
_force_exscan = _V("exscan_algorithm", type=str, default="",
                   description="Force the exscan algorithm")
_force_reduce_scatter = _V("reduce_scatter_algorithm", type=str, default="",
                           description="Force a reduce_scatter algorithm "
                                       "by name")
_force_gather = _V("gather_algorithm", type=str, default="",
                   description="Force a gather algorithm by name")
_force_scatter = _V("scatter_algorithm", type=str, default="",
                    description="Force a scatter algorithm by name")
_gather_binomial_max = _V("gather_binomial_max_bytes", type=int,
                          default=6 << 10,
                          description="Gather: per-rank bytes below which "
                                      "the binomial tree is used "
                                      "(reference: small-block binomial, "
                                      "coll_tuned_decision_fixed.c)")
_alltoall_small = _V("alltoall_small_msg", type=int, default=256,
                     description="Alltoall: bytes/dest below which bruck "
                                 "is used")
_alltoall_large = _V("alltoall_large_msg", type=int, default=32 << 10,
                     description="Alltoall: bytes/dest above which "
                                 "pairwise exchange is used")
_fast_cache_var = _V("fast_dispatch_cache", type=bool, default=True,
                     description="Memoize the routed allreduce dispatch "
                                 "per (comm, shape, dtype, op): repeat "
                                 "calls skip the decision pipeline "
                                 "entirely. Invalidated by any config "
                                 "mutation or breaker activity; bypassed "
                                 "while faultline is armed")
_host_small_max = _V("host_small_max_bytes", type=int, default=4096,
                     description="Fully-addressable allreduces at or "
                                 "below this many bytes reduce on the "
                                 "HOST (numpy over the rank axis + one "
                                 "device_put) instead of launching an "
                                 "XLA program — dispatch latency beats "
                                 "device compute at this size. 0 "
                                 "disables. Skipped under forced "
                                 "algorithms or a rules file")

# Quantized-wire cvars live in coll/quant (coll_quant_enable / _wire /
# _block / _min_bytes); decide_allreduce reads them through the quant
# module so the gate and the codec cannot disagree.

ALLREDUCE_ALGOS: dict[str, Callable] = {
    "native": spmd.allreduce_native,
    "recursive_doubling": spmd.allreduce_recursive_doubling,
    "ring": spmd.allreduce_ring,
    "ring_segmented": spmd.allreduce_ring_segmented,
    "rabenseifner": spmd.allreduce_reduce_scatter_allgather,
    "nonoverlapping": spmd.allreduce_nonoverlapping,
    "gather_reduce": spmd._allreduce_gather_reduce,
}


def _pallas_algos() -> None:
    """Extend the algorithm spaces with the Pallas kernel tier so the
    tuned rules (and tools/tune.py sweeps) can select pallas-vs-xla
    from measurement. Lazy: importing pallas pulls in Mosaic."""
    if "pallas_ring" in ALLREDUCE_ALGOS:
        return
    from . import pallas_ring as pr

    def _pallas_rd_guarded(b, axis_name, op):
        # recursive doubling needs a power-of-two ring; rules naming it
        # on other sizes degrade to the plain ring instead of failing at
        # trace time (the reference's decision functions guard the same
        # way before picking an algorithm)
        n = jax.lax.axis_size(axis_name)
        if n & (n - 1):
            return pr.allreduce_block(b, axis_name, op)
        return pr.allreduce_block_rd(b, axis_name, op)

    ALLREDUCE_ALGOS["pallas_ring"] = pr.allreduce_block
    ALLREDUCE_ALGOS["pallas_bidir"] = pr.allreduce_block_bidir
    ALLREDUCE_ALGOS["pallas_rd"] = _pallas_rd_guarded
    ALLREDUCE_ALGOS["pallas_ring_chunked"] = pr.allreduce_block_chunked
    ALLREDUCE_ALGOS["pallas_rsag"] = pr.allreduce_block_rsag
    BCAST_ALGOS["pallas_binomial"] = pr.bcast_block
    ALLGATHER_ALGOS["pallas_ring"] = pr.ring_allgather
    REDUCE_ALGOS["pallas_tree"] = pr.reduce_block
    REDUCE_SCATTER_ALGOS["pallas_ring"] = pr.ring_reduce_scatter
    GATHER_ALGOS["pallas_linear"] = pr.gather_block
    SCATTER_ALGOS["pallas_linear"] = pr.scatter_block


def _quant_algos() -> None:
    """Extend the allreduce space with the quantized-wire tier (lazy,
    like _pallas_algos: the names are selectable from rules files and
    forced vars before the module is imported)."""
    if "quant_ring" in ALLREDUCE_ALGOS:
        return
    from . import quant

    ALLREDUCE_ALGOS["quant_ring"] = quant.allreduce_quant_ring
    ALLREDUCE_ALGOS["quant_pallas"] = quant.allreduce_block_quant


def _sched_algos() -> None:
    """Extend the allreduce space with the schedule-compiler tier
    (coll/sched): IR programs lowered to fused jitted callables. Lazy
    like _pallas_algos — the names are selectable from rules files,
    forced vars and the schedule cache before the package is
    imported."""
    if "sched_ring" in ALLREDUCE_ALGOS:
        return
    from . import sched

    ALLREDUCE_ALGOS["sched_ring"] = sched.allreduce_sched_ring
    ALLREDUCE_ALGOS["sched_rd"] = sched.allreduce_sched_rd
    ALLREDUCE_ALGOS["sched_ring_seg"] = sched.allreduce_sched_ring_seg
    ALLREDUCE_ALGOS["sched_hier"] = sched.allreduce_sched_hier
    ALLREDUCE_ALGOS["sched_quant"] = sched.allreduce_sched_quant
    ALLREDUCE_ALGOS["sched_pallas_ring"] = sched.allreduce_sched_pallas_ring
    ALLREDUCE_ALGOS["sched_pallas_ring_seg"] = \
        sched.allreduce_sched_pallas_ring_seg
    REDUCE_SCATTER_ALGOS["sched_pallas_rs"] = sched.reduce_scatter_sched_pallas


def is_pallas_algo(name: str) -> bool:
    # quant_pallas is a Mosaic kernel too, as are the sched compiler's
    # fused device_pallas-tier kernels: same check_vma exemption.
    return name.startswith(("pallas", "sched_pallas")) \
        or name == "quant_pallas"


def is_quant_algo(name: str) -> bool:
    return name.startswith("quant")


def is_sched_algo(name: str) -> bool:
    """Schedule-compiler tier names (lowered IR programs)."""
    return name.startswith("sched_")


def _ensure_lazy(algo: str) -> None:
    """Trigger whichever lazy tier registration ``algo`` needs."""
    if is_pallas_algo(algo):
        _pallas_algos()
    if is_quant_algo(algo):
        _quant_algos()
    if is_sched_algo(algo):
        _sched_algos()


def _resolve_algo(opname: str, algo: str):
    """The callable behind an algorithm name (None if unknown),
    triggering lazy tier registrations on demand — how the sched
    autotuner and tools sweeps resolve candidates by name."""
    _ensure_lazy(algo)
    spaces = {
        "allreduce": ALLREDUCE_ALGOS,
        "alltoall": ALLTOALL_ALGOS,
        "allgather": ALLGATHER_ALGOS,
        "bcast": BCAST_ALGOS,
        "reduce": REDUCE_ALGOS,
        "scan": SCAN_ALGOS,
        "exscan": EXSCAN_ALGOS,
        "reduce_scatter": REDUCE_SCATTER_ALGOS,
        "gather": GATHER_ALGOS,
        "scatter": SCATTER_ALGOS,
    }
    space = spaces.get(opname)
    return None if space is None else space.get(algo)


#: Algorithm names that exist but are registered lazily (importing
#: pallas pulls in Mosaic; importing quant is cheap but kept symmetric).
#: Rules-file validation must know them without forcing the import.
_LAZY_ALGOS: dict[str, frozenset] = {
    "allreduce": frozenset({
        "pallas_ring", "pallas_bidir", "pallas_rd", "pallas_ring_chunked",
        "pallas_rsag", "quant_ring", "quant_pallas",
        "sched_ring", "sched_rd", "sched_ring_seg", "sched_hier",
        "sched_quant", "sched_pallas_ring", "sched_pallas_ring_seg",
    }),
    "bcast": frozenset({"pallas_binomial"}),
    "allgather": frozenset({"pallas_ring"}),
    "reduce": frozenset({"pallas_tree"}),
    "reduce_scatter": frozenset({"pallas_ring", "sched_pallas_rs"}),
    "gather": frozenset({"pallas_linear"}),
    "scatter": frozenset({"pallas_linear"}),
}

ALLGATHER_ALGOS: dict[str, Callable] = {
    "native": spmd.allgather_native,
    "ring": spmd.allgather_ring,
    "bruck": spmd.allgather_bruck,
}

ALLTOALL_ALGOS: dict[str, Callable] = {
    "native": spmd.alltoall_native,
    "pairwise": spmd.alltoall_pairwise,
    "bruck": spmd.alltoall_bruck,
}

BCAST_ALGOS: dict[str, Callable] = {
    "native": spmd.bcast_native,
    "binomial": spmd.bcast_binomial,
    "chain": spmd.bcast_chain,
    "binary": spmd.bcast_binary,
    "pipelined": spmd.bcast_pipelined,
}

REDUCE_ALGOS: dict[str, Callable] = {
    "native": spmd.reduce_native,
    "binomial": spmd.reduce_binomial,
    "pipelined": spmd.reduce_pipelined,
}

SCAN_ALGOS: dict[str, Callable] = {
    "native": spmd.scan_native,
    "recursive_doubling": spmd.scan_recursive_doubling,
    "linear_chain": spmd.scan_linear_chain,
}

EXSCAN_ALGOS: dict[str, Callable] = {
    "native": spmd.exscan_native,
    "recursive_doubling": spmd.exscan_recursive_doubling,
    "linear_chain": spmd.exscan_linear_chain,
}

REDUCE_SCATTER_ALGOS: dict[str, Callable] = {
    "native": spmd.reduce_scatter_native,
    "ring": spmd.reduce_scatter_ring,
    "recursive_halving": spmd.reduce_scatter_recursive_halving,
}

GATHER_ALGOS: dict[str, Callable] = {
    "native": spmd.gather_native,
    "binomial": spmd.gather_binomial,
}

SCATTER_ALGOS: dict[str, Callable] = {
    "native": spmd.scatter_native,
    "binomial": spmd.scatter_binomial,
}


def _algo_space(opname: str) -> set:
    """Every selectable algorithm name for ``opname``, including the
    lazily registered tiers (without importing them)."""
    spaces = {
        "allreduce": ALLREDUCE_ALGOS,
        "alltoall": ALLTOALL_ALGOS,
        "allgather": ALLGATHER_ALGOS,
        "bcast": BCAST_ALGOS,
        "reduce": REDUCE_ALGOS,
        "scan": SCAN_ALGOS,
        "exscan": EXSCAN_ALGOS,
        "reduce_scatter": REDUCE_SCATTER_ALGOS,
        "gather": GATHER_ALGOS,
        "scatter": SCATTER_ALGOS,
    }
    space = spaces.get(opname)
    if space is None:
        return set()
    return set(space) | set(_LAZY_ALGOS.get(opname, ()))


_KNOWN_OPNAMES = frozenset({
    "allreduce", "alltoall", "allgather", "bcast", "reduce", "scan",
    "exscan", "reduce_scatter", "gather", "scatter",
})


class Rules:
    """Dynamic decision rules loaded from a JSON file:
    {"allreduce": [{"max_bytes": N, "min_ranks": M, "algorithm": "ring"},
     ...], ...} — first matching entry wins.

    Band keys: min_bytes/max_bytes/min_ranks/max_ranks, plus the
    precision dimension: ``"dtype": "float32"`` restricts a rule to one
    payload dtype, and ``"allow_quant": false`` vetoes the automatic
    quantized-wire tier inside the rule's band (a rule carrying only
    the veto needs no "algorithm").

    Unknown opname keys and unknown algorithm names are NOT silent
    (reference regression: coll_tuned_dynamic_file.c ignores junk and
    users debug it for days) — each unknown key is logged ONCE through
    the monitoring layer, counted on the coll_tuned_rules_unknown pvar,
    and the rule is skipped, so a bogus rules file can never select a
    nonexistent algorithm."""

    def __init__(self, path: str) -> None:
        with open(path, "r", encoding="utf-8") as f:
            self._rules = json.load(f)
        self._warned: set = set()
        for opname in self._rules:
            if opname not in _KNOWN_OPNAMES:
                self._warn_once(
                    ("opname", opname),
                    "rules file names unknown operation %r "
                    "(known: %s)", opname, sorted(_KNOWN_OPNAMES),
                )

    def _warn_once(self, key: tuple, msg: str, *args) -> None:
        if key in self._warned:
            return
        self._warned.add(key)
        from ..core.counters import SPC

        SPC.record("coll_tuned_rules_unknown")
        logger.warning(msg, *args)

    def _matches(self, rule: dict, nbytes: int, nranks: int,
                 dtype) -> bool:
        if nbytes > rule.get("max_bytes", float("inf")):
            return False
        if nbytes < rule.get("min_bytes", 0):
            return False
        if nranks < rule.get("min_ranks", 0):
            return False
        if nranks > rule.get("max_ranks", float("inf")):
            return False
        want = rule.get("dtype")
        if want is not None and (dtype is None or str(dtype) != want):
            return False
        return True

    def decide(self, opname: str, nbytes: int, nranks: int,
               dtype=None) -> Optional[str]:
        known = _algo_space(opname)
        for rule in self._rules.get(opname, ()):
            if not self._matches(rule, nbytes, nranks, dtype):
                continue
            algo = rule.get("algorithm")
            if algo is None:
                continue  # veto-only rule (allow_quant band)
            if algo not in known:
                self._warn_once(
                    ("algo", opname, algo),
                    "rules file names unknown %s algorithm %r "
                    "(known: %s); rule skipped", opname, algo,
                    sorted(known),
                )
                continue
            return algo
        return None

    def allows_quant(self, opname: str, nbytes: int, nranks: int,
                     dtype=None) -> bool:
        """False when the first matching rule carries
        ``"allow_quant": false`` — the user-rules veto on the
        automatic quantized-wire tier."""
        for rule in self._rules.get(opname, ()):
            if not self._matches(rule, nbytes, nranks, dtype):
                continue
            if "allow_quant" in rule:
                return bool(rule["allow_quant"])
        return True


_rules_cache: dict[str, Rules] = {}


def _rules() -> Optional[Rules]:
    path = _rules_file.value
    if not path:
        return None
    r = _rules_cache.get(path)
    if r is None:
        try:
            r = Rules(path)
        except (OSError, ValueError, KeyError) as exc:
            logger.warning("cannot load rules file %s: %s", path, exc)
            r = Rules.__new__(Rules)
            r._rules = {}
        _rules_cache[path] = r
    return r


def _nbytes(x) -> int:
    """Bytes per rank of a rank-major pytree (block size, not total)."""
    total = 0
    for leaf in jax.tree.leaves(x):
        arr = jnp.asarray(leaf)
        total += (arr.size // max(arr.shape[0], 1)) * arr.dtype.itemsize
    return total


def _sched_lookup(opname: str, nbytes: int, nranks: int, dtype=None,
                  op=None, scope: Optional[str] = None) -> Optional[str]:
    """Compiled-schedule cache consult (the precedence slot between the
    correctness guards and the static priors). ``nbytes`` is bytes per
    rank — the same convention as Rules bands and the cache's size
    buckets. ``scope`` carries the communicator identity for SLO
    frontier selection."""
    from . import sched

    return sched.lookup(opname, nbytes, nranks, dtype=dtype, op=op,
                        scope=scope)


def decide_allreduce(op: Op, nbytes: int, nranks: int, dtype=None,
                     allow_quant: Optional[bool] = None,
                     scope: Optional[str] = None) -> str:
    """Pick the allreduce algorithm; precision-aware since the quant
    tier exists.  ``nbytes`` is BYTES PER RANK (the block size of the
    rank-major payload, see _nbytes) — the one byte convention shared
    by Rules bands, the schedule cache's size buckets and the priors.
    ``dtype`` is the payload element type (None = unknown → quant
    refused).  ``allow_quant`` overrides the coll_quant_enable cvar
    (True forces consideration, False vetoes); user rules can veto per
    band via ``"allow_quant": false``.

    Precedence: forced var > rules file > correctness guard
    (non-commutative/joint → ordered gather_reduce) > tuned
    compiled-schedule cache > static priors (sched/priors)."""
    forced = _force_allreduce.value
    if forced:
        return forced
    rules = _rules()
    if rules is not None:
        got = rules.decide("allreduce", nbytes, nranks, dtype)
        if got:
            return got
    if not op.commutative or _is_joint(op):
        return "gather_reduce"
    tuned_pick = _sched_lookup("allreduce", nbytes, nranks, dtype, op,
                               scope=scope)
    if tuned_pick:
        if allow_quant is False and (is_quant_algo(tuned_pick)
                                     or tuned_pick == "sched_quant"):
            tuned_pick = None  # caller's explicit lossy-wire veto wins
        if tuned_pick:
            return tuned_pick
    from .sched import priors

    return priors.prior_allreduce(op, nbytes, nranks, dtype,
                                  allow_quant, rules)


def decide_alltoall(nbytes_per_dest: int, nranks: int) -> str:
    forced = _force_alltoall.value
    if forced:
        return forced
    rules = _rules()
    if rules is not None:
        got = rules.decide("alltoall", nbytes_per_dest, nranks)
        if got:
            return got
    got = _sched_lookup("alltoall", nbytes_per_dest, nranks)
    if got:
        return got
    from .sched import priors

    return priors.prior_alltoall(nbytes_per_dest, nranks)


def decide_allgather(nbytes: int, nranks: int) -> str:
    forced = _force_allgather.value
    if forced:
        return forced
    rules = _rules()
    if rules is not None:
        got = rules.decide("allgather", nbytes, nranks)
        if got:
            return got
    got = _sched_lookup("allgather", nbytes, nranks)
    if got:
        return got
    from .sched import priors

    return priors.prior_allgather(nbytes, nranks)


def decide_bcast(nbytes: int, nranks: int) -> str:
    """Reference regime (coll_tuned_decision_fixed.c:250-310): binomial
    for small messages, binary tree mid-size, segmented pipeline/chain
    for bulk. Native (XLA's own broadcast lowering) stays the default
    when preferred — XLA already emits the ICI-optimal schedule; the
    algorithm tiers are for rules-file/sweep selection and spanning
    reuse."""
    forced = _force_bcast.value
    if forced:
        return forced
    rules = _rules()
    if rules is not None:
        got = rules.decide("bcast", nbytes, nranks)
        if got:
            return got
    got = _sched_lookup("bcast", nbytes, nranks)
    if got:
        return got
    from .sched import priors

    return priors.prior_bcast(nbytes, nranks)


def decide_scan(op: Op, nbytes: int, nranks: int) -> str:
    """Scan space: the log-depth doubling exchange for small payloads,
    the associative-scan native plan otherwise; joint (paired-word)
    ops stay native — the variants exchange leaves positionally."""
    forced = _force_scan.value
    if forced:
        return forced
    rules = _rules()
    if rules is not None:
        got = rules.decide("scan", nbytes, nranks)
        if got:
            return got
    if _is_joint(op):
        return "native"
    got = _sched_lookup("scan", nbytes, nranks)
    if got:
        return got
    from .sched import priors

    return priors.prior_scan(op, nbytes, nranks)


def decide_exscan(op: Op, nbytes: int, nranks: int) -> str:
    forced = _force_exscan.value
    if forced:
        return forced
    rules = _rules()
    if rules is not None:
        got = rules.decide("exscan", nbytes, nranks)
        if got:
            return got
    if _is_joint(op):
        return "native"
    got = _sched_lookup("exscan", nbytes, nranks)
    if got:
        return got
    from .sched import priors

    return priors.prior_exscan(op, nbytes, nranks)


def decide_reduce(op: Op, nbytes: int, nranks: int) -> str:
    """Reference: coll_tuned_reduce_decision / decision_fixed — binomial
    for small messages, pipelined chains above; non-commutative ops take
    the ordered path. Here 'native' (the XLA allreduce + root slice) is
    the large-message answer: XLA already emits the ICI-optimal
    schedule."""
    forced = _force_reduce.value
    if forced:
        return forced
    rules = _rules()
    if rules is not None:
        got = rules.decide("reduce", nbytes, nranks)
        if got:
            return got
    if not op.commutative or _is_joint(op):
        return "native"  # ordered handling lives in the algo fallback
    got = _sched_lookup("reduce", nbytes, nranks)
    if got:
        return got
    from .sched import priors

    return priors.prior_reduce(op, nbytes, nranks)


def decide_reduce_scatter(op: Op, nbytes: int, nranks: int) -> str:
    """Reference: coll_base_reduce_scatter.c decision — recursive
    halving for small commutative power-of-two cases, ring for large."""
    forced = _force_reduce_scatter.value
    if forced:
        return forced
    rules = _rules()
    if rules is not None:
        got = rules.decide("reduce_scatter", nbytes, nranks)
        if got:
            return got
    if not op.commutative or _is_joint(op):
        # ring/halving accumulate out of rank order; the native path's
        # ordered gather-reduce fallback is the only correct one
        return "native"
    got = _sched_lookup("reduce_scatter", nbytes, nranks)
    if got:
        return got
    from .sched import priors

    return priors.prior_reduce_scatter(op, nbytes, nranks)


def decide_gather(nbytes: int, nranks: int) -> str:
    forced = _force_gather.value
    if forced:
        return forced
    rules = _rules()
    if rules is not None:
        got = rules.decide("gather", nbytes, nranks)
        if got:
            return got
    got = _sched_lookup("gather", nbytes, nranks)
    if got:
        return got
    from .sched import priors

    return priors.prior_gather(nbytes, nranks)


def decide_scatter(nbytes: int, nranks: int) -> str:
    """Default is ALWAYS native: on a single controller scatter is a
    pure reshard (put_rank_major), while the algorithm-form path must
    first stage the buffer replicated n-ways just to tear it apart
    again. The tree algorithms exist for parity with
    coll_base_scatter.c and are reachable only by forced var or rules
    file (e.g. for spanning-comm reuse where the staging is the
    transport anyway)."""
    forced = _force_scatter.value
    if forced:
        return forced
    rules = _rules()
    if rules is not None:
        got = rules.decide("scatter", nbytes, nranks)
        if got:
            return got
    got = _sched_lookup("scatter", nbytes, nranks)
    if got:
        return got
    from .sched import priors

    return priors.prior_scatter(nbytes, nranks)


def allreduce_by_decision(x: jax.Array, axis_name: str, op,
                          allow_quant: Optional[bool] = None
                          ) -> jax.Array:
    """Traced (inside shard_map/jit) allreduce of a plain array over
    ``axis_name``, routed through the same decision pipeline the comm
    vtable uses — this is how per-bucket dispatch (parallel/bucketer)
    gets tuned scheduling and the quant tier without a communicator
    object.  The decision runs at trace time (axis sizes are static)."""
    op = op_lookup(op)
    nranks = jax.lax.axis_size(axis_name)
    if nranks == 1:
        return x
    nbytes = x.size * x.dtype.itemsize
    algo = decide_allreduce(op, nbytes, nranks, dtype=x.dtype,
                            allow_quant=allow_quant)
    # Circuit breaker: route around tiers that tripped on a previous
    # kernel/transport fault. The decision runs at trace time, so this
    # is the only breaker hook the traced path gets (no runtime catch
    # is possible inside shard_map) — dispatch-time retry lives in
    # TunedColl.allreduce.
    from . import breaker

    algo = breaker.route("allreduce", algo)
    _ensure_lazy(algo)
    fn = ALLREDUCE_ALGOS.get(algo)
    if fn is None:
        raise ArgumentError(
            f"unknown allreduce algorithm {algo!r}; known: "
            f"{sorted(ALLREDUCE_ALGOS)}"
        )
    from ..core.counters import SPC

    SPC.record(f"coll_allreduce_algo_{algo}")
    # commtrace: one instant per decision shows *which* tier the tuned
    # table (plus breaker routing) actually picked on the timeline.
    from ..trace import span as tspan

    tspan.instant("tuned.tier", cat="coll", op="allreduce",
                  algo=algo, nbytes=nbytes)
    if is_quant_algo(algo) or algo == "sched_quant":
        from . import quant

        quant.record_wire_stats(nbytes, x.dtype.itemsize)
    if algo == "ring_segmented":
        seg_elems = max(1, _seg_bytes.value // x.dtype.itemsize)
        return fn(x, axis_name, op, segment_elems=seg_elems)
    return fn(x, axis_name, op)


def _probe_steps(comm, opname: str, algo: str) -> None:
    """Walk the chosen program's step count and probe faultline at
    each one (only ever called with a plan armed). sched_* algorithms
    report their real IR round count; the closed-form tiers use the
    ring-equivalent 2*(n-1) so ``after_step=`` has a stable meaning
    everywhere."""
    from ..ft import inject

    nsteps = 2 * (comm.size - 1)
    try:
        from . import sched as _sched

        if algo in _sched.ALGOS:
            nsteps = _sched.build_schedule(algo, comm.size).rounds()
    except Exception:  # commlint: allow(broadexcept)
        pass  # a schedule build error is the dispatch path's to raise
    for step in range(1, nsteps + 1):
        inject.coll_step(comm, opname, step)


@COLL.register
class TunedColl(XlaColl):
    """Decision layer over the full algorithm space. Inherits the
    XLA-native lowering for operations whose decision says 'native'."""

    NAME = "tuned"
    PRIORITY = 80
    DESCRIPTION = "algorithm decision layer (reference: coll/tuned)"

    def _allreduce_plan(self, comm, x, op, deny: tuple = ()):
        """Decision + compiled plan for allreduce; x is leaf-checked
        and comm.size > 1. The whole per-call decision pipeline lives
        here so persistent_program can resolve it once."""
        return self._allreduce_choice(comm, x, op, deny)[1]

    def _allreduce_choice(self, comm, x, op, deny: tuple = ()):
        """(algo, plan) so the dispatch-time breaker retry knows which
        tier it just ran. ``deny`` excludes tiers that already failed
        in this call."""
        is_plain_array = hasattr(x, "dtype") and hasattr(x, "shape")
        nbytes = _nbytes(x)
        algo = decide_allreduce(
            op, nbytes, comm.size,
            dtype=x.dtype if is_plain_array else None,
            scope=str(comm.cid),
        )
        from . import breaker

        algo = breaker.route("allreduce", algo, deny=deny,
                             scope=str(comm.cid))
        _ensure_lazy(algo)
        fn = ALLREDUCE_ALGOS.get(algo)
        if fn is None:
            raise ArgumentError(
                f"unknown allreduce algorithm {algo!r}; known: "
                f"{sorted(ALLREDUCE_ALGOS)}"
            )
        leaves = jax.tree.leaves(x)
        # The explicit single-buffer algorithms (ring, rd, ...) operate
        # on one plain array; any pytree container (even single-leaf)
        # routes through the pytree-aware ordered gather+reduce.
        if algo not in ("native", "gather_reduce") and not is_plain_array:
            fn = ALLREDUCE_ALGOS["gather_reduce"]
            algo = "gather_reduce"
        key = ("allreduce", algo, op.cache_key, _dtype_key(x))
        if is_quant_algo(algo) or algo == "sched_quant":
            from . import quant

            wire = quant._wire_var.value
            blk = quant._block_var.value
            key = key + (wire, blk)
            quant.record_wire_stats(nbytes, x.dtype.itemsize, wire, blk)
        if algo == "ring_segmented":
            seg_elems = max(
                1, _seg_bytes.value // jnp.asarray(leaves[0]).dtype.itemsize
            )
            per_rank = lambda b: fn(b, "ranks", op, segment_elems=seg_elems)
            key = key + (seg_elems,)
        else:
            per_rank = lambda b: fn(b, "ranks", op)
        from ..core.counters import SPC

        SPC.record(f"coll_allreduce_algo_{algo}")
        from ..trace import span as tspan

        tspan.instant("tuned.tier", cat="coll", op="allreduce",
                      algo=algo, nbytes=nbytes,
                      denied=list(deny) if deny else None)
        return algo, compile_plan(comm, key, per_rank,
                                  check_vma=not is_pallas_algo(algo))

    # Host-reducible predefined ops: ufunc.reduce over the rank axis
    # preserves dtype and matches the device tier's combine.
    _HOST_NP_OPS = {
        "sum": np.add, "prod": np.multiply,
        "max": np.maximum, "min": np.minimum,
    }

    def _fast_allreduce(self, comm, x, op):
        """Memoized hot-path dispatch: the routed-and-compiled plan for
        (shape, dtype, op) is cached on the comm and repeat calls skip
        the whole decision pipeline (~hundreds of us of rules, breaker
        walk, key building and SPC f-strings per call in r05 profiles).
        Tiny fully-addressable payloads get the host tier instead — a
        numpy reduction over the rank axis plus one device_put beats an
        XLA program launch below ~4 KiB. Returns the result, or None
        when the slow path must run (cache disabled/invalid, pytree
        input, faultline armed, breaker non-quiet)."""
        if not _fast_cache_var.value or not isinstance(x, jax.Array):
            return None
        if x.ndim < 1 or x.shape[0] != comm.size:
            return None  # slow path raises the proper ArgumentError
        from ..ft import inject

        if inject.armed():
            return None  # every drill must see the real dispatch
        from ..health import ledger as health
        from . import breaker

        from .sched import cache as sched_cache, slo as sched_slo

        stamp = (config.generation(), breaker.generation(),
                 health.LEDGER.generation(),
                 sched_cache.CACHE.generation(),
                 sched_slo.generation())
        cache = comm.__dict__.setdefault("_tuned_fast", {})
        key = (x.shape, x.dtype.name, op.cache_key)
        ent = cache.get(key)
        if ent is None or ent[0] != stamp:
            if not breaker.quiet() or not health.LEDGER.quiet():
                return None  # lazy OPEN->HALF_OPEN / quarantine
                # cooldown are live transitions a memo would miss
            fn = self._build_fast_allreduce(comm, x, op)
            if fn is None:
                return None
            ent = cache[key] = (stamp, fn)
        try:
            return ent[1](x)
        except ArgumentError:
            raise
        except Exception:  # commlint: allow(broadexcept)
            # Tier fault under a memoized plan: forget the entry and
            # let the slow path re-route (and trip the breaker there).
            cache.pop(key, None)
            return None

    def _build_fast_allreduce(self, comm, x, op):
        from ..core.counters import SPC

        limit = _host_small_max.value
        if (0 < limit >= x.size * x.dtype.itemsize and op.predefined
                and op.name in self._HOST_NP_OPS
                and x.is_fully_addressable
                and not _force_allreduce.value and _rules() is None):
            ufunc = self._HOST_NP_OPS[op.name]
            SPC.record("coll_allreduce_algo_host")

            def host_plan(buf):
                a = np.asarray(buf)
                red = ufunc.reduce(a, axis=0)
                return jax.device_put(np.broadcast_to(red, a.shape),
                                      buf.sharding)

            return host_plan
        try:
            _algo, plan = self._allreduce_choice(comm, x, op)
        except ArgumentError:
            raise
        except Exception:  # commlint: allow(broadexcept)
            return None  # slow path surfaces the real error
        return plan

    def allreduce(self, comm, x, op):
        op = op_lookup(op)
        if comm.size > 1:
            out = self._fast_allreduce(comm, x, op)
            if out is not None:
                return out
        x = _leaf_check(comm, x)
        if comm.size == 1:
            return x
        from ..core.errors import RevokedError
        from ..ft import inject, lifeboat
        from ..health import ledger as health, sentinel
        from . import breaker

        scope = str(comm.cid)
        deny: tuple = ()
        while True:
            # Epoch/revocation fence at the top of the retry loop: a
            # comm revoked mid-degradation (a peer died while we were
            # falling tiers) must surface RevokedError, never keep
            # consuming tiers on a poisoned communicator.
            lifeboat.check(comm)
            algo, plan = self._allreduce_choice(comm, x, op, deny)

            def _run(algo=algo, plan=plan):
                # kernel_fault runs inside the bounded closure so an
                # injected wedge@coll stall is cancellable: the
                # sentinel abandons the wedged worker and the dispatch
                # falls to the next tier mid-flight. The per-step
                # probes give rank_kill@coll:after_step=k its
                # mid-collective firing point.
                if inject.armed():
                    inject.kernel_fault("allreduce", algo,
                                        cid=comm.cid)
                    _probe_steps(comm, "allreduce", algo)
                return plan(x)

            try:
                out = sentinel.maybe_bounded(
                    _run, what=f"allreduce[{algo}]")
            except ArgumentError:
                raise  # caller error, not a tier fault
            except RevokedError:
                raise  # recovery-surface error, not a tier fault
            except Exception as exc:  # commlint: allow(broadexcept)
                # Tier fault (kernel compile/launch failure, injected
                # FaultInjected, sentinel StallError on a wedged tier,
                # transport death inside the plan): trip the breaker,
                # report the transport tier to the health ledger, and
                # degrade to the next-cheaper tier instead of failing
                # the collective.
                #
                # StallError only *abandons* the wedged worker — the
                # stalled plan(x) keeps executing and may complete
                # concurrently with the retry below. Safe in a single
                # process because every tier is a pure function of its
                # input buffer and the late result is dropped; across
                # controllers a rank-local stall leaves ranks on
                # divergent tiers with an extra in-flight device
                # collective (hazard documented in DESIGN.md §17).
                #
                # On a revoked comm the fault is not a tier problem —
                # the peer is dead (sentinel StallError, injected
                # FaultInjected): convert to RevokedError so every
                # survivor exits the collective the same way instead
                # of burning tiers against a poisoned communicator.
                if lifeboat.revoked(comm):
                    raise RevokedError(
                        f"{comm.name} revoked during allreduce[{algo}]"
                        f" ({type(exc).__name__}: {exc})"
                    ) from exc
                if not breaker.enabled() \
                        or breaker.next_tier(algo) is None:
                    raise
                breaker.record_failure("allreduce", algo)
                health.report_failure(health.tier_of_algo(algo),
                                      scope=scope,
                                      cause=type(exc).__name__)
                from ..core.counters import SPC

                SPC.record("coll_tier_fallbacks")
                logger.warning(
                    "allreduce tier %r failed (%s: %s); degrading to "
                    "%r", algo, type(exc).__name__, exc,
                    breaker.next_tier(algo),
                )
                deny = deny + (algo,)
                continue
            if breaker.enabled():
                breaker.record_success("allreduce", algo)
                health.report_success(health.tier_of_algo(algo),
                                      scope=scope)
            return out

    def alltoall(self, comm, x):
        x = rank_major_check(comm, x, min_ndim=2)
        if x.shape[1] != comm.size:
            raise ArgumentError(
                f"alltoall needs (size, size, ...) buffer, got {x.shape}"
            )
        if comm.size == 1:
            return x
        per_dest = (x.size // (comm.size * comm.size)) * x.dtype.itemsize
        algo = decide_alltoall(per_dest, comm.size)
        fn = ALLTOALL_ALGOS.get(algo)
        if fn is None:
            raise ArgumentError(f"unknown alltoall algorithm {algo!r}")
        key = ("alltoall", algo, x.shape, str(x.dtype))
        plan = compile_plan(comm, key, lambda b: fn(b, "ranks"))
        return plan(x)

    def allgather(self, comm, x):
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return x[:, None]
        algo = decide_allgather(_nbytes(x), comm.size)
        if is_pallas_algo(algo):
            _pallas_algos()
        fn = ALLGATHER_ALGOS.get(algo)
        if fn is None:
            raise ArgumentError(f"unknown allgather algorithm {algo!r}")
        key = ("allgather", algo, x.shape, str(x.dtype))
        plan = compile_plan(comm, key, lambda b: fn(b, "ranks"),
                            check_vma=not is_pallas_algo(algo))
        return plan(x)

    def bcast(self, comm, x, root):
        x = _leaf_check(comm, x)
        if comm.size == 1:
            return x
        algo = decide_bcast(_nbytes(x), comm.size)
        if is_pallas_algo(algo):
            _pallas_algos()
        fn = BCAST_ALGOS.get(algo)
        if fn is None:
            raise ArgumentError(f"unknown bcast algorithm {algo!r}")
        key = ("bcast", algo, root, _dtype_key(x))
        plan = compile_plan(comm, key, lambda b: fn(b, "ranks", root=root),
                            check_vma=not is_pallas_algo(algo))
        return plan(x)

    def reduce(self, comm, x, op, root):
        op = op_lookup(op)
        if comm.size == 1:
            return super().reduce(comm, x, op, root)
        algo = decide_reduce(op, _nbytes(x), comm.size)
        is_plain_array = hasattr(x, "dtype") and hasattr(x, "shape")
        if algo == "native" or not is_plain_array:
            return super().reduce(comm, x, op, root)
        if is_pallas_algo(algo):
            _pallas_algos()
        fn = REDUCE_ALGOS.get(algo)
        if fn is None:
            raise ArgumentError(
                f"unknown reduce algorithm {algo!r}; known: "
                f"{sorted(REDUCE_ALGOS)}"
            )
        x = rank_major_check(comm, x)
        from ..core.counters import SPC

        SPC.record(f"coll_reduce_algo_{algo}")
        key = ("reduce", algo, op.cache_key, root, x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: fn(b, "ranks", op, root=root),
            check_vma=not is_pallas_algo(algo),
        )
        return plan(x)[root]

    def _prefix(self, comm, x, op, opname: str, decide, algos, native):
        """Shared scan/exscan dispatch over the tuned decision space
        (reference: the per-op decision functions of coll/tuned)."""
        op = op_lookup(op)
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return native(self, comm, x, op)
        algo = decide(op, _nbytes(x), comm.size)
        fn = algos.get(algo)
        if fn is None:
            raise ArgumentError(
                f"unknown {opname} algorithm {algo!r}; known: "
                f"{sorted(algos)}"
            )
        key = (opname, algo, op.cache_key, x.shape, str(x.dtype))
        plan = compile_plan(
            comm, key, lambda b: fn(b, "ranks", op)
        )
        return plan(x)

    def scan(self, comm, x, op):
        return self._prefix(comm, x, op, "scan", decide_scan,
                            SCAN_ALGOS, XlaColl.scan)

    def exscan(self, comm, x, op):
        return self._prefix(comm, x, op, "exscan", decide_exscan,
                            EXSCAN_ALGOS, XlaColl.exscan)

    def reduce_scatter_block(self, comm, x, op):
        op = op_lookup(op)
        x = rank_major_check(comm, x, min_ndim=2)
        if x.shape[1] != comm.size:
            raise ArgumentError(
                f"reduce_scatter_block needs (size, size, ...) buffer, "
                f"got {x.shape}"
            )
        if comm.size == 1:
            return x[:, 0]
        per_rank = (x.size // (comm.size * comm.size)) * x.dtype.itemsize
        algo = decide_reduce_scatter(op, per_rank, comm.size)
        if algo == "native":
            return super().reduce_scatter_block(comm, x, op)
        if is_pallas_algo(algo):
            _pallas_algos()
        fn = REDUCE_SCATTER_ALGOS.get(algo)
        if fn is None:
            raise ArgumentError(
                f"unknown reduce_scatter algorithm {algo!r}; known: "
                f"{sorted(REDUCE_SCATTER_ALGOS)}"
            )
        from ..core.counters import SPC

        SPC.record(f"coll_reduce_scatter_algo_{algo}")
        key = ("reduce_scatter_block", algo, op.cache_key, x.shape,
               str(x.dtype))
        plan = compile_plan(comm, key, lambda b: fn(b, "ranks", op),
                            check_vma=not is_pallas_algo(algo))
        return plan(x)

    def gather(self, comm, x, root):
        x = rank_major_check(comm, x)
        if comm.size == 1:
            return x[:, None][root]
        algo = decide_gather(_nbytes(x), comm.size)
        if algo == "native":
            return super().gather(comm, x, root)
        if is_pallas_algo(algo):
            _pallas_algos()
        fn = GATHER_ALGOS.get(algo)
        if fn is None:
            raise ArgumentError(
                f"unknown gather algorithm {algo!r}; known: "
                f"{sorted(GATHER_ALGOS)}"
            )
        from ..core.counters import SPC

        SPC.record(f"coll_gather_algo_{algo}")
        key = ("gather", algo, root, x.shape, str(x.dtype))
        plan = compile_plan(comm, key, lambda b: fn(b, "ranks", root=root),
                            check_vma=not is_pallas_algo(algo))
        return plan(x)[root]

    def scatter(self, comm, x, root):
        arr = jnp.asarray(x)
        if arr.shape[0] != comm.size:
            raise ArgumentError(
                f"scatter needs (size, ...) buffer, got {arr.shape}"
            )
        if comm.size == 1:
            return comm.put_rank_major(arr)
        algo = decide_scatter(
            (arr.size // comm.size) * arr.dtype.itemsize, comm.size
        )
        if algo == "native":
            return super().scatter(comm, x, root)
        if is_pallas_algo(algo):
            _pallas_algos()
        fn = SCATTER_ALGOS.get(algo)
        if fn is None:
            raise ArgumentError(
                f"unknown scatter algorithm {algo!r}; known: "
                f"{sorted(SCATTER_ALGOS)}"
            )
        from ..core.counters import SPC

        SPC.record(f"coll_scatter_algo_{algo}")
        # Algorithm-form scatter runs inside the mesh: stage root's
        # buffer as replicated rank-major rows so the traced tree sees
        # it on-device (only root's copy is semantically significant).
        stacked = comm.put_rank_major(
            jnp.broadcast_to(arr[None], (comm.size,) + arr.shape)
        )
        key = ("scatter", algo, root, stacked.shape, str(stacked.dtype))
        plan = compile_plan(comm, key, lambda b: fn(b, "ranks", root=root),
                            check_vma=not is_pallas_algo(algo))
        return plan(stacked)

"""coll/self — trivial collectives for size-1 communicators.

Reference: ompi/mca/coll/self (1,167 LoC of identity operations). In the
driver model COMM_SELF-style comms skip plan compilation entirely.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops import lookup as op_lookup
from .framework import COLL, CollComponent


@COLL.register
class SelfColl(CollComponent):
    NAME = "self"
    PRIORITY = 100
    DESCRIPTION = "size-1 communicator fast paths (reference: coll/self)"

    def available(self, comm=None, **_):
        return comm is not None and comm.size == 1

    def allreduce(self, comm, x, op):
        return x

    def bcast(self, comm, x, root):
        return x

    def reduce(self, comm, x, op, root):
        return jax.tree.map(lambda l: l[0], x)

    def allgather(self, comm, x):
        return jnp.asarray(x)[:, None]

    def reduce_scatter_block(self, comm, x, op):
        return jnp.asarray(x)[:, 0]

    def alltoall(self, comm, x):
        return x

    def gather(self, comm, x, root):
        return jnp.asarray(x)

    def scatter(self, comm, x, root):
        return comm.put_rank_major(x)

    def scan(self, comm, x, op):
        return x

    def exscan(self, comm, x, op):
        op = op_lookup(op)
        arr = jnp.asarray(x)
        if op.has_identity:
            return op.identity_like(arr)
        return jnp.zeros_like(arr)

    def barrier(self, comm):
        return None

"""SPMD collective algorithm library — usable inside shard_map/pjit.

TPU-native re-design of ompi/mca/coll/base's algorithm library
(reference: coll_base_allreduce.c — nonoverlapping:53,
recursivedoubling:130, ring:341, ring_segmented:618, redscat_allgather
(Rabenseifner):970; coll_base_{bcast,allgather,alltoall,...}.c; tree
builders in coll_base_topo.c).

Where the reference expresses each algorithm as a loop of PML send/recv
with CPU reduction per segment, here each algorithm is a *traced* program
over a named mesh axis: neighbor exchange is `lax.ppermute` (compiled to
ICI DMA), the reduction is the Op's combine executed on the VPU/MXU
against HBM-resident values, and XLA overlaps the DMA with the combine —
the overlap the reference gets from segmented pipelining falls out of the
compiler schedule.

Every function takes ``axis_name`` (the mesh axis the collective runs
over) and is valid inside `jax.shard_map`. The number of ranks is static
at trace time (`lax.axis_size`), so all schedules (ring permutations,
binomial trees, butterfly exchanges) are unrolled into the XLA graph —
the analog of libnbc's precompiled round schedules (nbc_internal.h:149).

The XLA-native entries (`allreduce_native` etc.) lower to XLA's own
all-reduce, which the runtime maps to the ICI fabric's optimal schedule;
the explicit variants exist for (a) the tuned decision space, (b) ops XLA
cannot reduce natively, (c) segment-size control for overlap tuning.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..core.errors import ArgumentError
from ..ops import Op
from ..ops import op as _op_mod


def _size(axis_name: str) -> int:
    return lax.axis_size(axis_name)


def _rank(axis_name: str):
    return lax.axis_index(axis_name)


def _ring_perm(n: int, shift: int = 1) -> list[tuple[int, int]]:
    return [(i, (i + shift) % n) for i in range(n)]


def _flatten_pad(x: jax.Array, n: int) -> tuple[jax.Array, int]:
    """Ravel and zero-pad so the element count divides n."""
    flat = x.reshape(-1)
    total = flat.shape[0]
    padded = ((total + n - 1) // n) * n
    if padded != total:
        flat = jnp.pad(flat, (0, padded - total))
    return flat, total


# ---------------------------------------------------------------------------
# allreduce family
# ---------------------------------------------------------------------------

def allreduce_native(x: Any, axis_name: str, op: Op) -> Any:
    """XLA-native allreduce: lax.psum/pmax/pmin where the op maps directly
    (SUM/MAX/MIN); otherwise allgather + on-device tree reduction.

    This is the default data-parallel gradient path (SURVEY §2.6 DP row).
    """
    if op.xla_reduce is not None:
        fn = getattr(lax, op.xla_reduce)
        return fn(x, axis_name)
    return _allreduce_gather_reduce(x, axis_name, op)


def _allreduce_gather_reduce(x: Any, axis_name: str, op: Op) -> Any:
    """Allgather then local tree-reduce — handles arbitrary (including
    non-commutative and joint MAXLOC/MINLOC) ops in rank order."""
    n = _size(axis_name)
    gathered = jax.tree.map(
        lambda leaf: lax.all_gather(leaf, axis_name, axis=0), x
    )
    return _tree_reduce_ranks(gathered, n, op)


def _tree_reduce_ranks(gathered: Any, n: int, op: Op) -> Any:
    """Reduce a leading rank axis with a balanced tree that preserves rank
    order (valid for non-commutative ops)."""
    parts = [jax.tree.map(lambda g, i=i: g[i], gathered) for i in range(n)]
    while len(parts) > 1:
        nxt = []
        for i in range(0, len(parts) - 1, 2):
            nxt.append(op.combine(parts[i], parts[i + 1]))
        if len(parts) % 2:
            nxt.append(parts[-1])
        parts = nxt
    return parts[0]


def allreduce_recursive_doubling(
    x: jax.Array, axis_name: str, op: Op
) -> jax.Array:
    """Butterfly exchange, log2(n) rounds of full-buffer exchanges.

    Reference algorithm: coll_base_allreduce.c:130
    (ompi_coll_base_allreduce_intra_recursivedoubling); the tuned layer
    picks it for small messages (<10 KB cutoff,
    coll_tuned_decision_fixed.c:53,66).

    Non-power-of-two rank counts use the standard fold/unfold pre/post
    phase. Requires a commutative op: the butterfly combines in
    partner-order, so non-commutative (and joint) ops are routed to the
    ordered gather+reduce path, as the reference's tuned layer falls back.
    """
    n = _size(axis_name)
    if n == 1:
        return x
    if not op.commutative or _op_mod._is_joint(op):
        return _allreduce_gather_reduce(x, axis_name, op)
    pof2 = 1 << (n.bit_length() - 1)
    rem = n - pof2
    rank = _rank(axis_name)

    if rem > 0:
        # Fold: even ranks among the first 2*rem send to rank+1, which
        # combines. Ranks >= 2*rem are unaffected.
        perm = [(2 * i, 2 * i + 1) for i in range(rem)]
        recv = lax.ppermute(x, axis_name, perm)
        is_odd_low = (rank < 2 * rem) & (rank % 2 == 1)
        x = jnp.where(is_odd_low, op.combine(recv, x), x)
        # Active ranks: odd ranks < 2*rem (relabeled i//2) and ranks
        # >= 2*rem (relabeled rank - rem).
        active = ((rank < 2 * rem) & (rank % 2 == 1)) | (rank >= 2 * rem)

        def phys(newrank: int) -> int:
            return 2 * newrank + 1 if newrank < rem else newrank + rem

        for k in range(int(math.log2(pof2))):
            dist = 1 << k
            perm = []
            for nr in range(pof2):
                partner = nr ^ dist
                perm.append((phys(nr), phys(partner)))
            recv = lax.ppermute(x, axis_name, perm)
            x = jnp.where(active, op.combine(x, recv), x)

        # Unfold: odd low ranks send the result back to rank-1.
        perm = [(2 * i + 1, 2 * i) for i in range(rem)]
        recv = lax.ppermute(x, axis_name, perm)
        is_even_low = (rank < 2 * rem) & (rank % 2 == 0)
        x = jnp.where(is_even_low, recv, x)
        return x

    for k in range(int(math.log2(n))):
        dist = 1 << k
        perm = [(i, i ^ dist) for i in range(n)]
        recv = lax.ppermute(x, axis_name, perm)
        x = op.combine(x, recv)
    return x


def allreduce_ring(x: jax.Array, axis_name: str, op: Op) -> jax.Array:
    """Bandwidth-optimal ring: n-1 reduce-scatter steps + n-1 allgather
    steps, each moving size/n bytes over single-hop ICI links.

    Reference algorithm: coll_base_allreduce.c:341
    (ompi_coll_base_allreduce_intra_ring); tuned picks it for commutative
    ops ≤1 MB/rank (coll_tuned_decision_fixed.c:69-72).
    """
    n = _size(axis_name)
    if n == 1:
        return x
    rank = _rank(axis_name)
    flat, total = _flatten_pad(x, n)
    blocks = flat.reshape(n, -1)
    right = _ring_perm(n, 1)

    # Reduce-scatter phase: after n-1 hops rank i holds the full reduction
    # of block (i+1) mod n.
    carry = jnp.take(blocks, rank, axis=0)
    for k in range(n - 1):
        recvd = lax.ppermute(carry, axis_name, right)
        idx = (rank - k - 1) % n
        carry = op.combine(recvd, jnp.take(blocks, idx, axis=0))

    # Allgather phase: circulate the completed blocks.
    out = jnp.zeros_like(blocks)
    out = out.at[(rank + 1) % n].set(carry)
    cur = carry
    for k in range(n - 1):
        cur = lax.ppermute(cur, axis_name, right)
        out = out.at[(rank - k) % n].set(cur)

    return out.reshape(-1)[:total].reshape(x.shape)


def allreduce_ring_segmented(
    x: jax.Array, axis_name: str, op: Op, segment_elems: int = 0
) -> jax.Array:
    """Segmented ring: the buffer is cut into segments that move through
    the ring independently, bounding per-step working-set size.

    Reference: coll_base_allreduce.c:618 (..._intra_ring_segmented), with
    the tuned 1 MB segment default (coll_tuned_decision_fixed.c:73). Under
    XLA the segments' ppermutes are independent program slices the
    scheduler can overlap with the combines.
    """
    n = _size(axis_name)
    if n == 1:
        return x
    flat = x.reshape(-1)
    total = flat.shape[0]
    if segment_elems <= 0 or total <= segment_elems:
        return allreduce_ring(x, axis_name, op)
    pieces = []
    for start in range(0, total, segment_elems):
        seg = flat[start : start + segment_elems]
        pieces.append(allreduce_ring(seg, axis_name, op))
    return jnp.concatenate(pieces).reshape(x.shape)


def allreduce_reduce_scatter_allgather(
    x: jax.Array, axis_name: str, op: Op
) -> jax.Array:
    """Rabenseifner: recursive-halving reduce-scatter + recursive-doubling
    allgather — latency log2(n), bandwidth-optimal for large buffers.

    Reference: coll_base_allreduce.c:970
    (ompi_coll_base_allreduce_intra_redscat_allgather). Power-of-two rank
    counts; callers (tuned) fall back to ring otherwise, as the reference
    does for the non-pof2 remainder handling.
    """
    n = _size(axis_name)
    if n == 1:
        return x
    if n & (n - 1):
        return allreduce_ring(x, axis_name, op)
    rank = _rank(axis_name)
    flat, total = _flatten_pad(x, n)
    blocks = flat.reshape(n, -1)

    # Recursive halving reduce-scatter: each round the block range is
    # halved; a rank keeps the half containing its own block index and
    # trades partials for the other half with partner = rank ^ half.
    steps = int(math.log2(n))
    cur = blocks  # my partials for the current block range
    cnt = n
    for k in range(steps):
        half = cnt // 2
        mask_upper = (rank & half) != 0  # am I in the upper half-range?
        perm = [(i, i ^ half) for i in range(n)]
        lower, upper = cur[:half], cur[half:]
        # Give away the half I am not keeping; receive exactly the half
        # I keep (the partner gives away its mirror half).
        send = jnp.where(mask_upper, lower, upper)
        recv = lax.ppermute(send, axis_name, perm)
        keep = jnp.where(mask_upper, upper, lower)
        cur = op.combine(keep, recv)
        cnt = half

    # cur is (1, m): the fully reduced block whose index == rank.
    have = cur

    # Recursive doubling allgather: ranges merge back; an upper partner's
    # range is prepended, a lower partner's appended. After all rounds the
    # rows sit in block order (the owned range start telescopes to 0).
    for k in range(steps):
        dist = 1 << k
        perm = [(i, i ^ dist) for i in range(n)]
        recv = lax.ppermute(have, axis_name, perm)
        mask_upper = (rank & dist) != 0
        have = jnp.where(
            mask_upper,
            jnp.concatenate([recv, have], axis=0),
            jnp.concatenate([have, recv], axis=0),
        )

    return have.reshape(-1)[:total].reshape(x.shape)


def allreduce_nonoverlapping(
    x: jax.Array, axis_name: str, op: Op, root: int = 0
) -> jax.Array:
    """Reduce-to-root then broadcast — the non-commutative-safe fallback.

    Reference: coll_base_allreduce.c:53 (..._intra_nonoverlapping), chosen
    by tuned for non-commutative ops (coll_tuned_decision_fixed.c:85-86).
    """
    red = reduce_binomial(x, axis_name, op, root=root)
    return bcast_native(red, axis_name, root=root)


# ---------------------------------------------------------------------------
# bcast / reduce
# ---------------------------------------------------------------------------

def bcast_native(x: jax.Array, axis_name: str, root: int = 0) -> jax.Array:
    """Broadcast root's value: mask + psum (a single fabric all-reduce,
    which XLA lowers to the ICI-optimal broadcast schedule)."""
    rank = _rank(axis_name)
    contrib = jax.tree.map(
        lambda leaf: jnp.where(rank == root, leaf, jnp.zeros_like(leaf)), x
    )
    return jax.tree.map(lambda leaf: lax.psum(leaf, axis_name), contrib)


def bcast_binomial(x: jax.Array, axis_name: str, root: int = 0) -> jax.Array:
    """Binomial-tree broadcast: log2(n) rounds, round k has the first 2^k
    (root-relative) ranks send to rank+2^k.

    Reference: coll_base_bcast.c (ompi_coll_base_bcast_intra_binomial) via
    the tree builders in coll_base_topo.c.
    """
    n = _size(axis_name)
    if n == 1:
        return x
    rank = _rank(axis_name)
    vrank = (rank - root) % n  # root-relative rank
    rounds = (n - 1).bit_length()
    for k in range(rounds):
        dist = 1 << k
        perm = []
        for v in range(min(dist, n - dist)):
            src = (v + root) % n
            dst = (v + dist + root) % n
            perm.append((src, dst))
        recv = lax.ppermute(x, axis_name, perm)
        takes = (vrank >= dist) & (vrank < 2 * dist)
        x = jax.tree.map(
            lambda leaf, r: jnp.where(takes, r, leaf), x, recv
        )
    return x


def reduce_binomial(
    x: jax.Array, axis_name: str, op: Op, root: int = 0
) -> jax.Array:
    """Binomial-tree reduction to root (others return op-identity or their
    partial; only root's value is defined, per MPI semantics).

    Reference: coll_base_reduce.c (ompi_coll_base_reduce_intra_binomial).
    Requires a commutative op for the tree pairing; non-commutative ops go
    through the ordered gather+reduce path.
    """
    n = _size(axis_name)
    if n == 1:
        return x
    if not op.commutative or _op_mod._is_joint(op):
        return _allreduce_gather_reduce(x, axis_name, op)
    rank = _rank(axis_name)
    vrank = (rank - root) % n
    rounds = (n - 1).bit_length()
    for k in range(rounds):
        mask = 1 << k
        # One sender per pair: vranks that are odd multiples of `mask`
        # send their accumulated subtree to vrank-mask and go idle.
        perm = []
        for vr in range(0, n, 2 * mask):
            if vr + mask < n:
                perm.append(((vr + mask + root) % n, (vr + root) % n))
        recv = lax.ppermute(x, axis_name, perm)
        receives = (vrank % (2 * mask) == 0) & (vrank + mask < n)
        x = jax.tree.map(
            lambda leaf, r: jnp.where(receives, op.combine(leaf, r), leaf),
            x,
            recv,
        )
    return x


def reduce_native(
    x: jax.Array, axis_name: str, op: Op, root: int = 0
) -> jax.Array:
    """Reduce via the fabric allreduce (every rank computes; root reads)."""
    del root
    return allreduce_native(x, axis_name, op)


def _segment_leaf(leaf: jax.Array, segments: int):
    """Static split of a flattened leaf into `segments` chunks (+ the
    restore function). Segment count is a trace-time constant, so each
    chunk's collective chain is an independent program XLA can overlap
    — the pipelining the reference gets from segsize knobs."""
    flat = leaf.reshape(-1)
    import numpy as _np

    bounds = _np.linspace(0, flat.shape[0], segments + 1).astype(int)
    chunks = [flat[int(a):int(b)] for a, b in zip(bounds, bounds[1:])
              if b > a]

    def restore(parts):
        return jnp.concatenate(parts).reshape(leaf.shape)

    return chunks, restore


def _auto_segments(x, target_bytes: int = 64 * 1024, cap: int = 8) -> int:
    total = sum(
        leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(x)
    )
    return int(max(1, min(cap, total // max(target_bytes, 1))))


def bcast_chain(x, axis_name: str, root: int = 0) -> jax.Array:
    """Chain broadcast: the payload hops rank-to-rank down the (root-
    relative) chain, n-1 single-hop rounds.

    Reference: coll_base_bcast.c (ompi_coll_base_bcast_intra_chain with
    fanout 1); the building block of the pipelined variant."""
    n = _size(axis_name)
    if n == 1:
        return x
    rank = _rank(axis_name)
    vrank = (rank - root) % n
    perm = [((root + i) % n, (root + i + 1) % n) for i in range(n - 1)]

    def chain_one(v):
        for h in range(n - 1):
            recv = lax.ppermute(v, axis_name, perm)
            v = jnp.where(vrank == h + 1, recv, v)
        return v

    return jax.tree.map(chain_one, x)


def bcast_pipelined(x, axis_name: str, root: int = 0,
                    segments: int | None = None) -> jax.Array:
    """Pipelined (segmented-chain) broadcast: the payload splits into
    static segments, each circulating the chain independently — XLA
    overlaps the per-segment hops, so the wire sees a full pipeline
    after the (n-1)-hop fill.

    Reference: coll_base_bcast.c (..._intra_pipeline) with the tuned
    segsize rules (coll_tuned_decision_fixed.c:250-310)."""
    n = _size(axis_name)
    if n == 1:
        return x
    segs = segments if segments else _auto_segments(x)
    if segs <= 1:
        return bcast_chain(x, axis_name, root)
    rank = _rank(axis_name)
    vrank = (rank - root) % n
    perm = [((root + i) % n, (root + i + 1) % n) for i in range(n - 1)]

    def pipe_one(leaf):
        chunks, restore = _segment_leaf(leaf, segs)
        out = []
        for c in chunks:
            v = c
            for h in range(n - 1):
                recv = lax.ppermute(v, axis_name, perm)
                v = jnp.where(vrank == h + 1, recv, v)
            out.append(v)
        return restore(out)

    return jax.tree.map(pipe_one, x)


def bcast_binary(x, axis_name: str, root: int = 0) -> jax.Array:
    """Binary-tree broadcast: node v forwards to children 2v+1 / 2v+2
    (root-relative), depth ceil(log2) rounds with fanout 2.

    Reference: coll_base_bcast.c (..._intra_bintree) via the
    coll_base_topo.c tree builders."""
    n = _size(axis_name)
    if n == 1:
        return x
    rank = _rank(axis_name)
    vrank = (rank - root) % n

    def phys(v: int) -> int:
        return (v + root) % n

    def tree_one(v):
        level_start = 0  # first vrank of the sending level
        width = 1
        while level_start + width - 1 < n - 1:
            # one ppermute per child side — a ppermute source must be
            # unique, and a binary node feeds two children per round
            for side in (1, 2):
                perm = [
                    (phys(s), phys(2 * s + side))
                    for s in range(level_start,
                                   min(level_start + width, n))
                    if 2 * s + side < n
                ]
                if not perm:
                    continue
                recv = lax.ppermute(v, axis_name, perm)
                takes_lo = 2 * level_start + 1
                takes_hi = 2 * (level_start + width - 1) + 2
                child_parity = side % 2  # left children odd, right even
                takes = ((vrank >= takes_lo) & (vrank <= takes_hi)
                         & (vrank % 2 == child_parity))
                v = jnp.where(takes, recv, v)
            level_start = 2 * level_start + 1
            width = 2 * width
        return v

    return jax.tree.map(tree_one, x)


def reduce_pipelined(
    x, axis_name: str, op: Op, root: int = 0,
    segments: int | None = None,
) -> jax.Array:
    """Pipelined chain reduction toward root: partials flow down the
    reverse chain combining at every hop, segmented so consecutive
    segments keep the wire busy. Chain order is x_0 + (x_1 + (...)) —
    MPI rank order when root is 0, so non-commutative ops are safe
    there; elsewhere they fall back to the ordered gather path.

    Reference: coll_base_reduce.c (..._intra_pipeline /
    ..._intra_chain), segsize rules coll_tuned_decision_fixed.c:250-310.
    Only root's result is defined (MPI reduce semantics)."""
    n = _size(axis_name)
    if n == 1:
        return x
    if (not op.commutative or _op_mod._is_joint(op)) and root != 0:
        return _allreduce_gather_reduce(x, axis_name, op)
    if _op_mod._is_joint(op):
        return _allreduce_gather_reduce(x, axis_name, op)
    rank = _rank(axis_name)
    vrank = (rank - root) % n
    segs = segments if segments else _auto_segments(x)
    rev = [((root + i + 1) % n, (root + i) % n) for i in range(n - 1)]

    def chain_reduce(v):
        for h in range(n - 1):
            recv = lax.ppermute(v, axis_name, rev)
            combines = vrank == (n - 2 - h)
            v = jnp.where(combines, op.combine(v, recv), v)
        return v

    def pipe_one(leaf):
        if segs <= 1:
            return chain_reduce(leaf)
        chunks, restore = _segment_leaf(leaf, segs)
        return restore([chain_reduce(c) for c in chunks])

    return jax.tree.map(pipe_one, x)


def scan_recursive_doubling(x, axis_name: str, op: Op) -> jax.Array:
    """Inclusive prefix via recursive doubling: log2(n) rounds, round k
    combines the prefix from rank-2^k (associative order preserved, so
    non-commutative ops are safe).

    Reference: the scan recursion of coll_base_scan.c restructured to
    the log-depth doubling exchange (the pattern of
    allreduce_intra_recursivedoubling, coll_base_allreduce.c:130)."""
    n = _size(axis_name)
    if n == 1:
        return x
    rank = _rank(axis_name)

    def one(leaf):
        acc = leaf
        k = 1
        while k < n:
            perm = [(i, i + k) for i in range(n - k)]
            recv = lax.ppermute(acc, axis_name, perm)
            acc = jnp.where(rank >= k, op.combine(recv, acc), acc)
            k <<= 1
        return acc

    return jax.tree.map(one, x)


def scan_linear_chain(x, axis_name: str, op: Op) -> jax.Array:
    """Inclusive prefix via the linear chain: the running prefix flows
    rank-to-rank, each rank folding in its contribution — the
    reference's own recursion shape (coll_base_scan.c), n-1 hops."""
    n = _size(axis_name)
    if n == 1:
        return x
    rank = _rank(axis_name)
    perm = [(i, i + 1) for i in range(n - 1)]

    def one(leaf):
        acc = leaf
        for h in range(n - 1):
            recv = lax.ppermute(acc, axis_name, perm)
            acc = jnp.where(rank == h + 1, op.combine(recv, leaf), acc)
        return acc

    return jax.tree.map(one, x)


def _exscan_from_inclusive(inc, x, axis_name: str, op: Op):
    """Shift an inclusive scan down one rank; rank 0 gets the op
    identity (exscan semantics)."""
    n = _size(axis_name)
    rank = _rank(axis_name)
    perm = [(i, i + 1) for i in range(n - 1)]

    def one(leaf_inc, leaf_x):
        prev = lax.ppermute(leaf_inc, axis_name, perm)
        if op.has_identity:
            ident = op.identity_like(leaf_x)
        else:
            ident = jnp.zeros_like(leaf_x)
        return jnp.where(rank == 0, ident, prev)

    return jax.tree.map(one, inc, x)


def exscan_recursive_doubling(x, axis_name: str, op: Op) -> jax.Array:
    """Exclusive prefix: recursive-doubling inclusive scan + one-hop
    shift (reference: coll_base_exscan.c semantics)."""
    return _exscan_from_inclusive(
        scan_recursive_doubling(x, axis_name, op), x, axis_name, op
    )


def exscan_linear_chain(x, axis_name: str, op: Op) -> jax.Array:
    """Exclusive prefix via the linear chain + one-hop shift."""
    return _exscan_from_inclusive(
        scan_linear_chain(x, axis_name, op), x, axis_name, op
    )


# ---------------------------------------------------------------------------
# allgather / reduce_scatter
# ---------------------------------------------------------------------------

def allgather_native(x: jax.Array, axis_name: str) -> jax.Array:
    """XLA-native all-gather; result has a new leading rank axis."""
    return lax.all_gather(x, axis_name, axis=0)


def allgather_ring(x: jax.Array, axis_name: str) -> jax.Array:
    """Ring allgather: n-1 single-hop forwards.

    Reference: coll_base_allgather.c (..._intra_ring)."""
    n = _size(axis_name)
    rank = _rank(axis_name)
    out = jnp.zeros((n,) + x.shape, x.dtype)
    out = out.at[rank].set(x)
    cur = x
    right = _ring_perm(n, 1)
    for k in range(n - 1):
        cur = lax.ppermute(cur, axis_name, right)
        out = out.at[(rank - k - 1) % n].set(cur)
    return out


def allgather_bruck(x: jax.Array, axis_name: str) -> jax.Array:
    """Bruck allgather: ceil(log2 n) rounds of doubling-size exchanges.

    Reference: coll_base_allgather.c (..._intra_bruck)."""
    n = _size(axis_name)
    rank = _rank(axis_name)
    have = x[None]  # rows: blocks (rank, rank+1, ...) in circular order
    k = 1
    while k < n:
        perm = [(i, (i - k) % n) for i in range(n)]  # send to rank-k
        recv = lax.ppermute(have[: min(k, n - k)], axis_name, perm)
        have = jnp.concatenate([have, recv], axis=0)[:n]
        k *= 2
    # Row j of `have` is block (rank + j) mod n; rotate into rank order.
    idx = (jnp.arange(n) - rank) % n
    return jnp.take(have, idx, axis=0)


def reduce_scatter_native(x: jax.Array, axis_name: str, op: Op) -> jax.Array:
    """XLA-native reduce-scatter over leading axis (psum_scatter) for SUM;
    generic ops reduce then slice."""
    n = _size(axis_name)
    if x.shape[0] != n:
        raise ArgumentError(
            f"reduce_scatter input leading dim {x.shape[0]} != ranks {n}"
        )
    if op.xla_reduce == "psum":
        # tiled=False removes the scattered leading axis, matching the
        # (block_shape,) result of the ring variant and the generic path.
        return lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=False)
    red = allreduce_native(x, axis_name, op)
    return jnp.take(red, _rank(axis_name), axis=0)


def reduce_scatter_ring(x: jax.Array, axis_name: str, op: Op) -> jax.Array:
    """Ring reduce-scatter (the first phase of the ring allreduce).

    Reference: coll_base_reduce_scatter.c (..._intra_ring)."""
    n = _size(axis_name)
    rank = _rank(axis_name)
    if x.shape[0] != n:
        raise ArgumentError(
            f"reduce_scatter input leading dim {x.shape[0]} != ranks {n}"
        )
    if n == 1:
        return x[0]
    right = _ring_perm(n, 1)
    # The partial for block b starts at rank b+1 and travels n-1 hops
    # rightward, accumulating each rank's contribution, to finish at rank
    # b. So rank i injects block (i-1) first and absorbs block i last.
    carry = jnp.take(x, (rank - 1) % n, axis=0)
    for k in range(n - 1):
        recvd = lax.ppermute(carry, axis_name, right)
        idx = (rank - k - 2) % n
        carry = op.combine(recvd, jnp.take(x, idx, axis=0))
    return carry


# ---------------------------------------------------------------------------
# alltoall / gather / scatter / scan / barrier
# ---------------------------------------------------------------------------

def alltoall_native(x: jax.Array, axis_name: str) -> jax.Array:
    """XLA-native all-to-all over the leading (per-destination) axis."""
    n = _size(axis_name)
    if x.shape[0] != n:
        raise ArgumentError(
            f"alltoall input leading dim {x.shape[0]} != ranks {n}"
        )
    return lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0, tiled=False)


def alltoall_pairwise(x: jax.Array, axis_name: str) -> jax.Array:
    """Pairwise-exchange alltoall: n-1 rounds, round k exchanges with
    rank±k — the large-message algorithm.

    Reference: coll_base_alltoall.c (..._intra_pairwise), selected by
    tuned for large messages (coll_tuned_decision_fixed.c:130-141)."""
    n = _size(axis_name)
    rank = _rank(axis_name)
    if x.shape[0] != n:
        raise ArgumentError(
            f"alltoall input leading dim {x.shape[0]} != ranks {n}"
        )
    out = jnp.zeros_like(x)
    out = out.at[rank].set(jnp.take(x, rank, axis=0))
    for k in range(1, n):
        send_to = [(i, (i + k) % n) for i in range(n)]
        # Block destined for rank+k travels directly there.
        payload = jnp.take(x, (rank + k) % n, axis=0)
        recvd = lax.ppermute(payload, axis_name, send_to)
        out = out.at[(rank - k) % n].set(recvd)
    return out


def alltoall_bruck(x: jax.Array, axis_name: str) -> jax.Array:
    """Bruck alltoall: log2(n) rounds of bit-indexed block exchanges —
    the small-message, latency-optimal algorithm.

    Reference: coll_base_alltoall.c (..._intra_bruck)."""
    n = _size(axis_name)
    rank = _rank(axis_name)
    if x.shape[0] != n:
        raise ArgumentError(
            f"alltoall input leading dim {x.shape[0]} != ranks {n}"
        )
    # Phase 1: local rotation so block j holds data for rank (rank+j).
    idx = (jnp.arange(n) + rank) % n
    cur = jnp.take(x, idx, axis=0)
    # Phase 2: for each bit k, send blocks whose index has bit k set to
    # rank+2^k.
    k = 1
    while k < n:
        perm = [(i, (i + k) % n) for i in range(n)]
        mask = (jnp.arange(n) & k) != 0
        recvd = lax.ppermute(cur, axis_name, perm)
        cur = jnp.where(mask[(...,) + (None,) * (cur.ndim - 1)], recvd, cur)
        k *= 2
    # Phase 3: inverse rotation + reversal to restore source order.
    idx = (rank - jnp.arange(n)) % n
    return jnp.take(cur, idx, axis=0)


def gather_native(x: jax.Array, axis_name: str, root: int = 0) -> jax.Array:
    """Gather to root (SPMD form: every rank materializes the gather; the
    driver layer slices root's copy — on TPU the allgather IS the
    binomial gather's fabric cost)."""
    del root
    return lax.all_gather(x, axis_name, axis=0)


def scatter_native(x: jax.Array, axis_name: str, root: int = 0) -> jax.Array:
    """Scatter root's (n, ...) buffer: broadcast-free implementation —
    each rank takes its row after a root-masked psum."""
    rank = _rank(axis_name)
    rooted = bcast_native(x, axis_name, root=root)
    return jnp.take(rooted, rank, axis=0)


def _pow2_rows(n: int) -> int:
    return 1 << (n - 1).bit_length() if n > 1 else 1


def gather_binomial(x: jax.Array, axis_name: str, root: int = 0
                    ) -> jax.Array:
    """Binomial-tree gather to root: round k has the (root-relative)
    ranks whose lowest set bit is 2^k forward their accumulated 2^k-row
    subtree block to vrank-2^k — total traffic matches MPI's binomial
    gather (each round moves statically-sized 2^k-row slabs, placed with
    dynamic offsets), unlike an allgather which moves n rows everywhere.

    Reference: coll_base_gather.c (ompi_coll_base_gather_intra_binomial).
    Result rows are defined only at root (MPI semantics); output is in
    rank order."""
    n = _size(axis_name)
    if n == 1:
        return x[None]
    rank = _rank(axis_name)
    vrank = (rank - root) % n
    np2 = _pow2_rows(n)
    # Accumulator in vrank space, padded to a power of two so every
    # subtree slab [vr, vr + 2^k) is in bounds.
    out = jnp.zeros((np2,) + x.shape, x.dtype)
    zeros = (0,) * x.ndim
    out = lax.dynamic_update_slice(out, x[None], (vrank,) + zeros)
    for k in range((n - 1).bit_length()):
        blk = 1 << k
        # Senders this round: vranks that are odd multiples of 2^k.
        perm = []
        for vr in range(blk, n, 2 * blk):
            perm.append(((vr + root) % n, (vr - blk + root) % n))
        payload = lax.dynamic_slice(
            out, (vrank,) + zeros, (blk,) + x.shape
        )
        recvd = lax.ppermute(payload, axis_name, perm)
        receives = (vrank % (2 * blk) == 0) & (vrank + blk < n)
        merged = lax.dynamic_update_slice(
            out, recvd, (vrank + blk,) + zeros
        )
        out = jnp.where(receives, merged, out)
    # vrank-space row j holds rank ((j + root) % n)'s block.
    idx = (jnp.arange(n) - root) % n
    return jnp.take(out, idx, axis=0)


def scatter_binomial(x: jax.Array, axis_name: str, root: int = 0
                     ) -> jax.Array:
    """Binomial-tree scatter from root — the gather tree run in reverse:
    rounds go from the widest slab down; in round k every current
    holder of a 2^(k+1)-row slab forwards its upper 2^k rows to
    vrank+2^k. Per-round traffic is the statically-sized slab.

    Reference: coll_base_scatter.c (ompi_coll_base_scatter_intra_binomial).
    Input (n, ...) significant at root; every rank returns its row."""
    n = _size(axis_name)
    if n == 1:
        return x[0]
    rank = _rank(axis_name)
    vrank = (rank - root) % n
    np2 = _pow2_rows(n)
    zeros = (0,) * (x.ndim - 1)
    # Rotate root's buffer into vrank space and pad to a power of two.
    idx = (jnp.arange(np2) + root) % n  # row j <- rank (j+root)%n's data
    buf = jnp.take(x, idx, axis=0)
    for k in reversed(range((n - 1).bit_length())):
        blk = 1 << k
        perm = []
        for vr in range(0, n, 2 * blk):
            if vr + blk < n:
                perm.append(((vr + root) % n, (vr + blk + root) % n))
        # A holder at this level sits at a multiple of 2^(k+1); its
        # outgoing slab is rows [vrank + blk, vrank + 2*blk).
        send_lo = jnp.minimum(vrank + blk, np2 - blk)
        payload = lax.dynamic_slice(
            buf, (send_lo,) + zeros, (blk,) + x.shape[1:]
        )
        recvd = lax.ppermute(payload, axis_name, perm)
        receives = vrank % (2 * blk) == blk
        merged = lax.dynamic_update_slice(buf, recvd, (vrank,) + zeros)
        buf = jnp.where(receives, merged, buf)
    return lax.dynamic_slice(
        buf, (vrank,) + zeros, (1,) + x.shape[1:]
    )[0]


def reduce_scatter_recursive_halving(
    x: jax.Array, axis_name: str, op: Op
) -> jax.Array:
    """Recursive-halving reduce-scatter (power-of-two ranks): log2(n)
    rounds; round k exchanges half the active window with the partner
    at distance n/2^(k+1) and folds the received half — each round's
    payload is a statically-sized slab at a rank-dependent offset.

    Reference: coll_base_reduce_scatter.c
    (ompi_coll_base_reduce_scatter_intra_basic_recursivehalving).
    Non-power-of-two or non-commutative inputs fall back to the ring."""
    n = _size(axis_name)
    if x.shape[0] != n:
        raise ArgumentError(
            f"reduce_scatter input leading dim {x.shape[0]} != ranks {n}"
        )
    if n == 1:
        return x[0]
    if n & (n - 1) or not op.commutative or _op_mod._is_joint(op):
        return reduce_scatter_ring(x, axis_name, op)
    rank = _rank(axis_name)
    zeros = (0,) * (x.ndim - 1)
    buf = x
    lo = jnp.zeros((), jnp.int32)  # active window start (length n>>k)
    half = n // 2
    while half >= 1:
        partner_dist = half
        partner = rank ^ partner_dist
        # Keep the half containing our own row; send the other half.
        keep_upper = (rank & partner_dist) != 0
        send_lo = jnp.where(keep_upper, lo, lo + half)
        keep_lo = jnp.where(keep_upper, lo + half, lo)
        payload = lax.dynamic_slice(
            buf, (send_lo,) + zeros, (half,) + x.shape[1:]
        )
        perm = [(i, i ^ partner_dist) for i in range(n)]
        recvd = lax.ppermute(payload, axis_name, perm)
        kept = lax.dynamic_slice(
            buf, (keep_lo,) + zeros, (half,) + x.shape[1:]
        )
        buf = lax.dynamic_update_slice(
            buf, op.combine(kept, recvd), (keep_lo,) + zeros
        )
        lo = keep_lo
        half //= 2
    return lax.dynamic_slice(buf, (lo,) + zeros, (1,) + x.shape[1:])[0]


def scan_native(x: jax.Array, axis_name: str, op: Op) -> jax.Array:
    """Inclusive prefix reduction over ranks.

    Reference: coll_base_scan.c — linear recursion; here: allgather +
    on-device associative scan + row select (log-depth on the VPU)."""
    rank = _rank(axis_name)
    gathered = lax.all_gather(x, axis_name, axis=0)
    scanned = lax.associative_scan(
        lambda a, b: op.combine(a, b), gathered, axis=0
    )
    return jnp.take(scanned, rank, axis=0)


def exscan_native(x: jax.Array, axis_name: str, op: Op) -> jax.Array:
    """Exclusive prefix reduction; rank 0's result is the op identity
    (MPI leaves it undefined — identity is the useful choice)."""
    rank = _rank(axis_name)
    gathered = lax.all_gather(x, axis_name, axis=0)
    scanned = lax.associative_scan(
        lambda a, b: op.combine(a, b), gathered, axis=0
    )
    prev = jnp.take(scanned, jnp.maximum(rank - 1, 0), axis=0)
    if op.has_identity:
        ident = op.identity_like(x)
    else:
        ident = jnp.zeros_like(x)
    return jnp.where(rank == 0, ident, prev)


def barrier(axis_name: str):
    """Fabric barrier: a 1-element allreduce (the reference's
    recursive-doubling barrier collapses to the same fabric round-trip)."""
    return lax.psum(jnp.ones((), jnp.int32), axis_name)


# ---------------------------------------------------------------------------
# sendrecv / ring-shift building blocks (SP/PP substrate, SURVEY §2.6)
# ---------------------------------------------------------------------------

def ring_shift(x: Any, axis_name: str, shift: int = 1) -> Any:
    """Shift values around the ring by `shift` (the ring-attention /
    pipeline-edge primitive; reference analog: the ring pass inside
    allreduce_intra_ring, coll_base_allreduce.c:341)."""
    n = _size(axis_name)
    perm = _ring_perm(n, shift % n)
    return jax.tree.map(lambda leaf: lax.ppermute(leaf, axis_name, perm), x)


def sendrecv(x: Any, axis_name: str, perm: Sequence[tuple[int, int]]) -> Any:
    """Explicit (src, dst) permutation exchange — typed edge channels."""
    return jax.tree.map(
        lambda leaf: lax.ppermute(leaf, axis_name, list(perm)), x
    )

"""Hierarchical cross-slice collectives: ICI inside, DCN between.

TPU-native equivalent of the reference's two-level pattern (reference:
coll/sm intra-node + tuned inter-node selection, SURVEY §2.6
"Hierarchical/topology-aware"; SURVEY §7 step 7: "hierarchical
collectives (intra-slice ICI reduce → inter-slice exchange → ICI
bcast)"). The three phases:

1. **intra-slice reduce** on the slice's communicator — device-resident,
   MXU/VPU combine (the coll/sm analog, but on the fabric);
2. **inter-slice exchange** among slice leaders over DCN — staged
   through the host pool, combined with the native op kernels
   (ring or recursive-doubling schedule over the wire);
3. **intra-slice bcast** of the global result back over ICI.

`SliceHandle` carries one slice's view (its communicator + DCN endpoint
+ peer wiring). In production each controller process holds one handle;
tests hold several in one process (the reference's
multi-rank-over-loopback strategy, SURVEY §4).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..core import config
from ..core.counters import SPC
from ..core.errors import OmpiTpuError
from ..core.logging import get_logger
from ..ops import lookup as op_lookup

logger = get_logger("coll.hier")

_HIER_TAG = 0x48494552  # "HIER"

# Tuned decision knobs for the inter-slice phase (reference lineage:
# coll_tuned_decision_fixed.c:45-87 — allreduce <10KB -> recursive
# doubling, large -> (segmented) ring with 1MiB segments).
_schedule_var = config.register(
    "coll", "hier", "schedule", type=str, default="",
    description="Force the inter-slice schedule (rd|ring|gather); "
                "empty = tuned decision",
)
_small_var = config.register(
    "coll", "hier", "small_msg", type=int, default=10_000,
    description="Bytes below which small-message schedules are chosen "
                "(reference: coll_tuned_decision_fixed.c:53)",
)
_segment_var = config.register(
    "coll", "hier", "segment_bytes", type=int, default=1 << 20,
    description="Segment size for pipelining the intra-slice reduce "
                "against the inter-slice wire (reference: 1MiB ring "
                "segments, coll_tuned_decision_fixed.c:73)",
)


def choose_schedule(n_slices: int, nbytes: int) -> str:
    """The per-(leaders, bytes) decision (coll/tuned's fixed rules,
    restricted to the inter-slice exchange):

    - forced override via coll_hier_schedule;
    - small messages: recursive doubling (pof2 leader counts) or
      gather-at-leader (non-pof2 — one extra hop beats 2(n-1) latency
      terms of a ring at tiny sizes);
    - large messages: ring (bandwidth-optimal, segment-pipelined).
    """
    forced = (_schedule_var.value or "").strip()
    if forced:
        return forced
    pof2 = n_slices & (n_slices - 1) == 0
    if nbytes < _small_var.value:
        return "rd" if pof2 else "gather"
    return "ring"


class HierError(OmpiTpuError):
    errclass = "ERR_OTHER"


#: In-place ufunc per predefined op (SUM's np_combine is a lambda, not
#: a ufunc, so the out= form needs its own table). Custom/decorated ops
#: fall back to the allocating np_reduce path.
_INPLACE_UFUNC = {
    "sum": np.add,
    "prod": np.multiply,
    "max": np.maximum,
    "min": np.minimum,
    "band": np.bitwise_and,
    "bor": np.bitwise_or,
    "bxor": np.bitwise_xor,
}


def _inplace_ufunc(op):
    """The ufunc that can fold into an accumulator with out=, or None
    (which keeps the tiered np_reduce path — native op kernels, custom
    combines)."""
    if getattr(op, "predefined", False):
        return _INPLACE_UFUNC.get(op.name)
    return None


def _fold(acc: np.ndarray, incoming: np.ndarray, op) -> np.ndarray:
    """acc = acc (op) incoming, in place when the op allows it."""
    ufunc = _inplace_ufunc(op)
    if ufunc is not None and acc.flags.writeable:
        ufunc(acc, incoming, out=acc)
        return acc
    return op.np_reduce(acc, incoming)


@dataclass
class SliceHandle:
    """One slice's participation in a hierarchical collective."""

    comm: object  # intra-slice communicator
    endpoint: object  # DcnEndpoint (leader's listener)
    slice_id: int
    n_slices: int
    peer_ids: dict  # slice_id -> DCN peer id (leader wiring)

    def __post_init__(self):
        # (src_slice, tag) -> payloads that arrived out of order: a
        # fast peer's round-k+1 message can land before a slow peer's
        # round-k one (the reason ob1 has matching queues)
        self._reorder: dict = {}

    def wire_check(self) -> None:
        missing = [
            s for s in range(self.n_slices)
            if s != self.slice_id and s not in self.peer_ids
        ]
        if missing:
            raise HierError(
                f"slice {self.slice_id}: unwired peers {missing}"
            )

    def recv_from(self, src_slice: int, tag: int,
                  timeout: float) -> bytes:
        """Receive the message from `src_slice` with `tag`, buffering
        any other traffic (wire convention: connect cookie is
        slice_id+1, so a passive link's peer id is -(src_slice+1))."""
        key = (src_slice, tag)
        q = self._reorder.get(key)
        if q:
            return q.pop(0)
        deadline = time.monotonic() + timeout
        passive_peer = -(src_slice + 1)
        while True:
            got = self.endpoint.poll_recv()
            if got is None:
                # fail fast when the source slice's links are all gone
                # instead of burning the whole timeout (peer_links is
                # -1 while the handshake is still in flight — only a
                # known-then-died peer trips this)
                if self.endpoint.peer_links(passive_peer) == 0:
                    self.endpoint.check_peer(
                        passive_peer, what=f"slice {src_slice}"
                    )
                if time.monotonic() >= deadline:
                    raise HierError(
                        f"slice {self.slice_id}: timeout waiting for "
                        f"{key}"
                    )
                # Park on the engine's completion condition variable
                # instead of spin-sleeping: on small-core hosts the
                # spinner steals the transport threads' cycles (same
                # fix as the fabric's idle hook). Drain send
                # completions first — wait_event also wakes on those,
                # and an unconsumed one would turn the park back into
                # a hot spin (the fabric's progress pass drains them
                # the same way).
                drain = getattr(self.endpoint, "poll_send_complete",
                                None)
                if drain is not None:
                    while drain() is not None:
                        pass
                wait = getattr(self.endpoint, "wait_event", None)
                if wait is not None:
                    wait(0.05)
                else:
                    time.sleep(0.0002)
                continue
            peer, got_tag, raw = got
            src = -peer - 1 if peer < 0 else None
            if src is None:
                raise HierError(
                    f"slice {self.slice_id}: message on active link "
                    f"(peer {peer}); hier traffic must arrive passively"
                )
            if (src, got_tag) == key:
                return raw
            self._reorder.setdefault((src, got_tag), []).append(raw)

    def recv_reduce_into(self, src_slice: int, tag: int, timeout: float,
                         acc: np.ndarray, op) -> np.ndarray:
        """Receive src's block and fold it into ``acc`` — the
        accumulate hook of the exchange schedules. The base
        implementation receives bytes and folds in place (saving the
        np_reduce result allocation); transports whose frames are
        peer-mapped (coll/sm's fastpath slab) override this to reduce
        DIRECTLY out of the sender's frame, skipping the wire copy
        entirely (the PiP-style single-copy reduction plane)."""
        raw = self.recv_from(src_slice, tag, timeout)
        incoming = np.frombuffer(raw, acc.dtype).reshape(acc.shape)
        return _fold(acc, incoming, op)


def _exchange_ring(h: SliceHandle, block: np.ndarray, op,
                   timeout: float, tag_base: int = _HIER_TAG
                   ) -> np.ndarray:
    """Inter-slice reduce via a ring over DCN: n-1 rounds, each slice
    forwards the partial to the next slice (reference:
    allreduce_intra_ring's structure, over the wire)."""
    # Circulate each slice's ORIGINAL block around the ring while
    # accumulating separately — forwarding the accumulator instead
    # double-counts contributions for n >= 3.
    acc = block.copy()
    cur = block
    right = (h.slice_id + 1) % h.n_slices
    left = (h.slice_id - 1) % h.n_slices
    for rnd in range(h.n_slices - 1):
        h.endpoint.send_bytes(
            h.peer_ids[right], tag_base + rnd, cur.tobytes()
        )
        raw = h.recv_from(left, tag_base + rnd, timeout)
        # the received block is FORWARDED next round, so the ring keeps
        # the copying receive; only the fold itself goes in-place
        cur = np.frombuffer(raw, block.dtype).reshape(block.shape)
        acc = _fold(acc, cur, op)
    return acc


def _exchange_rd(h: SliceHandle, block: np.ndarray, op,
                 timeout: float, tag_base: int = _HIER_TAG
                 ) -> np.ndarray:
    """Recursive doubling over DCN (reference:
    allreduce_intra_recursivedoubling) — log2(n) rounds for
    power-of-two slice counts."""
    acc = block.copy()
    dist = 1
    rnd = 0
    while dist < h.n_slices:
        partner = h.slice_id ^ dist
        h.endpoint.send_bytes(
            h.peer_ids[partner], tag_base + rnd, acc.tobytes()
        )
        acc = h.recv_reduce_into(partner, tag_base + rnd, timeout,
                                 acc, op)
        dist <<= 1
        rnd += 1
    return acc


def _exchange_gather(h: SliceHandle, block: np.ndarray, op,
                     timeout: float, tag_base: int = _HIER_TAG
                     ) -> np.ndarray:
    """Gather-at-leader: every slice sends its partial to slice 0,
    which reduces and broadcasts the result back — 2 latency terms
    total, the small-message winner for non-pof2 leader counts
    (reference analog: reduce+bcast 'nonoverlapping',
    coll_base_allreduce.c:53)."""
    if h.slice_id == 0:
        acc = block.copy()
        for src in range(1, h.n_slices):
            acc = h.recv_reduce_into(src, tag_base, timeout, acc, op)
        for dst in range(1, h.n_slices):
            h.endpoint.send_bytes(
                h.peer_ids[dst], tag_base + 1, acc.tobytes()
            )
        return acc
    h.endpoint.send_bytes(h.peer_ids[0], tag_base, block.tobytes())
    raw = h.recv_from(0, tag_base + 1, timeout)
    return np.frombuffer(raw, block.dtype).reshape(block.shape)


def allreduce(h: SliceHandle, x, op="sum", *, timeout: float = 30.0,
              schedule: Optional[str] = None,
              segment_bytes: Optional[int] = None,
              tag_base: int = _HIER_TAG):
    """Hierarchical allreduce of a rank-major intra-slice buffer. In
    production each controller process drives its own handle; tests
    drive several handles on threads (endpoints are thread-safe).

    Large payloads pipeline: the buffer splits into segments, every
    segment's intra-slice reduce is enqueued on the devices up front
    (JAX async dispatch), and the wire exchanges segment k while the
    devices still compute segments k+1... — the overlap of phase 1
    with phase 2 (reference analog: segmented ring, 1MiB segments,
    coll_tuned_decision_fixed.c:73-81)."""
    seg = segment_bytes if segment_bytes is not None \
        else int(_segment_var.value)
    arr = x if hasattr(x, "nbytes") else None
    per_rank_bytes = (arr.nbytes // h.comm.size) if arr is not None else 0
    if h.n_slices > 1 and seg > 0 and per_rank_bytes > seg:
        return _allreduce_pipelined(h, x, op, timeout=timeout,
                                    schedule=schedule, seg_bytes=seg,
                                    tag_base=tag_base)
    partial = phase1_local_reduce(h, x, op)
    global_block = phase2_exchange(
        h, partial, op, timeout=timeout, schedule=schedule,
        tag_base=tag_base,
    )
    return phase3_local_bcast(h, global_block)


def _allreduce_pipelined(h: SliceHandle, x, op, *, timeout: float,
                         schedule: Optional[str], seg_bytes: int,
                         tag_base: int = _HIER_TAG):
    import jax
    import jax.numpy as jnp

    opo = op_lookup(op)
    n = h.comm.size
    flat = x.reshape(n, -1)
    elems = int(flat.shape[1])
    itemsize = jnp.dtype(flat.dtype).itemsize
    seg_elems = max(1, seg_bytes // itemsize)
    bounds = list(range(0, elems, seg_elems)) + [elems]
    # Phase 1 for EVERY segment is enqueued before any wire work: the
    # device runs ahead of the exchange loop below.
    reduced = [
        h.comm.reduce(flat[:, lo:hi],
                      op=opo.name if opo.predefined else opo, root=0)
        for lo, hi in zip(bounds, bounds[1:])
    ]
    SPC.record("hier_pipelined_allreduces")
    rounds_span = h.n_slices + 2  # tag namespace per segment
    bcasts = []
    for s, dev_red in enumerate(reduced):
        partial = np.asarray(jax.device_get(dev_red))
        seg_out = phase2_exchange(
            h, partial, op, timeout=timeout, schedule=schedule,
            tag_base=tag_base + s * rounds_span,
        )
        # Phase 3 per segment, enqueued IMMEDIATELY: the intra-slice
        # bcast of segment s runs on the devices (async dispatch) while
        # segment s+1 is still on the wire — exchange/bcast overlap,
        # not just phase-1/wire overlap (the reference's segmented ring
        # pipelines all three stages the same way,
        # coll_base_allreduce.c:618-717).
        bcasts.append(phase3_local_bcast(h, seg_out.reshape(-1)))
        SPC.record("hier_segments")
    full = jnp.concatenate(bcasts, axis=1)
    return full.reshape((n,) + x.shape[1:])


def phase1_local_reduce(h: SliceHandle, x, op="sum") -> np.ndarray:
    op = op_lookup(op)
    red = h.comm.reduce(x, op=op.name if op.predefined else op, root=0)
    import jax

    SPC.record("hier_local_reduce")
    return np.asarray(jax.device_get(red))


def phase2_exchange(h: SliceHandle, partial: np.ndarray, op="sum", *,
                    timeout: float = 30.0,
                    schedule: Optional[str] = None,
                    tag_base: int = _HIER_TAG) -> np.ndarray:
    """Inter-slice combine. Schedule per (leaders, bytes) from the
    tuned decision (`choose_schedule`), overridable via `schedule`
    ('rd'|'ring'|'gather') or the coll_hier_schedule config var."""
    op = op_lookup(op)
    if h.n_slices == 1:
        return partial
    h.wire_check()
    if schedule is None:
        schedule = choose_schedule(h.n_slices, int(partial.nbytes))
    if schedule == "rd":
        if h.n_slices & (h.n_slices - 1):
            raise HierError(
                "recursive doubling needs a power-of-two slice count"
            )
        out = _exchange_rd(h, partial, op, timeout, tag_base)
    elif schedule == "ring":
        out = _exchange_ring(h, partial, op, timeout, tag_base)
    elif schedule == "gather":
        out = _exchange_gather(h, partial, op, timeout, tag_base)
    else:
        raise HierError(f"unknown schedule {schedule!r}")
    SPC.record("hier_dcn_exchanges")
    SPC.record(f"hier_sched_{schedule}")
    return out


def phase3_local_bcast(h: SliceHandle, global_block: np.ndarray):
    buf = h.comm.put_rank_major(
        np.ascontiguousarray(
            np.broadcast_to(
                global_block, (h.comm.size,) + global_block.shape
            )
        )
    )
    SPC.record("hier_local_bcast")
    return h.comm.bcast(buf, root=0)


def wire_slices(handles: list[SliceHandle], *, nlinks: int = 1) -> None:
    """Test/loopback wiring: connect every handle's endpoint to every
    other (production uses modex.exchange_dcn_addresses + connect)."""
    for a in handles:
        for b in handles:
            if a.slice_id == b.slice_id:
                continue
            if b.slice_id not in a.peer_ids:
                a.peer_ids[b.slice_id] = a.endpoint.connect(
                    b.endpoint.address[0], b.endpoint.address[1],
                    cookie=a.slice_id + 1, nlinks=nlinks,
                )


# ---------------------------------------------------------------------------
# COLL component: process-spanning communicators route through the comm
# vtable (VERDICT r2 item 2; reference: every comm gets its coll table by
# component query/priority, coll_base_comm_select.c:110-152, with the
# intra/inter-node hierarchy a component concern like coll/sm).
#
# `FabricSlice` is the auto-wired SliceHandle: phases 1/3 run on a local
# sub-communicator of this controller's ranks; the phase-2 inter-slice
# exchange is MPI p2p between slice-leader ranks on the PARENT comm —
# i.e. it rides the pml/fabric engine over DCN, the same layering as the
# reference's colls sitting on PML send/recv (SURVEY §1 invariant).
# ---------------------------------------------------------------------------

from .framework import COLL, CollComponent  # noqa: E402


def _fabric_wired() -> bool:
    from ..pml.framework import PML

    try:
        ob1 = PML.component("ob1")
    except Exception:
        return False
    return getattr(ob1, "_fabric", None) is not None


class FabricSlice:
    """A SliceHandle built automatically from a spanning comm's proc
    table. Duck-types the surface the exchange schedules use
    (slice_id / n_slices / peer_ids / endpoint.send_bytes / recv_from /
    comm for the local phases); no hand wiring, no extra listener."""

    def __init__(self, parent) -> None:
        import jax

        from ..communicator import Communicator
        from ..group import Group

        self.parent = parent
        procs = parent.procs
        self.slices = sorted({p.process_index for p in procs})
        slices = self.slices
        my = jax.process_index()
        self.slice_id = slices.index(my)
        self.n_slices = len(slices)
        self.peer_ids = {s: s for s in range(self.n_slices)}
        self.leaders: dict[int, int] = {}
        self.local_ranks: list[int] = []
        self.rank_slice: list[int] = []  # comm rank -> slice index
        self.members: list[list[int]] = [[] for _ in slices]  # per slice
        for r, p in enumerate(procs):
            s = slices.index(p.process_index)
            self.rank_slice.append(s)
            self.members[s].append(r)
            self.leaders.setdefault(s, r)
            if p.process_index == my:
                self.local_ranks.append(r)
        world_ranks = [parent.group.world_ranks[r]
                       for r in self.local_ranks]
        self.comm = Communicator(
            Group(world_ranks), parent._world_procs,
            name=f"{parent.name}.hier_local", parent_cid=parent.cid,
        )
        self.endpoint = self  # send_bytes/recv below
        self._pending: list = []
        # Per-collective tag epoch: every vtable collective on this comm
        # gets a disjoint tag window, so an aborted attempt's stale
        # payloads can never match a retry's receives (all controllers
        # bump at entry, keeping epochs aligned in MPI program order).
        self._epoch = 0

    # SliceHandle surface -------------------------------------------------

    def wire_check(self) -> None:
        pass  # reachability is the fabric's concern (checked per send)

    def send_bytes(self, peer_slice: int, tag: int, raw: bytes) -> None:
        dst = self.leaders[peer_slice]
        me = self.leaders[self.slice_id]
        req = self.parent.rank(me).isend(
            np.frombuffer(raw, np.uint8).copy(), dest=dst, tag=tag
        )
        self._pending.append(req)

    def recv_from(self, src_slice: int, tag: int,
                  timeout: float) -> bytes:
        me = self.leaders[self.slice_id]
        req = self.parent.rank(me).irecv(
            source=self.leaders[src_slice], tag=tag
        )
        # honor the deadline so a dead peer raises instead of wedging
        # the surviving controllers (SliceHandle.recv_from semantics)
        val = req.result(timeout=timeout)
        return np.asarray(val).tobytes()

    def recv_reduce_into(self, src_slice: int, tag: int, timeout: float,
                         acc: np.ndarray, op) -> np.ndarray:
        """SliceHandle.recv_reduce_into, for the duck-typed surface
        (coll/sm's ShmSlice overrides with the zero-copy slab fold)."""
        raw = self.recv_from(src_slice, tag, timeout)
        incoming = np.frombuffer(raw, acc.dtype).reshape(acc.shape)
        return _fold(acc, incoming, op)

    def rank_ordered(self) -> bool:
        """True when comm ranks ascend with slice index (each process's
        ranks contiguous, processes in rank order) — the layout where a
        slice-ordered fold equals MPI's rank-ordered reduction."""
        return all(a <= b for a, b in
                   zip(self.rank_slice, self.rank_slice[1:]))

    def ordered_schedule(self, opo) -> Optional[str]:
        """None for commutative ops; the slice-ordered 'gather'
        schedule for non-commutative ones — which equals MPI rank order
        only when ranks ascend with slices, so anything else raises
        (reference: non-commutative ops take the ordered path,
        coll_tuned_decision_fixed.c:85)."""
        if getattr(opo, "commutative", True):
            return None
        if not self.rank_ordered():
            raise HierError(
                "non-commutative ops on a spanning comm need ranks "
                "contiguous per process and processes in rank order"
            )
        return "gather"

    def finish(self) -> None:
        """Drain outstanding leader isends (rendezvous sends complete
        when the peer's CTS arrives during its own exchange)."""
        pending, self._pending = self._pending, []
        for req in pending:
            req.wait()

    def abort_pending(self) -> None:
        """Drop references to in-flight sends after a failed exchange
        (they may never complete if the peer died; the next collective
        uses a fresh tag epoch so late stragglers cannot match it)."""
        self._pending = []

    def next_tag_base(self) -> int:
        """Allocate this collective's tag window."""
        epoch = self._epoch
        self._epoch += 1
        return _HIER_TAG + (epoch % 4096) * 0x10000

    def local_rank_major(self, x):
        """Validate the spanning-comm buffer convention: each controller
        contributes a rank-major buffer over its LOCAL ranks."""
        import jax.numpy as jnp

        from ..core.errors import ArgumentError

        arr = x if hasattr(x, "shape") else jnp.asarray(x)
        if arr.ndim < 1 or arr.shape[0] != self.comm.size:
            raise ArgumentError(
                f"{self.parent.name} spans {self.n_slices} controller "
                f"processes; each contributes a rank-major buffer over "
                f"its {self.comm.size} local ranks, got shape "
                f"{getattr(arr, 'shape', None)}"
            )
        return arr


def comm_slice(comm) -> FabricSlice:
    """The comm's auto-wired hier handle (built once, cached) — the
    module-level entry for non-coll callers (osc/fabric_window);
    delegates to the single implementation on HierColl."""
    return HierColl.comm_slice(comm)


# -- spanning-comm data-movement and prefix collectives ---------------------
# (reference: every comm operation comes from the per-comm coll table,
# coll_base_functions.h:45-66; these run leader exchanges over the
# fabric p2p and the device tier inside each slice)

def _np_bytes(arr: np.ndarray) -> bytes:
    """Self-describing wire form (dtype+shape ride along)."""
    import io

    bio = io.BytesIO()
    np.save(bio, np.ascontiguousarray(arr), allow_pickle=False)
    return bio.getvalue()


def _np_from(raw: bytes) -> np.ndarray:
    import io

    return np.load(io.BytesIO(raw), allow_pickle=False)


def _np_list_bytes(arrs) -> bytes:
    """Wire form for a LIST of (possibly ragged) arrays — the v-variant
    payloads, where every block carries its own shape/dtype."""
    import io

    bio = io.BytesIO()
    np.savez(bio, *[np.ascontiguousarray(np.asarray(a)) for a in arrs])
    return bio.getvalue()


def _np_list_from(raw: bytes) -> list[np.ndarray]:
    import io

    with np.load(io.BytesIO(raw), allow_pickle=False) as z:
        return [z[f"arr_{i}"] for i in range(len(z.files))]


def _concat_global_order(h, parts: dict) -> np.ndarray:
    """Concatenate per-slice block lists in GLOBAL rank order (member
    lists are rank-sorted per slice but interleave across slices)."""
    order = sorted(
        ((r, parts[s][i]) for s, ranks in enumerate(h.members)
         for i, r in enumerate(ranks)),
        key=lambda t: t[0],
    )
    return np.concatenate([p for _, p in order], axis=0)


def _hier_op(fn):
    """Wrap a HierColl exchange method with the epoch/abort protocol."""
    import functools

    @functools.wraps(fn)
    def wrapped(self, comm, *args, **kw):
        h = self.comm_slice(comm)
        tag = h.next_tag_base()
        try:
            out = fn(self, comm, h, tag, *args, **kw)
            h.finish()
        except BaseException:
            h.abort_pending()
            raise
        return out

    return wrapped


class _HierDataOps:
    """Mixin: the data-movement / prefix operations of HierColl."""

    @_hier_op
    def allgather(self, comm, h, tag, x):
        x = h.local_rank_major(x)
        arr = np.asarray(x)
        raw = _np_bytes(arr)  # identical for every destination
        for s in range(h.n_slices):
            if s != h.slice_id:
                h.send_bytes(s, tag, raw)
        parts = {h.slice_id: arr}
        for s in range(h.n_slices):
            if s != h.slice_id:
                parts[s] = _np_from(h.recv_from(s, tag, timeout=60.0))
        full = np.empty((comm.size,) + arr.shape[1:], arr.dtype)
        for s, ranks in enumerate(h.members):
            for i, r in enumerate(ranks):
                full[r] = parts[s][i]
        out = np.broadcast_to(full, (h.comm.size,) + full.shape)
        SPC.record("hier_allgathers")
        return h.comm.put_rank_major(np.ascontiguousarray(out))

    @_hier_op
    def gather(self, comm, h, tag, x, root):
        import jax

        x = h.local_rank_major(x)
        arr = np.asarray(x)
        root_slice = h.rank_slice[root]
        if h.slice_id != root_slice:
            h.send_bytes(root_slice, tag, _np_bytes(arr))
            return None
        full = np.empty((comm.size,) + arr.shape[1:], arr.dtype)
        for s, ranks in enumerate(h.members):
            part = arr if s == root_slice else _np_from(
                h.recv_from(s, tag, timeout=60.0))
            for i, r in enumerate(ranks):
                full[r] = part[i]
        SPC.record("hier_gathers")
        return jax.device_put(full, comm.procs[root].device)

    @_hier_op
    def scatter(self, comm, h, tag, x, root):
        root_slice = h.rank_slice[root]
        if h.slice_id == root_slice:
            arr = np.asarray(x)
            if arr.shape[0] != comm.size:
                from ..core.errors import ArgumentError

                raise ArgumentError(
                    f"scatter root buffer needs leading dim "
                    f"{comm.size}, got {arr.shape}"
                )
            for s in range(h.n_slices):
                if s != root_slice:
                    h.send_bytes(s, tag, _np_bytes(arr[h.members[s]]))
            local = arr[h.members[h.slice_id]]
        else:
            local = _np_from(h.recv_from(root_slice, tag, timeout=60.0))
        SPC.record("hier_scatters")
        return h.comm.put_rank_major(np.ascontiguousarray(local))

    @_hier_op
    def alltoall(self, comm, h, tag, x):
        from ..core.errors import ArgumentError

        x = h.local_rank_major(x)
        arr = np.asarray(x)
        if arr.ndim < 2 or arr.shape[1] != comm.size:
            raise ArgumentError(
                f"spanning alltoall needs (local_ranks, comm_size, ...) "
                f"buffer, got {arr.shape}"
            )
        for s in range(h.n_slices):
            if s != h.slice_id:
                h.send_bytes(s, tag, _np_bytes(arr[:, h.members[s]]))
        out = np.empty_like(arr)
        mine = h.members[h.slice_id]
        out[:, mine] = arr[:, mine].swapaxes(0, 1)
        for s in range(h.n_slices):
            if s != h.slice_id:
                recv = _np_from(h.recv_from(s, tag, timeout=60.0))
                out[:, h.members[s]] = recv.swapaxes(0, 1)
        SPC.record("hier_alltoalls")
        return h.comm.put_rank_major(np.ascontiguousarray(out))

    @_hier_op
    def reduce_scatter_block(self, comm, h, tag, x, op):
        from ..core.errors import ArgumentError

        opo = op_lookup(op)
        x = h.local_rank_major(x)
        if x.ndim < 2 or x.shape[1] != comm.size:
            raise ArgumentError(
                f"spanning reduce_scatter_block needs (local_ranks, "
                f"comm_size, ...) buffer, got {x.shape}"
            )
        schedule = h.ordered_schedule(opo)
        partial = phase1_local_reduce(h, x, opo)
        full = phase2_exchange(h, partial, opo, timeout=60.0,
                               schedule=schedule, tag_base=tag)
        SPC.record("hier_reduce_scatters")
        return h.comm.put_rank_major(
            np.ascontiguousarray(full[h.members[h.slice_id]]))

    # -- vector (v/w) variants: per-rank counts, ragged blocks ----------
    # (reference: the *v family + alltoallw, coll_base_functions.h:75-76;
    # each controller contributes its LOCAL ranks' blocks, matching the
    # driver-model convention of the non-vector family)

    def _local_list(self, h, values, what: str):
        from ..core.errors import ArgumentError

        if len(values) != h.comm.size:
            raise ArgumentError(
                f"spanning {what} takes one block per LOCAL rank "
                f"({h.comm.size}), got {len(values)}"
            )
        return [np.asarray(v) for v in values]

    @_hier_op
    def allgatherv(self, comm, h, tag, values):
        import jax

        host = self._local_list(h, values, "allgatherv")
        raw = _np_list_bytes(host)  # identical for every destination
        for s in range(h.n_slices):
            if s != h.slice_id:
                h.send_bytes(s, tag, raw)
        parts = {h.slice_id: host}
        for s in range(h.n_slices):
            if s != h.slice_id:
                parts[s] = _np_list_from(h.recv_from(s, tag,
                                                     timeout=60.0))
        SPC.record("hier_allgathervs")
        return jax.device_put(_concat_global_order(h, parts),
                              h.comm.replicated_sharding())

    @_hier_op
    def gatherv(self, comm, h, tag, values, root):
        import jax

        host = self._local_list(h, values, "gatherv")
        root_slice = h.rank_slice[root]
        if h.slice_id != root_slice:
            h.send_bytes(root_slice, tag, _np_list_bytes(host))
            return None
        parts = {root_slice: host}
        for s in range(h.n_slices):
            if s != root_slice:
                parts[s] = _np_list_from(h.recv_from(s, tag,
                                                     timeout=60.0))
        SPC.record("hier_gathervs")
        return jax.device_put(_concat_global_order(h, parts),
                              comm.procs[root].device)

    @_hier_op
    def scatterv(self, comm, h, tag, blocks, root):
        import jax

        from ..core.errors import ArgumentError

        root_slice = h.rank_slice[root]
        if h.slice_id == root_slice:
            if len(blocks) != comm.size:
                raise ArgumentError(
                    f"spanning scatterv root needs one block per GLOBAL "
                    f"rank ({comm.size}), got {len(blocks)}"
                )
            for s in range(h.n_slices):
                if s != root_slice:
                    h.send_bytes(s, tag, _np_list_bytes(
                        [blocks[r] for r in h.members[s]]))
            mine = [np.asarray(blocks[r])
                    for r in h.members[root_slice]]
        else:
            mine = _np_list_from(h.recv_from(root_slice, tag,
                                             timeout=60.0))
        SPC.record("hier_scattervs")
        return [jax.device_put(b, h.comm.devices[i])
                for i, b in enumerate(mine)]

    @_hier_op
    def alltoallw(self, comm, h, tag, blocks):
        import jax

        from ..core.errors import ArgumentError

        if len(blocks) != h.comm.size:
            raise ArgumentError(
                f"spanning alltoallw takes one send list per LOCAL rank "
                f"({h.comm.size}), got {len(blocks)}"
            )
        for row in blocks:
            if len(row) != comm.size:
                raise ArgumentError(
                    f"each send list needs one block per GLOBAL rank "
                    f"({comm.size}), got {len(row)}"
                )
        mine = h.members[h.slice_id]
        # ship each slice the blocks destined for its members,
        # src-major then dst order (reconstructed symmetrically)
        for s in range(h.n_slices):
            if s != h.slice_id:
                flat = [blocks[i][d]
                        for i in range(len(mine))
                        for d in h.members[s]]
                h.send_bytes(s, tag, _np_list_bytes(flat))
        # out[local_dst][global_src]
        out = [[None] * comm.size for _ in mine]
        for i, src_global in enumerate(mine):
            for di, d in enumerate(mine):
                out[di][src_global] = np.asarray(blocks[i][d])
        for s in range(h.n_slices):
            if s == h.slice_id:
                continue
            flat = _np_list_from(h.recv_from(s, tag, timeout=60.0))
            srcs = h.members[s]
            k = 0
            for src_global in srcs:
                for di in range(len(mine)):
                    out[di][src_global] = flat[k]
                    k += 1
        SPC.record("hier_alltoallws")
        return [
            [jax.device_put(b, h.comm.devices[di]) for b in row]
            for di, row in enumerate(out)
        ]

    def alltoallv(self, comm, blocks):
        """Ragged all-to-all: out[local_dst] = concatenation over
        GLOBAL src rank order of blocks[src][dst]."""
        import jax.numpy as jnp

        nested = self.alltoallw(comm, blocks)
        return [jnp.concatenate([jnp.asarray(b) for b in row], axis=0)
                for row in nested]

    @_hier_op
    def reduce_scatter(self, comm, h, tag, values, counts, op):
        import jax

        from ..core.errors import ArgumentError

        opo = op_lookup(op)
        host = self._local_list(h, values, "reduce_scatter")
        if len(counts) != comm.size:
            raise ArgumentError(
                f"need one count per GLOBAL rank ({comm.size}), got "
                f"{len(counts)}"
            )
        total = sum(counts)
        for v in host:
            if v.shape[0] != total:
                raise ArgumentError(
                    f"buffer rows {v.shape[0]} != sum(counts) {total}"
                )
        schedule = h.ordered_schedule(opo)
        stacked = h.comm.put_rank_major(np.stack(host))
        partial = phase1_local_reduce(h, stacked, opo)
        full = phase2_exchange(h, partial, opo, timeout=60.0,
                               schedule=schedule, tag_base=tag)
        SPC.record("hier_reduce_scatter_vs")
        out, start = [], 0
        offsets = {}
        for r, c in enumerate(counts):
            offsets[r] = (start, c)
            start += c
        for i, r in enumerate(h.members[h.slice_id]):
            lo, c = offsets[r]
            out.append(jax.device_put(full[lo:lo + c],
                                      h.comm.devices[i]))
        return out

    # -- neighborhood collectives (reference: coll_base_functions.h:
    #    62-66) over the comm's attached cart/graph/dist_graph topology.
    #    The adjacency is GLOBAL knowledge (the controller builds the
    #    topology), so only block payloads cross the wire.

    @staticmethod
    def _edges(comm):
        from ..topo.topology import TopologyError, edge_fns

        if comm.topo is None:
            raise TopologyError("communicator has no topology")
        return edge_fns(comm.topo)

    @_hier_op
    def neighbor_allgather(self, comm, h, tag, x):
        """Each of this controller's ranks receives its topology
        neighbors' blocks in neighbor order (in-neighbors for
        dist_graph); returns a dict keyed by GLOBAL rank id (this
        controller's ranks only). Sparse exchange: each slice ships
        only the blocks the destination's ranks actually neighbor,
        id-tagged — not a full allgather."""
        import jax.numpy as jnp

        _, ins = self._edges(comm)
        x = h.local_rank_major(x)
        arr = np.asarray(x)
        local = h.members[h.slice_id]
        blk = {r: arr[i] for i, r in enumerate(local)}
        for s in range(h.n_slices):
            if s == h.slice_id:
                continue
            needed = sorted({n for r2 in h.members[s]
                             for n in ins(r2) if n in blk})
            payload = [np.asarray(needed, np.int64)]
            payload += [blk[n] for n in needed]
            h.send_bytes(s, tag, _np_list_bytes(payload))
        have = dict(blk)
        for s in range(h.n_slices):
            if s == h.slice_id:
                continue
            got = _np_list_from(h.recv_from(s, tag, timeout=60.0))
            for rid, b in zip(got[0].ravel().astype(int).tolist(),
                              got[1:]):
                have[int(rid)] = b
        out = {}
        for r in local:
            neigh = ins(r)
            out[r] = (jnp.stack([jnp.asarray(have[n]) for n in neigh])
                      if neigh else
                      jnp.zeros((0,) + arr.shape[1:], arr.dtype))
        SPC.record("hier_neighbor_allgathers")
        return out

    @_hier_op
    def neighbor_alltoall(self, comm, h, tag, sendblocks):
        """sendblocks: dict keyed by GLOBAL rank id (this controller's
        ranks), each one block per OUT neighbor in order; returns
        {global_rank: stacked blocks from IN neighbors}. Duplicate
        edges (a periodic cart dim of size 2 lists a neighbor twice)
        pair position-wise, the MPI matching — payloads travel in
        canonical (src, out-position) order so both ends reconstruct
        the same pairing from the shared global adjacency."""
        from collections import Counter

        import jax.numpy as jnp

        from ..topo.topology import TopologyError

        outs, ins = self._edges(comm)
        # Count-aware validation, cached on the immutable topology:
        # every in-edge occurrence needs a matching out-edge occurrence
        # (surplus out-edges are tolerated — their blocks go unread,
        # matching the single-controller mailbox behavior).
        topo = comm.topo
        if not getattr(topo, "_hier_edge_validated", False):
            out_counts = {r: Counter(outs(r))
                          for r in range(comm.size)}
            for r in range(comm.size):
                for src, k in Counter(ins(r)).items():
                    if out_counts[src].get(r, 0) < k:
                        raise TopologyError(
                            f"rank {r} lists {src} as in-neighbor x{k} "
                            f"but rank {src} has fewer out-edges to {r}"
                        )
            topo._hier_edge_validated = True
        local = h.members[h.slice_id]
        buckets: dict[int, list] = {s: [] for s in range(h.n_slices)}
        for src in local:
            for j, dst in enumerate(outs(src)):
                buckets[h.rank_slice[dst]].append(
                    np.asarray(sendblocks[src][j]))
        for s in range(h.n_slices):
            if s != h.slice_id:
                h.send_bytes(s, tag, _np_list_bytes(buckets[s]))
        # Rebuild (src, dst) FIFOs by walking every slice's sources in
        # the same canonical order the sender enumerated.
        mail: dict[tuple[int, int], list] = {}

        def feed(src_list, blocks):
            it = iter(blocks)
            for src in src_list:
                for dst in outs(src):
                    if h.rank_slice[dst] == h.slice_id:
                        mail.setdefault((src, dst), []).append(next(it))

        feed(local, buckets[h.slice_id])
        for s in range(h.n_slices):
            if s != h.slice_id:
                feed(h.members[s],
                     _np_list_from(h.recv_from(s, tag, timeout=60.0)))
        out = {}
        for r in local:
            got = [jnp.asarray(mail[(src, r)].pop(0)) for src in ins(r)]
            out[r] = jnp.stack(got) if got else None
        SPC.record("hier_neighbor_alltoalls")
        return out

    def _prefix(self, comm, h, tag, x, op, *, inclusive: bool):
        opo = op_lookup(op)
        if not h.rank_ordered():
            raise HierError(
                "scan on a spanning comm needs ranks contiguous per "
                "process and processes in rank order (prefix order IS "
                "rank order)"
            )
        x = h.local_rank_major(x)
        arr = np.asarray(x)
        n_local = arr.shape[0]
        # local inclusive prefix + slice total
        pref = np.empty_like(arr)
        acc = arr[0]
        pref[0] = acc
        for i in range(1, n_local):
            acc = opo.np_reduce(acc, arr[i])
            pref[i] = acc
        total = acc
        # slice totals flow upward: every lower slice's total folds into
        # my offset in slice order
        for s in range(h.slice_id + 1, h.n_slices):
            h.send_bytes(s, tag, _np_bytes(total))
        offset = None
        for s in range(h.slice_id):
            t = _np_from(h.recv_from(s, tag, timeout=60.0))
            offset = t if offset is None else opo.np_reduce(offset, t)
        if inclusive:
            out = pref if offset is None else np.stack(
                [opo.np_reduce(offset, p) for p in pref])
        else:
            rows = []
            for i in range(n_local):
                prev = offset if i == 0 else (
                    pref[i - 1] if offset is None
                    else opo.np_reduce(offset, pref[i - 1]))
                rows.append(np.zeros_like(arr[0]) if prev is None
                            else prev)
            out = np.stack(rows)
        SPC.record("hier_scans" if inclusive else "hier_exscans")
        return h.comm.put_rank_major(np.ascontiguousarray(out))

    @_hier_op
    def scan(self, comm, h, tag, x, op="sum"):
        return self._prefix(comm, h, tag, x, op, inclusive=True)

    @_hier_op
    def exscan(self, comm, h, tag, x, op="sum"):
        return self._prefix(comm, h, tag, x, op, inclusive=False)


@COLL.register
class HierColl(_HierDataOps, CollComponent):
    NAME = "hier"
    PRIORITY = 85  # above tuned (80): device tiers cannot cross controllers
    DESCRIPTION = ("two-level ICI+DCN collectives for process-spanning "
                   "communicators (auto-wired from the fabric)")
    #: Subclasses swap the leader-exchange handle (coll/smcoll routes
    #: it over raw shared-memory frames) and the per-comm cache slot.
    SLICE_FACTORY = FabricSlice
    SLICE_ATTR = "_hier_slice"

    @classmethod
    def comm_slice(cls, comm):
        """This component's cached exchange handle for `comm`."""
        h = getattr(comm, cls.SLICE_ATTR, None)
        if h is None:
            h = cls.SLICE_FACTORY(comm)
            setattr(comm, cls.SLICE_ATTR, h)
        return h

    def available(self, comm=None, **_) -> bool:
        if comm is None:
            return False
        import jax

        try:
            idxs = {p.process_index for p in comm.procs}
        except Exception:
            return False
        return (len(idxs) > 1 and jax.process_index() in idxs
                and _fabric_wired())

    def allreduce(self, comm, x, op):
        h = self.comm_slice(comm)
        opo = op_lookup(op)
        schedule = h.ordered_schedule(opo)
        try:
            out = allreduce(h, h.local_rank_major(x), op,
                            schedule=schedule,
                            tag_base=h.next_tag_base())
            h.finish()
        except BaseException:
            h.abort_pending()
            raise
        return out

    def bcast(self, comm, x, root):
        import jax.numpy as jnp

        h = self.comm_slice(comm)
        x = h.local_rank_major(x)
        root_slice = h.rank_slice[root]
        tag = h.next_tag_base()
        try:
            if h.slice_id == root_slice:
                block = np.asarray(x[h.local_ranks.index(root)])
                for s in range(h.n_slices):
                    if s != root_slice:
                        h.send_bytes(s, tag, block.tobytes())
            else:
                raw = h.recv_from(root_slice, tag, timeout=60.0)
                block = np.frombuffer(
                    raw, jnp.dtype(x.dtype)
                ).reshape(x.shape[1:]).copy()
            out = phase3_local_bcast(h, block)
            h.finish()
        except BaseException:
            h.abort_pending()
            raise
        return out

    def reduce(self, comm, x, op, root):
        """Result lands on the root rank's device (root's controller);
        other controllers return None (MPI: recvbuf significant only
        at root)."""
        import jax

        h = self.comm_slice(comm)
        x = h.local_rank_major(x)
        opo = op_lookup(op)
        h.ordered_schedule(opo)  # layout guard for non-commutative ops
        partial = phase1_local_reduce(h, x, opo)
        root_slice = h.rank_slice[root]
        tag = h.next_tag_base()
        try:
            if h.slice_id == root_slice:
                # fold in ascending slice order = MPI rank order for
                # rank-ordered layouts (and a fixed deterministic order
                # regardless)
                parts = []
                for s in range(h.n_slices):
                    if s == root_slice:
                        parts.append(partial)
                    else:
                        raw = h.recv_from(s, tag, timeout=60.0)
                        parts.append(np.frombuffer(
                            raw, partial.dtype).reshape(partial.shape))
                acc = parts[0]
                for p in parts[1:]:
                    acc = opo.np_reduce(acc, p)
                h.finish()
                return jax.device_put(acc, comm.procs[root].device)
            h.send_bytes(root_slice, tag, partial.tobytes())
            h.finish()
        except BaseException:
            h.abort_pending()
            raise
        return None

    def barrier(self, comm):
        """Local device barrier, then a zero-payload leader exchange
        (gather+release — no controller leaves before all entered)."""
        h = self.comm_slice(comm)
        h.comm.barrier()
        token = np.zeros(1, np.uint8)
        try:
            _exchange_gather(h, token, op_lookup("max"), timeout=60.0,
                             tag_base=h.next_tag_base())
            h.finish()
        except BaseException:
            h.abort_pending()
            raise
        SPC.record("hier_vtable_barriers")
        return None
